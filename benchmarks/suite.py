"""BASELINE.md measurement suite: configs 1-5 on real hardware.

Run on the TPU host:  python benchmarks/suite.py [--rows-scale 1.0]
Prints one JSON line per config; paste results into BASELINE.md.

Config map (BASELINE.json):
  1 README monitor smoke — end-to-end standalone SQL latency
  2 TSBS single-groupby-1-1-1 @ scaled rows — device scan+agg
  3 TSBS double-groupby-5 + high-cardinality hosts — device scan+agg
  4 PromQL rate(cpu[5m]) + avg_over_time over 10k series / 24h
  5 compaction + 1s→1m downsample over a multi-SST region

CPU denominators are same-machine pandas columnar equivalents (the
reference publishes no numbers; see BASELINE.md).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _p(name, value, unit, extra=None):
    doc = {"config": name, "value": round(value, 2), "unit": unit}
    if extra:
        doc.update(extra)
    print(json.dumps(doc), flush=True)


# ---------------------------------------------------------------------------
def config1_monitor(tmpdir):
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    dn = DatanodeInstance(DatanodeOptions(
        data_home=f"{tmpdir}/monitor", register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query("CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME"
                " INDEX, cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))")
    rng = np.random.default_rng(1)
    t_ins = time.perf_counter()
    for chunk in range(10):
        rows = ", ".join(
            f"('host{int(h)}', {1000 + chunk * 1000 + i}, "
            f"{float(c):.2f}, {float(m):.1f})"
            for i, (h, c, m) in enumerate(zip(
                rng.integers(0, 8, 1000), rng.random(1000) * 100,
                rng.random(1000) * 4096)))
        fe.do_query(f"INSERT INTO monitor VALUES {rows}")
    ins_dt = time.perf_counter() - t_ins
    q = "SELECT host, avg(cpu) FROM monitor GROUP BY host ORDER BY host"
    fe.do_query(q)                                   # warm / compile
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        out = fe.do_query(q)[-1]
    dt = (time.perf_counter() - t0) / iters
    assert out.batches[0].num_rows == 8
    _p("1_monitor_smoke", dt * 1e3, "ms/query",
       {"insert_rows_per_s": round(10_000 / ins_dt)})
    fe.shutdown()


# ---------------------------------------------------------------------------
def _device_groupby(n_rows, num_groups, n_metrics, ops, iters=6):
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate

    rng = np.random.default_rng(7)
    gids = np.sort(rng.integers(0, num_groups, n_rows)).astype(np.int32)
    ts = rng.integers(0, 3_600_000, n_rows).astype(np.int32)
    metrics = tuple(rng.random(n_rows, dtype=np.float32) * 100
                    for _ in range(n_metrics))
    mask = np.ones(n_rows, bool)
    d = (jax.device_put(gids), jax.device_put(mask), jax.device_put(ts),
         tuple(jax.device_put(m) for m in metrics))

    @jax.jit
    def step(gids_a, mask_a, ts_a, ms_a, shift):
        ms_a = (ms_a[0] + shift,) + ms_a[1:]
        return sorted_grouped_aggregate(gids_a, mask_a, ts_a, ms_a,
                                        num_groups=num_groups, ops=ops)

    out = step(*d, jnp.float32(0))
    float(np.asarray(out[1])[0])
    t0 = time.perf_counter()
    for i in range(iters):
        out = step(*d, jnp.float32(i + 1))
    float(np.asarray(out[1])[0])
    dt = (time.perf_counter() - t0) / iters

    import pandas as pd
    df = pd.DataFrame({"g": gids})
    for i, m in enumerate(metrics):
        df[f"m{i}"] = m
    t0 = time.perf_counter()
    df.groupby("g").agg({f"m{i}": ("mean" if op == "avg" else op)
                         for i, op in enumerate(ops)})
    cpu_dt = time.perf_counter() - t0
    return n_rows / dt, n_rows / cpu_dt


def config2_tsbs_single(scale):
    n = int(100e6 * scale)
    tpu, cpu = _device_groupby(n, 8 * 60, 1, ("max",))
    _p("2_tsbs_single_groupby_1_1_1", tpu / 1e6, "Mrows/s",
       {"rows": n, "cpu_mrows_s": round(cpu / 1e6, 2),
        "vs_cpu": round(tpu / cpu, 1)})


def config3_tsbs_double_highcard(scale):
    n = int(100e6 * scale)
    groups = 10_000 * 12                 # 10k hosts × 12 5-min buckets
    tpu, cpu = _device_groupby(n, groups, 5, ("avg",) * 5)
    _p("3_tsbs_double_groupby_5_highcard", tpu / 1e6, "Mrows/s",
       {"rows": n, "groups": groups,
        "cpu_mrows_s": round(cpu / 1e6, 2),
        "vs_cpu": round(tpu / cpu, 1)})


# ---------------------------------------------------------------------------
def config4_promql(scale):
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.window import AlignedWindowEval, SeriesMatrix

    num_series = int(10_000 * max(scale, 0.1))
    pts = 5760                            # 24h at 15s scrape
    n = num_series * pts
    rng = np.random.default_rng(11)
    sids = np.repeat(np.arange(num_series, dtype=np.int32), pts)
    ts = np.tile(np.arange(pts, dtype=np.int64) * 15_000, num_series)
    vals = np.cumsum(rng.random(n, dtype=np.float32), dtype=np.float32)
    matrix = SeriesMatrix.build(sids, ts, vals, num_series)
    d_ts, d_vals, d_lens, base = matrix.device_arrays()
    d_ts = jax.device_put(d_ts)
    d_vals = jax.device_put(d_vals)
    d_lens = jax.device_put(d_lens)
    nsteps = 1440                         # 24h at 1m step
    add = jax.jit(lambda v, s: v + s)

    def eval_once(i):
        """Engine-style evaluation: AlignedWindowEval shares the bounds
        pass, cumsums, and the one stacked gather between rate and
        avg_over_time — the same path PromqlEngine takes."""
        v2 = add(d_vals, jnp.float32(i))
        awe = AlignedWindowEval(d_ts, v2, d_lens, 300_000 - base, 60_000,
                                300_000, nsteps)
        r, ok = awe.eval("rate")
        a, ok2 = awe.eval("avg_over_time")
        return r, a, jnp.logical_and(ok, ok2)

    out = eval_once(0)
    float(np.asarray(out[0])[0, 0])
    iters = 4
    t0 = time.perf_counter()
    for i in range(iters):
        out = eval_once(i)
    float(np.asarray(out[0])[0, 0])
    dt = (time.perf_counter() - t0) / iters
    _p("4_promql_rate_avg_24h", dt * 1e3, "ms/eval",
       {"series": num_series, "points": n, "steps": nsteps,
        "points_per_s_m": round(n / dt / 1e6, 1),
        "outputs_per_s_m": round(2 * num_series * nsteps / dt / 1e6, 1)})


# ---------------------------------------------------------------------------
def config5_downsample(tmpdir, scale):
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance

    n_rows = int(8e6 * max(scale, 0.1))
    per_sst = n_rows // 4
    dn = DatanodeInstance(DatanodeOptions(
        data_home=f"{tmpdir}/ds", register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query("CREATE TABLE raw (host STRING, ts TIMESTAMP TIME INDEX,"
                " v DOUBLE, PRIMARY KEY(host))")
    fe.do_query("CREATE TABLE agg (host STRING, ts TIMESTAMP TIME INDEX,"
                " v DOUBLE, PRIMARY KEY(host))")
    raw = fe.catalog.table("greptime", "public", "raw")
    rng = np.random.default_rng(3)
    n_hosts = 100
    secs_per_sst = per_sst // n_hosts     # every host emits 1 point/sec
    t_load = time.perf_counter()
    for s in range(4):
        base_ts = s * secs_per_sst * 1000
        ts = np.tile(np.arange(secs_per_sst, dtype=np.int64) * 1000
                     + base_ts, n_hosts)
        host = np.repeat([f"h{i}" for i in range(n_hosts)], secs_per_sst)
        cols = {"host": host, "ts": ts, "v": rng.random(len(ts))}
        raw.insert(cols)
        raw.flush()
    n_rows = 4 * secs_per_sst * n_hosts
    load_dt = time.perf_counter() - t_load

    from greptimedb_tpu.storage.downsample import downsample_region
    fe.do_query("CREATE TABLE agg_warm (host STRING, ts TIMESTAMP TIME "
                "INDEX, v DOUBLE, PRIMARY KEY(host))")
    agg = fe.catalog.table("greptime", "public", "agg")
    src_region = next(iter(raw.regions.values()))
    dst_region = next(iter(agg.regions.values()))
    warm_region = next(iter(fe.catalog.table(
        "greptime", "public", "agg_warm").regions.values()))
    # cold pass pays XLA compile + scan-cache build (once per process /
    # region); the timed pass is the steady state a periodic maintenance
    # job runs in — kernels compiled, source region device-resident (the
    # same warm-then-time protocol as config 4)
    t0 = time.perf_counter()
    downsample_region(src_region, warm_region, stride_ms=60_000,
                      aggs={"v": "avg"})
    cold_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    downsample_region(src_region, dst_region, stride_ms=60_000,
                      aggs={"v": "avg"})
    dt = time.perf_counter() - t0
    out_rows = sum(b.num_rows for b in agg.scan_batches())
    _p("5_downsample_1s_to_1m", n_rows / dt / 1e6, "Mrows/s",
       {"rows_in": n_rows, "rows_out": out_rows,
        "load_rows_per_s": round(n_rows / load_dt),
        "downsample_s": round(dt, 2), "cold_s": round(cold_dt, 2)})
    fe.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-scale", type=float, default=1.0,
                    help="scale factor on row counts (1.0 = full size)")
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--block-rows", type=int, default=50_000_000)
    args = ap.parse_args()
    import tempfile
    want = set(args.configs.split(","))
    with tempfile.TemporaryDirectory() as tmpdir:
        if "1" in want:
            config1_monitor(tmpdir)
        if "2" in want:
            config2_tsbs_single(args.rows_scale)
        if "3" in want:
            config3_tsbs_double_highcard(args.rows_scale)
        if "3b" in want:
            config3_blocked_1b(block_rows=args.block_rows)
        if "4" in want:
            config4_promql(args.rows_scale)
        if "5" in want:
            config5_downsample(tmpdir, args.rows_scale)




def config3_blocked_1b(total_rows: int = 1_000_000_000,
                       block_rows: int = 50_000_000):
    """BASELINE config 3 at its true scale: 1B rows streamed through
    HBM-sized time blocks, per-block device aggregation, device-side
    moment merge (sum/count add; min/max reduce) — the time-axis
    blocking design from SURVEY §5/§7. Data is generated on device per
    block (same methodology as bench.py: measures the scan+aggregate
    path, not host→device transfer of synthetic data)."""
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate

    groups = 10_000 * 12
    # exact sorted-uniform ids without int32-overflowing products
    # (x64 is off on TPU): block = groups * reps rows
    reps = max(1, block_rows // groups)
    block_rows = groups * reps

    @jax.jit
    def block_moments(key):
        kv = key
        # sorted-by-construction group ids (region scans arrive sorted
        # from the device merge; sorting here would be a datagen artifact)
        gids = jnp.repeat(jnp.arange(groups, dtype=jnp.int32), reps)
        ts = jnp.zeros((block_rows,), jnp.int32)
        mask = jnp.ones((block_rows,), bool)
        vals = tuple(
            jax.random.uniform(jax.random.fold_in(kv, i),
                               (block_rows,), jnp.float32) * 100
            for i in range(5))
        # per-block moments: sums + counts (avg folds at the end)
        (s0, s1, s2, s3, s4), counts = sorted_grouped_aggregate(
            gids, mask, ts, vals, num_groups=groups, ops=("sum",) * 5)
        return jnp.stack([s0, s1, s2, s3, s4]), counts

    @jax.jit
    def merge(acc_s, acc_c, s, c):
        return acc_s + s, acc_c + c

    n_blocks = total_rows // block_rows
    key = jax.random.PRNGKey(0)
    s, c = block_moments(key)
    jax.block_until_ready(c)
    t0 = time.perf_counter()
    acc_s, acc_c = s, c
    for i in range(1, n_blocks):
        s, c = block_moments(jax.random.fold_in(key, i))
        acc_s, acc_c = merge(acc_s, acc_c, s, c)
    final_avg = acc_s / jnp.maximum(acc_c, 1)[None, :]
    float(np.asarray(final_avg)[0, 0])            # force completion
    dt = time.perf_counter() - t0
    rows_done = (n_blocks - 1) * block_rows       # first block was warmup
    _p("3b_tsbs_double_groupby_1B_blocked", rows_done / dt / 1e6,
       "Mrows/s", {"rows": rows_done + block_rows, "blocks": n_blocks,
                   "groups": groups, "block_rows": block_rows,
                   "wall_s": round(dt, 1)})


if __name__ == "__main__":
    main()
