"""BASELINE.md measurement suite: configs 1-5 on real hardware.

Run on the TPU host:  python benchmarks/suite.py [--rows-scale 1.0]
Prints one JSON line per config; paste results into BASELINE.md.

Config map (BASELINE.json):
  1 README monitor smoke — end-to-end standalone SQL latency
  2 TSBS single-groupby-1-1-1 @ scaled rows — device scan+agg
  3 TSBS double-groupby-5 + high-cardinality hosts — device scan+agg
  4 PromQL rate(cpu[5m]) + avg_over_time over 10k series / 24h
  5 compaction + 1s→1m downsample over a multi-SST region

CPU denominators are same-machine pandas columnar equivalents (the
reference publishes no numbers; see BASELINE.md).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _p(name, value, unit, extra=None):
    doc = {"config": name, "value": round(value, 2), "unit": unit}
    if extra:
        doc.update(extra)
    print(json.dumps(doc), flush=True)


# ---------------------------------------------------------------------------
def config1_monitor(tmpdir):
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    dn = DatanodeInstance(DatanodeOptions(
        data_home=f"{tmpdir}/monitor", register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query("CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME"
                " INDEX, cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))")
    rng = np.random.default_rng(1)
    t_ins = time.perf_counter()
    for chunk in range(10):
        rows = ", ".join(
            f"('host{int(h)}', {1000 + chunk * 1000 + i}, "
            f"{float(c):.2f}, {float(m):.1f})"
            for i, (h, c, m) in enumerate(zip(
                rng.integers(0, 8, 1000), rng.random(1000) * 100,
                rng.random(1000) * 4096)))
        fe.do_query(f"INSERT INTO monitor VALUES {rows}")
    ins_dt = time.perf_counter() - t_ins
    q = "SELECT host, avg(cpu) FROM monitor GROUP BY host ORDER BY host"
    fe.do_query(q)                                   # warm / compile
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        out = fe.do_query(q)[-1]
    dt = (time.perf_counter() - t0) / iters
    assert out.batches[0].num_rows == 8
    _p("1_monitor_smoke", dt * 1e3, "ms/query",
       {"insert_rows_per_s": round(10_000 / ins_dt)})
    fe.shutdown()


# ---------------------------------------------------------------------------
def _device_groupby(n_rows, num_groups, n_metrics, ops, iters=6):
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate

    rng = np.random.default_rng(7)
    gids = np.sort(rng.integers(0, num_groups, n_rows)).astype(np.int32)
    ts = rng.integers(0, 3_600_000, n_rows).astype(np.int32)
    metrics = tuple(rng.random(n_rows, dtype=np.float32) * 100
                    for _ in range(n_metrics))
    mask = np.ones(n_rows, bool)
    d = (jax.device_put(gids), jax.device_put(mask), jax.device_put(ts),
         tuple(jax.device_put(m) for m in metrics))

    @jax.jit
    def step(gids_a, mask_a, ts_a, ms_a, shift):
        ms_a = (ms_a[0] + shift,) + ms_a[1:]
        return sorted_grouped_aggregate(gids_a, mask_a, ts_a, ms_a,
                                        num_groups=num_groups, ops=ops)

    out = step(*d, jnp.float32(0))
    float(np.asarray(out[1])[0])
    t0 = time.perf_counter()
    for i in range(iters):
        out = step(*d, jnp.float32(i + 1))
    float(np.asarray(out[1])[0])
    dt = (time.perf_counter() - t0) / iters

    import pandas as pd
    df = pd.DataFrame({"g": gids})
    for i, m in enumerate(metrics):
        df[f"m{i}"] = m
    t0 = time.perf_counter()
    df.groupby("g").agg({f"m{i}": ("mean" if op == "avg" else op)
                         for i, op in enumerate(ops)})
    cpu_dt = time.perf_counter() - t0
    return n_rows / dt, n_rows / cpu_dt


def config2_tsbs_single(scale):
    n = int(100e6 * scale)
    tpu, cpu = _device_groupby(n, 8 * 60, 1, ("max",))
    _p("2_tsbs_single_groupby_1_1_1", tpu / 1e6, "Mrows/s",
       {"rows": n, "cpu_mrows_s": round(cpu / 1e6, 2),
        "vs_cpu": round(tpu / cpu, 1)})


def config3_tsbs_double_highcard(scale):
    n = int(100e6 * scale)
    groups = 10_000 * 12                 # 10k hosts × 12 5-min buckets
    tpu, cpu = _device_groupby(n, groups, 5, ("avg",) * 5)
    _p("3_tsbs_double_groupby_5_highcard", tpu / 1e6, "Mrows/s",
       {"rows": n, "groups": groups,
        "cpu_mrows_s": round(cpu / 1e6, 2),
        "vs_cpu": round(tpu / cpu, 1)})


# ---------------------------------------------------------------------------
def config4_promql(scale):
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.window import (
        SeriesMatrix, range_aggregate_cumsum)

    num_series = int(10_000 * max(scale, 0.1))
    pts = 5760                            # 24h at 15s scrape
    n = num_series * pts
    rng = np.random.default_rng(11)
    sids = np.repeat(np.arange(num_series, dtype=np.int32), pts)
    ts = np.tile(np.arange(pts, dtype=np.int64) * 15_000, num_series)
    vals = np.cumsum(rng.random(n, dtype=np.float32), dtype=np.float32)
    matrix = SeriesMatrix.build(sids, ts, vals, num_series)
    d_ts, d_vals, d_lens, base = matrix.device_arrays()
    d_ts = jax.device_put(d_ts)
    d_vals = jax.device_put(d_vals)
    d_lens = jax.device_put(d_lens)
    nsteps = 1440                         # 24h at 1m step

    @jax.jit
    def eval_rate(ts2d, v2d, lens, shift):
        r, ok = range_aggregate_cumsum(
            ts2d, v2d + shift, lens, 300_000 - base, 60_000, 300_000,
            op="rate", nsteps=nsteps)
        a, ok2 = range_aggregate_cumsum(
            ts2d, v2d + shift, lens, 300_000 - base, 60_000, 300_000,
            op="avg_over_time", nsteps=nsteps)
        return r, a, ok & ok2

    out = eval_rate(d_ts, d_vals, d_lens, jnp.float32(0))
    float(np.asarray(out[0])[0, 0])
    iters = 4
    t0 = time.perf_counter()
    for i in range(iters):
        out = eval_rate(d_ts, d_vals, d_lens, jnp.float32(i))
    float(np.asarray(out[0])[0, 0])
    dt = (time.perf_counter() - t0) / iters
    _p("4_promql_rate_avg_24h", dt * 1e3, "ms/eval",
       {"series": num_series, "points": n, "steps": nsteps,
        "points_per_s_m": round(n / dt / 1e6, 1),
        "outputs_per_s_m": round(2 * num_series * nsteps / dt / 1e6, 1)})


# ---------------------------------------------------------------------------
def config5_downsample(tmpdir, scale):
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance

    n_rows = int(8e6 * max(scale, 0.1))
    per_sst = n_rows // 4
    dn = DatanodeInstance(DatanodeOptions(
        data_home=f"{tmpdir}/ds", register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query("CREATE TABLE raw (host STRING, ts TIMESTAMP TIME INDEX,"
                " v DOUBLE, PRIMARY KEY(host))")
    fe.do_query("CREATE TABLE agg (host STRING, ts TIMESTAMP TIME INDEX,"
                " v DOUBLE, PRIMARY KEY(host))")
    raw = fe.catalog.table("greptime", "public", "raw")
    rng = np.random.default_rng(3)
    n_hosts = 100
    secs_per_sst = per_sst // n_hosts     # every host emits 1 point/sec
    t_load = time.perf_counter()
    for s in range(4):
        base_ts = s * secs_per_sst * 1000
        ts = np.tile(np.arange(secs_per_sst, dtype=np.int64) * 1000
                     + base_ts, n_hosts)
        host = np.repeat([f"h{i}" for i in range(n_hosts)], secs_per_sst)
        cols = {"host": host.tolist(), "ts": ts.tolist(),
                "v": rng.random(len(ts)).tolist()}
        raw.insert(cols)
        raw.flush()
    n_rows = 4 * secs_per_sst * n_hosts
    load_dt = time.perf_counter() - t_load

    from greptimedb_tpu.storage.downsample import downsample_region
    agg = fe.catalog.table("greptime", "public", "agg")
    src_region = next(iter(raw.regions.values()))
    dst_region = next(iter(agg.regions.values()))
    t0 = time.perf_counter()
    downsample_region(src_region, dst_region, stride_ms=60_000,
                      aggs={"v": "avg"})
    dt = time.perf_counter() - t0
    out_rows = sum(b.num_rows for b in agg.scan_batches())
    _p("5_downsample_1s_to_1m", n_rows / dt / 1e6, "Mrows/s",
       {"rows_in": n_rows, "rows_out": out_rows,
        "load_rows_per_s": round(n_rows / load_dt),
        "downsample_s": round(dt, 2)})
    fe.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-scale", type=float, default=1.0,
                    help="scale factor on row counts (1.0 = full size)")
    ap.add_argument("--configs", default="1,2,3,4,5")
    args = ap.parse_args()
    import tempfile
    want = set(args.configs.split(","))
    with tempfile.TemporaryDirectory() as tmpdir:
        if "1" in want:
            config1_monitor(tmpdir)
        if "2" in want:
            config2_tsbs_single(args.rows_scale)
        if "3" in want:
            config3_tsbs_double_highcard(args.rows_scale)
        if "4" in want:
            config4_promql(args.rows_scale)
        if "5" in want:
            config5_downsample(tmpdir, args.rows_scale)


if __name__ == "__main__":
    main()
