"""Kernel-scaling bench: sorted_grouped_aggregate across group counts.

Measures the BASELINE.md kernel-scaling table (25M rows, 5 metrics) in the
pipeline-realistic staging: gids/values device-resident (the scan cache
keeps them in HBM across queries) and segment ends precomputed (the LSM
scan path has run boundaries on the host already — tpu_exec ships them
with the query).

Usage: PYTHONPATH=. python benchmarks/scaling_profile.py
"""

import argparse
import functools
import time

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=3):
    """Time device compute: reduce outputs to one scalar ON DEVICE so the
    (tunnel) D2H transfer cost doesn't pollute the measurement."""
    @jax.jit
    def reduced(*a):
        leaves = jax.tree_util.tree_leaves(fn(*a))
        return sum(jnp.sum(jnp.nan_to_num(jnp.asarray(x, jnp.float32)))
                   for x in leaves)

    s = reduced(*args)
    np.asarray(s)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(reduced(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=25_000_000)
    ap.add_argument("--groups", default="480,12000,120000,1200000")
    ap.add_argument("--op-sets", default="avg,minmax,firstlast")
    args = ap.parse_args()
    from greptimedb_tpu.ops.kernels import _sorted_grouped_aggregate_pre

    OP_SETS = {
        "avg": ("avg",) * 5,
        "minmax": ("min", "max", "min", "max", "min"),
        "firstlast": ("first", "last"),
    }
    n = args.rows
    rng = np.random.default_rng(0)
    vals = jax.device_put(rng.random(n, dtype=np.float32))
    mask = jnp.ones(n, bool)
    ts = jax.device_put(np.arange(n, dtype=np.int32))
    for G in [int(g) for g in args.groups.split(",")]:
        gids_np = np.sort(rng.integers(0, G, n)).astype(np.int32)
        ends_np = np.cumsum(np.bincount(gids_np, minlength=G),
                            dtype=np.int64).astype(np.int32)
        # static longest-segment bucket, as the scan pipeline stages it
        # (enables the shift-doubling min/max + first/last kernels)
        from greptimedb_tpu.ops.kernels import seg_len_bucket
        seg_k = seg_len_bucket(
            int(np.diff(ends_np, prepend=np.int32(0)).max()))
        gids = jax.device_put(gids_np)
        ends = jax.device_put(ends_np)
        line = [f"G={G:>8}:"]
        for name in args.op_sets.split(","):
            ops = OP_SETS[name]
            f = functools.partial(_sorted_grouped_aggregate_pre,
                                  num_groups=G, ops=ops,
                                  has_col_masks=False, seg_len_k=seg_k)
            t = timeit(f, gids, mask, ts, tuple(vals for _ in ops), (),
                       ends)
            line.append(f"{name}[{len(ops)}c] {t*1e3:7.0f}ms"
                        f" {n/t/1e6:7.1f} Mrows/s")
        print("  ".join(line), flush=True)


if __name__ == "__main__":
    main()
