"""Cold-scan benchmark: TSBS-shaped queries over REAL stored SSTs.

Unlike the kernel microbenches (suite.py configs 2/3) this measures the
whole database path: Parquet decode → slice merge/dedup → H2D → device
moment kernel → fold, via the block-streaming executor
(query/stream_exec.py), against a region ingested and flushed through
the real write path. Reports cold (streamed, nothing resident) and warm
(device scan cache) throughput side by side.

Usage:
    python benchmarks/cold_scan.py --rows 50000000 [--hosts 4000]
                                   [--slice-rows 16000000]
"""

from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import time

import numpy as np


def _p(name, value, unit, extra=None):
    doc = {"bench": name, "value": round(value, 2), "unit": unit}
    if extra:
        doc.update(extra)
    print(json.dumps(doc), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=50_000_000)
    ap.add_argument("--hosts", type=int, default=4000)
    ap.add_argument("--ssts", type=int, default=8)
    ap.add_argument("--slice-rows", type=int, default=16_000_000)
    ap.add_argument("--keep-dir", default=None,
                    help="reuse/keep the data dir (skips ingest when the "
                         "row count matches)")
    args = ap.parse_args()

    from greptimedb_tpu.common.jax_cache import enable_compile_cache
    enable_compile_cache("/tmp/coldscan-xla-cache")
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.query import stream_exec, tpu_exec
    from greptimedb_tpu.session import QueryContext

    tmpdir = args.keep_dir or tempfile.mkdtemp(prefix="coldscan-")
    dn = DatanodeInstance(DatanodeOptions(
        data_home=tmpdir, register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    ctx = QueryContext()

    existing = None
    try:
        existing = fe.catalog.table("greptime", "public", "cpu")
    except Exception:
        existing = None

    if existing is None:
        fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP TIME "
                    "INDEX, usage_user DOUBLE, usage_system DOUBLE, "
                    "PRIMARY KEY(hostname))")
    table = fe.catalog.table("greptime", "public", "cpu")
    region = next(iter(table.regions.values()))
    have = stream_exec.region_estimated_rows(region)

    n = args.rows
    if have < n:
        # TSBS devops shape: H hosts, one point per host per 10s interval
        from greptimedb_tpu.storage.region import IngestProfile
        region = next(iter(table.regions.values()))
        rng = np.random.default_rng(42)
        per_sst = n // args.ssts
        points_per_host = max(per_sst // args.hosts, 1)
        hostnames = np.array([f"host_{i}" for i in range(args.hosts)])
        load_dt = 0.0
        profile = IngestProfile()
        for s in range(args.ssts):
            # data generation happens OUTSIDE the timed window: the
            # metric is the database write path, not np.random
            base = s * points_per_host * 10_000
            ts = np.tile(np.arange(points_per_host, dtype=np.int64)
                         * 10_000 + base, args.hosts)
            host = np.repeat(hostnames, points_per_host).astype(object)
            k = len(ts)
            batch = {
                "hostname": host, "ts": ts,
                "usage_user": (rng.random(k) * 100).round(2),
                "usage_system": (rng.random(k) * 100).round(2)}
            # WAL-less direct-to-SST load (the loader path COPY FROM and
            # Flight bulk do_put use)
            t0 = time.perf_counter()
            table.bulk_load(batch)
            load_dt += time.perf_counter() - t0
            if region.last_ingest_profile is not None:
                profile.merge(region.last_ingest_profile)
            print(f"  ingested sst {s + 1}/{args.ssts} "
                  f"({(s + 1) * k:,} rows)", flush=True)
        n = args.ssts * args.hosts * points_per_host
        _p("ingest_bulk", n / load_dt / 1e6, "Mrows/s",
           {"rows": n, "seconds": round(load_dt, 1),
            "stages": {k: round(v, 3)
                       for k, v in sorted(profile.stages.items(),
                                          key=lambda kv: -kv[1])}})
    else:
        n = have

    queries = {
        "single_groupby": "SELECT hostname, avg(usage_user) FROM cpu "
                          "GROUP BY hostname",
        "double_groupby": "SELECT hostname, date_bin(INTERVAL '1 hour', ts)"
                          " AS bucket, avg(usage_user), avg(usage_system) "
                          "FROM cpu GROUP BY hostname, bucket",
    }

    # ---- cold: force streaming, nothing resident ----
    stream_exec.configure_streaming(threshold_rows=1,
                                    slice_rows=args.slice_rows)
    tpu_exec.SCAN_CACHE._entries.clear()
    for qname, sql in queries.items():
        # once to absorb XLA compile (reported separately), then best of
        # two timed runs — shared/throttled hosts show ±25% run-to-run
        # noise and the metric is the engine, not the neighbors
        t0 = time.perf_counter()
        out = fe.do_query(sql, ctx)
        first_dt = time.perf_counter() - t0
        dt = float("inf")
        for _ in range(2):
            tpu_exec.SCAN_CACHE._entries.clear()
            t0 = time.perf_counter()
            out = fe.do_query(sql, ctx)
            dt = min(dt, time.perf_counter() - t0)
        if isinstance(out, list):
            out = out[0]
        groups = out.num_rows
        _p(f"cold_stream_{qname}", n / dt / 1e6, "Mrows/s",
           {"rows": n, "seconds": round(dt, 2), "groups": groups,
            "first_run_s": round(first_dt, 2)})

    # ---- warm: cached device path (only when the region fits) ----
    stream_exec.configure_streaming(threshold_rows=1 << 62)
    if n <= 120_000_000:
        fe.do_query(queries["single_groupby"], ctx)   # build cache
        for qname, sql in queries.items():
            fe.do_query(sql, ctx)                     # absorb XLA compile
            t0 = time.perf_counter()
            fe.do_query(sql, ctx)
            dt = time.perf_counter() - t0
            _p(f"warm_cached_{qname}", n / dt / 1e6, "Mrows/s",
               {"rows": n, "seconds": round(dt, 3)})

    fe.shutdown()
    if args.keep_dir is None:
        shutil.rmtree(tmpdir, ignore_errors=True)
    elif args.keep_dir:
        print(f"  data kept in {tmpdir}", flush=True)


if __name__ == "__main__":
    main()
