"""NYC-taxi-style ingest + query benchmark harness.

Reference behavior: benchmarks/src/bin/nyc-taxi.rs:36-80 — load TLC trip
data through the gRPC client with parallel workers (batch 4096), then
time count / avg / group-by queries. No internet access here, so the
trip data is synthesized with the same shape (vendor, passenger_count,
distance, fares, payment_type over pickup timestamps).

Usage:
    python benchmarks/nyc_taxi.py [--rows 1000000] [--workers 4]
    python benchmarks/nyc_taxi.py --via-flight    # load over the wire
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BATCH = 4096
DDL = """
CREATE TABLE trips (
    vendor_id STRING,
    pickup_ts TIMESTAMP TIME INDEX,
    passenger_count BIGINT,
    trip_distance DOUBLE,
    fare_amount DOUBLE,
    tip_amount DOUBLE,
    total_amount DOUBLE,
    payment_type STRING,
    PRIMARY KEY(vendor_id)
)"""

QUERIES = [
    ("count", "SELECT count(*) FROM trips"),
    ("avg_fare", "SELECT avg(fare_amount) FROM trips"),
    ("group_vendor",
     "SELECT vendor_id, count(*), avg(total_amount) FROM trips"
     " GROUP BY vendor_id ORDER BY vendor_id"),
    ("group_payment",
     "SELECT payment_type, avg(tip_amount) FROM trips"
     " GROUP BY payment_type ORDER BY payment_type"),
    ("filtered",
     "SELECT count(*) FROM trips WHERE trip_distance > 5.0"),
]


def gen_batch(rng, base_ts: int, n: int) -> dict:
    dist = np.round(rng.gamma(2.0, 1.8, n), 2)
    fare = np.round(3.0 + dist * 2.5 + rng.random(n), 2)
    tip = np.round(fare * rng.random(n) * 0.3, 2)
    return {
        "vendor_id": [f"V{v}" for v in rng.integers(1, 5, n)],
        "pickup_ts": (base_ts + np.arange(n, dtype=np.int64) * 1000
                      ).tolist(),
        "passenger_count": rng.integers(1, 7, n).tolist(),
        "trip_distance": dist.tolist(),
        "fare_amount": fare.tolist(),
        "tip_amount": tip.tolist(),
        "total_amount": np.round(fare + tip, 2).tolist(),
        "payment_type": [("card", "cash", "dispute")[p]
                         for p in rng.integers(0, 3, n)],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--via-flight", action="store_true",
                    help="load + query over the Flight wire protocol")
    args = ap.parse_args()

    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance

    tmp = tempfile.mkdtemp(prefix="nyc_taxi_")
    dn = DatanodeInstance(DatanodeOptions(
        data_home=tmp, register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query(DDL)

    if args.via_flight:
        from greptimedb_tpu.client.flight import Database
        from greptimedb_tpu.servers.flight import FlightFrontendServer
        server = FlightFrontendServer(fe)
        server.serve_in_background()
        while server.port == 0:
            time.sleep(0.01)

        def make_sink():
            return Database(server.address)

        def write(sink, cols):
            return sink.insert("trips", cols, tag_columns=["vendor_id"],
                               timestamp_column="pickup_ts")

        def query(sql):
            return make_sink().sql(sql)
    else:
        table = fe.catalog.table("greptime", "public", "trips")

        def make_sink():
            return table

        def write(sink, cols):
            return sink.insert(cols)

        def query(sql):
            return fe.do_query(sql)[-1].batches

    # ---- parallel ingest (reference: parallel gRPC clients, batch 4096)
    n_batches = (args.rows + BATCH - 1) // BATCH
    t0 = time.perf_counter()

    def worker(wid: int) -> int:
        rng = np.random.default_rng(wid)
        sink = make_sink()
        wrote = 0
        for b in range(wid, n_batches, args.workers):
            n = min(BATCH, args.rows - b * BATCH)
            if n <= 0:
                break
            wrote += write(sink, gen_batch(rng, b * BATCH * 1000, n))
        return wrote

    with concurrent.futures.ThreadPoolExecutor(args.workers) as pool:
        total = sum(pool.map(worker, range(args.workers)))
    ingest_dt = time.perf_counter() - t0
    print(json.dumps({"phase": "ingest", "rows": total,
                      "rows_per_s": round(total / ingest_dt),
                      "seconds": round(ingest_dt, 2),
                      "workers": args.workers,
                      "via": "flight" if args.via_flight else "local"}),
          flush=True)

    # ---- queries (warm once, then timed) ----
    for name, sql in QUERIES:
        query(sql)
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            batches = query(sql)
        dt = (time.perf_counter() - t0) / iters
        nrows = sum(b.num_rows for b in batches)
        print(json.dumps({"phase": "query", "name": name,
                          "ms": round(dt * 1e3, 1),
                          "result_rows": nrows}), flush=True)
    fe.shutdown()


if __name__ == "__main__":
    main()
