"""Flagship benchmark: TSBS-style scan+aggregate throughput on TPU.

Models the north-star config (BASELINE.json): TSBS cpu-only
`single-groupby-5-8-1`-shape query — group by (host, 1-minute bucket) over
one hour, per-minute MAX of 5 metric columns — on rows resident in HBM in
the engine's post-merge layout (sorted by group key, which is what region
scans produce after the device merge/dedup pass). Uses the scatter-free
sorted-segment kernel (ops/kernels.py:sorted_grouped_aggregate); measured
~44x faster than XLA scatter segment_sum on v5e for this shape.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`vs_baseline` is the speedup vs a same-machine CPU columnar baseline
(pandas groupby over the identical arrays — the stand-in denominator for
"CPU DataFusion", since the reference publishes no numbers; BASELINE.md).

Timing notes: on the axon tunnel jax.block_until_ready returns before
remote completion, so each timed iteration fetches a scalar result to host;
iterations use distinct shifted inputs so no result can be reused.
"""

import json
import os
import time

import numpy as np

HOSTS, BUCKETS = 8, 60
NUM_GROUPS = HOSTS * BUCKETS
OPS = ("max",) * 5  # TSBS single-groupby computes per-minute max


def gen_data(n_rows: int, seed: int = 42):
    rng = np.random.default_rng(seed)
    # post-merge region-scan layout: rows sorted by (host, minute bucket)
    gids = np.sort(rng.integers(0, NUM_GROUPS, n_rows)).astype(np.int32)
    ts = ((gids % BUCKETS) * 60_000 +
          rng.integers(0, 60_000, n_rows)).astype(np.int32)
    metrics = tuple(rng.random(n_rows, dtype=np.float32) * 100
                    for _ in range(5))
    return gids, ts, metrics


def bench_tpu(gids, ts, metrics, iters=8):
    import jax
    from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate

    import jax.numpy as jnp

    n = len(gids)
    mask = np.ones(n, bool)
    d_gids = jax.device_put(gids)
    d_ts = jax.device_put(ts)
    d_mask = jax.device_put(mask)
    d_ms = tuple(jax.device_put(m) for m in metrics)

    # Data arrays are jit *arguments* (not closure constants) so the compiled
    # program is code-only — closure capture bakes 16.7M-row arrays into the
    # HLO as constants, which blows remote-compile payload limits.
    @jax.jit
    def step(gids_a, mask_a, ts_a, ms_a, shift):
        # distinct shift per iteration → distinct numerics, so the runtime
        # cannot reuse a previous result
        ms_a = (ms_a[0] + shift,) + ms_a[1:]
        return sorted_grouped_aggregate(gids_a, mask_a, ts_a, ms_a,
                                        num_groups=NUM_GROUPS, ops=OPS)

    def step_i(shift):
        return step(d_gids, d_mask, d_ts, d_ms, shift)

    out = step_i(jnp.float32(0))
    float(np.asarray(out[1])[0])     # compile + warmup, forced to completion
    t0 = time.perf_counter()
    for i in range(iters):
        out = step_i(jnp.float32(i + 1))
    float(np.asarray(out[1])[0])     # stream order ⇒ all iters completed
    dt = (time.perf_counter() - t0) / iters
    return n / dt, out


def bench_cpu(gids, ts, metrics):
    """CPU columnar baseline: pandas groupby-max over identical data."""
    import pandas as pd
    df = pd.DataFrame({"g": gids})
    for i, m in enumerate(metrics):
        df[f"m{i}"] = m
    t0 = time.perf_counter()
    df.groupby("g").agg({f"m{i}": "max" for i in range(5)})
    dt = time.perf_counter() - t0
    return len(gids) / dt


def main():
    n_rows = int(os.environ.get("GREPTIME_BENCH_ROWS", 1 << 24))
    gids, ts, metrics = gen_data(n_rows)

    tpu_rps, out = bench_tpu(gids, ts, metrics)

    # sanity: TPU result must agree with a numpy oracle on one group
    # (last iteration shifted metric 0 by +iters)
    g0 = gids == 0
    if g0.any():
        got = float(np.asarray(out[0][0])[0])
        assert abs(got - float(metrics[0][g0].max()) - 8.0) < 1e-2, got

    cpu_rps = bench_cpu(gids, ts, metrics)

    print(json.dumps({
        "metric": "tsbs_single_groupby_scan_agg_throughput",
        "value": round(tpu_rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
    }))


if __name__ == "__main__":
    main()
