"""Flagship benchmark: TSBS-style scan+aggregate throughput on TPU.

Models the north-star config (BASELINE.json): TSBS cpu-only
`single-groupby-5-8-1`-shape query — group by (host, 1-minute bucket) over
one hour, per-minute MAX of 5 metric columns — on rows resident in HBM in
the engine's post-merge layout (sorted by group key, which is what region
scans produce after the device merge/dedup pass). Uses the scatter-free
sorted-segment kernel (ops/kernels.py:sorted_grouped_aggregate); measured
~44x faster than XLA scatter segment_sum on v5e for this shape.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`vs_baseline` is the speedup vs a same-machine CPU columnar baseline
(pandas groupby over the identical arrays — the stand-in denominator for
"CPU DataFusion", since the reference publishes no numbers; BASELINE.md).

Timing notes: on the axon tunnel jax.block_until_ready returns before
remote completion, so each timed iteration fetches a scalar result to host;
iterations use distinct shifted inputs so no result can be reused.
"""

import json
import os
import time

import numpy as np

HOSTS, BUCKETS = 8, 60
NUM_GROUPS = HOSTS * BUCKETS
OPS = ("max",) * 5  # TSBS single-groupby computes per-minute max


def gen_data(n_rows: int, seed: int = 42):
    rng = np.random.default_rng(seed)
    # post-merge region-scan layout: rows sorted by (host, minute bucket)
    gids = np.sort(rng.integers(0, NUM_GROUPS, n_rows)).astype(np.int32)
    ts = ((gids % BUCKETS) * 60_000 +
          rng.integers(0, 60_000, n_rows)).astype(np.int32)
    metrics = tuple(rng.random(n_rows, dtype=np.float32) * 100
                    for _ in range(5))
    return gids, ts, metrics


def bench_tpu(gids, ts, metrics, iters=8):
    import jax
    from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate

    import jax.numpy as jnp

    n = len(gids)
    mask = np.ones(n, bool)
    d_gids = jax.device_put(gids)
    d_ts = jax.device_put(ts)
    d_mask = jax.device_put(mask)
    d_ms = tuple(jax.device_put(m) for m in metrics)

    # Data arrays are jit *arguments* (not closure constants) so the compiled
    # program is code-only — closure capture bakes 16.7M-row arrays into the
    # HLO as constants, which blows remote-compile payload limits.
    @jax.jit
    def step(gids_a, mask_a, ts_a, ms_a, shift):
        # distinct shift per iteration → distinct numerics, so the runtime
        # cannot reuse a previous result
        ms_a = (ms_a[0] + shift,) + ms_a[1:]
        return sorted_grouped_aggregate(gids_a, mask_a, ts_a, ms_a,
                                        num_groups=NUM_GROUPS, ops=OPS)

    def step_i(shift):
        return step(d_gids, d_mask, d_ts, d_ms, shift)

    out = step_i(jnp.float32(0))
    float(np.asarray(out[1])[0])     # compile + warmup, forced to completion
    t0 = time.perf_counter()
    for i in range(iters):
        out = step_i(jnp.float32(i + 1))
    float(np.asarray(out[1])[0])     # stream order ⇒ all iters completed
    dt = (time.perf_counter() - t0) / iters
    return n / dt, out


def bench_cpu(gids, ts, metrics):
    """CPU columnar baseline: pandas groupby-max over identical data."""
    import pandas as pd
    df = pd.DataFrame({"g": gids})
    for i, m in enumerate(metrics):
        df[f"m{i}"] = m
    t0 = time.perf_counter()
    df.groupby("g").agg({f"m{i}": "max" for i in range(5)})
    dt = time.perf_counter() - t0
    return len(gids) / dt


def bench_cold_e2e(n_rows: int):
    """Second driver metric: cold single-groupby Mrows/s over a small
    REGION PERSISTED THROUGH THE REAL WRITE PATH — parquet decode →
    lean slice reduce → fold, via frontend.do_query with the scan cache
    cleared. The flagship kernel number above has been flat for rounds
    while the actual work moved to this path; carrying both makes a
    regression in either visible (ISSUE 1 satellite)."""
    import shutil
    import tempfile

    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.query import stream_exec, tpu_exec
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-cold-")
    fe = None
    saved_threshold = stream_exec.stream_threshold_rows()
    try:
        dn = DatanodeInstance(DatanodeOptions(
            data_home=tmpdir, register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        ctx = QueryContext()
        fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                    "TIME INDEX, usage_user DOUBLE, "
                    "PRIMARY KEY(hostname))")
        table = fe.catalog.table("greptime", "public", "cpu")
        rng = np.random.default_rng(7)
        hosts = 500
        per = n_rows // hosts
        ts = np.tile(np.arange(per, dtype=np.int64) * 10_000, hosts)
        host = np.repeat(
            np.array([f"host_{i}" for i in range(hosts)]),
            per).astype(object)
        table.bulk_load({"hostname": host, "ts": ts,
                         "usage_user": rng.random(len(ts)) * 100})
        n = hosts * per
        sql = "SELECT hostname, avg(usage_user) FROM cpu GROUP BY hostname"
        stream_exec.configure_streaming(threshold_rows=1)
        fe.do_query(sql, ctx)              # absorb one-time costs
        dt = float("inf")
        for _ in range(2):                 # best of 2: noisy shared hosts
            tpu_exec.SCAN_CACHE._entries.clear()
            t0 = time.perf_counter()
            fe.do_query(sql, ctx)
            dt = min(dt, time.perf_counter() - t0)
        # stage breakdown of the final run: the scan profiler +
        # ExecStats collector (so BENCH rounds capture where the time
        # went, not just the headline rate — ISSUE 2 satellite)
        region = next(iter(table.regions.values()))
        sp = region.last_scan_profile
        st = fe.query_engine.last_exec_stats
        profile = {
            "scan_profile": None if sp is None else {
                "path": sp.path, "rows": sp.rows,
                "total_s": round(sp.total_s, 4),
                "stages": {k: round(v, 4)
                           for k, v in sp.stages.items()},
                "counters": sp.counters,
            },
            "exec_stats": None if st is None else {
                "dispatch": st.dispatch,
                "stages": {s.stage: {"rows": s.rows, "files": s.files,
                                     "ms": round(s.elapsed_s * 1e3, 2)}
                           for s in st.stages.values()},
            },
        }
        return n / dt, profile             # rows/sec + stage breakdown
    finally:
        # the streaming threshold is process-global: restore it so any
        # metric added after this one measures the normal dispatch, and
        # stop the engine's background workers before deleting their dir
        stream_exec.configure_streaming(threshold_rows=saved_threshold)
        if fe is not None:
            fe.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def bench_rollup_e2e(n_rows: int):
    """Third driver metric: rollup-served double-groupby throughput
    (ISSUE 3). A 1s→1m flow folds the region once; the timed query is
    the same GROUP BY (host, 5m bucket) aggregate served cold through
    the `rollup-rewrite` dispatch — the scan cache is cleared every
    iteration, so the win measured is "aggregate table vs raw SSTs",
    not cache warmth. Value is EFFECTIVE raw-row throughput: raw rows
    the answer covers / elapsed. `vs_raw_scan` is the speedup against
    the identical query with the rewrite disabled (cold raw scan)."""
    import shutil
    import tempfile

    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.query import tpu_exec
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-rollup-")
    fe = None
    try:
        dn = DatanodeInstance(DatanodeOptions(
            data_home=tmpdir, register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        ctx = QueryContext()
        fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                    "TIME INDEX, usage_user DOUBLE, "
                    "PRIMARY KEY(hostname))")
        table = fe.catalog.table("greptime", "public", "cpu")
        rng = np.random.default_rng(7)
        hosts = 500
        per = n_rows // hosts
        ts = np.tile(np.arange(per, dtype=np.int64) * 1_000, hosts)
        host = np.repeat(
            np.array([f"host_{i}" for i in range(hosts)]),
            per).astype(object)
        table.bulk_load({"hostname": host, "ts": ts,
                         "usage_user": rng.random(len(ts)) * 100})
        n = hosts * per
        fe.do_query(
            "CREATE FLOW cpu_1m AS SELECT hostname, "
            "date_bin(INTERVAL '1 minute', ts) AS b, "
            "sum(usage_user) AS u_sum, count(usage_user) AS u_cnt "
            "FROM cpu GROUP BY hostname, b", ctx)
        dn.flow_manager.tick()             # fold once, off the clock
        sql = ("SELECT hostname, date_bin(INTERVAL '5 minutes', ts) AS b, "
               "avg(usage_user) FROM cpu GROUP BY hostname, b")
        fe.do_query(sql, ctx)              # absorb one-time costs

        def timed(q):
            dt = float("inf")
            for _ in range(2):             # best of 2: noisy shared hosts
                tpu_exec.SCAN_CACHE._entries.clear()
                t0 = time.perf_counter()
                fe.do_query(q, ctx)
                dt = min(dt, time.perf_counter() - t0)
            return dt

        dt_roll = timed(sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0", ctx)
        dt_raw = timed(sql)
        fe.do_query("SET rollup_rewrite = 1", ctx)
        return n / dt_roll, dt_raw / dt_roll
    finally:
        if fe is not None:
            fe.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def bench_ingest_failpoint_overhead(n_rows: int):
    """Fourth driver metric (ISSUE 4): bulk-ingest throughput with the
    failpoint layer compiled in but INACTIVE, differentialed against the
    same ingest with every failpoint call stubbed out entirely. The
    instrumented sites are one module-bool branch each, so the ratio must
    sit inside run-to-run noise — BASELINE.md publishes the numbers and
    the assert here keeps future instrumentation honest."""
    import shutil
    import tempfile
    import timeit

    from greptimedb_tpu.common import failpoint as fp
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)

    assert fp.active_count() == 0
    # (a) raw cost of one inactive fail_point() evaluation
    per_call_ns = timeit.timeit(
        lambda: fp.fail_point("wal_append"), number=1_000_000) * 1e3

    # (b) end-to-end bulk ingest, instrumented vs stubbed
    rng = np.random.default_rng(11)
    hosts = 200
    per = n_rows // hosts
    host = np.repeat(np.array([f"host_{i}" for i in range(hosts)]),
                     per).astype(object)
    ts = np.tile(np.arange(per, dtype=np.int64) * 1000, hosts)
    vals = rng.random(hosts * per)

    def ingest_once() -> float:
        tmpdir = tempfile.mkdtemp(prefix="bench-fp-")
        try:
            dn = DatanodeInstance(DatanodeOptions(
                data_home=tmpdir, register_numbers_table=False))
            dn.start()
            from greptimedb_tpu.frontend.instance import FrontendInstance
            fe = FrontendInstance(dn)
            fe.start()
            fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                        "TIME INDEX, usage_user DOUBLE, "
                        "PRIMARY KEY(hostname))")
            table = fe.catalog.table("greptime", "public", "cpu")
            t0 = time.perf_counter()
            table.bulk_load({"hostname": host, "ts": ts,
                             "usage_user": vals})
            dt = time.perf_counter() - t0
            fe.shutdown()
            return dt
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)

    ingest_once()                         # absorb one-time costs
    # interleave the two configurations (best of 2 each) so slow-drift
    # on a shared box lands on both sides of the differential
    saved = (fp.fail_point, fp.fires)
    dt_instrumented = dt_stubbed = float("inf")
    try:
        for _ in range(2):
            fp.fail_point, fp.fires = saved
            dt_instrumented = min(dt_instrumented, ingest_once())
            fp.fail_point = lambda name: None   # the layer compiled "out"
            fp.fires = lambda name: False
            dt_stubbed = min(dt_stubbed, ingest_once())
    finally:
        fp.fail_point, fp.fires = saved
    ratio = dt_stubbed / dt_instrumented  # 1.0 = zero overhead
    # instrumented must stay within noise of stubbed-out: on a 2-vCPU
    # shared box run-to-run jitter is ~±10%; a 30% wall-clock regression
    # would mean someone put a failpoint in a per-row loop
    assert ratio >= 0.7, (
        f"inactive failpoint layer cost {1/ratio:.2f}x on bulk ingest")
    return len(ts) / dt_instrumented, ratio, per_call_ns


def bench_self_monitoring_overhead(n_rows: int):
    """Seventh driver metric (ISSUE 8): bulk-ingest throughput with the
    self-monitoring scraper ticking aggressively in the background
    (0.5s cadence — 60x the production default) vs with it off, same
    interleaved best-of-2 differential as the failpoint assertion. The
    scraper writes its registry snapshot through the normal ingest path
    under telemetry.suppress_metrics, so the only cost the user ingest
    can see is the scrape writes' share of the box — the target is <3%
    at the PRODUCTION cadence, which the 60x-tightened loop bounds from
    far above."""
    import shutil
    import tempfile

    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)

    rng = np.random.default_rng(13)
    hosts = 200
    per = n_rows // hosts
    host = np.repeat(np.array([f"host_{i}" for i in range(hosts)]),
                     per).astype(object)
    ts = np.tile(np.arange(per, dtype=np.int64) * 1000, hosts)
    vals = rng.random(hosts * per)

    def ingest_once(monitor: bool) -> "tuple[float, int]":
        tmpdir = tempfile.mkdtemp(prefix="bench-mon-")
        try:
            dn = DatanodeInstance(DatanodeOptions(
                data_home=tmpdir, register_numbers_table=False,
                self_monitor_interval_s=0))   # cadence driven explicitly
            dn.start()
            from greptimedb_tpu.frontend.instance import FrontendInstance
            fe = FrontendInstance(dn)
            fe.start()
            fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                        "TIME INDEX, usage_user DOUBLE, "
                        "PRIMARY KEY(hostname))")
            if monitor:
                fe.self_monitor.tick()         # tables exist up front
                fe.self_monitor.start_background(0.5)
            table = fe.catalog.table("greptime", "public", "cpu")
            t0 = time.perf_counter()
            table.bulk_load({"hostname": host, "ts": ts,
                             "usage_user": vals})
            dt = time.perf_counter() - t0
            ticks = int(fe.self_monitor.stats["ticks"]) if monitor else 0
            fe.shutdown()
            return dt, ticks
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)

    ingest_once(False)                        # absorb one-time costs
    dt_on = dt_off = float("inf")
    ticks_seen = 0
    for _ in range(2):
        dt, ticks = ingest_once(True)
        dt_on = min(dt_on, dt)
        ticks_seen = max(ticks_seen, ticks)
        dt, _ = ingest_once(False)
        dt_off = min(dt_off, dt)
    overhead = dt_on / dt_off - 1.0           # 0.0 = free
    return len(ts) / dt_on, overhead, ticks_seen


def bench_trace_store_overhead(n_rows: int):
    """Tenth driver metric (ISSUE 15): bulk-ingest + mixed small-query
    throughput with the durable trace store's sink at sample ratio 1.0
    (worst case: EVERY trace retained, buffered and written) and at the
    production default 0.01, against the sink uninstalled. The <3% bar
    binds at the default ratio — the PR 8 self-monitoring precedent."""
    import shutil
    import tempfile

    from greptimedb_tpu.common import trace_store
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)

    rng = np.random.default_rng(17)
    hosts = 200
    per = n_rows // hosts
    host = np.repeat(np.array([f"host_{i}" for i in range(hosts)]),
                     per).astype(object)
    ts = np.tile(np.arange(per, dtype=np.int64) * 1000, hosts)
    vals = rng.random(hosts * per)
    n_queries = 300

    def run_once(ratio) -> "tuple[float, float]":
        """(bulk_ingest_s, mixed_query_s + trace_flush_s) for one
        configuration; ratio=None uninstalls the sink entirely. The
        flush that writes retained spans into trace_spans is TIMED —
        at ratio 1.0 it IS the dominant bill, and excluding it would
        let a write-path regression pass the <3% assert."""
        tmpdir = tempfile.mkdtemp(prefix="bench-trace-")
        try:
            dn = DatanodeInstance(DatanodeOptions(
                data_home=tmpdir, register_numbers_table=False,
                self_monitor_interval_s=0))
            dn.start()
            from greptimedb_tpu.frontend.instance import FrontendInstance
            fe = FrontendInstance(dn)
            fe.start()
            if ratio is None:
                trace_store.install(None)
            else:
                trace_store.configure(sample_ratio=ratio)
            fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                        "TIME INDEX, usage_user DOUBLE, "
                        "PRIMARY KEY(hostname))")
            table = fe.catalog.table("greptime", "public", "cpu")
            t0 = time.perf_counter()
            table.bulk_load({"hostname": host, "ts": ts,
                             "usage_user": vals})
            ingest_dt = time.perf_counter() - t0
            t0 = time.perf_counter()
            for i in range(n_queries):
                fe.do_query(f"SELECT usage_user FROM cpu WHERE "
                            f"hostname = 'host_{i % hosts}' LIMIT 5")
            if ratio is not None:
                s = trace_store.sink()
                if s is not None:
                    s.flush()
            query_dt = time.perf_counter() - t0
            fe.shutdown()
            return ingest_dt, query_dt
        finally:
            trace_store.install(None)
            trace_store.configure(sample_ratio=0.01)
            shutil.rmtree(tmpdir, ignore_errors=True)

    run_once(None)                               # absorb one-time costs
    best = {}
    for _ in range(2):                           # interleaved best-of-2
        for key, ratio in (("off", None), ("full", 1.0),
                           ("default", 0.01)):
            ing, q = run_once(ratio)
            b = best.get(key, (float("inf"), float("inf")))
            best[key] = (min(b[0], ing), min(b[1], q))
    ing_off, q_off = best["off"]
    ing_full, q_full = best["full"]
    ing_def, q_def = best["default"]
    overhead_default = (ing_def + q_def) / (ing_off + q_off) - 1.0
    overhead_full = (ing_full + q_full) / (ing_off + q_off) - 1.0
    return (len(ts) / ing_def, overhead_default, overhead_full,
            n_queries / q_def)


def bench_profiler_overhead(n_rows: int):
    """Eleventh driver metric (ISSUE 17): mixed bulk-ingest + small-query
    throughput with the continuous profiler sampling at the default
    19 Hz, against the sampler disabled. The sampler holds the GIL for
    each sys._current_frames() walk, so the bill is real but bounded by
    the rate — the <3% bar binds at the default."""
    import shutil
    import tempfile

    from greptimedb_tpu.common import profiler

    rng = np.random.default_rng(23)
    hosts = 200
    per = n_rows // hosts
    host = np.repeat(np.array([f"host_{i}" for i in range(hosts)]),
                     per).astype(object)
    ts = np.tile(np.arange(per, dtype=np.int64) * 1000, hosts)
    vals = rng.random(hosts * per)
    n_queries = 300

    def run_once(enabled: bool) -> float:
        """Wall seconds for one ingest + query pass, profiler on/off.
        The flush that persists the sampled window is TIMED — it is
        part of the feature's bill exactly like the trace store's."""
        from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                      DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance
        tmpdir = tempfile.mkdtemp(prefix="bench-prof-")
        try:
            dn = DatanodeInstance(DatanodeOptions(
                data_home=tmpdir, register_numbers_table=False,
                self_monitor_interval_s=0))
            dn.start()
            fe = FrontendInstance(dn)
            fe.start()
            profiler.configure(enabled=enabled, hz=19.0)
            fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                        "TIME INDEX, usage_user DOUBLE, "
                        "PRIMARY KEY(hostname))")
            table = fe.catalog.table("greptime", "public", "cpu")
            t0 = time.perf_counter()
            table.bulk_load({"hostname": host, "ts": ts,
                             "usage_user": vals})
            for i in range(n_queries):
                fe.do_query(f"SELECT usage_user FROM cpu WHERE "
                            f"hostname = 'host_{i % hosts}' LIMIT 5")
            if enabled:
                fe.profiler.flush()
            dt = time.perf_counter() - t0
            fe.shutdown()
            return dt
        finally:
            profiler.configure(enabled=False)
            profiler.install(None)
            shutil.rmtree(tmpdir, ignore_errors=True)

    run_once(False)                              # absorb one-time costs
    best = {"off": float("inf"), "on": float("inf")}
    for _ in range(2):                           # interleaved best-of-2
        best["off"] = min(best["off"], run_once(False))
        best["on"] = min(best["on"], run_once(True))
    overhead = best["on"] / best["off"] - 1.0
    return overhead, len(ts) / best["on"], n_queries / best["on"]


def emit_profiler_overhead():
    rows = int(os.environ.get("GREPTIME_BENCH_PROF_ROWS", 2_000_000))
    overhead, rps, qps = bench_profiler_overhead(rows)
    assert overhead < 0.03, (
        f"continuous profiler costs {overhead:.1%} at the default "
        f"19 Hz — the bar is <3%")
    print(json.dumps({
        "metric": "profiler_overhead",
        "value": round(overhead * 100, 2),
        "unit": "percent",
        "sample_hz": 19.0,
        "ingest_mrows_s_sampling": round(rps / 1e6, 2),
        "point_qps_sampling": round(qps, 1),
        "rows": rows,
    }))


def emit_trace_store_overhead():
    rows = int(os.environ.get("GREPTIME_BENCH_TRACE_ROWS", 2_000_000))
    rps, overhead_default, overhead_full, qps = \
        bench_trace_store_overhead(rows)
    assert overhead_default < 0.03, (
        f"trace store costs {overhead_default:.1%} at the default "
        f"0.01 sample ratio — the bar is <3%")
    print(json.dumps({
        "metric": "trace_store_overhead",
        "value": round(overhead_default * 100, 2),
        "unit": "percent",
        "overhead_at_ratio_1_pct": round(overhead_full * 100, 2),
        "ingest_mrows_s_at_default": round(rps / 1e6, 2),
        "point_qps_at_default": round(qps, 1),
        "rows": rows,
    }))


def bench_concurrent_qps(n_clients: int = 1000):
    """Eighth driver metric (ISSUE 12): the missing dimension — sustained
    QPS × tail latency under a 1000-logical-client MIXED workload (small
    point scans + remote-write bursts through the ingest coalescer)
    against a persisted region, plus the WAL group-commit on/off
    differential on fsync-enabled concurrent ingest.

    The differential is published twice: `raw` on this box's fsync (a
    VM write cache makes fsync ~0.15 ms, cheaper than the Python write
    path, so raw barely moves), and `fsync2ms` with a modeled 2 ms
    device sync via the existing wal_fsync delay failpoint — the
    hardware-independent number (same technique as the dist-scatter
    metric's modeled 10 ms RPC hop). The assert keeps the modeled
    differential honest; BASELINE.md publishes both."""
    import shutil
    import tempfile
    import threading
    import timeit
    from queue import Queue

    from greptimedb_tpu.common import failpoint as fp
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.servers.coalesce import COALESCER
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.storage.wal import Wal, configure_group_commit
    from greptimedb_tpu.storage.write_batch import WriteBatch

    # ---- (a) raw Wal.append cost (the hoisted-import satellite) ----
    wal_dir = tempfile.mkdtemp(prefix="bench-qps-wal-")
    w = Wal(wal_dir, sync_on_write=False)
    seq_box = [0]

    def one_append():
        seq_box[0] += 1
        w.append(seq_box[0], b"x" * 64)

    append_ns = timeit.timeit(one_append, number=50_000) / 50_000 * 1e9
    w.close()
    shutil.rmtree(wal_dir, ignore_errors=True)

    # ---- (b) group-commit differential on fsync-enabled ingest ----
    from greptimedb_tpu.datatypes import Schema
    from greptimedb_tpu.datatypes.data_type import (
        FLOAT64, STRING, TIMESTAMP_MILLISECOND)
    from greptimedb_tpu.datatypes.schema import ColumnSchema, SemanticType
    from greptimedb_tpu.storage.object_store import FsObjectStore
    from greptimedb_tpu.storage.region import Region, RegionDescriptor

    schema = Schema([
        ColumnSchema("host", STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("v", FLOAT64),
    ])
    n_threads, per, rows_per = 16, 8, 20

    def sync_ingest_once(group_on: bool, delay_ms: int) -> float:
        configure_group_commit(enabled=group_on)
        home = tempfile.mkdtemp(prefix="bench-qps-gc-")
        try:
            region = Region.create(
                RegionDescriptor("gc", schema, "gc",
                                 os.path.join(home, "wal")),
                FsObjectStore(os.path.join(home, "data")),
                wal=Wal(os.path.join(home, "wal"), sync_on_write=True))
            errs = []

            def writer(i):
                try:
                    for j in range(per):
                        wb = WriteBatch(region.schema)
                        base = (i * per + j) * rows_per
                        wb.put({"host": [f"h{i}"] * rows_per,
                                "ts": list(range(base, base + rows_per)),
                                "v": [1.0] * rows_per})
                        region.write(wb)
                except Exception as e:  # noqa: BLE001 — assert below
                    errs.append(e)

            threads = [threading.Thread(target=writer, args=(i,))
                       for i in range(n_threads)]
            import contextlib
            ctx = fp.cfg("wal_fsync", f"delay({delay_ms})") if delay_ms \
                else contextlib.nullcontext()
            with ctx:
                t0 = time.perf_counter()
                [t.start() for t in threads]
                [t.join() for t in threads]
                dt = time.perf_counter() - t0
            assert not errs, errs
            got = region.snapshot().read_merged().num_rows
            assert got == n_threads * per * rows_per, got
            region.close()
            return dt
        finally:
            shutil.rmtree(home, ignore_errors=True)

    sync_ingest_once(True, 0)                 # absorb one-time costs
    ratios = {}
    for label, delay in (("raw", 0), ("fsync2ms", 2)):
        dt_on = min(sync_ingest_once(True, delay) for _ in range(2))
        dt_off = min(sync_ingest_once(False, delay) for _ in range(2))
        ratios[label] = dt_off / dt_on
    configure_group_commit(enabled=True)
    assert ratios["fsync2ms"] > 1.5, (
        f"group commit only {ratios['fsync2ms']:.2f}x on modeled-fsync "
        f"concurrent ingest — the shared fsync is not being shared")

    # ---- (c) 1000-logical-client mixed workload over a persisted
    # region: sustained QPS and p50/p95/p99 ----
    home = tempfile.mkdtemp(prefix="bench-qps-")
    try:
        dn = DatanodeInstance(DatanodeOptions(
            data_home=home, register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        fe.do_query("CREATE TABLE qps (host STRING, ts TIMESTAMP "
                    "TIME INDEX, v DOUBLE, PRIMARY KEY(host))")
        table = fe.catalog.table("greptime", "public", "qps")
        hosts = 64
        per_host = 512
        host_col = np.repeat(
            np.array([f"h{i}" for i in range(hosts)]), per_host
        ).astype(object)
        ts_col = np.tile(
            np.arange(per_host, dtype=np.int64) * 1000, hosts)
        table.bulk_load({"host": host_col, "ts": ts_col,
                         "v": np.random.default_rng(7).random(
                             hosts * per_host)})
        table.flush()                          # persisted region

        ops_per_client = 4                     # 3 point scans + 1 burst
        latencies = []
        lat_lock = threading.Lock()
        work: "Queue[int]" = Queue()
        for c in range(n_clients):
            work.put(c)
        errs = []

        def client_ops(c: int):
            ctx = QueryContext()
            local = []
            for k in range(ops_per_client):
                t0 = time.perf_counter()
                if k < 3:
                    fe.do_query(
                        f"SELECT v FROM qps WHERE host = "
                        f"'h{(c * 7 + k) % hosts}' LIMIT 5")
                else:
                    COALESCER.ingest(
                        fe, "qps_rw",
                        {"ts": [int(time.time() * 1000) + c],
                         "host": [f"h{c % hosts}"],
                         "v": [float(c)]},
                        tag_columns=("host",), timestamp_column="ts",
                        ctx=ctx)
                local.append(time.perf_counter() - t0)
            with lat_lock:
                latencies.extend(local)

        def worker():
            while True:
                try:
                    c = work.get_nowait()
                except Exception:  # noqa: BLE001 — queue drained
                    return
                try:
                    client_ops(c)
                except Exception as e:  # noqa: BLE001 — assert below
                    errs.append(e)

        n_workers = 32
        threads = [threading.Thread(target=worker)
                   for _ in range(n_workers)]
        t0 = time.perf_counter()
        [t.start() for t in threads]
        [t.join() for t in threads]
        wall = time.perf_counter() - t0
        assert not errs, errs[:3]
        assert len(latencies) == n_clients * ops_per_client
        lat_ms = np.sort(np.array(latencies)) * 1e3
        qps = len(latencies) / wall
        p50, p95, p99 = (float(np.percentile(lat_ms, p))
                         for p in (50, 95, 99))
        fe.shutdown()
    finally:
        shutil.rmtree(home, ignore_errors=True)
    return qps, p50, p95, p99, ratios, append_ns


def bench_lock_overhead():
    """Sixth driver metric (ISSUE 7): the lock-order detector's
    inactive-mode cost, same methodology as the failpoint ~190ns/call
    assertion. The TrackedLock factory must hand back a PLAIN
    threading.Lock when the detector is off — production acquires pay
    literally zero extra — so the differential against threading.Lock
    is asserted structurally (identical type) AND by wall clock."""
    import threading
    import timeit

    from greptimedb_tpu.common import locks

    # bench.py never imports pytest, so auto-detection leaves the
    # detector off unless the operator forced it via env
    assert not locks.enabled(), (
        "detector unexpectedly ON in bench (GREPTIME_LOCK_CHECK set, or "
        "pytest leaked into the process) — inactive-mode numbers would "
        "be meaningless")
    tracked = locks.TrackedLock("bench.lock")
    raw = threading.Lock()
    assert type(tracked) is type(raw), (
        "inactive TrackedLock must BE threading.Lock, not a wrapper")

    n = 1_000_000

    def cycle(lk):
        def run():
            lk.acquire()
            lk.release()
        return run

    # interleave best-of-3 so shared-box drift lands on both sides
    t_tracked = t_raw = float("inf")
    for _ in range(3):
        t_tracked = min(t_tracked, timeit.timeit(cycle(tracked), number=n))
        t_raw = min(t_raw, timeit.timeit(cycle(raw), number=n))
    ns_tracked = t_tracked / n * 1e9
    ns_raw = t_raw / n * 1e9
    ratio = t_raw / t_tracked            # 1.0 = zero overhead
    # same objects, same type: anything past noise means the factory
    # started wrapping inactive locks
    assert ratio >= 0.7, (
        f"inactive TrackedLock cost {1/ratio:.2f}x a raw threading.Lock "
        f"({ns_tracked:.1f}ns vs {ns_raw:.1f}ns per acquire/release)")

    # active-mode cost, for the record (what tests pay, never production)
    forced = locks.TrackedLock("bench.lock_active", force=True)
    t_active = timeit.timeit(cycle(forced), number=n // 10)
    ns_active = t_active / (n // 10) * 1e9
    return ns_tracked, ns_raw, ratio, ns_active


def bench_greptsan_inactive_overhead():
    """ISSUE 10: greptsan's off-mode cost, held to the same bar as
    tracked_lock_inactive_overhead. tracked_state() is a FACTORY that
    returns its argument unchanged when the race detector is off, so
    the wrapped dict IS a plain dict — the identity assert below is the
    real regression detector (any wrapping in off mode fails it first),
    while the timed get/set/contains cycle on a region-map-shaped dict
    (same object on both sides, by construction) publishes the noise
    floor the <1.1x acceptance bar is read against — the
    bench_lock_overhead methodology exactly."""
    import timeit

    from greptimedb_tpu.devtools import greptsan

    assert not greptsan.enabled(), (
        "race detector unexpectedly ON in bench (GREPTIME_RACE_CHECK "
        "set, or pytest leaked in) — inactive numbers would be "
        "meaningless")
    raw = {f"region_{i}": i for i in range(64)}
    wrapped = greptsan.tracked_state(raw, "bench.regions")
    assert wrapped is raw and type(wrapped) is dict, (
        "inactive tracked_state must return its argument unchanged")

    n = 1_000_000

    def cycle(d):
        def run():
            d["region_7"] = 7
            d.get("region_9")
            "region_11" in d
        return run

    t_wrapped = t_raw = float("inf")
    for _ in range(3):       # interleave best-of-3: drift lands on both
        t_wrapped = min(t_wrapped, timeit.timeit(cycle(wrapped),
                                                 number=n))
        t_raw = min(t_raw, timeit.timeit(cycle(raw), number=n))
    ns_wrapped = t_wrapped / n * 1e9
    ns_raw = t_raw / n * 1e9
    ratio = t_wrapped / t_raw            # 1.0 = zero overhead
    # same noise tolerance as bench_lock_overhead's inactive ratio
    # (its >=0.7 bar): the identity assert above already catches any
    # real off-mode wrapping, so the timing bound only needs to reject
    # gross regressions, not flake on shared-box drift. The published
    # inactive_ratio is what the <1.1x acceptance reading uses.
    assert ratio <= 1 / 0.7, (
        f"inactive tracked_state cost {ratio:.2f}x a raw dict "
        f"({ns_wrapped:.1f}ns vs {ns_raw:.1f}ns per cycle) — beyond "
        f"even shared-box noise for what must be the SAME object")

    # active-mode cost for the record (tests only): per-access record +
    # vector-clock race check on a tracked dict
    import subprocess
    import sys
    code = (
        "import timeit\n"
        "from greptimedb_tpu.devtools import greptsan\n"
        "assert greptsan.enabled()\n"
        "d = greptsan.tracked_state({'k': 1}, 'bench.active')\n"
        "t = timeit.timeit(lambda: d.get('k'), number=100000)\n"
        "print(t / 100000 * 1e9)\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=dict(os.environ, GREPTIME_RACE_CHECK="1",
                              JAX_PLATFORMS="cpu"))
    ns_active = float(proc.stdout.strip()) if proc.returncode == 0 \
        else float("nan")
    return ns_wrapped, ns_raw, ratio, ns_active


def bench_dist_scatter(n_rows: int):
    """Fifth driver metric (ISSUE 5): multi-datanode group-by through the
    distributed frontend. 4 in-process datanodes host an 8-region
    hash-partitioned table; the timed query is a full-table GROUP BY
    (hostname) avg, cold (scan cache cleared per iteration, so each
    datanode pays SST decode + merge + reduce). Two differentials
    against SET dist_fanout = 1 (the pre-PR serial fan-out):

    - ``vs_serial`` — cold, same-process, compute-bound run. On a box
      with fewer cores than datanodes this approaches 1.0 (the serial
      path already saturates the cores through XLA/numpy intra-op
      threads); it expresses the parallel win only when
      cores >= datanodes.
    - ``vs_serial_warm_10ms_rpc`` — the warm dashboard shape: scan
      caches hot, and each datanode RPC carries a modeled 10ms
      network+queueing latency (dist_rpc failpoint, action delay(10) —
      what every real multi-host hop pays). Serial sums the four hops,
      the scatter overlaps them; this is the hardware-independent
      measure of the fan-out mechanism itself.

    Also probes the acceptance criterion: a tag-point query must report
    `regions pruned 7/8` in its dispatch."""
    import shutil
    import tempfile

    from greptimedb_tpu.common import failpoint

    from greptimedb_tpu.client import LocalDatanodeClient
    from greptimedb_tpu.common.runtime import (configure_dist_fanout,
                                               dist_fanout)
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.distributed import DistInstance
    from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
    from greptimedb_tpu.query import tpu_exec
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-dist-")
    datanodes = {}
    saved_fanout = dist_fanout()
    try:
        srv = MetaSrv(MemKv())
        meta = MetaClient(srv)
        clients = {}
        for i in range(1, 5):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{tmpdir}/dn{i}", node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        ctx = QueryContext()
        fe.do_query(
            "CREATE TABLE cpu (hostname STRING, ts TIMESTAMP TIME INDEX, "
            "usage_user DOUBLE, PRIMARY KEY(hostname)) "
            "PARTITION BY HASH (hostname) PARTITIONS 8", ctx)
        table = fe.catalog.table("greptime", "public", "cpu")
        rng = np.random.default_rng(7)
        hosts = 256
        per = n_rows // hosts
        ts = np.tile(np.arange(per, dtype=np.int64) * 10_000, hosts)
        host = np.repeat(
            np.array([f"host_{i}" for i in range(hosts)]),
            per).astype(object)
        table.bulk_load({"hostname": host, "ts": ts,
                         "usage_user": rng.random(len(ts)) * 100})
        table.flush()
        n = hosts * per
        sql = ("SELECT hostname, avg(usage_user) FROM cpu "
               "GROUP BY hostname")
        fe.do_query(sql, ctx)              # absorb one-time costs

        def timed(cold: bool, iters: int = 2, node_ms_out: dict = None):
            dt = float("inf")
            for _ in range(iters):         # best of N: noisy shared hosts
                if cold:
                    tpu_exec.SCAN_CACHE._entries.clear()
                t0 = time.perf_counter()
                fe.do_query(sql, ctx)
                it = time.perf_counter() - t0
                if it < dt and node_ms_out is not None:
                    # snapshot the vector of the BEST iteration, so the
                    # emitted per-node breakdown profiles the same run
                    # as the throughput published next to it
                    node_ms_out.clear()
                    node_ms_out.update(table.last_scatter_node_ms)
                dt = min(dt, it)
            return dt

        configure_dist_fanout(8)
        # per-node latency vector of the winning parallel scatter (ISSUE
        # 6: the per-node timings the old slowest_node_ms max discarded)
        from greptimedb_tpu.common.exec_stats import node_sort_key
        best_node_ms: dict = {}
        dt_parallel = timed(cold=True, node_ms_out=best_node_ms)
        node_ms = {k: round(best_node_ms[k], 2)
                   for k in sorted(best_node_ms, key=node_sort_key)}
        configure_dist_fanout(1)           # the pre-PR serial scatter
        dt_serial = timed(cold=True)

        # warm + modeled per-RPC network latency: the hop cost every
        # real multi-host hop pays, which the scatter exists to overlap
        fe.do_query(sql, ctx)              # heat every region's cache
        failpoint.configure("dist_rpc", "delay(10)")
        try:
            configure_dist_fanout(8)
            dt_par_net = timed(cold=False, iters=3)
            configure_dist_fanout(1)
            dt_ser_net = timed(cold=False, iters=3)
        finally:
            failpoint.configure("dist_rpc", None)
        configure_dist_fanout(8)

        fe.do_query("SELECT hostname, avg(usage_user) FROM cpu "
                    "WHERE hostname = 'host_7' GROUP BY hostname", ctx)
        dispatch = fe.query_engine.last_exec_stats.dispatch
        assert "regions pruned 7/8" in dispatch, dispatch
        return (n / dt_parallel, dt_serial / dt_parallel,
                dt_ser_net / dt_par_net, node_ms)
    finally:
        configure_dist_fanout(saved_fanout)
        for dn in datanodes.values():
            dn.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def _record_batches_bytes(batches):
    """Bytes a raw-row scatter ships: column buffers (+ validity), with
    object/string columns measured by their encoded text lengths."""
    total = 0
    for b in batches:
        for v in b.columns:
            data = getattr(v, "data", None)
            if data is None:
                continue
            if getattr(data, "dtype", None) is not None and \
                    data.dtype == object:
                total += int(sum(len(str(x)) for x in data
                                 if x is not None))
            else:
                total += int(getattr(data, "nbytes", 0) or 0)
            validity = getattr(v, "validity", None)
            if validity is not None:
                total += int(getattr(validity, "nbytes", 0) or 0)
    return total


def bench_dist_partial_agg(n_rows: int):
    """Seventh driver metric (ISSUE 14): distributed GROUP BY through
    the sketch partial pushdown. 4 in-process datanodes host an
    8-region hash table; the timed query is the TSBS-ish wide shape —
    GROUP BY tag with count / count(DISTINCT) / approx_percentile(95)
    — which before this PR fell back to pulling RAW ROWS from every
    region. Differential: `SET dist_partial_agg = 0` (the raw-row
    fallback). Published: rows/s through the pushdown, the speedup vs
    raw, and the wire-byte comparison — partial frames actually folded
    (ExecStats partial_bytes) vs the bytes a raw scatter ships
    (projected scan batches) — asserted >= 3x smaller."""
    import shutil
    import tempfile

    from greptimedb_tpu.client import LocalDatanodeClient
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.distributed import DistInstance
    from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-distagg-")
    datanodes = {}
    try:
        srv = MetaSrv(MemKv())
        meta = MetaClient(srv)
        clients = {}
        for i in range(1, 5):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{tmpdir}/dn{i}", node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        ctx = QueryContext()
        fe.do_query(
            "CREATE TABLE cpu (hostname STRING, ts TIMESTAMP TIME INDEX, "
            "usage_user DOUBLE, uid BIGINT, PRIMARY KEY(hostname)) "
            "PARTITION BY HASH (hostname) PARTITIONS 8", ctx)
        table = fe.catalog.table("greptime", "public", "cpu")
        rng = np.random.default_rng(11)
        hosts = 256
        per = n_rows // hosts
        ts = np.tile(np.arange(per, dtype=np.int64) * 10_000, hosts)
        host = np.repeat(
            np.array([f"host_{i}" for i in range(hosts)]),
            per).astype(object)
        # uid: ~2000 revisiting users — the classic "distinct users per
        # host" cardinality shape count(DISTINCT) exists for
        table.bulk_load({"hostname": host, "ts": ts,
                         "usage_user": rng.random(len(ts)) * 100,
                         "uid": rng.integers(0, 2000, len(ts))})
        table.flush()
        n = hosts * per
        sql = ("SELECT hostname, count(usage_user) AS c, "
               "count(DISTINCT uid) AS cd, "
               "approx_percentile(usage_user, 95) AS p95 "
               "FROM cpu GROUP BY hostname")

        def timed(iters=2):
            dt = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                fe.do_query(sql, ctx)
                dt = min(dt, time.perf_counter() - t0)
            return dt

        fe.do_query(sql, ctx)              # warm caches + compiles
        dt_partial = timed()
        stats = fe.query_engine.last_exec_stats
        assert "aggregate-pushdown" in (stats.dispatch or ""), \
            stats.dispatch
        partial_bytes = stats.totals()["partial_bytes"]
        assert partial_bytes > 0

        # the raw-row differential: what the pre-PR fallback shipped
        raw_bytes = _record_batches_bytes(table.scan_batches(
            projection=["hostname", "ts", "usage_user", "uid"]))
        fe.do_query("SET dist_partial_agg = 0", ctx)
        try:
            fe.do_query(sql, ctx)
            dt_raw = timed()
        finally:
            fe.do_query("SET dist_partial_agg = 1", ctx)
        reduction = raw_bytes / max(partial_bytes, 1)
        assert reduction >= 3.0, (raw_bytes, partial_bytes, reduction)
        return (n / dt_partial, dt_raw / dt_partial, partial_bytes,
                raw_bytes, reduction)
    finally:
        for dn in datanodes.values():
            dn.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def emit_dist_partial_agg():
    n_rows = int(os.environ.get("GREPTIME_BENCH_DISTAGG_ROWS", 2_000_000))
    rps, vs_raw, partial_b, raw_b, reduction = \
        bench_dist_partial_agg(n_rows)
    print(json.dumps({
        "metric": "dist_partial_agg_throughput",
        "value": round(rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_raw_pull": round(vs_raw, 2),
        "partial_wire_bytes": int(partial_b),
        "raw_wire_bytes": int(raw_b),
        "wire_byte_reduction": round(reduction, 1),
        "rows": n_rows,
        "datanodes": 4,
    }))


def bench_promql_dist_range(n_rows: int):
    """Eleventh driver metric (ISSUE 16): a distributed PromQL range
    query through the plan-IR pushdown. 4 in-process datanodes host an
    8-region hash table; the timed query is the canonical dashboard
    shape — `sum by (hostname) (rate(cpu[1m]))` over the whole span —
    which before this PR pulled RAW SAMPLES from every region to the
    frontend row path. Now it lowers onto the same TpuPlan SQL ships:
    datanodes fold regions into per-(series, bucket) moment frames,
    only frames cross the wire, the frontend reconstructs rate and
    folds by hostname. Differential: `SET dist_partial_agg = 0` (the
    raw-pull row path). Published: rows/s through the IR, the speedup
    vs raw-pull (>= 3x asserted), and the wire-byte comparison —
    moment frames folded (ExecStats partial_bytes) vs the bytes a raw
    scatter ships."""
    import shutil
    import tempfile

    from greptimedb_tpu.client import LocalDatanodeClient
    from greptimedb_tpu.common import exec_stats
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.distributed import DistInstance
    from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-promql-")
    datanodes = {}
    try:
        srv = MetaSrv(MemKv())
        meta = MetaClient(srv)
        clients = {}
        for i in range(1, 5):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{tmpdir}/dn{i}", node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        ctx = QueryContext()
        fe.do_query(
            "CREATE TABLE cpu (hostname STRING, ts TIMESTAMP TIME INDEX, "
            "usage_user DOUBLE, PRIMARY KEY(hostname)) "
            "PARTITION BY HASH (hostname) PARTITIONS 8", ctx)
        table = fe.catalog.table("greptime", "public", "cpu")
        rng = np.random.default_rng(11)
        hosts = 256
        per = n_rows // hosts
        ts = np.tile(np.arange(per, dtype=np.int64) * 10_000, hosts)
        host = np.repeat(
            np.array([f"host_{i}" for i in range(hosts)]),
            per).astype(object)
        # a counter: monotone per series, the shape rate() exists for
        vals = np.tile(np.cumsum(rng.random(per) * 5.0), hosts)
        table.bulk_load({"hostname": host, "ts": ts, "usage_user": vals})
        table.flush()
        n = hosts * per
        end_s = (per - 1) * 10
        tql = (f"TQL EVAL (0, {end_s}, '60s') "
               "sum by (hostname) (rate(cpu[1m]))")

        def timed(iters=2):
            dt = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                fe.do_query(tql, ctx)
                dt = min(dt, time.perf_counter() - t0)
            return dt

        fe.do_query(tql, ctx)              # warm caches + compiles
        stats = exec_stats.ExecStats()
        with exec_stats.collect(stats):
            fe.do_query(tql, ctx)
        partial_bytes = stats.totals()["partial_bytes"]
        assert partial_bytes > 0, "PromQL did not ride the IR pushdown"
        dt_ir = timed()

        # the raw-pull differential: what the pre-PR row path shipped
        raw_bytes = _record_batches_bytes(table.scan_batches(
            projection=["hostname", "ts", "usage_user"]))
        fe.do_query("SET dist_partial_agg = 0", ctx)
        try:
            fe.do_query(tql, ctx)
            dt_raw = timed()
        finally:
            fe.do_query("SET dist_partial_agg = 1", ctx)
        speedup = dt_raw / dt_ir
        assert speedup >= 3.0, (dt_ir, dt_raw, speedup)
        wire_reduction = raw_bytes / max(partial_bytes, 1)
        return (n / dt_ir, speedup, partial_bytes, raw_bytes,
                wire_reduction)
    finally:
        for dn in datanodes.values():
            dn.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def emit_promql_dist_range():
    n_rows = int(os.environ.get("GREPTIME_BENCH_PROMQL_ROWS", 2_000_000))
    rps, vs_raw, partial_b, raw_b, reduction = \
        bench_promql_dist_range(n_rows)
    print(json.dumps({
        "metric": "promql_dist_range_query_throughput",
        "value": round(rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_raw_pull": round(vs_raw, 2),
        "partial_wire_bytes": int(partial_b),
        "raw_wire_bytes": int(raw_b),
        "wire_byte_reduction": round(reduction, 1),
        "rows": n_rows,
        "datanodes": 4,
    }))


def bench_region_migration_availability(n_rows: int):
    """Sixth driver metric (ISSUE 9): migrate a region between datanodes
    UNDER sustained single-row ingest and measure availability:

    - ``handoff_window_ms`` — the fenced window (WAL-tail capture →
      route commit, from the op doc's state timestamps): the ONLY span
      in which writes to the migrating region stall.
    - ``max_write_stall_ms`` — the worst user-visible insert latency
      during the whole migration (the stale-route retry riding over the
      fence; every other insert proceeds at normal speed).
    - ``lost_rows`` / ``dup_rows`` — acked-write continuity: every row
      the ingest thread got an ack for is readable EXACTLY once after
      the handoff (asserted zero/zero, then published).

    2 in-process datanodes over one SHARED object store (the elastic
    deployment shape); the balancer + heartbeats run in a background
    pump thread at production-like cadence while the foreground ingests.
    """
    import shutil
    import tempfile
    import threading

    from greptimedb_tpu.client import LocalDatanodeClient
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.distributed import DistInstance
    from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.storage.object_store import FsObjectStore

    tmpdir = tempfile.mkdtemp(prefix="bench-migrate-")
    datanodes = {}
    try:
        shared = FsObjectStore(f"{tmpdir}/shared")
        srv = MetaSrv(MemKv())
        srv.balancer.resend_interval_s = 0.05
        meta = MetaClient(srv)
        clients = {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{tmpdir}/dn{i}", node_id=i,
                register_numbers_table=False), store=shared)
            dn.start()
            dn.attach_meta(meta)
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        ctx = QueryContext()
        fe.do_query(
            "CREATE TABLE mig (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host)) "
            "PARTITION BY RANGE COLUMNS (host) ("
            "  PARTITION r0 VALUES LESS THAN ('m'),"
            "  PARTITION r1 VALUES LESS THAN (MAXVALUE))", ctx)
        table = fe.catalog.table("greptime", "public", "mig")
        # preload the region that will move (host 'a' < 'm' → region 0)
        ts0 = np.arange(n_rows, dtype=np.int64) * 1000
        table.bulk_load({
            "host": np.array(["a"] * n_rows, dtype=object), "ts": ts0,
            "v": np.random.default_rng(3).random(n_rows)})
        table.flush()
        route = srv.table_route("greptime.public.mig")
        src = next(rr.leader.id for rr in route.region_routes
                   if rr.region_number == 0)
        dst = 2 if src == 1 else 1

        stop = threading.Event()

        def pump():
            while not stop.is_set():
                srv.balancer.tick()
                for i, dn in datanodes.items():
                    resp = srv.handle_heartbeat(i)
                    for msg in resp.mailbox:
                        dn._handle_mailbox(msg)
                time.sleep(0.02)

        acked = []
        stalls = []
        ingest_stop = threading.Event()

        def ingest():
            n = 0
            while not ingest_stop.is_set():
                n += 1
                key_ts = 10_000_000 + n
                t0 = time.perf_counter()
                try:
                    fe.do_query(
                        f"INSERT INTO mig VALUES ('a', {key_ts}, 1.0)",
                        ctx)
                except Exception:  # noqa: BLE001 — an unacked write
                    continue       # during the fault is legal
                stalls.append((time.perf_counter() - t0) * 1e3)
                acked.append(key_ts)

        pump_t = threading.Thread(target=pump, daemon=True)
        ingest_t = threading.Thread(target=ingest, daemon=True)
        pump_t.start()
        ingest_t.start()
        time.sleep(0.3)                       # steady-state ingest
        fe.do_query(f"ADMIN MIGRATE REGION mig 0 TO {dst}", ctx)
        t0 = time.time()
        while srv.balancer.ops() and time.time() - t0 < 120:
            time.sleep(0.05)
        time.sleep(0.3)                       # post-handoff ingest
        ingest_stop.set()
        ingest_t.join(timeout=60)
        stop.set()
        pump_t.join(timeout=10)

        done = srv.balancer.done_ops()[-1]
        assert done["state"] == "done", done
        times = done.get("times", {})
        handoff_ms = max(0, times.get("release", 0) -
                         times.get("open", 0))
        # continuity: every acked row readable exactly once
        out = fe.do_query(
            "SELECT ts FROM mig WHERE ts >= 10000000", ctx)[-1]
        got = [r[0] for b in out.batches for r in b.rows()]
        lost = len(set(acked) - set(got))
        dup = len(got) - len(set(got))
        assert lost == 0, f"lost {lost} acked rows"
        assert dup == 0, f"{dup} duplicated rows"
        new_owner = next(
            rr.leader.id for rr in
            srv.table_route("greptime.public.mig").region_routes
            if rr.region_number == 0)
        assert new_owner == dst
        return (handoff_ms, max(stalls) if stalls else 0.0, len(acked),
                lost, dup)
    finally:
        for dn in datanodes.values():
            dn.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def bench_replicated_read_qps(n_rows: int = 100_000):
    """Eighth driver metric (ISSUE 19): read-QPS scaling across region
    read replicas, plus failover quality numbers:

    - ``qps_{1,2,3}_replicas`` — SET read_replica = 'follower' point
      reads against the same region served by 1 (leader only), 2 and 3
      replicas; the rotating least-assigned pool spreads the load.
    - ``promotion_handoff_ms`` — kill -9 twin of the leader under
      sustained fsync-acked ingest → time until a write acks through
      the promoted follower (lease loss + salvage + route commit).
    - ``acked_lost_rows`` / ``dup_rows`` — every row acked before or
      after the fault is readable exactly once (asserted zero/zero,
      then published).

    3 in-process datanodes over one SHARED object store AND one shared
    data_home (node-scoped WAL dirs) — the deployment shape where
    promotion can salvage the dead leader's fsynced WAL tail.
    """
    import shutil
    import tempfile
    import threading

    from greptimedb_tpu.client import LocalDatanodeClient
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.distributed import (DistInstance,
                                                     configure_read_replica)
    from greptimedb_tpu.meta import (DatanodeStat, MemKv, MetaClient,
                                     MetaSrv, Peer)
    from greptimedb_tpu.query.stream_exec import region_stat_entries
    from greptimedb_tpu.session import QueryContext
    from greptimedb_tpu.storage.object_store import FsObjectStore

    tmpdir = tempfile.mkdtemp(prefix="bench-replica-")
    datanodes = {}
    stop = threading.Event()
    pump_t = None
    try:
        shared = FsObjectStore(f"{tmpdir}/shared")
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600.0)
        srv.balancer.resend_interval_s = 0.05
        meta = MetaClient(srv)
        clients = {}
        for i in (1, 2, 3):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{tmpdir}/home", node_id=i,
                wal_sync_on_write=True,
                register_numbers_table=False), store=shared)
            dn.start()
            dn.attach_meta(meta)
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        ctx = QueryContext()
        fe.do_query(
            "CREATE TABLE rr (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))", ctx)
        table = fe.catalog.table("greptime", "public", "rr")
        table.bulk_load({
            "host": np.array([f"h{i % 64}" for i in range(n_rows)],
                             dtype=object),
            "ts": np.arange(n_rows, dtype=np.int64) * 1000,
            "v": np.random.default_rng(7).random(n_rows)})
        table.flush()
        route = srv.table_route("greptime.public.rr")
        leader = next(rr.leader.id for rr in route.region_routes
                      if rr.region_number == 0)
        followers = [i for i in (1, 2, 3) if i != leader]

        dead = set()

        def pump():
            # production cadence stand-in: balancer ticks + full
            # stat-bearing heartbeats (they carry replicated_seq, the
            # lag gate behind replica read eligibility) + failover scan
            while not stop.is_set():
                try:
                    srv.balancer.tick()
                    srv.failover_check()
                    for i, dn in list(datanodes.items()):
                        if i in dead:
                            continue       # kill -9 twin: silence
                        regions = dn.storage.list_regions()
                        entries, rows_, nb = region_stat_entries(
                            regions.values())
                        resp = srv.handle_heartbeat(i, DatanodeStat(
                            region_count=len(regions),
                            approximate_rows=rows_,
                            approximate_bytes=nb,
                            region_stats=entries))
                        for msg in resp.mailbox:
                            dn._handle_mailbox(msg)
                except Exception:  # noqa: BLE001 — a mid-fault pump
                    pass           # round retries on the next tick
                time.sleep(0.02)

        pump_t = threading.Thread(target=pump, daemon=True)
        pump_t.start()

        def wait_replica(target):
            deadline = time.time() + 60
            while time.time() < deadline:
                caught = any(
                    r.get("table_name") == "greptime.public.rr" and
                    r.get("peer_id") == target and
                    r.get("is_leader") == "No" and
                    r.get("status") == "ALIVE" and
                    r.get("lag_ms") is not None
                    for r in srv.region_peers())
                if caught and not srv.balancer.ops():
                    return
                time.sleep(0.02)
            raise AssertionError(f"replica on dn{target} never caught up")

        configure_read_replica(mode="follower", max_lag_ms=60_000)

        def measure_qps(seconds=1.2, threads=4):
            counts = [0] * threads
            t_end = time.perf_counter() + seconds

            def worker(k):
                rng = np.random.default_rng(k)
                while time.perf_counter() < t_end:
                    h = int(rng.integers(0, 64))
                    fe.do_query(
                        f"SELECT count(*) FROM rr WHERE host = 'h{h}'",
                        ctx)
                    counts[k] += 1

            ws = [threading.Thread(target=worker, args=(k,))
                  for k in range(threads)]
            for w in ws:
                w.start()
            for w in ws:
                w.join()
            return sum(counts) / seconds

        qps = {1: measure_qps()}                  # leader only
        fe.do_query(f"ADMIN ADD REPLICA rr 0 TO {followers[0]}", ctx)
        wait_replica(followers[0])
        qps[2] = measure_qps()
        fe.do_query(f"ADMIN ADD REPLICA rr 0 TO {followers[1]}", ctx)
        wait_replica(followers[1])
        qps[3] = measure_qps()

        # --- promotion handoff under sustained fsync-acked ingest ---
        acked = []
        ingest_stop = threading.Event()

        def ingest():
            n = 0
            while not ingest_stop.is_set():
                n += 1
                key_ts = 10_000_000 + n
                try:
                    fe.do_query(
                        f"INSERT INTO rr VALUES ('w', {key_ts}, 1.0)",
                        ctx)
                except Exception:  # noqa: BLE001 — an unacked write
                    continue       # during the fault is legal
                acked.append((key_ts, time.perf_counter()))

        ingest_t = threading.Thread(target=ingest, daemon=True)
        ingest_t.start()
        time.sleep(0.3)                           # steady-state ingest
        t_kill = time.perf_counter()
        dn = datanodes[leader]
        for region in dn.storage.list_regions().values():
            with region._writer_lock:              # kill -9 twin: stop
                region.closed = True               # answering mid-state
                region.wal.close()
        dead.add(leader)
        srv._last_seen[leader] = 0.0               # lease lost
        t0 = time.time()
        while time.time() - t0 < 60:
            rt = srv.table_route("greptime.public.rr")
            lid = next(r.leader.id for r in rt.region_routes
                       if r.region_number == 0)
            if lid != leader:
                break
            time.sleep(0.005)
        else:
            raise AssertionError("promotion never committed")
        t_flip = time.perf_counter()
        # first ack THROUGH the promoted follower bounds the handoff
        # (acks before the route flip were in-flight writes the kill
        # loop let drain under the writer lock — not handoff evidence)
        t0 = time.time()
        while time.time() - t0 < 60:
            if any(t > t_flip for _, t in acked):
                break
            time.sleep(0.005)
        first_ack = min(t for _, t in acked if t > t_flip)
        handoff_ms = (first_ack - t_kill) * 1e3
        time.sleep(0.3)                           # post-handoff ingest
        ingest_stop.set()
        ingest_t.join(timeout=60)

        # continuity: every acked row readable exactly once
        configure_read_replica(mode="leader")
        out = fe.do_query(
            "SELECT ts FROM rr WHERE ts >= 10000000", ctx)[-1]
        got = [r[0] for b in out.batches for r in b.rows()]
        lost = len({k for k, _ in acked} - set(got))
        dup = len(got) - len(set(got))
        assert lost == 0, f"lost {lost} acked rows"
        assert dup == 0, f"{dup} duplicated rows"
        return (qps[1], qps[2], qps[3], handoff_ms, len(acked), lost,
                dup)
    finally:
        stop.set()
        if pump_t is not None:
            pump_t.join(timeout=10)
        configure_read_replica(mode="leader", max_lag_ms=5000)
        for dn in datanodes.values():
            try:
                dn.shutdown()
            except Exception:  # noqa: BLE001 — the killed twin's WAL is
                pass           # already closed
        shutil.rmtree(tmpdir, ignore_errors=True)


def emit_replicated_read_qps():
    q1, q2, q3, handoff_ms, acked_n, lost, dup = \
        bench_replicated_read_qps()
    print(json.dumps({
        "metric": "replicated_read_qps",
        "value": round(q3, 1),
        "unit": "qps_at_3_replicas",
        "qps_1_replica": round(q1, 1),
        "qps_2_replicas": round(q2, 1),
        "qps_3_replicas": round(q3, 1),
        "promotion_handoff_ms": round(handoff_ms, 1),
        "acked_writes_during_failover": acked_n,
        "acked_lost_rows": lost,
        "dup_rows": dup,
    }))


def bench_index_point_query(n_series: int = 100_000, files: int = 16):
    """Seventh driver metric (ISSUE 13): high-cardinality point-query
    throughput against a persisted many-SST region, with the per-SST
    secondary index on vs off (`SET sst_index = 0`).

    Layout is the shape the index exists for: the series dictionary is
    primed once (so sids are host-ordered), then each of `files` bulk
    batches carries a SCATTERED 1/files-th of the series — every SST's
    coarse sid_range spans nearly the whole keyspace (stats-only file
    pruning keeps everything) while its bloom holds only its own sids
    (index pruning drops ~(files-1)/files of the files). Point + IN(8)
    queries alternate; the scan cache is cleared per query on both sides
    so the differential measures the cold read path, not cache warmth.

    Asserts: answers identical on/off (zero drift), differential >= 3x,
    and `files pruned by index` visible in the EXPLAIN ANALYZE profile
    (index_files_pruned / index_files_checked on the prune stage)."""
    import shutil
    import tempfile

    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.query import tpu_exec
    from greptimedb_tpu.session import QueryContext

    tmpdir = tempfile.mkdtemp(prefix="bench-index-")
    fe = None
    rows_per = 16
    try:
        dn = DatanodeInstance(DatanodeOptions(
            data_home=tmpdir, register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        ctx = QueryContext()
        fe.do_query("CREATE TABLE idx (host STRING, ts TIMESTAMP "
                    "TIME INDEX, v DOUBLE, PRIMARY KEY(host))")
        table = fe.catalog.table("greptime", "public", "idx")
        for region in table.regions.values():
            # keep the scattered L0 layout: auto-compaction would merge
            # the batches into per-window files that genuinely contain
            # every series (nothing left for any index to prune)
            region.max_l0_files = 1 << 30
        rng = np.random.default_rng(17)
        hosts_all = np.array([f"h{i:06d}" for i in range(n_series)],
                             dtype=object)
        # values are dyadic rationals (multiples of 1/8, < 512): exactly
        # representable in BOTH float64 and the index-off resident
        # path's f32 device mirrors, so the zero-drift assertion below
        # compares semantics, not float rounding regimes
        def vals(n: int) -> np.ndarray:
            return rng.integers(0, 4096, n).astype(np.float64) / 8.0

        # prime the dictionary in host order: one row per series
        table.bulk_load({"host": hosts_all,
                         "ts": np.zeros(n_series, dtype=np.int64),
                         "v": vals(n_series)})
        total = n_series
        for k in range(files):
            sel = hosts_all[k::files]
            host_col = np.repeat(sel, rows_per)
            ts_col = np.tile(
                (np.arange(rows_per, dtype=np.int64) + 1) * 1000 + k,
                len(sel))
            table.bulk_load({"host": host_col, "ts": ts_col,
                             "v": vals(len(host_col))})
            total += len(host_col)
        n_ssts = sum(len(r.version_control.current.ssts.all_files())
                     for r in table.regions.values())
        assert n_ssts >= files, f"expected >= {files} SSTs, got {n_ssts}"
        fe.do_query("SET tpu_dispatch_min_rows = 131072", ctx)

        def point_sql(i: int) -> str:
            return (f"SELECT host, max(v), count(v) FROM idx WHERE "
                    f"host = '{hosts_all[i % n_series]}' GROUP BY host")

        def in8_sql(i: int) -> str:
            picks = ", ".join(
                f"'{hosts_all[(i * 131 + j * 977) % n_series]}'"
                for j in range(8))
            return (f"SELECT host, avg(v) FROM idx WHERE host IN "
                    f"({picks}) GROUP BY host ORDER BY host")

        def run(sql: str):
            out = fe.do_query(sql, ctx)[-1]
            return sorted(tuple(r) for b in out.batches
                          for r in b.rows())

        def timed(iters: int) -> float:
            t0 = time.perf_counter()
            for i in range(iters):
                tpu_exec.SCAN_CACHE._entries.clear()
                run(point_sql(i * 7919))
                tpu_exec.SCAN_CACHE._entries.clear()
                run(in8_sql(i))
            return (time.perf_counter() - t0) / (2 * iters)

        # zero answer drift on vs off, for both shapes
        for sql in (point_sql(42), in8_sql(3)):
            tpu_exec.SCAN_CACHE._entries.clear()
            on_rows = run(sql)
            fe.do_query("SET sst_index = 0", ctx)
            tpu_exec.SCAN_CACHE._entries.clear()
            off_rows = run(sql)
            fe.do_query("SET sst_index = 1", ctx)
            assert on_rows == off_rows, sql

        timed(1)                               # absorb one-time costs
        dt_on = timed(6)
        fe.do_query("SET sst_index = 0", ctx)
        dt_off = timed(2)
        fe.do_query("SET sst_index = 1", ctx)

        # EXPLAIN ANALYZE profile: files pruned by index must be visible
        tpu_exec.SCAN_CACHE._entries.clear()
        run(point_sql(123))
        st = fe.query_engine.last_exec_stats
        prune = st.stages["prune"].detail
        pruned = int(prune.get("index_files_pruned", 0))
        checked = int(prune.get("index_files_checked", 0))
        assert pruned >= files - 2, (pruned, checked)
        speedup = dt_off / dt_on
        assert speedup >= 3.0, (
            f"index differential only {speedup:.2f}x on the many-SST "
            f"region (on={dt_on * 1e3:.1f}ms off={dt_off * 1e3:.1f}ms)")
        return (1.0 / dt_on, speedup, total, n_ssts,
                {"dispatch": st.dispatch,
                 "files_pruned_by_index": f"{pruned}/{checked}",
                 "query_ms_index_on": round(dt_on * 1e3, 2),
                 "query_ms_index_off": round(dt_off * 1e3, 2)})
    finally:
        if fe is not None:
            fe.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)


def emit_index_point_query():
    """The ISSUE 13 metric, runnable alone via `make bench-index`
    (GREPTIME_BENCH_ONLY=index)."""
    n_series = int(os.environ.get("GREPTIME_BENCH_INDEX_SERIES",
                                  100_000))
    n_files = int(os.environ.get("GREPTIME_BENCH_INDEX_FILES", 16))
    qps, speedup, rows, n_ssts, profile = \
        bench_index_point_query(n_series, n_files)
    print(json.dumps({
        "metric": "high_cardinality_point_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/s",
        "series": n_series,
        "rows": rows,
        "sst_files": n_ssts,
        "vs_index_off": round(speedup, 2),
        "profile": profile,
    }))


def emit_concurrent_qps():
    """The ISSUE 12 metric, runnable alone via `make bench-qps`
    (GREPTIME_BENCH_ONLY=concurrent_qps)."""
    n_clients = int(os.environ.get("GREPTIME_BENCH_QPS_CLIENTS", 1000))
    qps, p50, p95, p99, ratios, append_ns = \
        bench_concurrent_qps(n_clients)
    print(json.dumps({
        "metric": "concurrent_qps_p99",
        "value": round(qps, 0),
        "unit": "qps",
        "clients": n_clients,
        "p50_ms": round(p50, 2),
        "p95_ms": round(p95, 2),
        "p99_ms": round(p99, 2),
        "group_commit_speedup_fsync2ms": round(ratios["fsync2ms"], 2),
        "group_commit_speedup_raw": round(ratios["raw"], 2),
        "wal_append_ns": round(append_ns, 0),
    }))


def main():
    if os.environ.get("GREPTIME_BENCH_ONLY") == "concurrent_qps":
        emit_concurrent_qps()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "index":
        emit_index_point_query()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "distagg":
        emit_dist_partial_agg()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "promql":
        emit_promql_dist_range()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "replica":
        emit_replicated_read_qps()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "trace":
        emit_trace_store_overhead()
        return
    if os.environ.get("GREPTIME_BENCH_ONLY") == "prof":
        emit_profiler_overhead()
        return
    n_rows = int(os.environ.get("GREPTIME_BENCH_ROWS", 1 << 24))
    gids, ts, metrics = gen_data(n_rows)

    tpu_rps, out = bench_tpu(gids, ts, metrics)

    # sanity: TPU result must agree with a numpy oracle on one group
    # (last iteration shifted metric 0 by +iters)
    g0 = gids == 0
    if g0.any():
        got = float(np.asarray(out[0][0])[0])
        assert abs(got - float(metrics[0][g0].max()) - 8.0) < 1e-2, got

    cpu_rps = bench_cpu(gids, ts, metrics)

    print(json.dumps({
        "metric": "tsbs_single_groupby_scan_agg_throughput",
        "value": round(tpu_rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
    }))

    cold_rows = int(os.environ.get("GREPTIME_BENCH_COLD_ROWS", 4_000_000))
    cold_rps, cold_profile = bench_cold_e2e(cold_rows)
    print(json.dumps({
        "metric": "cold_single_groupby_e2e_throughput",
        "value": round(cold_rps / 1e6, 2),
        "unit": "Mrows/s",
        "rows": cold_rows,
    }))
    print(json.dumps({
        "metric": "cold_scan_stage_profile",
        "unit": "json",
        **cold_profile,
    }))

    roll_rows = int(os.environ.get("GREPTIME_BENCH_ROLLUP_ROWS",
                                   4_000_000))
    roll_rps, vs_raw = bench_rollup_e2e(roll_rows)
    print(json.dumps({
        "metric": "rollup_groupby_e2e_throughput",
        "value": round(roll_rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_raw_scan": round(vs_raw, 2),
        "rows": roll_rows,
    }))

    dist_rows = int(os.environ.get("GREPTIME_BENCH_DIST_ROWS", 2_000_000))
    dist_rps, vs_serial, vs_serial_net, node_ms = \
        bench_dist_scatter(dist_rows)
    print(json.dumps({
        "metric": "dist_scatter_gather_throughput",
        "value": round(dist_rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_serial": round(vs_serial, 2),
        "vs_serial_warm_10ms_rpc": round(vs_serial_net, 2),
        "rows": dist_rows,
        "datanodes": 4,
        "scatter_node_ms": node_ms,
    }))

    emit_dist_partial_agg()

    emit_promql_dist_range()

    mig_rows = int(os.environ.get("GREPTIME_BENCH_MIGRATE_ROWS",
                                  1_000_000))
    handoff_ms, max_stall_ms, acked_n, lost, dup = \
        bench_region_migration_availability(mig_rows)
    print(json.dumps({
        "metric": "region_migration_availability",
        "value": round(handoff_ms, 1),
        "unit": "ms_handoff_window",
        "max_write_stall_ms": round(max_stall_ms, 1),
        "migrated_rows": mig_rows,
        "acked_writes_during_migration": acked_n,
        "lost_rows": lost,
        "dup_rows": dup,
    }))

    emit_replicated_read_qps()

    fp_rows = int(os.environ.get("GREPTIME_BENCH_FAILPOINT_ROWS",
                                 2_000_000))
    ingest_rps, fp_ratio, fp_ns = bench_ingest_failpoint_overhead(fp_rows)
    print(json.dumps({
        "metric": "bulk_ingest_e2e_throughput",
        "value": round(ingest_rps / 1e6, 2),
        "unit": "Mrows/s",
        "rows": fp_rows,
        "failpoint_inactive_ratio": round(fp_ratio, 3),
        "failpoint_inactive_ns_per_call": round(fp_ns, 1),
    }))

    emit_index_point_query()

    mon_rows = int(os.environ.get("GREPTIME_BENCH_MONITOR_ROWS",
                                  2_000_000))
    mon_rps, mon_overhead, mon_ticks = \
        bench_self_monitoring_overhead(mon_rows)
    print(json.dumps({
        "metric": "self_monitoring_overhead",
        "value": round(mon_overhead * 100, 2),
        "unit": "percent",
        "ingest_mrows_s_with_scraper": round(mon_rps / 1e6, 2),
        "rows": mon_rows,
        "scrape_interval_s": 0.5,
        "ticks_during_ingest": mon_ticks,
    }))

    lk_ns, lk_raw_ns, lk_ratio, lk_active_ns = bench_lock_overhead()
    print(json.dumps({
        "metric": "tracked_lock_inactive_overhead",
        "value": round(lk_ns, 1),
        "unit": "ns/acquire-release",
        "raw_lock_ns": round(lk_raw_ns, 1),
        "inactive_ratio": round(lk_ratio, 3),
        "active_mode_ns": round(lk_active_ns, 1),
    }))

    san_ns, san_raw_ns, san_ratio, san_active_ns = \
        bench_greptsan_inactive_overhead()
    print(json.dumps({
        "metric": "greptsan_inactive_overhead",
        "value": round(san_ns, 1),
        "unit": "ns/dict-cycle",
        "raw_dict_ns": round(san_raw_ns, 1),
        "inactive_ratio": round(san_ratio, 3),
        "active_mode_ns_per_get": round(san_active_ns, 1),
    }))

    emit_trace_store_overhead()

    emit_profiler_overhead()

    emit_concurrent_qps()


if __name__ == "__main__":
    main()
