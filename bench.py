"""Flagship benchmark: TSBS-style scan+aggregate throughput on TPU.

Models the north-star config (BASELINE.json): TSBS cpu-only
`single-groupby`-shape query — time-range filter, group by host tag and
1-minute time buckets, aggregate 5 metric columns — over synthetic devops
rows resident in HBM (the memtable layout of greptimedb_tpu).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`vs_baseline` is the speedup vs a same-machine CPU columnar baseline
(pandas groupby over the identical arrays — the stand-in denominator for
"CPU DataFusion" since the reference publishes no numbers, BASELINE.md).
"""

import json
import os
import time

import numpy as np


def gen_data(n_rows: int, hosts: int, seed: int = 42):
    rng = np.random.default_rng(seed)
    gids = rng.integers(0, hosts, n_rows).astype(np.int32)
    # one hour of data, ms resolution, int32-safe offsets
    ts = rng.integers(0, 3_600_000, n_rows).astype(np.int32)
    metrics = [rng.random(n_rows, dtype=np.float32) * 100 for _ in range(5)]
    return gids, ts, metrics


def bench_tpu(gids, ts, metrics, hosts, buckets, iters=5):
    import jax
    import jax.numpy as jnp
    from greptimedb_tpu.ops.kernels import (
        combine_group_ids, grouped_aggregate, time_bucket_ids)

    num_groups = hosts * buckets
    ops = ("avg",) * 5

    @jax.jit
    def step(gids, ts, m0, m1, m2, m3, m4):
        mask = (ts >= 0) & (ts < 3_600_000)
        b = time_bucket_ids(ts, 0, 60_000, buckets)
        full = combine_group_ids(gids, b, buckets)
        return grouped_aggregate(full, mask, ts, (m0, m1, m2, m3, m4),
                                 num_groups=num_groups, ops=ops)

    d_gids = jax.device_put(gids)
    d_ts = jax.device_put(ts)
    d_metrics = [jax.device_put(m) for m in metrics]
    jax.block_until_ready(step(d_gids, d_ts, *d_metrics))  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(d_gids, d_ts, *d_metrics)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    return len(gids) / dt, out


def bench_cpu(gids, ts, metrics, hosts, buckets):
    """CPU columnar baseline: pandas groupby over identical data."""
    import pandas as pd
    df = pd.DataFrame({"host": gids, "bucket": (ts // 60_000)})
    for i, m in enumerate(metrics):
        df[f"m{i}"] = m
    t0 = time.perf_counter()
    df[(ts >= 0) & (ts < 3_600_000)].groupby(["host", "bucket"]).agg(
        {f"m{i}": "mean" for i in range(5)})
    dt = time.perf_counter() - t0
    return len(gids) / dt


def main():
    n_rows = int(os.environ.get("GREPTIME_BENCH_ROWS", 1 << 24))
    hosts, buckets = 8, 60
    gids, ts, metrics = gen_data(n_rows, hosts)

    tpu_rps, out = bench_tpu(gids, ts, metrics, hosts, buckets)

    # sanity: TPU result must agree with a numpy oracle on one group
    avg0 = np.asarray(out[0][0]).reshape(hosts, buckets)
    sel = (gids == 0) & (ts // 60_000 == 0)
    if sel.any():
        assert abs(float(avg0[0, 0]) - float(metrics[0][sel].mean())) < 1e-2

    cpu_rps = bench_cpu(gids, ts, metrics, hosts, buckets)

    print(json.dumps({
        "metric": "tsbs_single_groupby_scan_agg_throughput",
        "value": round(tpu_rps / 1e6, 2),
        "unit": "Mrows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
    }))


if __name__ == "__main__":
    main()
