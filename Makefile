# Entry points for the checks CI runs (.github/workflows/ci.yml).
# `make check` is the one command a contributor needs before pushing.

PY ?= python

.PHONY: check lint typecheck test test-slow race baseline bench bench-qps \
	bench-index bench-distagg bench-trace bench-promql bench-prof \
	bench-replica prof

check: lint typecheck test

# greptlint: project-invariant static analyzer (rules GL01-GL14;
# GL10-GL13 are interprocedural over the repo-wide call graph).
# Exit 0 requires a clean scan modulo .greptlint-baseline.json.
lint:
	$(PY) -m greptimedb_tpu.devtools.greptlint greptimedb_tpu/

# mypy is scoped by mypy.ini (common/, errors.py, utils/, devtools/).
# The build image does not ship mypy; skip with a notice rather than
# fail so `make check` works everywhere (CI installs it).
typecheck:
	@$(PY) -c "import mypy" 2>/dev/null \
	  && $(PY) -m mypy --config-file mypy.ini \
	  || echo "mypy not installed; skipping typecheck (see mypy.ini)"

# tier-1 suite: the ROADMAP.md verify command (lock-order detector is
# auto-enabled under pytest; greptlint runs inside as tests/test_greptlint.py)
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly

test-slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  --continue-on-collection-errors -p no:cacheprovider

# greptsan happens-before race detector, focused: the seeded selftest
# plus the multi-thread hammer (concurrent ingest+flush+compact+
# scatter+balancer+self-monitor) under an explicit GREPTIME_RACE_CHECK=1.
# The full `make test` run carries the detector too (auto-on under
# pytest); this target is the quick iteration loop for concurrency work.
race:
	GREPTIME_RACE_CHECK=1 JAX_PLATFORMS=cpu $(PY) -m pytest \
	  tests/test_greptsan.py tests/test_locks.py -q \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# Re-record grandfathered findings. Only for CONSCIOUS grandfathering —
# the tier-1 gate asserts the baseline total only ever shrinks (≤ 10).
baseline:
	$(PY) -m greptimedb_tpu.devtools.greptlint greptimedb_tpu/ \
	  --write-baseline

bench:
	JAX_PLATFORMS=cpu $(PY) bench.py

# only the ISSUE 12 front-door metric: 1000-logical-client mixed
# workload QPS × p99 + the WAL group-commit on/off differential
bench-qps:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=concurrent_qps $(PY) bench.py

# only the ISSUE 13 metric: high-cardinality point/IN query throughput
# on a ~100k-series, >=16-SST region with the per-SST secondary index
# on vs `SET sst_index = 0` (asserts the >=3x differential)
bench-index:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=index $(PY) bench.py

# only the ISSUE 15 metric: bulk-ingest + point-query differential with
# the durable trace store's sink at sample ratio 1.0 / 0.01 vs off
# (asserts <3% overhead at the default 0.01 ratio)
bench-trace:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=trace $(PY) bench.py

# only the ISSUE 14 metric: 4-datanode GROUP BY with
# count/count-distinct/p95 through the sketch partial pushdown vs the
# raw-row fallback (`SET dist_partial_agg = 0`); asserts the >=3x
# wire-byte reduction
bench-distagg:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=distagg $(PY) bench.py

# only the ISSUE 17 metric: mixed bulk-ingest + point-query throughput
# with the continuous profiler sampling at the default 19 Hz vs off
# (asserts <3% overhead)
bench-prof:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=prof $(PY) bench.py

# quick continuous-profiling demo: boots a standalone frontend with
# `SET profiling = 1`, runs a short mixed workload and prints the
# ADMIN SHOW PROFILE 'last' tree (ISSUE 17)
prof:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	  tests/test_profiler.py -q -k standalone_end_to_end \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# only the ISSUE 19 metric: read QPS at 1/2/3 region replicas under
# SET read_replica = 'follower', plus the leader kill -9 promotion
# handoff window and the acked-loss/dup counts (asserted zero)
bench-replica:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=replica $(PY) bench.py

# only the ISSUE 16 metric: 4-datanode PromQL range query
# `sum by (hostname) (rate(...))` through the plan-IR pushdown vs the
# raw-pull row path (`SET dist_partial_agg = 0`); asserts the >=3x
# speedup and publishes the wire-byte ratio
bench-promql:
	JAX_PLATFORMS=cpu GREPTIME_BENCH_ONLY=promql $(PY) bench.py
