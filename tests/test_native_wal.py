"""Native C++ WAL tests: format compat with the Python WAL, group
commit, rotation, obsolete GC, torn-tail replay.

Mirrors the reference's WAL coverage (src/storage/src/wal.rs:253-300
round-trip tests; raft-engine backed log store semantics) plus
cross-implementation compatibility — the two WALs share one on-disk
format, so each must replay the other's log byte-for-byte.
"""

import os
import threading

import pytest

from greptimedb_tpu.storage.native_wal import (
    NativeWal, load_library, make_wal)
from greptimedb_tpu.storage.wal import Wal

pytestmark = pytest.mark.skipif(load_library() is None,
                                reason="native WAL toolchain unavailable")


class TestNativeWal:
    def test_roundtrip(self, tmp_path):
        w = NativeWal(str(tmp_path / "wal"))
        w.append(1, b"one")
        w.append(2, b"two")
        w.append(3, b"three")
        w.sync()
        got = [(s, p) for s, _v, p in w.read_from(2)]
        assert got == [(2, b"two"), (3, b"three")]
        w.close()

    def test_schema_version_carried(self, tmp_path):
        w = NativeWal(str(tmp_path / "wal"))
        w.append(1, b"a", schema_version=7)
        got = list(w.read_from(0))
        assert got == [(1, 7, b"a")]
        w.close()

    def test_python_reads_native_log(self, tmp_path):
        n = NativeWal(str(tmp_path / "wal"))
        for i in range(10):
            n.append(i, f"rec{i}".encode())
        n.sync()
        n.close()
        p = Wal(str(tmp_path / "wal"))
        got = [(s, pl) for s, _v, pl in p.read_from(0)]
        assert got == [(i, f"rec{i}".encode()) for i in range(10)]
        p.close()

    def test_native_reads_python_log(self, tmp_path):
        p = Wal(str(tmp_path / "wal"))
        for i in range(10):
            p.append(i, f"rec{i}".encode())
        p.close()
        n = NativeWal(str(tmp_path / "wal"))
        got = [(s, pl) for s, _v, pl in n.read_from(5)]
        assert got == [(i, f"rec{i}".encode()) for i in range(5, 10)]
        n.close()

    def test_native_resumes_python_segment(self, tmp_path):
        p = Wal(str(tmp_path / "wal"))
        p.append(1, b"from-python")
        p.close()
        n = NativeWal(str(tmp_path / "wal"))
        n.append(2, b"from-native")
        n.sync()
        got = [pl for _s, _v, pl in n.read_from(0)]
        assert got == [b"from-python", b"from-native"]
        # both records landed in ONE segment (resume, not new file)
        assert len([f for f in os.listdir(tmp_path / "wal")
                    if f.endswith(".wal")]) == 1
        n.close()

    def test_segment_rotation_and_obsolete(self, tmp_path):
        w = NativeWal(str(tmp_path / "wal"), segment_bytes=64)
        for i in range(10):
            w.append(i, bytes(40))        # every append rotates
        w.sync()
        segs = [f for f in os.listdir(tmp_path / "wal")
                if f.endswith(".wal")]
        assert len(segs) > 3
        w.obsolete(7)
        remaining = sorted(f for f in os.listdir(tmp_path / "wal")
                           if f.endswith(".wal"))
        assert int(remaining[0][:-4]) >= 7
        got = [s for s, _v, _p in w.read_from(8)]
        assert got == [8, 9]
        w.close()

    def test_group_commit_many_writers(self, tmp_path):
        """32 threads × 32 sync-on-write appends: every append must be
        durable on return, sharing group fsyncs."""
        w = NativeWal(str(tmp_path / "wal"), sync_on_write=True,
                      group_interval_us=200)
        errors = []

        def writer(tid):
            try:
                for i in range(32):
                    w.append(tid * 1000 + i, f"{tid}:{i}".encode())
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        got = list(w.read_from(0))
        assert len(got) == 32 * 32
        w.close()

    def test_torn_tail_tolerated(self, tmp_path):
        w = NativeWal(str(tmp_path / "wal"))
        w.append(1, b"good")
        w.sync()
        w.close()
        # simulate a crash mid-append: garbage tail
        seg = [f for f in os.listdir(tmp_path / "wal")
               if f.endswith(".wal")][0]
        with open(tmp_path / "wal" / seg, "ab") as f:
            f.write(b"\x55\x00\x00\x00garbage")
        w2 = NativeWal(str(tmp_path / "wal"))
        got = [p for _s, _v, p in w2.read_from(0)]
        assert got == [b"good"]
        w2.close()

    def test_make_wal_backends(self, tmp_path):
        assert isinstance(make_wal(str(tmp_path / "a")), NativeWal)
        assert isinstance(
            make_wal(str(tmp_path / "b"), backend="python"), Wal)
        py = make_wal(str(tmp_path / "b"), backend="python")
        assert not isinstance(py, NativeWal)

    def test_region_engine_uses_native_wal(self, tmp_path):
        """The storage engine's default WAL is the native one (auto)."""
        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        region = dn.storage.create_region(
            "r_native", _schema())
        assert isinstance(region.wal, NativeWal)
        dn.shutdown()


def _schema():
    from greptimedb_tpu.datatypes import data_type as dt
    from greptimedb_tpu.datatypes.schema import (
        ColumnSchema, Schema, SemanticType)
    return Schema([
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("v", dt.FLOAT64)])
