"""Self-monitoring pipeline tests (ISSUE 8).

The scraper (monitor/scraper.py) walks the shared telemetry registry +
per-region heat each tick and writes both through the NORMAL ingest
path into greptime_private system tables — so the node's own history
is ordinary data: SQL queries it, flows roll it up, retention sweeps
it. The recursion guard (telemetry.suppress_metrics) is regression-
tested here: idle ticks must persist IDENTICAL counter values, not
self-amplify from the act of recording them.
"""

import time

import numpy as np
import pytest

from greptimedb_tpu.common.telemetry import registry_snapshot
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.monitor import (NODE_METRICS_TABLE, PRIVATE_SCHEMA,
                                    REGION_HEAT_TABLE)
from greptimedb_tpu.monitor.scraper import configure_retention, retention_ms


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    frontend = FrontendInstance(dn)
    frontend.start()
    frontend.do_query(
        "CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
        "v DOUBLE, PRIMARY KEY(host))")
    frontend.do_query("INSERT INTO cpu VALUES ('a', 1000, 1.5), "
                      "('b', 2000, 2.5)")
    saved = retention_ms()
    yield frontend
    configure_retention(saved)
    frontend.shutdown()


def _pydict(fe, sql):
    out = fe.do_query(sql)[-1]
    return out.batches[0].to_pydict()


class TestScrape:
    def test_tick_creates_queryable_system_tables(self, fe):
        written = fe.self_monitor.tick()
        assert written > 0
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{NODE_METRICS_TABLE}")
        assert d["count(*)"][0] > 50          # a live registry is big
        d = _pydict(fe, f"SELECT node, region, rows, size_bytes, "
                        f"ingest_rate_rps FROM {PRIVATE_SCHEMA}."
                        f"{REGION_HEAT_TABLE}")
        assert d["node"] == ["standalone"]
        assert d["rows"] == [2]
        assert d["size_bytes"][0] > 0

    def test_system_tables_are_ordinary_tables(self, fe):
        """The history tables ride the normal mito path: tagged schema,
        time index, visible in information_schema.tables."""
        fe.self_monitor.tick()
        t = fe.catalog.table("greptime", PRIVATE_SCHEMA,
                             NODE_METRICS_TABLE)
        assert t.schema.tag_names() == ["node", "metric_name", "labels"]
        assert t.schema.timestamp_column.name == "ts"
        d = _pydict(fe, "SELECT table_name FROM information_schema.tables"
                        f" WHERE table_schema = '{PRIVATE_SCHEMA}'")
        assert set(d["table_name"]) >= {NODE_METRICS_TABLE,
                                        REGION_HEAT_TABLE}

    def test_persisted_values_match_registry_snapshot(self, fe):
        """What lands in node_metrics is exactly what the registry
        reported at the snapshot instant."""
        before = {(n, l): v for n, l, v, _ in registry_snapshot()}
        fe.self_monitor.tick()
        d = _pydict(fe, f"SELECT metric_name, labels, value FROM "
                        f"{PRIVATE_SCHEMA}.{NODE_METRICS_TABLE}")
        got = dict(zip(zip(d["metric_name"], d["labels"]), d["value"]))
        # the registry is process-global (other tests may have bumped
        # it), so assert persisted == snapshotted, not an absolute
        key = ("greptime_region_write_rows_total", "")
        assert key in got and got[key] == before[key] >= 2.0

    def test_idle_ticks_converge_not_amplify(self, fe):
        """Satellite: the scraper must never recurse. Its own writes run
        under suppress_metrics, so consecutive idle ticks persist the
        SAME ingest-counter values — without the guard every tick's
        write bumps the write counters the next tick scrapes and the
        series grows forever on an idle node."""
        for _ in range(3):
            fe.self_monitor.tick()
            time.sleep(0.005)        # distinct ts per tick
        d = _pydict(fe, f"SELECT ts, value FROM {PRIVATE_SCHEMA}."
                        f"{NODE_METRICS_TABLE} WHERE metric_name = "
                        f"'greptime_region_write_rows_total'")
        assert len(d["value"]) == 3
        assert len(set(d["value"])) == 1, (
            f"ingest counter self-amplified across idle ticks: "
            f"{d['value']}")
        # the write-path timer histogram converges too (each tick's
        # write times region_write — the whole write path must be
        # suppressed, not just the top-level insert span)
        d = _pydict(fe, f"SELECT value FROM {PRIVATE_SCHEMA}."
                        f"{NODE_METRICS_TABLE} WHERE metric_name = "
                        f"'greptime_region_write_seconds_count'")
        assert len(set(d["value"])) <= 1

    def test_region_heat_rate_derived_across_ticks(self, fe):
        fe.self_monitor.tick()
        time.sleep(0.05)
        vals = np.arange(500, dtype=np.float64)
        fe.catalog.table("greptime", "public", "cpu").insert({
            "host": ["a"] * 500,
            "ts": (np.arange(500, dtype=np.int64) + 10) * 1000,
            "v": vals})
        fe.self_monitor.tick()
        d = _pydict(fe, f"SELECT ts, ingest_rate_rps FROM "
                        f"{PRIVATE_SCHEMA}.{REGION_HEAT_TABLE}")
        assert max(d["ingest_rate_rps"]) > 0.0

    def test_heat_walk_skips_the_scrape_target(self, fe):
        """greptime_private's own regions never appear in region_heat —
        the monitoring store must not monitor itself into a feedback
        loop."""
        fe.self_monitor.tick()
        fe.self_monitor.tick()
        heat = fe.self_monitor._heat_rows()
        private = fe.catalog.table("greptime", PRIVATE_SCHEMA,
                                   NODE_METRICS_TABLE)
        private_regions = {r.name for r in private.regions.values()}
        assert private_regions
        assert not private_regions & {h["region"] for h in heat}

    def test_scrape_failure_contained(self, fe, monkeypatch):
        def boom(*a, **kw):
            raise RuntimeError("ingest exploded")
        monkeypatch.setattr(fe, "handle_row_insert", boom)
        assert fe.self_monitor.tick() == 0
        assert "ingest exploded" in str(fe.self_monitor.stats["last_error"])
        monkeypatch.undo()
        assert fe.self_monitor.tick() > 0     # recovers next tick
        assert fe.self_monitor.stats["last_error"] is None

    def test_self_monitor_view(self, fe):
        fe.self_monitor.tick()
        d = _pydict(fe, "SELECT node, ticks, metric_rows, rows_written, "
                        "retention_ms FROM information_schema.self_monitor")
        assert d["node"] == ["standalone"]
        assert d["ticks"] == [1]
        assert d["rows_written"][0] == d["metric_rows"][0] + 1  # + heat


class TestRetention:
    def test_sweep_deletes_aged_rows(self, fe):
        fe.self_monitor.tick()
        # plant rows far past any window through the same ingest path
        old_ms = int(time.time() * 1000) - 10 * 24 * 3600 * 1000
        fe.handle_row_insert(
            NODE_METRICS_TABLE,
            {"node": ["standalone"], "metric_name": ["stale_metric"],
             "labels": [""], "ts": [old_ms], "value": [1.0],
             "kind": ["counter"]},
            tag_columns=("node", "metric_name", "labels"),
            timestamp_column="ts", ctx=fe.self_monitor._ctx())
        fe.do_query("SET self_monitor_retention_ms = 60000")
        assert retention_ms() == 60000
        fe.self_monitor.tick()
        assert int(fe.self_monitor.stats["retention_deleted"]) >= 1
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{NODE_METRICS_TABLE} WHERE metric_name = "
                        f"'stale_metric'")
        assert d["count(*)"][0] == 0

    def test_zero_disables_sweep(self, fe):
        fe.do_query("SET self_monitor_retention_ms = 0")
        fe.self_monitor.tick()
        assert int(fe.self_monitor.stats["retention_deleted"]) == 0

    def test_sweep_is_batched_per_tick(self, fe, monkeypatch):
        """A huge backlog (retention turned on after days off) deletes
        in bounded chunks across ticks instead of materializing every
        expired key at once inside the scrape lock."""
        fe.self_monitor.tick()
        old_ms = int(time.time() * 1000) - 10 * 24 * 3600 * 1000
        fe.handle_row_insert(
            NODE_METRICS_TABLE,
            {"node": ["standalone"] * 5,
             "metric_name": [f"stale_{i}" for i in range(5)],
             "labels": [""] * 5, "ts": [old_ms + i for i in range(5)],
             "value": [1.0] * 5, "kind": ["counter"] * 5},
            tag_columns=("node", "metric_name", "labels"),
            timestamp_column="ts", ctx=fe.self_monitor._ctx())
        monkeypatch.setattr(type(fe.self_monitor), "SWEEP_BATCH_ROWS", 2)
        configure_retention(60_000)
        before = int(fe.self_monitor.stats["retention_deleted"])
        fe.self_monitor.tick()
        assert int(fe.self_monitor.stats["retention_deleted"]) \
            - before == 2                     # capped, not all 5
        for _ in range(4):                    # backlog drains tick by tick
            fe.self_monitor.tick()
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{NODE_METRICS_TABLE} WHERE ts < {old_ms + 10}")
        assert d["count(*)"][0] == 0


class TestFlowRollup:
    def test_flow_rolls_up_self_metrics(self, fe):
        """The history is ordinary data: a standing flow aggregates
        node_metrics into a coarser sink exactly like user tables."""
        from greptimedb_tpu.session import QueryContext
        fe.self_monitor.tick()
        time.sleep(0.005)
        fe.self_monitor.tick()
        # flows are keyed under the session schema (cross-schema sources
        # are rejected), so run the DDL with greptime_private current
        ctx = QueryContext(current_schema=PRIVATE_SCHEMA)
        fe.do_query(
            "CREATE FLOW metrics_1m AS SELECT node, metric_name, labels, "
            "date_bin(INTERVAL '1 minute', ts) AS b, max(value) AS v_max, "
            "count(*) AS n FROM node_metrics "
            "GROUP BY node, metric_name, labels, b", ctx)
        written = fe.datanode.flow_manager.tick()
        assert sum(written.values()) > 0
        out = fe.do_query("SELECT count(*) FROM metrics_1m", ctx)[-1]
        assert out.batches[0].to_pydict()["count(*)"][0] > 0
        fe.do_query("DROP FLOW metrics_1m", ctx)


class TestDistributedHeat:
    def test_meta_region_heat_rates(self):
        """MetaSrv.region_heat: per-(node, region) rows/size plus the
        ingest rate derived across consecutive FULL stat beats."""
        from greptimedb_tpu.meta import MemKv, MetaSrv, Peer
        from greptimedb_tpu.meta.service import DatanodeStat
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        srv.register_datanode(Peer(1, "dn1"))
        t0 = time.time()
        srv.handle_heartbeat(1, DatanodeStat(
            region_count=1, approximate_rows=1000,
            region_stats=[{"region": "7_0000000000", "rows": 1000,
                           "size_bytes": 4096}]), now=t0)
        srv.handle_heartbeat(1, DatanodeStat(
            region_count=1, approximate_rows=3000,
            region_stats=[{"region": "7_0000000000", "rows": 3000,
                           "size_bytes": 8192}]), now=t0 + 2)
        rows = srv.region_heat(now=t0 + 2)
        assert rows == [{"node": "dn1", "region": "7_0000000000",
                         "rows": 3000, "size_bytes": 8192,
                         # cost-planner inputs ride the heat rows since
                         # ISSUE 14; zero for a beat that omits them
                         "series": 0, "time_span": 0,
                         "ingest_rate_rps": 1000.0}]

    def test_dead_node_rate_zeroes(self):
        from greptimedb_tpu.meta import MemKv, MetaSrv, Peer
        from greptimedb_tpu.meta.service import DatanodeStat
        srv = MetaSrv(MemKv(), datanode_lease_secs=10)
        srv.register_datanode(Peer(1, "dn1"))
        t0 = time.time()
        stat = DatanodeStat(
            region_count=1, approximate_rows=1000,
            region_stats=[{"region": "7_0000000000", "rows": 1000,
                           "size_bytes": 4096}])
        srv.handle_heartbeat(1, stat, now=t0)
        srv.handle_heartbeat(1, DatanodeStat(
            region_count=1, approximate_rows=9000,
            region_stats=[{"region": "7_0000000000", "rows": 9000,
                           "size_bytes": 4096}]), now=t0 + 1)
        # within the lease: a hot rate
        assert srv.region_heat(now=t0 + 1)[0]["ingest_rate_rps"] > 0
        # lease long expired: the rate is a derivative, it must zero
        assert srv.region_heat(now=t0 + 600)[0]["ingest_rate_rps"] == 0.0

    def test_dist_frontend_scrapes_cluster_heat(self, tmp_path):
        """A distributed frontend's scraper persists the meta-fed,
        cluster-wide heat: every datanode's regions appear even though
        only the frontend scrapes."""
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(srv)
        datanodes, clients = {}, {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
        fe = DistInstance(meta, clients)
        try:
            fe.do_query(
                "CREATE TABLE hashed (host STRING, ts TIMESTAMP TIME "
                "INDEX, v DOUBLE, PRIMARY KEY(host)) "
                "PARTITION BY HASH (host) PARTITIONS 4")
            fe.do_query("INSERT INTO hashed VALUES " + ", ".join(
                f"('h{i}', {1000 + i}, 1.0)" for i in range(32)))
            # two full stat beats per node so meta derives rates (built
            # by the same walker the real heartbeat task uses)
            from greptimedb_tpu.meta.service import DatanodeStat
            from greptimedb_tpu.query.stream_exec import region_stat_entries

            def full_beat(dn):
                regions = dn.storage.list_regions()
                stats, rows, size = region_stat_entries(regions.values())
                srv.handle_heartbeat(dn.opts.node_id, DatanodeStat(
                    region_count=len(regions), approximate_rows=rows,
                    approximate_bytes=size, region_stats=stats))
            for dn in datanodes.values():
                full_beat(dn)
            time.sleep(0.02)
            for dn in datanodes.values():
                full_beat(dn)
            n = fe.self_monitor.tick()
            assert n > 0
            d = _pydict(fe, f"SELECT node, region, rows FROM "
                            f"{PRIVATE_SCHEMA}.{REGION_HEAT_TABLE}")
            assert set(d["node"]) == {"dn1", "dn2"}
            assert sum(d["rows"]) == 32
        finally:
            for dn in datanodes.values():
                dn.shutdown()
