"""Mesh-sharded kernels vs single-chip / numpy oracles (8 virtual devices)."""

import jax
import numpy as np
import pytest

from greptimedb_tpu.ops.kernels import grouped_aggregate
from greptimedb_tpu.ops.window import SeriesMatrix, range_aggregate_cumsum
from greptimedb_tpu.parallel import (
    distributed_grouped_aggregate,
    make_mesh,
    series_sharded_range_aggregate,
    time_blocked_window_sum,
)

RNG = np.random.default_rng(7)


def make_rows(n=10_000, groups=37):
    gids = RNG.integers(0, groups, n).astype(np.int32)
    mask = RNG.random(n) > 0.1
    ts = RNG.integers(0, 1_000_000, n).astype(np.int64)
    vals = RNG.normal(size=n).astype(np.float32)
    return gids, mask, ts, vals


def test_mesh_factoring():
    mesh = make_mesh()
    assert mesh.size == len(jax.devices())
    assert mesh.axis_names == ("region", "block")
    assert make_mesh(jax.devices()[:1]).shape == {"region": 1, "block": 1}


@pytest.mark.parametrize("ops", [
    ("sum", "count", "avg", "min", "max"),
    ("stddev", "variance"),
    ("first", "last"),
])
def test_distributed_matches_single_chip(ops):
    groups = 37
    gids, mask, ts, vals = make_rows(groups=groups)
    mesh = make_mesh()
    values = tuple(vals for _ in ops)
    got, counts = distributed_grouped_aggregate(
        gids, mask, ts, values, num_groups=groups, ops=ops, mesh=mesh)
    want, want_counts = grouped_aggregate(
        gids, mask, ts, values, num_groups=groups, ops=ops)
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(want_counts))
    for op, g, w in zip(ops, got, want):
        g, w = np.asarray(g, np.float64), np.asarray(w, np.float64)
        if op in ("first", "last"):
            # ties on the extreme ts may pick different rows across layouts;
            # verify against the set of valid candidates instead
            ext = np.full(groups, np.inf if op == "first" else -np.inf)
            red = np.minimum if op == "first" else np.maximum
            for i in range(len(gids)):
                if mask[i]:
                    ext[gids[i]] = red(ext[gids[i]], ts[i])
            for gi in range(groups):
                if np.isfinite(ext[gi]):
                    cands = vals[(gids == gi) & mask & (ts == ext[gi])]
                    assert np.any(np.isclose(g[gi], cands, atol=1e-5)), op
        else:
            np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-4, err_msg=op)


def test_distributed_col_masks_and_padding():
    gids, mask, ts, vals = make_rows(n=1003, groups=5)  # force padding
    cm = RNG.random(1003) > 0.4
    mesh = make_mesh()
    got, _ = distributed_grouped_aggregate(
        gids, mask, ts, (vals,), (cm,), num_groups=5, ops=("sum",), mesh=mesh)
    want, _ = grouped_aggregate(gids, mask, ts, (vals,), (cm,),
                                num_groups=5, ops=("sum",), has_col_masks=True)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=1e-4)


@pytest.mark.parametrize("op", ["avg_over_time", "rate", "max_over_time"])
def test_series_sharded_range_matches_single(op):
    S, per = 13, 50  # S not divisible by 8 → exercises padding
    sids = np.repeat(np.arange(S), per).astype(np.int32)
    ts = np.tile(np.arange(per) * 10_000, S).astype(np.int64) + 5
    vals = RNG.normal(size=S * per).astype(np.float32).cumsum().astype(np.float32)
    m = SeriesMatrix.build(sids, ts, vals, S)
    t0, step, rng, nsteps = 60_000, 30_000, 60_000, 12
    mesh = make_mesh()
    out, ok = series_sharded_range_aggregate(
        m.ts, m.values, m.lengths, t0, step, rng, op=op, nsteps=nsteps,
        mesh=mesh)
    if op in ("avg_over_time", "rate"):
        want, want_ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths, t0, step, rng, op=op, nsteps=nsteps)
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(want_ok))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
    else:
        # gather path: check directly vs numpy sliding max
        for s in range(S):
            for i in range(nsteps):
                end = t0 + i * step
                sel = (ts[sids == s] > end - rng) & (ts[sids == s] <= end)
                if sel.any():
                    assert ok[s, i]
                    np.testing.assert_allclose(
                        out[s, i], vals[sids == s][sel].max(), rtol=1e-5)


@pytest.mark.parametrize("op", ["sum", "avg", "min", "max"])
def test_time_blocked_window(op):
    S, T, W = 5, 64, 7
    vals = RNG.normal(size=(S, T)).astype(np.float32)
    mesh = make_mesh()
    out = np.asarray(time_blocked_window_sum(vals, window=W, op=op, mesh=mesh))
    ident = {"sum": 0.0, "avg": 0.0, "min": np.inf, "max": -np.inf}[op]
    red = {"sum": np.sum, "avg": np.sum, "min": np.min, "max": np.max}[op]
    for t in range(T):
        lo = t - W + 1
        pad = max(0, -lo)
        win = vals[:, max(lo, 0):t + 1]
        if pad and op in ("sum", "avg"):
            win = np.concatenate([np.zeros((S, pad), np.float32), win], axis=1)
        elif pad:
            win = np.concatenate([np.full((S, pad), ident, np.float32), win],
                                 axis=1)
        want = red(win, axis=1)
        if op == "avg":
            want = want / W
        np.testing.assert_allclose(out[:, t], want, rtol=1e-4, atol=1e-5)


def test_distributed_first_last_int_exact():
    # int values above 2**24 must survive first/last without a float32
    # round-trip (odd values > 2**24 are not f32-representable); kept inside
    # int32 so the path works in the production x64-off regime
    n, groups = 257, 3
    gids = RNG.integers(0, groups, n).astype(np.int32)
    mask = np.ones(n, bool)
    ts = np.arange(n).astype(np.int32)
    vals = (RNG.integers(2**26, 2**28, n).astype(np.int64) * 4 + 1)
    mesh = make_mesh()
    (last,), _ = distributed_grouped_aggregate(
        gids, mask, ts, (vals,), num_groups=groups, ops=("last",), mesh=mesh)
    for g in range(groups):
        rows = np.nonzero(gids == g)[0]
        assert int(np.asarray(last)[g]) == int(vals[rows[-1]])


def test_series_sharded_rebase_path_with_padding():
    # x64 off + epoch-ms int64 ts + series padding: the rebase-to-int32 path
    # must pad with an int32-safe sentinel (regression: OverflowError)
    import jax as _jax
    S, per = 13, 16
    sids = np.repeat(np.arange(S), per).astype(np.int32)
    base = 1_700_000_000_000  # epoch ms, far outside int32
    ts = (np.tile(np.arange(per) * 10_000, S) + base).astype(np.int64)
    vals = RNG.random(S * per).astype(np.float32)
    m = SeriesMatrix.build(sids, ts, vals, S)
    mesh = make_mesh()
    prev = _jax.config.jax_enable_x64
    _jax.config.update("jax_enable_x64", False)
    try:
        out, ok = series_sharded_range_aggregate(
            m.ts, m.values, m.lengths, base + 60_000, 30_000, 60_000,
            op="sum_over_time", nsteps=4, mesh=mesh)
    finally:
        _jax.config.update("jax_enable_x64", prev)
    end0 = base + 60_000
    for s in range(3):
        sel = (ts[sids == s] > end0 - 60_000) & (ts[sids == s] <= end0)
        if sel.any():
            assert bool(np.asarray(ok)[s, 0])
            np.testing.assert_allclose(np.asarray(out)[s, 0],
                                       vals[sids == s][sel].sum(), rtol=1e-4)


def test_time_blocked_window_validation():
    mesh = make_mesh()
    with pytest.raises(ValueError):
        time_blocked_window_sum(np.zeros((2, 30), np.float32), window=3,
                                mesh=mesh)  # 30 not divisible by block axis
