"""The driver's gate, run in-suite.

Rounds 1 and 2 failed the driver's multichip dryrun while 490 tests passed,
because the suite ran with x64 on and the dryrun runs with it off. This test
executes the driver entry points verbatim in the suite's (now x64-off)
regime so that divergence is structurally impossible.
"""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    avg_cpu, max_mem, cnt, counts = out
    assert avg_cpu.shape == (graft.NUM_GROUPS,)
    assert int(np.asarray(counts).sum()) == len(args[0])


def test_driver_dryrun_multichip_verbatim():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the conftest 8-device virtual CPU mesh")
    assert not jax.config.jax_enable_x64  # the regime the driver uses
    graft._dryrun_impl(8)
