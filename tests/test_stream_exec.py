"""Block-streamed cold scan (query/stream_exec.py).

The streamed path must produce byte-identical aggregate answers to the
cached device path and the CPU fallback oracle — including MVCC
overwrites, delete tombstones, NULLs, memtable+SST mixes, time filters,
field filters, and first/last — because a (series, ts) key lives in
exactly one time slice. Mirrors the reference's chunk-reader tests
(src/storage/src/chunk.rs) at the query level.
"""

import numpy as np
import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT, \
    DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.catalog import MemoryCatalogManager
from greptimedb_tpu.datatypes import data_type as dt
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.mito import MitoEngine
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.query import stream_exec, tpu_exec
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql import parse_sql
from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
from greptimedb_tpu.storage.write_batch import WriteBatch
from greptimedb_tpu.table import CreateTableRequest


@pytest.fixture(autouse=True)
def _force_device_dispatch(monkeypatch):
    monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
    # the latency-adaptive floor would route these small test tables to
    # the CPU path; pin it so the device (and streaming) paths execute
    monkeypatch.setattr(tpu_exec, "_dispatch_min_rows", lambda: 0)


def make_world(tmp_path, *, n=6000, seed=3, flushes=4):
    """A region whose rows span several SSTs + a live memtable, with
    overwrites, deletes, and NULLs."""
    rng = np.random.default_rng(seed)
    schema = Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
        ColumnSchema("mem", dt.FLOAT64),
    ])
    storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
    mito = MitoEngine(storage)
    cm = MemoryCatalogManager()
    table = mito.create_table(CreateTableRequest(
        "m", schema, primary_key_indices=[0]))
    cm.register_table(CAT, SCH, "m", table)
    region = next(iter(table.regions.values()))

    chunk = n // (flushes + 1)
    for part in range(flushes + 1):
        hosts = [f"h{int(h)}" for h in rng.integers(0, 7, chunk)]
        # overlapping time ranges across flushes → overlapping SSTs,
        # repeated (host, ts) keys → MVCC overwrites across files
        ts = rng.integers(0, n * 40, chunk).astype(np.int64)
        cpu = rng.random(chunk).round(4)
        mem = [None if i % 13 == 0 else float(i % 50)
               for i in range(chunk)]
        wb = WriteBatch(schema)
        wb.put({"host": hosts, "ts": ts.tolist(), "cpu": cpu.tolist(),
                "mem": mem})
        region.write(wb)
        if part % 2 == 1:
            mdel = int(rng.integers(1, 40))
            wb = WriteBatch(schema)
            wb.delete({"host": [f"h{int(h)}"
                                for h in rng.integers(0, 7, mdel)],
                       "ts": rng.integers(0, n * 40, mdel).tolist()})
            region.write(wb)
        if part < flushes:
            region.flush()
    return storage, QueryEngine(cm), table, region


QUERIES = [
    "SELECT host, count(*), sum(cpu), avg(cpu) FROM m GROUP BY host "
    "ORDER BY host",
    "SELECT host, min(cpu), max(cpu), stddev(cpu) FROM m GROUP BY host "
    "ORDER BY host",
    "SELECT host, count(mem), avg(mem) FROM m GROUP BY host ORDER BY host",
    "SELECT host, first(cpu), last(cpu) FROM m GROUP BY host ORDER BY host",
    "SELECT host, date_bin(INTERVAL '30 seconds', ts) AS b, avg(cpu) "
    "FROM m GROUP BY host, b ORDER BY host, b LIMIT 50",
    "SELECT count(*), avg(cpu) FROM m",
    "SELECT host, avg(cpu) FROM m WHERE ts >= 40000 AND ts < 180000 "
    "GROUP BY host ORDER BY host",
    "SELECT host, count(*) FROM m WHERE cpu > 0.5 GROUP BY host "
    "ORDER BY host",
    "SELECT host, avg(cpu) FROM m WHERE host != 'h3' GROUP BY host "
    "ORDER BY host",
]


def rows_of(engine, sql):
    out = engine.execute(parse_sql(sql), QueryContext())
    return out.batches[0].to_pylist() if out.batches else []


def approx_equal(a, b):
    assert len(a) == len(b), f"{len(a)} vs {len(b)} rows"
    for ra, rb in zip(a, b):
        assert list(ra) == list(rb)
        for k in ra:
            va, vb = ra[k], rb[k]
            if isinstance(va, float) and isinstance(vb, float):
                if np.isnan(va) and np.isnan(vb):
                    continue
                np.testing.assert_allclose(va, vb, rtol=1e-4, atol=1e-5)
            else:
                assert va == vb, f"{k}: {va} != {vb}"


class TestStreamedMatchesCached:
    @pytest.mark.parametrize("sql", QUERIES)
    def test_query(self, tmp_path, monkeypatch, sql):
        storage, engine, table, region = make_world(tmp_path)
        try:
            want = rows_of(engine, sql)          # cached device path
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [0])
            monkeypatch.setattr(stream_exec, "_SLICE_ROWS", [700])
            monkeypatch.setattr(stream_exec, "_ROW_BUCKET_MIN", 256)
            got = rows_of(engine, sql)           # streamed path
            approx_equal(got, want)
        finally:
            storage.close()

    def test_lean_path_engages_on_clean_bulk_region(self, tmp_path,
                                                    monkeypatch):
        """A bulk-loaded region (dup-free, delete-free, key-disjoint
        files, no memtable rows) must take the zero-copy chunk-frame
        fast path — and produce the same answers as the general merge
        path with the lean proof disabled."""
        rng = np.random.default_rng(11)
        schema = Schema([
            ColumnSchema("host", dt.STRING, nullable=False,
                         semantic_type=SemanticType.TAG),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("cpu", dt.FLOAT64),
        ])
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        mito = MitoEngine(storage)
        cm = MemoryCatalogManager()
        table = mito.create_table(CreateTableRequest(
            "m", schema, primary_key_indices=[0]))
        cm.register_table(CAT, SCH, "m", table)
        engine = QueryEngine(cm)
        try:
            hosts = 5
            per = 400
            for batch_no in range(3):           # 3 time-disjoint files
                ts = np.tile(np.arange(per, dtype=np.int64) * 100
                             + batch_no * per * 100, hosts)
                host = np.repeat(np.array(
                    [f"h{i}" for i in range(hosts)]), per).astype(object)
                table.bulk_load({"host": host, "ts": ts,
                                 "cpu": rng.random(len(ts)).round(4)})
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [0])
            monkeypatch.setattr(stream_exec, "_SLICE_ROWS", [per * hosts])
            monkeypatch.setattr(stream_exec, "_ROW_BUCKET_MIN", 256)
            lean_calls = []
            orig = stream_exec._lean_chunk_frames

            def spy(*a, **k):
                r = orig(*a, **k)
                lean_calls.append(r is not None)
                return r
            monkeypatch.setattr(stream_exec, "_lean_chunk_frames", spy)
            sqls = [
                "SELECT host, count(*), avg(cpu) FROM m GROUP BY host "
                "ORDER BY host",
                "SELECT host, date_bin(INTERVAL '30 seconds', ts) AS b, "
                "min(cpu), max(cpu) FROM m GROUP BY host, b "
                "ORDER BY host, b LIMIT 40",
                "SELECT host, avg(cpu) FROM m WHERE ts >= 5000 AND "
                "ts < 100000 GROUP BY host ORDER BY host",
            ]
            got = [rows_of(engine, s) for s in sqls]
            assert lean_calls and all(lean_calls), \
                "clean bulk region must take the lean chunk-frame path"
            # same answers with the lean proof disabled (general path)
            monkeypatch.setattr(stream_exec, "_slice_lean_proof",
                                lambda *a, **k: (False, False, []))
            want = [rows_of(engine, s) for s in sqls]
            for g, w in zip(got, want):
                approx_equal(g, w)
        finally:
            storage.close()

    def test_first_last_across_key_disjoint_boundary_sid(self, tmp_path,
                                                         monkeypatch):
        """Two key-disjoint files sharing a boundary series with
        non-monotonic time across the concat: the dedup-skip proof holds
        (no key has two versions), but positional first/last must NOT
        trust concat order — regression for the round-6 review find."""
        schema = Schema([
            ColumnSchema("host", dt.STRING, nullable=False,
                         semantic_type=SemanticType.TAG),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("cpu", dt.FLOAT64),
        ])
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        mito = MitoEngine(storage)
        cm = MemoryCatalogManager()
        table = mito.create_table(CreateTableRequest(
            "m", schema, primary_key_indices=[0]))
        cm.register_table(CAT, SCH, "m", table)
        engine = QueryEngine(cm)
        try:
            # file A: sids for h00..h10, LATE times; h10 written here
            # first (larger ts)
            hosts_a = [f"h{i:02d}" for i in range(11) for _ in range(4)]
            ts_a = [5000 + 100 * j for _ in range(11) for j in range(4)]
            table.bulk_load({"host": np.array(hosts_a, dtype=object),
                             "ts": np.array(ts_a, dtype=np.int64),
                             "cpu": np.array(
                                 [float(t) for t in ts_a])})
            # file B: sids h10..h20, EARLY times (disjoint from A's
            # window, so the key rectangles stay disjoint)
            hosts_b = [f"h{i:02d}" for i in range(10, 21)
                       for _ in range(4)]
            ts_b = [100 * j for _ in range(11) for j in range(4)]
            table.bulk_load({"host": np.array(hosts_b, dtype=object),
                             "ts": np.array(ts_b, dtype=np.int64),
                             "cpu": np.array(
                                 [float(t) for t in ts_b])})
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [0])
            # one big slice spanning both files → concat path, and
            # disable the chunk-frame reader to force the general path
            monkeypatch.setattr(stream_exec, "_SLICE_ROWS", [100000])
            monkeypatch.setattr(stream_exec, "_ROW_BUCKET_MIN", 256)
            monkeypatch.setattr(stream_exec, "_lean_chunk_frames",
                                lambda *a, **k: None)
            rows = rows_of(engine,
                           "SELECT host, first(cpu), last(cpu) FROM m "
                           "WHERE host = 'h10' GROUP BY host")
            assert len(rows) == 1
            r = rows[0]
            # h10's earliest row is ts=0 (file B), latest ts=5300 (file A)
            assert r["first(cpu)"] == 0.0, r
            assert r["last(cpu)"] == 5300.0, r
        finally:
            storage.close()

    def test_streaming_actually_streams(self, tmp_path, monkeypatch):
        storage, engine, table, region = make_world(tmp_path)
        try:
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [0])
            monkeypatch.setattr(stream_exec, "_SLICE_ROWS", [700])
            monkeypatch.setattr(stream_exec, "_ROW_BUCKET_MIN", 256)
            calls = []
            orig = stream_exec._load_slice

            def spy(*a, **k):
                calls.append(1)
                return orig(*a, **k)
            monkeypatch.setattr(stream_exec, "_load_slice", spy)
            rows_of(engine, "SELECT host, avg(cpu) FROM m GROUP BY host")
            assert len(calls) > 3, "expected multiple slices"
            # the huge region never entered the scan cache
            assert region.uid not in tpu_exec.SCAN_CACHE._entries
        finally:
            storage.close()

    def test_wide_region_streams_on_byte_budget(self, tmp_path,
                                                monkeypatch):
        """A region under the ROW threshold still streams when its
        estimated decoded bytes exceed half the scan-cache budget (one
        fat region must not blow residency — the cache never evicts its
        newest entry)."""
        storage, engine, table, region = make_world(tmp_path)
        try:
            # row threshold far above the region; byte budget tiny
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS",
                                [1 << 62])
            est = stream_exec.region_estimated_bytes(region)
            assert est > 0
            monkeypatch.setattr(tpu_exec.SCAN_CACHE, "budget_bytes", est)
            called = []
            orig = stream_exec.stream_region_moment_frames

            def spy(*a, **k):
                called.append(1)
                return orig(*a, **k)
            monkeypatch.setattr(stream_exec,
                                "stream_region_moment_frames", spy)
            rows_of(engine, "SELECT host, avg(cpu) FROM m GROUP BY host")
            assert called, "wide region must stream, not cache"
            assert region.uid not in tpu_exec.SCAN_CACHE._entries
        finally:
            storage.close()

    def test_memtable_only_region(self, tmp_path, monkeypatch):
        storage, engine, table, region = make_world(
            tmp_path, n=900, flushes=0)
        try:
            want = rows_of(engine, "SELECT host, avg(cpu) FROM m "
                                   "GROUP BY host ORDER BY host")
            monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [0])
            monkeypatch.setattr(stream_exec, "_SLICE_ROWS", [200])
            monkeypatch.setattr(stream_exec, "_ROW_BUCKET_MIN", 64)
            got = rows_of(engine, "SELECT host, avg(cpu) FROM m "
                                  "GROUP BY host ORDER BY host")
            approx_equal(got, want)
        finally:
            storage.close()


class TestScanCacheBudget:
    def test_lru_byte_eviction_and_rebuild(self, tmp_path):
        """N regions whose combined scans exceed the budget: LRU scans
        evict whole, steady residency stays under budget, and an evicted
        region rebuilds correctly on the next query."""
        from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
        schema = Schema([
            ColumnSchema("host", dt.STRING, nullable=False,
                         semantic_type=SemanticType.TAG),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("cpu", dt.FLOAT64),
        ])
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        regions = []
        n = 4000                                 # ~100KB+ per scan
        for i in range(6):
            r = storage.create_region(f"r{i}", schema)
            wb = WriteBatch(schema)
            wb.put({"host": [f"h{j % 4}" for j in range(n)],
                    "ts": (np.arange(n) * 100 + i).tolist(),
                    "cpu": np.full(n, float(i)).tolist()})
            r.write(wb)
            regions.append(r)
        cache = tpu_exec._ScanCache(capacity=100)
        one = cache.get(regions[0]).nbytes
        cache.configure(budget_bytes=int(one * 2.5))
        for r in regions:
            cache.get(r)
        assert cache.resident_bytes() <= int(one * 2.5)
        assert len(cache._entries) <= 2
        # most-recent survives; evicted region rebuilds with right data
        assert regions[5].uid in cache._entries
        scan0 = cache.get(regions[0])
        assert scan0.num_rows == n
        assert float(scan0.fields["cpu"][0][0]) == 0.0
        # LRU order: touching r0 made it most-recent; r5 still cached
        assert list(cache._entries)[-1] == regions[0].uid
        storage.close()


class TestSlicePlanning:
    def test_single_slice_under_budget(self):
        assert stream_exec._plan_slices([(0, 99, 50)], 100, None, None) == \
            [(0, 100)]

    def test_cuts_on_chunk_edges(self):
        stats = [(0, 9, 40), (10, 19, 40), (20, 29, 40)]
        slices = stream_exec._plan_slices(stats, 60, None, None)
        assert slices[0][0] == 0 and slices[-1][1] == 30
        # contiguous, non-overlapping cover
        for (a, b), (c, d) in zip(slices, slices[1:]):
            assert b == c and a < b
        assert len(slices) >= 2

    def test_clip_bounds(self):
        stats = [(0, 99, 100)]
        assert stream_exec._plan_slices(stats, 1000, 40, 60) == [(40, 60)]
        assert stream_exec._plan_slices(stats, 1000, 200, None) == []
        assert stream_exec._plan_slices([], 1000, None, None) == []

    def test_overlapping_chunks(self):
        stats = [(0, 50, 30), (25, 75, 30), (50, 99, 30)]
        slices = stream_exec._plan_slices(stats, 45, None, None)
        assert slices[0][0] == 0 and slices[-1][1] == 100
        for (a, b), (c, d) in zip(slices, slices[1:]):
            assert b == c
