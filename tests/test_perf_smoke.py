"""Perf-smoke: the bulk_ingest stage profiler end to end on ~1M rows.

Slow-marked so tier-1 stays inside its timeout; the driver's perf bars
are measured by benchmarks/cold_scan.py — this test only asserts the
profiling machinery BASELINE.md's breakdown is built from keeps working
(stages present, times positive, rows counted, merge() accumulates).
"""

import shutil
import tempfile

import numpy as np
import pytest

pytestmark = pytest.mark.slow


@pytest.mark.slow
def test_bulk_ingest_stage_profile_end_to_end():
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.storage.region import IngestProfile

    tmpdir = tempfile.mkdtemp(prefix="perfsmoke-")
    fe = None
    try:
        dn = DatanodeInstance(DatanodeOptions(
            data_home=tmpdir, register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        fe.do_query("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP "
                    "TIME INDEX, usage_user DOUBLE, "
                    "PRIMARY KEY(hostname))")
        table = fe.catalog.table("greptime", "public", "cpu")
        region = next(iter(table.regions.values()))
        assert region.last_ingest_profile is None

        rng = np.random.default_rng(0)
        hosts = 200
        per = 1_000_000 // hosts
        total = IngestProfile()
        for batch_no in range(2):
            ts = np.tile(np.arange(per, dtype=np.int64) * 1_000
                         + batch_no * per * 1_000, hosts)
            host = np.repeat(
                np.array([f"host_{i}" for i in range(hosts)]),
                per).astype(object)
            n = table.bulk_load({
                "hostname": host, "ts": ts,
                "usage_user": rng.random(len(ts)) * 100})
            assert n == hosts * per
            prof = region.last_ingest_profile
            assert prof is not None
            assert prof.rows == hosts * per
            assert prof.total_s > 0
            assert prof.mrows_per_s() > 0
            # the stages the BASELINE breakdown publishes
            for stage in ("coerce", "series_encode", "sort_check",
                          "field_prep", "chunk_plan", "sst_write",
                          "manifest"):
                assert stage in prof.stages, stage
                assert prof.stages[stage] >= 0
            # stage times must account for (almost all of) the wall:
            # a profiler that loses a stage under-reports forever
            assert sum(prof.stages.values()) >= prof.total_s * 0.8
            total.merge(prof)

        assert total.rows == 2 * hosts * per
        assert total.total_s > 0
        desc = total.describe()
        assert "sst_write" in desc and "Mrows/s" in desc

        # the profiled load must be queryable (the profiler must not
        # perturb the write path)
        out = fe.do_query("SELECT count(*) FROM cpu")
        if isinstance(out, list):
            out = out[0]
        batch = out.batches[0] if out.batches else None
        assert batch is not None
        assert batch.column(0).data[0] == 2 * hosts * per
    finally:
        if fe is not None:
            fe.shutdown()
        shutil.rmtree(tmpdir, ignore_errors=True)
