"""file-table-engine tests: immutable external CSV/JSON/Parquet tables.

Mirrors the reference's immutable-engine tests
(src/file-table-engine/src/engine/immutable.rs: create/open/drop/scan,
insert rejection) plus the SQL surface (CREATE EXTERNAL TABLE).
"""

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import InvalidArgumentsError, UnsupportedError
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    f.shutdown()


def _write_parquet(fe, key="ext/data.parquet"):
    table = pa.table({
        "ts": pa.array([1000, 2000, 3000], pa.timestamp("ms")),
        "host": ["a", "b", "a"],
        "v": [1.5, 2.5, 3.5]})
    import io
    buf = io.BytesIO()
    pq.write_table(table, buf)
    fe.datanode.store.write(key, buf.getvalue())
    return key


def _write_csv(fe, key="ext/data.csv"):
    fe.datanode.store.write(key, b"ts,host,v\n1,a,1.5\n2,b,2.5\n")
    return key


class TestExternalTables:
    def test_parquet_declared_schema(self, fe):
        _write_parquet(fe)
        fe.do_query("CREATE EXTERNAL TABLE logs (ts TIMESTAMP TIME INDEX,"
                    " host STRING, v DOUBLE)"
                    " WITH (location='ext/data.parquet')")
        out = fe.do_query("SELECT host, sum(v) AS s FROM logs"
                          " GROUP BY host ORDER BY host")[-1]
        rows = [tuple(r) for b in out.batches for r in b.rows()]
        assert rows == [("a", 5.0), ("b", 2.5)]

    def test_csv_schema_inference(self, fe):
        _write_csv(fe)
        fe.do_query("CREATE EXTERNAL TABLE c WITH"
                    " (location='ext/data.csv', format='csv')")
        out = fe.do_query("SELECT count(*) FROM c")[-1]
        assert next(out.batches[0].rows())[0] == 2

    def test_insert_rejected(self, fe):
        _write_csv(fe)
        fe.do_query("CREATE EXTERNAL TABLE imm WITH"
                    " (location='ext/data.csv', format='csv')")
        with pytest.raises(UnsupportedError, match="insert"):
            fe.do_query("INSERT INTO imm VALUES (3, 'c', 3.5)")

    def test_survives_restart(self, fe, tmp_path):
        _write_parquet(fe)
        fe.do_query("CREATE EXTERNAL TABLE persisted (ts TIMESTAMP TIME"
                    " INDEX, host STRING, v DOUBLE)"
                    " WITH (location='ext/data.parquet')")
        fe.shutdown()
        dn2 = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn2.start()
        fe2 = FrontendInstance(dn2)
        fe2.start()
        out = fe2.do_query("SELECT count(*) FROM persisted")[-1]
        assert next(out.batches[0].rows())[0] == 3
        fe2.shutdown()

    def test_drop_keeps_data_file(self, fe):
        key = _write_csv(fe)
        fe.do_query("CREATE EXTERNAL TABLE dropme WITH"
                    " (location='ext/data.csv', format='csv')")
        fe.do_query("DROP TABLE dropme")
        assert fe.catalog.table("greptime", "public", "dropme") is None
        assert fe.datanode.store.exists(key)     # data is not ours

    def test_missing_location_errors(self, fe):
        with pytest.raises(InvalidArgumentsError, match="location"):
            fe.do_query("CREATE EXTERNAL TABLE nowhere (ts TIMESTAMP"
                        " TIME INDEX, v DOUBLE) WITH (format='csv')")

    def test_missing_declared_column_errors(self, fe):
        _write_csv(fe)
        fe.do_query("CREATE EXTERNAL TABLE misdeclared (ts TIMESTAMP"
                    " TIME INDEX, nope DOUBLE)"
                    " WITH (location='ext/data.csv', format='csv')")
        with pytest.raises(InvalidArgumentsError, match="nope"):
            fe.do_query("SELECT * FROM misdeclared")

    def test_show_tables_includes_external(self, fe):
        _write_csv(fe)
        fe.do_query("CREATE EXTERNAL TABLE shown WITH"
                    " (location='ext/data.csv', format='csv')")
        out = fe.do_query("SHOW TABLES")[-1]
        names = [r[0] for b in out.batches for r in b.rows()]
        assert "shown" in names
