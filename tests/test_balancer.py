"""Elastic region management tests (ISSUE 9).

The meta balancer (meta/balancer.py) drives split / migrate / rebalance
as resumable state machines persisted in the meta KV; datanode mailbox
handlers execute idempotent steps and ack back. These tests drive the
whole loop cooperatively (balancer.tick() + heartbeat pumping — the
test-suite twin of the background RepeatedTask) over a SHARED object
store, the elastic-deployment shape test_failover.py established.
"""

import threading
import time

import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.client import LocalDatanodeClient
from greptimedb_tpu.common import failpoint
from greptimedb_tpu.common.failpoint import SimulatedCrash
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import (
    GreptimeError, InvalidArgumentsError, StaleRouteError)
from greptimedb_tpu.frontend.distributed import DistInstance
from greptimedb_tpu.meta import MetaClient, MetaSrv, Peer
from greptimedb_tpu.meta.kv import FileKv, MemKv
from greptimedb_tpu.storage.object_store import FsObjectStore

FULL = f"{CAT}.{SCH}.ha"

DDL = """
CREATE TABLE ha (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))
"""


class Cluster:
    """In-process N-datanode cluster over one shared object store with a
    cooperative balancer pump."""

    def __init__(self, tmp_path, nodes=(1, 2), kv=None,
                 lease_secs=3600.0):
        self.tmp_path = tmp_path
        self.shared = FsObjectStore(str(tmp_path / "shared_store"))
        self.srv = MetaSrv(kv if kv is not None else MemKv(),
                           datanode_lease_secs=lease_secs)
        self.srv.balancer.resend_interval_s = 0.0
        self.meta = MetaClient(self.srv)
        self.datanodes = {}
        self.clients = {}
        for i in nodes:
            self._start_datanode(i)
        self.fe = DistInstance(self.meta, self.clients)

    def _start_datanode(self, i):
        dn = DatanodeInstance(
            DatanodeOptions(data_home=str(self.tmp_path / f"dn{i}"),
                            node_id=i, register_numbers_table=False),
            store=self.shared)
        dn.start()
        dn.attach_meta(self.meta)
        self.datanodes[i] = dn
        self.clients[i] = LocalDatanodeClient(dn)
        self.srv.register_datanode(Peer(i, f"dn{i}"))
        self.srv.handle_heartbeat(i)
        return dn

    def hard_kill(self, i):
        """Emulate kill -9: regions stop answering mid-state, nothing
        flushes, nothing acks. (The process-level twin lives in
        tests/test_cluster.py.)"""
        dn = self.datanodes[i]
        for region in dn.storage.list_regions().values():
            with region._writer_lock:
                region.closed = True
                region.wal.close()
        return dn

    def restart_datanode(self, i):
        """Reopen the killed node from its durable state (WAL replay +
        fence markers) and swap it into the live cluster."""
        dn = self._start_datanode(i)
        return dn

    def restart_meta(self):
        """Meta crash + restart over the SAME durable KV: the balancer
        reloads its __balancer/ op docs and resumes."""
        kv = self.srv.kv
        self.srv = MetaSrv(kv, datanode_lease_secs=3600.0)
        self.srv.balancer.resend_interval_s = 0.0
        self.meta = MetaClient(self.srv)
        for i in self.datanodes:
            self.srv.register_datanode(Peer(i, f"dn{i}"))
            self.srv.handle_heartbeat(i)
            self.datanodes[i].attach_meta(self.meta)
        self.fe = DistInstance(self.meta, self.clients)

    def pump(self, rounds=16, between=None):
        """tick + heartbeat-mailbox delivery until no ops remain."""
        for _ in range(rounds):
            self.srv.balancer.tick()
            for i, dn in list(self.datanodes.items()):
                resp = self.srv.handle_heartbeat(i)
                for msg in resp.mailbox:
                    dn._handle_mailbox(msg)
            if between is not None:
                between()
            if not self.srv.balancer.ops():
                return True
        return not self.srv.balancer.ops()

    def query_one(self, sql):
        out = self.fe.do_query(sql)[-1]
        return next(out.batches[0].rows())

    def scan_keys(self):
        out = self.fe.do_query("SELECT host, ts FROM ha")[-1]
        keys = [tuple(r) for b in out.batches for r in b.rows()]
        return keys

    def shutdown(self):
        for dn in self.datanodes.values():
            try:
                dn.shutdown()
            except Exception:  # noqa: BLE001 — crashed twins may be
                pass           # half-closed already (test teardown)


@pytest.fixture()
def cluster(tmp_path):
    failpoint.reset()
    c = Cluster(tmp_path)
    yield c
    failpoint.reset()
    c.shutdown()


def _setup_table(c, rows=10):
    c.fe.do_query(DDL)
    vals = ", ".join(f"('h{i % 10}', {1000 + i}, {float(i)})"
                     for i in range(rows))
    c.fe.do_query(f"INSERT INTO ha VALUES {vals}")


def _region0_owner(c):
    route = c.srv.table_route(FULL)
    return next(rr.leader.id for rr in route.region_routes
                if rr.region_number == 0)


class TestRuleRefinement:
    """Satellite 1: refinement round-trips through the mito codec and
    leaves the original rule untouched (callers assume immutability)."""

    def test_refine_and_codec_roundtrip(self):
        from greptimedb_tpu.mito.engine import (
            _deserialize_rule, _serialize_rule)
        from greptimedb_tpu.partition.rule import (
            MAXVALUE, RangePartitionRule, refine_range_rule)
        rule = RangePartitionRule("host", ["h5", MAXVALUE], [0, 1])
        refined = refine_range_rule(rule, 1, "h8", [4, 5])
        # original untouched (find_regions_by_filters callers + SHOW
        # CREATE TABLE hold references to the old lists)
        assert rule.bounds == ["h5", MAXVALUE]
        assert rule.regions == [0, 1]
        assert refined.bounds == ["h5", "h8", MAXVALUE]
        assert refined.regions == [0, 4, 5]
        back = _deserialize_rule(_serialize_rule(refined))
        assert back.bounds == refined.bounds
        assert back.regions == refined.regions
        # refined rule routes rows into the children
        assert refined.find_region("h6") == 4
        assert refined.find_region("h9") == 5
        assert refined.find_region("h1") == 0
        # pruning works over non-contiguous region numbers
        from greptimedb_tpu.sql import ast
        got = refined.find_regions_by_filters(
            [ast.BinaryOp(">=", ast.Column("host"),
                          ast.Literal("h8", "string"))])
        assert got == [5]

    def test_refine_range_columns_single(self):
        from greptimedb_tpu.mito.engine import (
            _deserialize_rule, _serialize_rule)
        from greptimedb_tpu.partition.rule import (
            MAXVALUE, RangeColumnsPartitionRule, refine_range_rule)
        rule = RangeColumnsPartitionRule(["host"],
                                         [("h5",), (MAXVALUE,)], [0, 1])
        refined = refine_range_rule(rule, 0, "h2", [2, 3])
        assert refined.bounds == [("h2",), ("h5",), (MAXVALUE,)]
        assert refined.regions == [2, 3, 1]
        back = _deserialize_rule(_serialize_rule(refined))
        assert back.bounds == refined.bounds

    def test_refine_rejections(self):
        from greptimedb_tpu.partition.rule import (
            MAXVALUE, HashPartitionRule, RangePartitionRule,
            refine_range_rule)
        rule = RangePartitionRule("host", ["h5", MAXVALUE], [0, 1])
        with pytest.raises(ValueError, match="not below"):
            refine_range_rule(rule, 0, "h7", [2, 3])   # above the bound
        with pytest.raises(ValueError, match="not above"):
            refine_range_rule(rule, 1, "h5", [2, 3])   # == lower bound
        with pytest.raises(ValueError, match="hash"):
            refine_range_rule(HashPartitionRule(["host"], [0, 1]),
                              0, "x", [2, 3])
        with pytest.raises(ValueError, match="not in rule"):
            refine_range_rule(rule, 9, "h2", [2, 3])

    def test_show_create_table_renders_refined_rule(self, cluster):
        """SHOW CREATE TABLE re-pulls the rule post-split (it used to
        render the stale CREATE-time clause forever)."""
        c = cluster
        _setup_table(c)
        c.fe.do_query("ADMIN SPLIT REGION ha 1 AT 'h7'")
        assert c.pump()
        out = c.fe.do_query("SHOW CREATE TABLE ha")[-1]
        text = out.batches[0].to_pydict()["Create Table"][0]
        assert "LESS THAN ('h5')" in text
        assert "LESS THAN ('h7')" in text
        assert "LESS THAN (MAXVALUE)" in text


class TestMigrate:
    def test_migrate_moves_data_and_releases_source(self, cluster):
        c = cluster
        _setup_table(c)
        src = _region0_owner(c)
        dst = 2 if src == 1 else 1
        out = c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")[-1]
        op_row = next(out.batches[0].rows())
        assert op_row[1] == "migrate"
        assert c.pump()
        done = c.srv.balancer.done_ops()
        assert [o["state"] for o in done] == ["done"], done
        route = c.srv.table_route(FULL)
        assert next(rr.leader.id for rr in route.region_routes
                    if rr.region_number == 0) == dst
        assert route.version == 1
        # the source node no longer hosts region 0 and its WAL is gone
        src_table = c.datanodes[src].catalog.table(CAT, SCH, "ha")
        if src_table is not None:
            assert 0 not in src_table.regions
        dst_table = c.datanodes[dst].catalog.table(CAT, SCH, "ha")
        assert 0 in dst_table.regions
        # zero acked loss/dup through the OLD frontend (stale route
        # refresh is transparent)
        assert c.query_one("SELECT count(*) AS c, sum(v) AS s FROM ha") \
            == (10, 45.0)
        c.fe.do_query("INSERT INTO ha VALUES ('h0', 99999, 42.0)")
        assert c.query_one("SELECT count(*) AS c FROM ha") == (11,)

    def test_wal_tail_ships_unflushed_acked_rows(self, cluster):
        """Rows acked between the snapshot flush and the fence live only
        in the source WAL — the shipped tail must carry them."""
        c = cluster
        _setup_table(c)
        src = _region0_owner(c)
        dst = 2 if src == 1 else 1
        c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")
        seq = [0]

        def tail_feeder():
            # runs between pump rounds WHILE the op still reads
            # "snapshot" (flush done, fence not yet sent): rows land in
            # the source WAL only, so only the shipped tail carries them
            op = (c.srv.balancer.ops() or [{}])[0]
            if op.get("state") == "snapshot":
                seq[0] += 1
                c.fe.do_query(
                    f"INSERT INTO ha VALUES ('h1', {50_000 + seq[0]}, "
                    f"1.5)")
        assert c.pump(between=tail_feeder)
        assert seq[0] > 0, "feeder never ran inside the handoff window"
        done = c.srv.balancer.done_ops()[0]
        assert done["state"] == "done"
        assert done["wal_tail"], "tail should have shipped rows"
        got = c.query_one("SELECT count(*) AS c FROM ha")
        assert got == (10 + seq[0],)

    def test_fenced_region_rejects_writes_typed(self, tmp_path):
        from greptimedb_tpu.storage.engine import (
            EngineConfig, StorageEngine)
        from greptimedb_tpu.datatypes import data_type as dt
        from greptimedb_tpu.datatypes.schema import (
            ColumnSchema, Schema, SemanticType)
        from greptimedb_tpu.storage.write_batch import WriteBatch
        eng = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        schema = Schema([
            ColumnSchema("host", dt.STRING,
                         semantic_type=SemanticType.TAG, nullable=False),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND,
                         semantic_type=SemanticType.TIMESTAMP,
                         nullable=False),
            ColumnSchema("v", dt.FLOAT64),
        ])
        region = eng.create_region("fence_t", schema)
        wb = WriteBatch(schema)
        wb.put({"host": ["a"], "ts": [1], "v": [1.0]})
        region.write(wb)
        region.fence()
        wb2 = WriteBatch(schema)
        wb2.put({"host": ["a"], "ts": [2], "v": [2.0]})
        with pytest.raises(StaleRouteError):
            region.write(wb2)
        with pytest.raises(StaleRouteError):
            region.bulk_ingest({"host": ["a"], "ts": [3], "v": [3.0]})
        # a fenced region never flushes (the shared dir belongs to the
        # adopting node after the snapshot)
        assert region.flush() == []
        region.unfence()
        region.write(wb2)
        eng.close()

    def test_fence_marker_survives_restart(self, tmp_path):
        """A crashed-and-reopened old owner must come back FENCED — an
        unfenced resurrection could ack writes the target never sees."""
        from greptimedb_tpu.storage.engine import (
            EngineConfig, StorageEngine)
        from greptimedb_tpu.datatypes import data_type as dt
        from greptimedb_tpu.datatypes.schema import (
            ColumnSchema, Schema, SemanticType)
        schema = Schema([
            ColumnSchema("host", dt.STRING,
                         semantic_type=SemanticType.TAG, nullable=False),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND,
                         semantic_type=SemanticType.TIMESTAMP,
                         nullable=False),
        ])
        eng = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        region = eng.create_region("fence_r", schema)
        region.fence()
        eng.close()
        eng2 = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        reopened = eng2.open_region("fence_r", schema)
        assert reopened.fenced
        reopened.unfence()
        eng2.close()

    def test_admin_validation_errors(self, cluster):
        c = cluster
        _setup_table(c)
        with pytest.raises(InvalidArgumentsError, match="not in the route"):
            c.fe.do_query("ADMIN MIGRATE REGION ha 9 TO 2")
        with pytest.raises(InvalidArgumentsError, match="not registered"):
            c.fe.do_query("ADMIN MIGRATE REGION ha 0 TO 42")
        src = _region0_owner(c)
        with pytest.raises(InvalidArgumentsError, match="already on"):
            c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {src}")
        # one in-flight op per table
        dst = 2 if src == 1 else 1
        c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")
        with pytest.raises(InvalidArgumentsError, match="in-flight"):
            c.fe.do_query("ADMIN SPLIT REGION ha 1 AT 'h7'")
        # region_peers surfaces the in-flight operation state
        row = next(p for p in c.srv.region_peers()
                   if p["region_number"] == 0)
        assert row["operation"] == "migrate:snapshot"
        assert row["op_id"].startswith("bop-")
        assert c.pump()

    def test_standalone_rejects_admin(self, tmp_path):
        from greptimedb_tpu.errors import UnsupportedError
        from greptimedb_tpu.frontend import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "sa"),
            register_numbers_table=False))
        fe = FrontendInstance(dn)
        fe.start()
        try:
            with pytest.raises(UnsupportedError, match="distributed"):
                fe.do_query("ADMIN REBALANCE")
            with pytest.raises(InvalidArgumentsError, match="balancer"):
                fe.do_query("SET balancer_split_size_bytes = 1000")
        finally:
            fe.shutdown()


class TestSplit:
    def test_split_at_explicit_value(self, cluster):
        c = cluster
        _setup_table(c)
        before = c.query_one("SELECT count(*) AS c, sum(v) AS s FROM ha")
        c.fe.do_query("ADMIN SPLIT REGION ha 1 AT 'h7'")
        assert c.pump()
        done = c.srv.balancer.done_ops()
        assert [o["state"] for o in done] == ["done"], done
        route = c.srv.table_route(FULL)
        regions = sorted(rr.region_number for rr in route.region_routes)
        assert regions == [0, 2, 3]
        # answers unchanged across the refined layout
        assert c.query_one(
            "SELECT count(*) AS c, sum(v) AS s FROM ha") == before
        # point query prunes to ONE child region
        assert c.query_one(
            "SELECT count(*) AS c FROM ha WHERE host >= 'h7'") == (3,)
        # writes route into the children
        c.fe.do_query("INSERT INTO ha VALUES ('h8', 77777, 1.0)")
        assert c.query_one(
            "SELECT count(*) AS c FROM ha WHERE host >= 'h7'") == (4,)
        # the parent region's storage is gone (no duplicate copies)
        keys = c.scan_keys()
        assert len(keys) == len(set(keys)) == 11

    def test_split_probes_median_when_no_at(self, cluster):
        c = cluster
        _setup_table(c)
        c.fe.do_query("ADMIN SPLIT REGION ha 1")
        assert c.pump()
        done = c.srv.balancer.done_ops()[0]
        assert done["state"] == "done"
        assert done["at_value"] is not None     # probed from the data
        before_keys = set(c.scan_keys())
        assert len(before_keys) == 10
        # both children non-empty (the probe guarantees a spread)
        route = c.srv.table_route(FULL)
        owner = {rr.region_number: rr.leader.id
                 for rr in route.region_routes}
        kids = [rn for rn in owner if rn not in (0, 1)]
        assert len(kids) == 2

    def test_probe_pins_before_copy_and_redelivery_is_idempotent(
            self, cluster):
        """A probed split pins the value in the op doc BEFORE any copy
        (a re-probe under ingest could move the median and duplicate
        rows across children), and a re-delivered prepare with the
        pinned value re-copies idempotently."""
        c = cluster
        _setup_table(c)
        route = c.srv.table_route(FULL)
        owner = next(rr.leader.id for rr in route.region_routes
                     if rr.region_number == 1)
        dn = c.datanodes[owner]
        # prepare without a pinned value is refused at the engine level
        with pytest.raises(InvalidArgumentsError, match="pinned"):
            dn.mito.prepare_split(CAT, SCH, "ha", 1, [2, 3], None)
        c.fe.do_query("ADMIN SPLIT REGION ha 1")
        # round 1 sends + answers the probe; round 2's tick consumes the
        # ack and PINS the value while the op still reads "prepare"
        c.pump(rounds=2)
        op = c.srv.balancer.ops()[0]
        assert op["state"] == "prepare" and op["at_value"] is not None
        pinned = op["at_value"]
        # re-deliver the prepare (lost-ack shape): same boundary, and
        # the final table has no duplicates
        seq, copied1 = dn.mito.prepare_split(CAT, SCH, "ha", 1, [2, 3],
                                             pinned)
        seq2, copied2 = dn.mito.prepare_split(CAT, SCH, "ha", 1, [2, 3],
                                              pinned)
        assert copied1 == copied2          # same rows, same boundary
        assert c.pump()
        assert c.srv.balancer.done_ops()[0]["at_value"] == pinned
        keys = c.scan_keys()
        assert len(keys) == len(set(keys)) == 10

    def test_split_under_ingest_keeps_delta(self, cluster):
        """Rows acked after the phase-1 snapshot copy must reach the
        children through the fenced catch-up copy."""
        c = cluster
        _setup_table(c)
        c.fe.do_query("ADMIN SPLIT REGION ha 1 AT 'h7'")
        fed = [0]

        def feeder():
            # only while the op still reads "prepare" (phase-1 copy done,
            # fence not yet sent): the fenced catch-up copy must carry
            # these rows into the children
            op = (c.srv.balancer.ops() or [{}])[0]
            if op.get("state") == "prepare":
                fed[0] += 1
                c.fe.do_query(
                    f"INSERT INTO ha VALUES ('h9', {60_000 + fed[0]}, "
                    f"9.5)")
        assert c.pump(between=feeder)
        assert fed[0] > 0
        assert c.srv.balancer.done_ops()[0]["state"] == "done"
        got = c.query_one("SELECT count(*) AS c FROM ha")
        assert got == (10 + fed[0],)
        keys = c.scan_keys()
        assert len(keys) == len(set(keys))


class TestRebalanceAndAuto:
    def test_admin_rebalance_levels_the_cluster(self, cluster):
        c = cluster
        _setup_table(c)
        # move everything onto one node first
        src = _region0_owner(c)
        dst = 2 if src == 1 else 1
        c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")
        assert c.pump()
        out = c.fe.do_query("ADMIN REBALANCE")[-1]
        assert out.batches[0].num_rows == 1    # one move enqueued
        assert c.pump()
        route = c.srv.table_route(FULL)
        owners = {rr.leader.id for rr in route.region_routes}
        assert owners == {1, 2}                # spread back to both
        assert c.query_one(
            "SELECT count(*) AS c FROM ha") == (10,)
        # balanced cluster: rebalance is a no-op
        out = c.fe.do_query("ADMIN REBALANCE")[-1]
        assert out.batches[0].num_rows == 0

    def test_auto_split_on_heat_threshold(self, cluster):
        """A region crossing the configured size threshold auto-splits
        on the next balancer tick (heartbeat-fed region heat)."""
        from greptimedb_tpu.meta import DatanodeStat
        c = cluster
        _setup_table(c, rows=40)
        c.fe.do_query("SET balancer_split_size_bytes = 1")
        assert c.srv.balancer.split_size_bytes == 1
        # feed a FULL stat beat so meta has region heat for the owner
        route = c.srv.table_route(FULL)
        tid = route.table_id
        owner1 = next(rr.leader.id for rr in route.region_routes
                      if rr.region_number == 1)
        stat = DatanodeStat(
            region_count=1, approximate_rows=1000,
            approximate_bytes=1 << 20,
            region_stats=[{"region": f"{tid}_{1:010d}", "rows": 1000,
                           "size_bytes": 1 << 20}])
        c.srv.handle_heartbeat(owner1, stat)
        assert c.pump(rounds=24)
        done = c.srv.balancer.done_ops()
        assert done and done[0]["kind"] == "split"
        assert done[0]["auto"] is True
        assert done[0]["state"] == "done"
        # data survives the auto-split
        assert c.query_one("SELECT count(*) AS c FROM ha") == (40,)

    def test_auto_disabled_knob(self, cluster):
        c = cluster
        _setup_table(c)
        c.fe.do_query("SET balancer_enabled = 0")
        assert c.srv.balancer.enabled is False
        summary = c.srv.balancer.tick()
        assert summary["auto_splits"] == 0 and summary["auto_moves"] == 0
        c.fe.do_query("SET balancer_enabled = 1")


#: the four balancer failpoints of satellite 2, with the component that
#: crashes at each (source datanode, source datanode, target datanode,
#: the metasrv itself)
TORTURE_POINTS = [
    ("balancer_snapshot_upload", "source"),
    ("balancer_handoff_fence", "source"),
    ("balancer_wal_tail_replay", "target"),
    ("balancer_route_commit", "meta"),
]


class TestMigrationTorture:
    """Satellite 2: crash at each balancer step under sustained ingest —
    no acked-row loss, no duplication, the operation resumes (or rolls
    back) after the crashed component restarts."""

    @pytest.mark.parametrize("point,component",
                             TORTURE_POINTS,
                             ids=[p for p, _ in TORTURE_POINTS])
    def test_crash_at_step_resumes_without_loss(self, tmp_path, point,
                                                component, request):
        failpoint.reset()
        c = Cluster(tmp_path)
        request.addfinalizer(failpoint.reset)
        request.addfinalizer(c.shutdown)
        _setup_table(c)
        src = _region0_owner(c)
        dst = 2 if src == 1 else 1
        acked = set(c.scan_keys())
        stop = threading.Event()
        errors = []

        def ingest():
            n = 0
            while not stop.is_set():
                n += 1
                key = ("h1", 100_000 + n)
                try:
                    c.fe.do_query(
                        f"INSERT INTO ha VALUES ('h1', {key[1]}, 1.0)")
                    acked.add(key)
                except (GreptimeError, Exception) as e:  # noqa: BLE001
                    # a write failing INSIDE the crash window is legal
                    # (it was never acked); anything else is recorded
                    errors.append(e)
                # cadence must beat the snapshot→fence window (~10ms on
                # a slow box): the wal_tail_replay point only fires if
                # at least one write lands between the snapshot flush
                # and the fence, so a 10ms sleep made capture a coin
                # flip that depended on how warmed-up the process was
                time.sleep(0.002)

        t = threading.Thread(target=ingest, daemon=True)
        t.start()
        try:
            c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")
            failpoint.configure(point, "crash")
            crashed = False
            try:
                c.pump(rounds=30)
            except SimulatedCrash:
                crashed = True
            assert crashed, f"failpoint {point} never fired"
            failpoint.configure(point, "off")
            # restart the crashed component from durable state
            if component == "source":
                c.hard_kill(src)
                c.restart_datanode(src)
            elif component == "target":
                c.hard_kill(dst)
                c.restart_datanode(dst)
            else:
                c.restart_meta()
            assert c.pump(rounds=40), \
                f"op never finished: {c.srv.balancer.ops()}"
        finally:
            stop.set()
            t.join(timeout=30)

        done = c.srv.balancer.done_ops()
        assert done, "op vanished"
        final = done[-1]
        # the op either resumed to completion or rolled back cleanly —
        # and in BOTH cases every acked row is exactly-once readable
        assert final["state"] in ("done", "failed"), final
        if final["state"] == "done":
            route = c.srv.table_route(FULL)
            assert next(rr.leader.id for rr in route.region_routes
                        if rr.region_number == 0) == dst
        # let any straggler insert retries settle, then check integrity
        keys = c.scan_keys()
        assert len(keys) == len(set(keys)), "duplicated rows"
        missing = acked - set(keys)
        assert not missing, f"lost {len(missing)} acked rows: " \
                            f"{sorted(missing)[:5]}"
        # no region manifest references a deleted SST (crash-safety of
        # the shared-store handoff)
        for dn in c.datanodes.values():
            for region in dn.storage.list_regions().values():
                if region.closed:
                    continue
                referenced = {f.file_name for f in
                              region.version_control.current.ssts
                              .all_files()}
                on_disk = {k.rsplit("/", 1)[-1] for k in
                           c.shared.list(f"{region.name}/sst/")}
                assert referenced <= on_disk, \
                    f"{region.name}: dangling {referenced - on_disk}"

    def test_meta_restart_mid_migration_resumes_from_kv(self, tmp_path):
        """A FileKv-backed metasrv dies after the fence; the restarted
        one reloads the op (WAL tail included) and completes it."""
        failpoint.reset()
        kv = FileKv(str(tmp_path / "meta.kv"))
        c = Cluster(tmp_path, kv=kv)
        try:
            _setup_table(c)
            src = _region0_owner(c)
            dst = 2 if src == 1 else 1
            c.fe.do_query(f"ADMIN MIGRATE REGION ha 0 TO {dst}")
            # advance exactly until the tail is captured (state: open)
            for _ in range(20):
                ops = c.srv.balancer.ops()
                if ops and ops[0]["state"] == "open":
                    break
                c.pump(rounds=1)
            ops = c.srv.balancer.ops()
            assert ops and ops[0]["state"] == "open", ops
            # meta "crashes"; a new one over the same FileKv resumes
            c.restart_meta()
            assert c.srv.balancer.ops(), "op lost across meta restart"
            assert c.pump(rounds=30)
            assert c.srv.balancer.done_ops()[-1]["state"] == "done"
            route = c.srv.table_route(FULL)
            assert next(rr.leader.id for rr in route.region_routes
                        if rr.region_number == 0) == dst
            assert c.query_one("SELECT count(*) AS c, sum(v) AS s "
                               "FROM ha") == (10, 45.0)
        finally:
            c.shutdown()


class TestElasticFailover:
    def test_dead_node_regions_replaced_and_queries_answer(self, tmp_path):
        """4-datanode cluster: a node dies; failover re-places its
        regions without operator action and queries keep answering —
        region_peers reflects the new placement."""
        c = Cluster(tmp_path, nodes=(1, 2, 3, 4), lease_secs=5.0)
        try:
            c.fe.do_query("""
CREATE TABLE ha (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h3'),
  PARTITION r1 VALUES LESS THAN ('h6'),
  PARTITION r2 VALUES LESS THAN ('h9'),
  PARTITION r3 VALUES LESS THAN (MAXVALUE))
""")
            vals = ", ".join(f"('h{i % 10}', {1000 + i}, 1.0)"
                             for i in range(40))
            c.fe.do_query(f"INSERT INTO ha VALUES {vals}")
            c.fe.catalog.table(CAT, SCH, "ha").flush()
            victim = _region0_owner(c)
            c.hard_kill(victim)
            # survivors keep beating; the victim goes silent past 2x its
            # lease (explicit `now` keeps this instant, test_failover
            # style)
            t0 = time.time()
            for t in range(1, 31):
                for i in c.datanodes:
                    if i != victim:
                        c.srv.handle_heartbeat(i, now=t0 + t)
            moves = c.srv.failover_check(now=t0 + 30)
            assert moves and all(m["from"] == victim for m in moves)
            for i, dn in c.datanodes.items():
                if i == victim:
                    continue
                resp = c.srv.handle_heartbeat(i, now=t0 + 31)
                for msg in resp.mailbox:
                    dn._handle_mailbox(msg)
            # queries answer across the re-placed layout (stale-route
            # refresh reroutes the old frontend)
            assert c.query_one("SELECT count(*) AS c FROM ha") == (40,)
            peers = c.srv.region_peers(now=t0 + 31)
            assert all(p["peer_id"] != victim for p in peers)
            assert {p["region_number"] for p in peers} == {0, 1, 2, 3}
            route = c.srv.table_route(FULL)
            assert route.version >= 1
        finally:
            c.shutdown()
