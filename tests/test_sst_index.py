"""Per-SST secondary index (ISSUE 13): differential + degrade sweep.

The contract under test: index-on and index-off answers are IDENTICAL
across predicate shapes (the sid-set is a pruning superset, never a
filter), bloom false positives are harmless, pre-upgrade files (no
sidecar) stay scannable, and a corrupt or unreadable sidecar degrades
to stats-only pruning with `greptime_sst_index_degrade_total` counting
it — never a failed query.
"""

import os

import numpy as np
import pytest

from greptimedb_tpu.common import failpoint as fp
from greptimedb_tpu.datatypes import Schema
from greptimedb_tpu.datatypes.data_type import (FLOAT64, STRING,
                                                TIMESTAMP_MILLISECOND)
from greptimedb_tpu.datatypes.schema import ColumnSchema, SemanticType
from greptimedb_tpu.storage import index as sst_index
from greptimedb_tpu.storage.index import (SstIndex, SstIndexCorrupt,
                                          configure_sst_index,
                                          index_file_name,
                                          sst_index_enabled)
from greptimedb_tpu.storage.object_store import FsObjectStore
from greptimedb_tpu.storage.region import Region, RegionDescriptor
from greptimedb_tpu.storage.write_batch import WriteBatch


def _counter_value(name: str) -> float:
    from prometheus_client import REGISTRY
    return REGISTRY.get_sample_value(name) or 0.0


@pytest.fixture(autouse=True)
def _index_on():
    """Every test starts (and leaves the process) with the index tier
    enabled — the default production state."""
    configure_sst_index(enabled=True)
    yield
    configure_sst_index(enabled=True)
    fp.clear_all()


# ---------------------------------------------------------------------------
# unit: bloom + row-group summary + codec
# ---------------------------------------------------------------------------

class TestSstIndexUnit:
    def test_membership_and_fp_rate(self):
        rng = np.random.default_rng(3)
        members = np.unique(rng.integers(0, 1 << 30, 4000))
        idx = SstIndex.build(np.sort(members), row_group_size=1 << 20)
        assert idx.may_contain(members).all()
        probes = np.setdiff1d(rng.integers(0, 1 << 30, 20000), members)
        fp_rate = idx.may_contain(probes).mean()
        assert fp_rate < 0.05, f"bloom fp rate {fp_rate:.3f}"

    def test_row_group_summary_exact(self):
        # rows sorted by sid; groups of 4: [1,1,3,3] [3,7,7,7] [9,9]
        sids = np.array([1, 1, 3, 3, 3, 7, 7, 7, 9, 9])
        idx = SstIndex.build(sids, row_group_size=4)
        assert list(idx.row_groups_for(np.array([3]))) == [True, True,
                                                           False]
        assert list(idx.row_groups_for(np.array([9]))) == [False, False,
                                                           True]
        # sid 5 is inside group bounds [3,7] but absent: the exact
        # per-group sid set (not just [lo, hi]) prunes it
        assert list(idx.row_groups_for(np.array([5]))) == [False, False,
                                                           False]
        assert not idx.row_groups_for(np.zeros(0, np.int64)).any()

    def test_codec_roundtrip(self):
        sids = np.repeat(np.arange(0, 50, 7), 5)
        idx = SstIndex.build(sids, row_group_size=8)
        idx2 = SstIndex.from_bytes(idx.to_bytes())
        assert idx2.num_rows == idx.num_rows
        assert (idx2.words == idx.words).all()
        assert (idx2.rg_lo == idx.rg_lo).all()
        assert idx2.may_contain_any(np.array([7]))
        assert not idx2.may_contain_any(np.array([6]))

    def test_codec_rejects_corruption(self):
        data = SstIndex.build(np.arange(100), 16).to_bytes()
        with pytest.raises(SstIndexCorrupt):
            SstIndex.from_bytes(b"junk" + data)
        with pytest.raises(SstIndexCorrupt):
            SstIndex.from_bytes(data[:-3])          # truncated payload
        flipped = bytearray(data)
        flipped[-1] ^= 0xFF
        with pytest.raises(SstIndexCorrupt):        # crc catches bitrot
            SstIndex.from_bytes(bytes(flipped))

    def test_false_positive_is_harmless(self, tmp_path, monkeypatch):
        """A bloom that answers 'maybe' for everything only loses the
        pruning — answers stay exact (the scan re-masks rows)."""
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        monkeypatch.setattr(SstIndex, "may_contain_any",
                            lambda self, s: True)
        sd = region.series_dict
        got = _rows_for(region, sd.sids_for_tag_values(0, ["h2"]))
        assert got == _full_rows(region, {"h2"})


# ---------------------------------------------------------------------------
# storage-level differential
# ---------------------------------------------------------------------------

def _make_schema(tag_nullable: bool = False) -> Schema:
    return Schema([
        ColumnSchema("host", STRING, nullable=tag_nullable,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("v", FLOAT64),
    ])


def _make_region(home: str, tag_nullable: bool = False) -> Region:
    return Region.create(
        RegionDescriptor("idx", _make_schema(tag_nullable), "idx",
                         os.path.join(home, "wal")),
        FsObjectStore(os.path.join(home, "data")))


def _ingest_overlapping_batches(region: Region) -> None:
    """Three flushed SSTs with overlapping sid RANGES but distinct sid
    sets (h4 rides every batch), plus an overwrite and a delete so the
    kept files still exercise MVCC dedup."""
    ts = 0
    for batch in (("h1", "h4"), ("h2", "h4"), ("h3", "h4")):
        wb = WriteBatch(region.schema)
        hosts = list(batch) * 3
        wb.put({"host": hosts, "ts": list(range(ts, ts + len(hosts))),
                "v": [float(ts + i) for i in range(len(hosts))]})
        region.write(wb)
        region.flush()
        ts += len(hosts)
    # overwrite one h2 key and delete one h4 key in a fourth file
    wb = WriteBatch(region.schema)
    wb.put({"host": ["h2"], "ts": [6], "v": [99.5]})
    region.write(wb)
    wb = WriteBatch(region.schema)
    wb.delete({"host": ["h4"], "ts": [1]})
    region.write(wb)
    region.flush()


def _rows_for(region: Region, sid_set) -> set:
    data = region.snapshot().read_merged(sid_set=sid_set)
    hosts = region.series_dict.decode_tag_column(data.series_ids, 0)
    return {(h, int(t), float(v)) for h, t, v in
            zip(hosts, data.ts, data.fields["v"][0])}


def _full_rows(region: Region, keep_hosts) -> set:
    data = region.snapshot().read_merged()
    hosts = region.series_dict.decode_tag_column(data.series_ids, 0)
    return {(h, int(t), float(v)) for h, t, v in
            zip(hosts, data.ts, data.fields["v"][0])
            if h in keep_hosts}


class TestScanSidSet:
    def test_point_scan_matches_full_scan(self, tmp_path):
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        sd = region.series_dict
        for hosts in (["h1"], ["h2"], ["h4"], ["h1", "h3"],
                      ["h2", "h4"], ["nope"]):
            sids = sd.sids_for_tag_values(0, hosts)
            assert _rows_for(region, sids) == \
                _full_rows(region, set(hosts)), hosts

    def test_files_pruned_before_footer(self, tmp_path):
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        sd = region.series_dict
        from greptimedb_tpu.common import exec_stats
        with exec_stats.collect() as st:
            _rows_for(region, sd.sids_for_tag_values(0, ["h2"]))
        prune = st.stages["prune"].detail
        # 4 files: file 1 range-pruned, file 3 bloom-pruned, files 2+4
        # (h2 lives in both) kept
        assert prune["index_files_checked"] == 4
        assert prune["index_files_pruned"] == 2

    def test_null_tags_excluded(self, tmp_path):
        """Rows whose tag is NULL form their own series; a point sid
        set never includes them (= is UNKNOWN on NULL), matching the
        engine's fillna(False) WHERE semantics."""
        region = _make_region(str(tmp_path), tag_nullable=True)
        wb = WriteBatch(region.schema)
        wb.put({"host": ["a", None, "a", None], "ts": [1, 2, 3, 4],
                "v": [1.0, 2.0, 3.0, 4.0]})
        region.write(wb)
        # memtable-only: parquet cannot encode a null dictionary value
        # (pre-existing writer limitation), but the sid-set path must
        # exclude NULL-tag series wherever the rows live
        sids = region.series_dict.sids_for_tag_values(0, ["a"])
        got = _rows_for(region, sids)
        assert got == {("a", 1, 1.0), ("a", 3, 3.0)}

    def test_pre_upgrade_files_stats_only(self, tmp_path):
        """Files written with the index disabled (= pre-upgrade files
        recovered from an old manifest) carry no sidecar and stay fully
        scannable through the stats-only path."""
        configure_sst_index(enabled=False)
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        assert all(f.index_file is None for f in
                   region.version_control.current.ssts.all_files())
        configure_sst_index(enabled=True)
        sd = region.series_dict
        assert _rows_for(region, sd.sids_for_tag_values(0, ["h3"])) == \
            _full_rows(region, {"h3"})

    def test_mixed_upgrade_files(self, tmp_path):
        """Half the files indexed, half pre-upgrade: the planner prunes
        what it can and keeps the rest — answers identical."""
        configure_sst_index(enabled=False)
        region = _make_region(str(tmp_path))
        wb = WriteBatch(region.schema)
        wb.put({"host": ["h1", "h4"], "ts": [0, 1], "v": [0.0, 1.0]})
        region.write(wb)
        region.flush()
        configure_sst_index(enabled=True)
        wb = WriteBatch(region.schema)
        wb.put({"host": ["h2", "h4"], "ts": [2, 3], "v": [2.0, 3.0]})
        region.write(wb)
        region.flush()
        metas = region.version_control.current.ssts.all_files()
        assert sorted(m.index_file is not None for m in metas) == \
            [False, True]
        sd = region.series_dict
        for hosts in (["h1"], ["h2"], ["h4"]):
            assert _rows_for(region, sd.sids_for_tag_values(0, hosts)) \
                == _full_rows(region, set(hosts))

    def test_corrupt_sidecar_degrades(self, tmp_path):
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        for f in region.version_control.current.ssts.all_files():
            assert f.index_file is not None
            region.store.write(f"idx/sst/{f.index_file}", b"garbage!")
        region.access_layer._sst_index.clear()   # drop parsed copies
        before = _counter_value("greptime_sst_index_degrade_total")
        sd = region.series_dict
        assert _rows_for(region, sd.sids_for_tag_values(0, ["h2"])) == \
            _full_rows(region, {"h2"})
        assert _counter_value("greptime_sst_index_degrade_total") > before

    def test_read_failpoint_degrades(self, tmp_path):
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        region.access_layer._sst_index.clear()
        before = _counter_value("greptime_sst_index_degrade_total")
        sd = region.series_dict
        with fp.cfg("sst_index_read", "err"):
            assert _rows_for(region, sd.sids_for_tag_values(0, ["h1"])) \
                == _full_rows(region, {"h1"})
        assert _counter_value("greptime_sst_index_degrade_total") > before

    def test_write_failpoint_degrades_to_stats_only(self, tmp_path):
        """An err (not crash) on the sidecar write must not fail the
        flush: the file commits stats-only."""
        region = _make_region(str(tmp_path))
        wb = WriteBatch(region.schema)
        wb.put({"host": ["h1"], "ts": [0], "v": [1.0]})
        region.write(wb)
        with fp.cfg("sst_index_write", "err"):
            region.flush()
        metas = region.version_control.current.ssts.all_files()
        assert len(metas) == 1 and metas[0].index_file is None
        assert _rows_for(region, region.series_dict.sids_for_tag_values(
            0, ["h1"])) == _full_rows(region, {"h1"})

    def test_sidecar_swept_with_orphan_sst(self, tmp_path):
        """Crash between sidecar publish and manifest commit: BOTH the
        data file and its sidecar are unreferenced orphans the reopen
        sweep collects (the full matrix cell lives in torture.py)."""
        region = _make_region(str(tmp_path))
        wb = WriteBatch(region.schema)
        wb.put({"host": ["h1"], "ts": [0], "v": [1.0]})
        region.write(wb)
        with fp.cfg("flush_commit", "crash"):
            with pytest.raises(fp.SimulatedCrash):
                region.flush()
        reopened = Region.open(
            RegionDescriptor("idx", None, "idx",
                             os.path.join(str(tmp_path), "wal")),
            FsObjectStore(os.path.join(str(tmp_path), "data")))
        on_disk = reopened.store.list("idx/sst/")
        assert on_disk == [], on_disk
        assert _rows_for(reopened, reopened.series_dict.
                         sids_for_tag_values(0, ["h1"])) == \
            _full_rows(reopened, {"h1"})

    def test_compaction_outputs_carry_indexes(self, tmp_path):
        region = _make_region(str(tmp_path))
        _ingest_overlapping_batches(region)
        region.compact()
        metas = region.version_control.current.ssts.all_files()
        assert metas and all(f.index_file is not None for f in metas)
        # sidecars of compacted-away inputs are deleted with their SSTs
        names = {f.index_file for f in metas} | \
            {f.file_name for f in metas}
        region.purger.sweep() if region.purger else None
        sd = region.series_dict
        assert _rows_for(region, sd.sids_for_tag_values(0, ["h2"])) == \
            _full_rows(region, {"h2"})
        assert names  # compaction preserved index coverage


# ---------------------------------------------------------------------------
# SQL-level differential: index-on == index-off across predicate shapes
# ---------------------------------------------------------------------------

@pytest.fixture()
def frontend(tmp_path):
    from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                  DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path),
                                          register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    yield fe
    fe.shutdown()


def _rows(out) -> list:
    return sorted(tuple(r) for b in out.batches for r in b.rows())


class TestSqlDifferential:
    QUERIES = [
        # point
        "SELECT host, max(v) FROM d WHERE host = 'h2' GROUP BY host",
        # IN
        "SELECT host, count(v) FROM d WHERE host IN ('h1', 'h3') "
        "GROUP BY host",
        # != is EXCLUDED from sid derivation (near-total set) but must
        # answer identically
        "SELECT host, sum(v) FROM d WHERE host != 'h2' GROUP BY host",
        # mixed tag + time
        "SELECT host, avg(v) FROM d WHERE host = 'h4' AND ts >= 3000 "
        "AND ts < 9000 GROUP BY host",
        # point + IN + range conjuncts together (sid sets intersect)
        "SELECT host, min(v) FROM d WHERE host IN ('h2', 'h4') "
        "AND host = 'h2' AND v >= 0 GROUP BY host",
        # never-seen value: provably empty
        "SELECT host, max(v) FROM d WHERE host = 'zzz' GROUP BY host",
        # raw row SELECT through the fallback path
        "SELECT host, ts, v FROM d WHERE host = 'h3' ORDER BY ts",
    ]

    def _setup(self, fe, ctx):
        fe.do_query("CREATE TABLE d (host STRING, ts TIMESTAMP "
                    "TIME INDEX, v DOUBLE, PRIMARY KEY(host))", ctx)
        ts = 0
        for batch in (("h1", "h4"), ("h2", "h4"), ("h3", "h4")):
            vals = []
            for i in range(6):
                h = batch[i % 2]
                vals.append(f"('{h}', {(ts + i) * 1000}, {ts + i}.5)")
            fe.do_query(f"INSERT INTO d VALUES {', '.join(vals)}", ctx)
            fe.do_query("ADMIN FLUSH TABLE d", ctx)
            ts += 6
        # an overwrite in a fourth file so kept files need dedup
        fe.do_query("INSERT INTO d VALUES ('h2', 7000, 123.5)", ctx)
        fe.do_query("ADMIN FLUSH TABLE d", ctx)

    def test_on_off_answers_identical(self, frontend):
        from greptimedb_tpu.query import tpu_exec
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        self._setup(frontend, ctx)
        frontend.do_query("SET tpu_dispatch_min_rows = 1", ctx)
        try:
            for q in self.QUERIES:
                answers = {}
                for on in (1, 0):
                    frontend.do_query(f"SET sst_index = {on}", ctx)
                    tpu_exec.SCAN_CACHE._entries.clear()
                    answers[on] = _rows(frontend.do_query(q, ctx)[-1])
                assert answers[1] == answers[0], q
        finally:
            frontend.do_query("SET sst_index = 1", ctx)
            frontend.do_query("SET tpu_dispatch_min_rows = 131072", ctx)

    def test_streamed_cold_differential(self, frontend, monkeypatch):
        """The streamed cold path threads the sid set through every
        slice (and the lean chunk reader): answers must match index-off
        with the same threshold. region_point_sids is pinned to None so
        the stream path itself (not the indexed-point route that would
        otherwise win) consumes the sid set."""
        from greptimedb_tpu.query import stream_exec, tpu_exec
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        self._setup(frontend, ctx)
        frontend.do_query("SET tpu_dispatch_min_rows = 1", ctx)
        saved = stream_exec.stream_threshold_rows()
        stream_exec.configure_streaming(threshold_rows=1)
        monkeypatch.setattr(tpu_exec, "region_point_sids",
                            lambda region, plan: None)
        try:
            for q in self.QUERIES[:5]:
                answers = {}
                for on in (1, 0):
                    frontend.do_query(f"SET sst_index = {on}", ctx)
                    tpu_exec.SCAN_CACHE._entries.clear()
                    answers[on] = _rows(frontend.do_query(q, ctx)[-1])
                assert answers[1] == answers[0], q
        finally:
            stream_exec.configure_streaming(threshold_rows=saved)
            frontend.do_query("SET sst_index = 1", ctx)
            frontend.do_query("SET tpu_dispatch_min_rows = 131072", ctx)

    def test_explain_analyze_reports_index_prune(self, frontend):
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        self._setup(frontend, ctx)
        frontend.do_query("SET tpu_dispatch_min_rows = 1", ctx)
        try:
            out = frontend.do_query(
                "EXPLAIN ANALYZE SELECT host, max(v) FROM d "
                "WHERE host = 'h2' GROUP BY host", ctx)[-1]
            text = "\n".join(str(r) for b in out.batches
                             for r in b.rows())
            assert "index_files_pruned" in text
            assert "indexed-point" in text
        finally:
            frontend.do_query("SET tpu_dispatch_min_rows = 131072", ctx)

    def test_promql_selector_differential(self, frontend):
        """The PromQL cold selector path resolves equality matchers to
        sid sets; answers must match the index-off run."""
        from greptimedb_tpu.query import stream_exec, tpu_exec
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        self._setup(frontend, ctx)
        saved = stream_exec.stream_threshold_rows()
        stream_exec.configure_streaming(threshold_rows=1)  # force cold
        try:
            answers = {}
            for on in (1, 0):
                frontend.do_query(f"SET sst_index = {on}", ctx)
                tpu_exec.SCAN_CACHE._entries.clear()
                out = frontend.do_query(
                    "TQL EVAL (0, 30, '5s') d{host=\"h2\"}", ctx)[-1]
                answers[on] = _rows(out)
            assert answers[1] == answers[0]
            assert answers[1], "selector returned nothing"
        finally:
            stream_exec.configure_streaming(threshold_rows=saved)
            frontend.do_query("SET sst_index = 1", ctx)
