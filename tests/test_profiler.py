"""Continuous profiling tests (ISSUE 17).

A wall-clock stack sampler attributes every sampled stack live — to the
owning statement via the process registry, to background work via the
background-jobs registry — and flushes aggregated folded stacks through
the self-monitor path into greptime_private.profile_samples. Surfaces:
ADMIN SHOW PROFILE, GET /debug/prof/cpu, and the
information_schema.profile_samples view.
"""

import json
import logging
import re
import time

import pytest

from greptimedb_tpu.common import profiler, trace_store
from greptimedb_tpu.common.profiler import (
    PRIVATE_SCHEMA, PROFILE_SAMPLES_TABLE, Profiler, fold_stack,
    stack_id)
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import InvalidArgumentsError
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture(autouse=True)
def _restore_knobs():
    saved = (profiler.enabled(), profiler.hz(), profiler.retention_ms())
    saved_sampler = profiler.sampler()
    saved_ratio = trace_store.sample_ratio()
    yield
    profiler.configure(enabled=saved[0], hz=saved[1],
                       retention_ms=saved[2])
    profiler.install(saved_sampler)
    trace_store.configure(sample_ratio=saved_ratio)
    from greptimedb_tpu.common.telemetry import set_slow_query_threshold_ms
    set_slow_query_threshold_ms(None)


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    frontend = FrontendInstance(dn)
    frontend.start()
    frontend.do_query(
        "CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
        "v DOUBLE, PRIMARY KEY(host))")
    frontend.do_query("INSERT INTO cpu VALUES ('a', 1000, 1.5), "
                      "('b', 2000, 2.5)")
    yield frontend
    frontend.shutdown()


def _pydict(fe, sql):
    out = fe.do_query(sql)[-1]
    return out.batches[0].to_pydict()


def _counter_value(name):
    from greptimedb_tpu.common.telemetry import registry_snapshot
    return sum(v for n, _l, v, _k in registry_snapshot() if n == name)


def _spin(fe, seconds, sql="SELECT host, avg(v) FROM cpu GROUP BY host"):
    """Keep query work on the books long enough for the sampler."""
    t0 = time.time()
    while time.time() - t0 < seconds:
        fe.do_query(sql)


class TestFolding:
    def test_fold_stack_root_first_and_trimmed(self):
        import sys
        frame = sys._getframe()
        stack = fold_stack(frame)
        parts = stack.split(";")
        # leaf is THIS function, root is the runner's entry — root-first
        assert parts[-1].endswith(
            ":test_fold_stack_root_first_and_trimmed")
        assert all(";" not in p for p in parts)
        # repo-internal files render package-relative, not absolute
        assert not any(p.startswith("/") for p in parts)

    def test_stack_id_stable_hash(self):
        assert stack_id("a;b;c") == stack_id("a;b;c")
        assert stack_id("a;b;c") != stack_id("a;b;d")
        assert re.fullmatch(r"[0-9a-f]{8}", stack_id("a;b;c"))

    def test_node_context_overrides_attribution_only_when_sampling(self):
        s = Profiler(node_label="frontend")
        old = profiler.install(s)
        try:
            assert not profiler.sampling_active()
            with profiler.node_context("dn7"):
                # knob off, no burst: bookkeeping short-circuits
                assert profiler.node_overrides() == {}
            profiler.configure(enabled=True)
            import threading
            with profiler.node_context("dn7"):
                assert profiler.node_overrides()[
                    threading.get_ident()] == "dn7"
            assert profiler.node_overrides() == {}
        finally:
            profiler.configure(enabled=False)
            profiler.install(old)


class TestKnobs:
    def test_set_profiling_and_hz(self, fe):
        fe.do_query("SET profiling = 1")
        assert profiler.enabled()
        fe.do_query("SET profile_hz = 50")
        assert profiler.hz() == 50.0
        fe.do_query("SET profiling = 0")
        assert not profiler.enabled()

    def test_hz_validation(self, fe):
        for bad in ("0.5", "99999", "'fast'"):
            with pytest.raises(InvalidArgumentsError):
                fe.do_query(f"SET profile_hz = {bad}")
        assert profiler.hz() != 0.5

    def test_retention_knob_independent_of_trace_knob(self, fe):
        fe.do_query("SET profile_retention_ms = 12345")
        assert profiler.retention_ms() == 12345
        assert trace_store.retention_ms() != 12345

    def test_no_thread_until_enabled(self, tmp_path):
        """Default-off means zero always-on cost: constructing a
        frontend must not start a sampler thread."""
        import threading
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "nt")))
        frontend = FrontendInstance(dn)
        try:
            names = {t.name for t in threading.enumerate()}
            assert not any(n.startswith("profiler-") for n in names)
        finally:
            frontend.shutdown()


def _parked_thread(body):
    """Run `body(ready, release)` on a worker thread; yields while the
    worker is parked. sample_once skips the CALLING thread (the sampler
    never profiles itself), so attribution tests need real peers."""
    import contextlib
    import threading

    @contextlib.contextmanager
    def cm():
        ready, release = threading.Event(), threading.Event()
        t = threading.Thread(target=body, args=(ready, release),
                             daemon=True)
        t.start()
        try:
            assert ready.wait(5)
            yield
        finally:
            release.set()
            t.join(timeout=5)

    return cm()


class TestAttribution:
    def test_query_samples_carry_statement_identity(self):
        """A thread inside process_list.track() samples as kind=query
        with the entry's id and trace id."""
        from greptimedb_tpu.common import process_list
        from greptimedb_tpu.common.telemetry import root_span
        s = Profiler(node_label="t")
        seen = {}

        def work(ready, release):
            with root_span("execute_stmt") as sp:
                seen["trace"] = sp["trace_id"]
                with process_list.track("SELECT 1", catalog="greptime",
                                        schema="public",
                                        trace_id=sp["trace_id"]):
                    ready.set()
                    release.wait(5)

        with _parked_thread(work):
            s.sample_once()
        q = [(k, c) for k, c in s._agg.items() if k[1] == "query"]
        assert q
        (node, kind, ident, trace, stack), _c = q[0]
        assert node == "t"
        assert ident.isdigit()
        assert trace == seen["trace"]
        assert s.last_query_trace == seen["trace"]

    def test_background_job_samples_attributed(self):
        """A thread inside background_jobs.job() samples by job kind and
        id, taking precedence over any process entry."""
        import threading

        from greptimedb_tpu.common import background_jobs
        s = Profiler(node_label="t")
        seen = {}
        done = threading.Event()
        go = threading.Event()

        def work():
            with background_jobs.job("flush", table="cpu") as j:
                seen.update(j)
                go.set()
                done.wait(5)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        try:
            assert go.wait(5)
            s.sample_once()
        finally:
            done.set()
            t.join(timeout=5)
        flush_keys = [k for k in s._agg if k[1] == "flush"]
        assert flush_keys
        assert flush_keys[0][2] == str(seen.get("job_id"))

    def test_unattributed_threads_are_idle(self):
        s = Profiler(node_label="t")

        def park(ready, release):
            ready.set()
            release.wait(5)

        with _parked_thread(park):
            s.sample_once()
        kinds = {k[1] for k in s._agg}
        assert "idle" in kinds

    def test_sampler_skips_its_own_thread(self):
        """The calling thread never shows up in its own sample pass —
        the sampler must not charge its overhead to the workload."""
        s = Profiler(node_label="t")
        s.sample_once()
        assert not any("sample_once" in k[4] for k in s._agg)


class TestFlushAndStore:
    def test_flush_writes_profile_samples(self, fe):
        fe.do_query("SET profiling = 1")
        _spin(fe, 0.4)
        assert fe.profiler.flush() > 0
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE}")
        assert d["count(*)"][0] > 0
        fe.do_query("SET profiling = 0")

    def test_windows_do_not_dedup_each_other(self, fe):
        """Two flush windows with the SAME folded stack land as distinct
        rows: ts is the window start, and stack_id tags the stack, so
        the mito (tags, ts) primary key never collapses history."""
        rows = [{"node": "n", "kind": "idle", "id": "", "trace_id": "",
                 "stack_id": stack_id("a;b"), "ts": 1000, "stack": "a;b",
                 "count": 3},
                {"node": "n", "kind": "idle", "id": "", "trace_id": "",
                 "stack_id": stack_id("a;b"), "ts": 2000, "stack": "a;b",
                 "count": 5}]
        fe.profiler.absorb_rows(rows)
        assert fe.profiler.flush() == 2
        d = _pydict(fe, f"SELECT count, ts FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE} WHERE node = 'n' "
                        f"ORDER BY ts")
        assert d["count"] == [3, 5]

    def test_flush_failure_contained_and_counted(self, fe):
        """An armed profiler_flush failpoint: the write fails, the rows
        drop (counted), nothing raises — the observer must never break
        its host."""
        from greptimedb_tpu.common import failpoint
        fe.profiler.absorb_rows([{
            "node": "n", "kind": "idle", "id": "", "trace_id": "",
            "stack_id": stack_id("x"), "ts": 1000, "stack": "x",
            "count": 1}])
        before = _counter_value("greptime_profiler_dropped_total")
        with failpoint.cfg("profiler_flush", "err"):
            assert fe.profiler.flush() == 0
        assert fe.profiler.stats["write_errors"] == 1
        assert _counter_value(
            "greptime_profiler_dropped_total") - before == 1
        # the failed rows are gone, not retried forever
        assert fe.profiler.pending_count() == 0

    def test_absorb_overflow_sheds_and_counts(self, fe, monkeypatch):
        monkeypatch.setattr(Profiler, "MAX_ABSORBED", 2)
        before = _counter_value("greptime_profiler_dropped_total")
        fe.profiler.absorb_rows([
            {"node": "n", "kind": "idle", "id": "", "trace_id": "",
             "stack_id": stack_id(f"s{i}"), "ts": 1000,
             "stack": f"s{i}", "count": 1}
            for i in range(5)])
        assert fe.profiler.stats["rows_absorbed"] == 2
        assert _counter_value(
            "greptime_profiler_dropped_total") - before == 3


class TestShowProfile:
    def test_standalone_end_to_end(self, fe):
        """SET profiling + real queries → ADMIN SHOW PROFILE 'last'
        renders a top-down self/total tree attributed to this query's
        trace (the `make prof` demo)."""
        fe.do_query("SET profiling = 1")
        fe.do_query("SET profile_hz = 97")
        _spin(fe, 0.8)
        out = fe.do_query("ADMIN SHOW PROFILE 'last'")[-1]
        assert out.is_batches
        names = out.batches[0].schema.names()
        assert names == ["frame", "node", "self_samples",
                         "total_samples"]
        rows = []
        for b in out.batches:
            rows.extend(b.to_pylist())
        assert rows
        # tree shape: the root row is unindented, self <= total, and
        # query frames from the engine appear somewhere in the tree
        assert not rows[0]["frame"].startswith(" ")
        assert all(r["self_samples"] <= r["total_samples"]
                   for r in rows)
        assert any("greptimedb_tpu" in r["frame"] for r in rows)
        fe.do_query("SET profiling = 0")

    def test_show_profile_by_trace_and_query_id(self, fe):
        fe.do_query("SET profiling = 1")
        fe.do_query("SET profile_hz = 97")
        _spin(fe, 0.8)
        tid = fe.profiler.last_query_trace
        assert tid is not None
        out = fe.do_query(f"ADMIN SHOW PROFILE '{tid}'")[-1]
        assert out.batches and out.batches[0].num_rows > 0
        # the numeric ident path reads by process-list id; stored rows
        # carry it in the id column
        d = _pydict(fe, f"SELECT id FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE} WHERE kind = 'query' "
                        f"AND trace_id = '{tid}' LIMIT 1")
        qid = d["id"][0]
        out = fe.do_query(f"ADMIN SHOW PROFILE '{qid}'")[-1]
        assert out.batches and out.batches[0].num_rows > 0
        fe.do_query("SET profiling = 0")

    def test_unknown_idents_error(self, fe):
        with pytest.raises(InvalidArgumentsError,
                           match="no query has been profiled"):
            fe.do_query("ADMIN SHOW PROFILE 'last'")
        with pytest.raises(InvalidArgumentsError, match="not found"):
            fe.do_query("ADMIN SHOW PROFILE "
                        "'f00dfeedf00dfeedf00dfeedf00dfeed'")

    def test_parser_rejects_unquoted_ident(self, fe):
        from greptimedb_tpu.errors import GreptimeError
        with pytest.raises(GreptimeError, match="quoted id"):
            fe.do_query("ADMIN SHOW PROFILE last")


class TestSlowQueryLine:
    def test_warn_line_carries_top_frames(self, fe, caplog):
        from greptimedb_tpu.common.telemetry import \
            set_slow_query_threshold_ms
        fe.do_query("SET profiling = 1")
        fe.do_query("SET profile_hz = 147")
        set_slow_query_threshold_ms(1)      # everything is "slow"
        sql = "SELECT host, avg(v), sum(v) FROM cpu GROUP BY host"
        with caplog.at_level(logging.WARNING,
                             logger="greptimedb_tpu.slow_query"):
            deadline = time.time() + 8
            while not any("profile_top=[" in r.getMessage()
                          for r in caplog.records) \
                    and time.time() < deadline:
                fe.do_query(sql)
        slow = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert slow
        hit = [m for m in slow if "profile_top=[" in m]
        assert hit, "WARN line never carried profile_top frames"
        assert "trace_stored=" in hit[0]
        fe.do_query("SET profiling = 0")

    def test_no_suffix_when_profiling_off(self, fe, caplog):
        from greptimedb_tpu.common.telemetry import \
            set_slow_query_threshold_ms
        set_slow_query_threshold_ms(1)
        with caplog.at_level(logging.WARNING,
                             logger="greptimedb_tpu.slow_query"):
            for _ in range(20):
                fe.do_query("SELECT host, avg(v) FROM cpu "
                            "GROUP BY host")
        slow = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert slow
        assert all("profile_top=" not in m for m in slow)


class TestMetricsSurface:
    def test_profiler_counters_published(self, fe):
        fe.do_query("SET profiling = 1")
        before = _counter_value("greptime_profiler_samples_total")
        _spin(fe, 0.3)
        assert _counter_value(
            "greptime_profiler_samples_total") > before
        assert _counter_value("greptime_profiler_overhead_ns_total") > 0
        fe.do_query("SET profiling = 0")

    def test_counters_in_runtime_metrics_view(self, fe):
        fe.do_query("SET profiling = 1")
        _spin(fe, 0.3)
        d = _pydict(fe, "SELECT metric_name FROM "
                        "information_schema.runtime_metrics WHERE "
                        "metric_name LIKE 'greptime_profiler%'")
        assert "greptime_profiler_samples_total" in d["metric_name"]
        assert "greptime_profiler_overhead_ns_total" \
            in d["metric_name"]
        fe.do_query("SET profiling = 0")


class TestRetentionSweep:
    """Satellite: _sweep_table generalizes over trace_spans AND
    profile_samples, each on its own knob."""

    def _plant_profile_row(self, fe, ts_ms):
        fe.profiler.absorb_rows([{
            "node": "old", "kind": "idle", "id": "", "trace_id": "",
            "stack_id": stack_id("stale"), "ts": ts_ms,
            "stack": "stale", "count": 1}])
        assert fe.profiler.flush() == 1

    def test_profile_retention_sweep_same_tick_as_flush(self, fe):
        """Flush-before-sweep: rows still pending in the sampler when
        retention tightens are flushed and then swept within ONE tick —
        the same property the trace store guarantees."""
        old_ms = int(time.time() * 1000) - 10 * 24 * 3600 * 1000
        fe.profiler.absorb_rows([{
            "node": "old", "kind": "idle", "id": "", "trace_id": "",
            "stack_id": stack_id("stale"), "ts": old_ms,
            "stack": "stale", "count": 1}])
        fe.do_query("SET profile_retention_ms = 60000")
        assert fe.profiler.pending_count() == 1    # not yet written
        fe.self_monitor.tick()
        assert fe.profiler.pending_count() == 0    # flushed this tick
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE} WHERE node = 'old'")
        assert d["count(*)"][0] == 0               # ...and swept

    def test_knobs_sweep_independently(self, fe):
        """trace_retention_ms sweeps trace_spans only;
        profile_retention_ms sweeps profile_samples only."""
        old_ms = int(time.time() * 1000) - 10 * 24 * 3600 * 1000
        # plant one aged row in each store
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        sink = trace_store.sink()
        sink.flush()
        self._plant_profile_row(fe, old_ms)
        trace_store.configure(sample_ratio=0.0)

        def counts():
            t = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                            f"{trace_store.TRACE_SPANS_TABLE}")
            p = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                            f"{PROFILE_SAMPLES_TABLE}")
            return t["count(*)"][0], p["count(*)"][0]

        t0, p0 = counts()
        assert t0 > 0 and p0 > 0
        # profile knob alone: profile row goes, trace rows stay
        fe.do_query("SET profile_retention_ms = 60000")
        fe.do_query("SET trace_retention_ms = 0")
        fe.self_monitor.tick()
        t1, p1 = counts()
        assert t1 == t0 and p1 == 0
        # trace knob alone sweeps the (freshly re-planted) other side
        self._plant_profile_row(fe, old_ms)
        fe.do_query("SET profile_retention_ms = 0")
        fe.do_query("SET trace_retention_ms = 1")
        time.sleep(0.01)
        fe.self_monitor.tick()
        t2, p2 = counts()
        assert t2 == 0 and p2 == 1
        fe.do_query("SET profile_retention_ms = 86400000")
        fe.do_query("SET trace_retention_ms = 259200000")

    def test_profile_sweep_batched(self, fe, monkeypatch):
        old_ms = int(time.time() * 1000) - 10 * 24 * 3600 * 1000
        fe.profiler.absorb_rows([{
            "node": "old", "kind": "idle", "id": "", "trace_id": "",
            "stack_id": stack_id(f"s{i}"), "ts": old_ms + i,
            "stack": f"s{i}", "count": 1} for i in range(5)])
        assert fe.profiler.flush() == 5
        monkeypatch.setattr(type(fe.self_monitor), "SWEEP_BATCH_ROWS", 2)
        fe.do_query("SET profile_retention_ms = 60000")
        fe.self_monitor.tick()
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE} WHERE node = 'old'")
        assert d["count(*)"][0] == 3               # capped per tick
        for _ in range(3):
            fe.self_monitor.tick()
        d = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                        f"{PROFILE_SAMPLES_TABLE} WHERE node = 'old'")
        assert d["count(*)"][0] == 0
        fe.do_query("SET profile_retention_ms = 86400000")


class TestInformationSchemaView:
    def test_view_serves_stored_rows(self, fe):
        fe.do_query("SET profiling = 1")
        _spin(fe, 0.4)
        d = _pydict(fe, "SELECT node, kind, count FROM "
                        "information_schema.profile_samples")
        assert d["node"] and "standalone" in d["node"]
        assert set(d["kind"]) <= {"query", "flush", "compaction",
                                  "flow", "balancer", "idle"}
        fe.do_query("SET profiling = 0")

    def test_view_empty_without_sampling(self, fe):
        d = _pydict(fe, "SELECT count(*) FROM "
                        "information_schema.profile_samples")
        assert d["count(*)"][0] == 0


class TestHttpBurst:
    @pytest.fixture()
    def server(self, fe):
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(fe, addr="127.0.0.1:0")
        srv.start()
        yield srv
        srv.shutdown()

    def _get(self, srv, path):
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}",
                    timeout=30) as resp:
                return (resp.status, resp.headers.get_content_type(),
                        resp.read())
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get_content_type(), e.read()

    def test_burst_folded_and_json(self, fe, server):
        """The burst works with `SET profiling` OFF: it has its own
        clock and rate."""
        assert not profiler.enabled()
        status, ctype, body = self._get(
            server, "/debug/prof/cpu?seconds=0.3&format=json&hz=147")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["sample_count"] > 0
        assert all({"node", "kind", "stack", "count"} <= set(r)
                   for r in doc["rows"])
        status, ctype, body = self._get(
            server, "/debug/prof/cpu?seconds=0.2&format=folded")
        assert status == 200 and ctype == "text/plain"
        line = body.decode().splitlines()[0]
        assert re.fullmatch(r"\S+ \d+", line)

    def test_burst_flamegraph_svg(self, fe, server):
        status, ctype, body = self._get(
            server, "/debug/prof/cpu?seconds=0.2&format=flamegraph")
        assert status == 200 and ctype == "image/svg+xml"
        assert body.startswith(b"<svg")
        assert b"samples" in body

    def test_bad_format_400(self, fe, server):
        status, _ctype, body = self._get(
            server, "/debug/prof/cpu?format=pprof")
        assert status == 400
        assert b"not supported" in body


class TestFlightAction:
    @staticmethod
    def _act(body):
        """Drive FlightDatanodeServer's action handler directly — the
        in-process twin of the socket round-trip (the profile branch
        only touches the process-global sampler, never self)."""
        import types

        from greptimedb_tpu.servers.flight import FlightDatanodeServer
        srv = types.SimpleNamespace()
        results = list(FlightDatanodeServer._do_action_inner(
            srv, "profile", body))
        return json.loads(results[0].body.to_pybytes())

    @staticmethod
    def _park(ready, release):
        ready.set()
        release.wait(5)

    def test_profile_action_drains_datanode_sampler(self):
        """The wire path: a writer-less datanode sampler accumulates,
        the Flight `profile` action hands rows to the caller."""
        s = Profiler(node_label="dn9")       # writer-less: datanode
        old = profiler.install(s)
        try:
            with _parked_thread(self._park):
                s.sample_once()
            assert s.pending_count() > 0
            resp = self._act({"drain": True})
            assert resp["ok"] and resp["rows"]
            assert all(r["node"] == "dn9" for r in resp["rows"])
            assert s.pending_count() == 0    # drained
        finally:
            profiler.install(old)

    def test_profile_action_burst(self):
        s = Profiler(node_label="dn9")
        old = profiler.install(s)
        try:
            with _parked_thread(self._park):
                resp = self._act({"seconds": 0.2, "hz": 147})
            assert resp["ok"]
            assert sum(r["count"] for r in resp["rows"]) > 0
        finally:
            s.stop()
            profiler.install(old)


class TestDistributedAttribution:
    """Acceptance: on an in-process 4-datanode cluster, a slow
    distributed query's ADMIN SHOW PROFILE '<trace_id>' sample nodes
    cover every datanode the PR 15 waterfall names, and >=90% of work
    samples are attributed (not idle)."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MetaClient, Peer
        from greptimedb_tpu.meta.kv import MemKv
        from greptimedb_tpu.meta.service import MetaSrv
        datanodes, clients = {}, {}
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(srv)
        for i in (1, 2, 3, 4):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        yield fe
        for dn in datanodes.values():
            dn.shutdown()

    @pytest.mark.slow
    def test_profile_nodes_cover_waterfall_datanodes(self, cluster):
        fe = cluster
        fe.do_query("SET profiling = 1")
        fe.do_query("SET profile_hz = 147")
        trace_store.configure(sample_ratio=1.0)
        fe.do_query(
            "CREATE TABLE m (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host)) "
            "PARTITION BY HASH (host) PARTITIONS 8")
        values = ", ".join(f"('h{i}', {1000 + i}, {float(i)})"
                           for i in range(2000))
        fe.do_query(f"INSERT INTO m VALUES {values}")
        sql = ("SELECT host, avg(v), sum(v), min(v), max(v) FROM m "
               "GROUP BY host")
        deadline = time.time() + 10
        tid = None
        while time.time() < deadline:
            fe.do_query(sql)
            tid = trace_store.sink().last_retained
            if tid and profiler.sampler().last_query_trace == tid:
                break
        assert tid is not None
        out = fe.do_query(f"ADMIN SHOW PROFILE '{tid}'")[-1]
        tree = []
        for b in out.batches:
            tree.extend(b.to_pylist())
        assert tree
        profile_nodes = {r["node"] for r in tree}
        # the trace's waterfall names the datanodes the scatter touched
        trace_store.sink().flush()
        spans = trace_store.fetch_trace(fe.catalog, tid)
        wf_nodes = {json.loads(r["attrs"])["peer"] for r in spans
                    if r["span_name"] == "dist_rpc"}
        assert wf_nodes                       # the query DID scatter
        assert wf_nodes <= profile_nodes, (
            f"profile missing datanodes: {wf_nodes - profile_nodes}")
        assert "frontend" in profile_nodes
        # attribution differential: >=90% of WORK samples (stacks inside
        # the engine/dispatch/storage) carry a statement or job, not idle
        d = _pydict(fe, "SELECT kind, stack, count FROM "
                        "information_schema.profile_samples")
        work = attributed = 0
        work_re = re.compile(
            r"execute_stmt|dist_rpc|region_moment|scan_batches|"
            r"tpu_exec|write_region")
        for kind, stack, count in zip(d["kind"], d["stack"], d["count"]):
            if not work_re.search(stack):
                continue
            work += count
            if kind != "idle":
                attributed += count
        assert work > 0
        assert attributed / work >= 0.9, (
            f"only {attributed}/{work} work samples attributed")
        fe.do_query("SET profiling = 0")

    @pytest.mark.slow
    def test_trace_id_joins_profile_to_trace_spans(self, cluster):
        """trace ids join profile_samples to trace_spans: one SQL query
        correlates a trace's spans with its sampled stacks."""
        fe = cluster
        fe.do_query("SET profiling = 1")
        fe.do_query("SET profile_hz = 147")
        trace_store.configure(sample_ratio=1.0)
        fe.do_query(
            "CREATE TABLE j (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host)) "
            "PARTITION BY HASH (host) PARTITIONS 4")
        fe.do_query("INSERT INTO j VALUES ('a', 1000, 1.0)")
        deadline = time.time() + 10
        tid = None
        while time.time() < deadline:
            fe.do_query("SELECT host, avg(v) FROM j GROUP BY host")
            tid = trace_store.sink().last_retained
            if tid and profiler.sampler().last_query_trace == tid:
                break
        trace_store.sink().flush()
        profiler.sync_and_fetch(fe.catalog, tid,
                                clients=list(fe.clients.values()))
        d = _pydict(fe, f"SELECT p.trace_id, t.span_name FROM "
                        f"{PRIVATE_SCHEMA}.{PROFILE_SAMPLES_TABLE} p "
                        f"JOIN {PRIVATE_SCHEMA}."
                        f"{trace_store.TRACE_SPANS_TABLE} t "
                        f"ON p.trace_id = t.trace_id "
                        f"WHERE p.trace_id = '{tid}'")
        assert d["trace_id"]
        assert "execute_stmt" in set(d["span_name"])
        fe.do_query("SET profiling = 0")
