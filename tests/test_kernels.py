"""Kernel tests against NumPy oracles.

Mirrors the reference's memtable/merge/dedup semantics tests
(src/storage/src/memtable/tests.rs, src/storage/src/read/merge.rs) and the
PromQL function tests (src/promql/src/functions/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from greptimedb_tpu.ops import Dictionary
from greptimedb_tpu.ops.kernels import (
    OP_DELETE, OP_PUT, combine_group_ids, grouped_aggregate,
    merge_dedup_numpy, pad_axis0, shape_bucket, sort_merge_dedup,
    time_bucket_ids,
)
from greptimedb_tpu.ops.window import (
    SeriesMatrix, instant_select, range_aggregate_cumsum,
    range_aggregate_gather,
)


class TestDictionary:
    def test_roundtrip(self):
        d = Dictionary()
        ids = d.encode(["a", "b", "a", "c"])
        assert ids.tolist() == [0, 1, 0, 2]
        assert d.decode(np.array([2, 0])) == ["c", "a"]
        assert d.encode_existing(["b", "zzz"]).tolist() == [1, -1]
        d2 = Dictionary.from_list(d.to_list())
        assert d2.encode_existing(["c"]).tolist() == [2]


class TestShapeBucket:
    def test_bucket(self):
        assert shape_bucket(1) == 1024
        assert shape_bucket(1025) == 2048
        assert shape_bucket(4096) == 4096

    def test_pad(self):
        a = np.arange(3)
        p = pad_axis0(a, 8, fill=-1)
        assert p.tolist() == [0, 1, 2, -1, -1, -1, -1, -1]


class TestGroupedAggregate:
    def _data(self, seed=0, n=1000, groups=7):
        rng = np.random.default_rng(seed)
        gids = rng.integers(0, groups, n).astype(np.int32)
        vals = rng.normal(size=n)
        mask = rng.random(n) > 0.3
        ts = rng.integers(0, 10_000, n).astype(np.int64)
        return gids, mask, ts, vals, groups

    def test_sum_count_avg_min_max(self):
        gids, mask, ts, vals, G = self._data()
        (s, c, a, mn, mx), counts = grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals),) * 5,
            num_groups=G, ops=("sum", "count", "avg", "min", "max"))
        for g in range(G):
            sel = (gids == g) & mask
            if sel.any():
                # f32 accumulation in the production (x64-off) regime
                np.testing.assert_allclose(s[g], vals[sel].sum(), rtol=1e-4,
                                           atol=1e-4)
                assert int(c[g]) == sel.sum()
                np.testing.assert_allclose(a[g], vals[sel].mean(), rtol=1e-4,
                                           atol=1e-4)
                np.testing.assert_allclose(mn[g], vals[sel].min())
                np.testing.assert_allclose(mx[g], vals[sel].max())
            assert int(counts[g]) == sel.sum()

    def test_first_last(self):
        gids = np.array([0, 0, 1, 1, 0], dtype=np.int32)
        ts = np.array([5, 1, 9, 2, 3], dtype=np.int64)
        vals = np.array([50.0, 10.0, 90.0, 20.0, 30.0])
        mask = np.ones(5, dtype=bool)
        (fst, lst), _ = grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals), jnp.asarray(vals)),
            num_groups=2, ops=("first", "last"))
        assert fst[0] == 10.0 and lst[0] == 50.0
        assert fst[1] == 20.0 and lst[1] == 90.0

    def test_empty_group(self):
        gids = np.array([0], dtype=np.int32)
        mask = np.ones(1, dtype=bool)
        ts = np.zeros(1, dtype=np.int64)
        vals = np.array([1.0])
        (a,), counts = grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals),), num_groups=3, ops=("avg",))
        assert counts[1] == 0 and counts[2] == 0
        assert np.isnan(a[1])

    def test_variance_large_tight_values(self):
        """Shifted-moment regression: int columns must not wrap on
        squaring, and f32 cancellation must not floor the variance of
        large, tight distributions (review r4)."""
        from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate
        gids = np.zeros(3, np.int32)
        mask = np.ones(3, bool)
        ts = np.arange(3, dtype=np.int32)
        for vals in (np.array([100000, 100000, 100001], np.int32),
                     np.array([100000.0, 100000.0, 100001.0], np.float32)):
            (v1,), _ = grouped_aggregate(
                jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
                (jnp.asarray(vals),), num_groups=1, ops=("variance",))
            (v2,), _ = sorted_grouped_aggregate(
                gids, mask, ts, (jnp.asarray(vals),), num_groups=1,
                ops=("variance",))
            np.testing.assert_allclose(float(v1[0]), 1 / 3, rtol=1e-3)
            np.testing.assert_allclose(float(v2[0]), 1 / 3, rtol=1e-3)

    def test_stddev(self):
        gids, mask, ts, vals, G = self._data(seed=3)
        (sd,), counts = grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals),), num_groups=G, ops=("stddev",))
        for g in range(G):
            sel = (gids == g) & mask
            if sel.sum() > 1:
                np.testing.assert_allclose(sd[g], vals[sel].std(ddof=1),
                                           rtol=1e-6)

    def test_time_bucket_combine(self):
        ts = jnp.array([0, 999, 1000, 2500], dtype=jnp.int32)
        b = time_bucket_ids(ts, 0, 1000, 4)
        assert b.tolist() == [0, 0, 1, 2]
        gid = combine_group_ids(jnp.array([1, 0, 1, 0]), b, 4)
        assert gid.tolist() == [4, 0, 5, 2]


class TestMergeDedup:
    def test_basic_dedup(self):
        # two runs: memtable overwrites an SST row at (s=0, ts=10)
        series = np.array([0, 0, 1, 0], dtype=np.int32)
        ts = np.array([10, 20, 10, 10], dtype=np.int64)
        seq = np.array([1, 2, 3, 7], dtype=np.int64)
        op = np.array([OP_PUT] * 4, dtype=np.int8)
        kept = merge_dedup_numpy(series, ts, seq, op)
        # rows sorted by (series, ts): winner at (0,10) is seq=7 → index 3
        assert kept.tolist() == [3, 1, 2]

    def test_delete_hides_row(self):
        series = np.array([0, 0], dtype=np.int32)
        ts = np.array([10, 10], dtype=np.int64)
        seq = np.array([1, 2], dtype=np.int64)
        op = np.array([OP_PUT, OP_DELETE], dtype=np.int8)
        kept = merge_dedup_numpy(series, ts, seq, op)
        assert kept.tolist() == []

    def test_device_matches_numpy(self):
        rng = np.random.default_rng(42)
        n = 500
        series = rng.integers(0, 20, n).astype(np.int32)
        ts = rng.integers(0, 50, n).astype(np.int64)
        seq = np.arange(n, dtype=np.int64)
        op = rng.choice([OP_PUT, OP_PUT, OP_PUT, OP_DELETE], n).astype(np.int8)
        valid = np.ones(n, dtype=bool)
        order, keep = sort_merge_dedup(
            jnp.asarray(series), jnp.asarray(ts), jnp.asarray(seq),
            jnp.asarray(op), jnp.asarray(valid))
        device_kept = np.asarray(order)[np.asarray(keep)]
        oracle = merge_dedup_numpy(series, ts, seq, op)
        assert device_kept.tolist() == oracle.tolist()

    def test_padding_rows_dropped(self):
        series = np.array([0, 0, 0], dtype=np.int32)
        ts = np.array([1, 2, 3], dtype=np.int64)
        seq = np.array([1, 2, 3], dtype=np.int64)
        op = np.zeros(3, dtype=np.int8)
        valid = np.array([True, True, False])
        order, keep = sort_merge_dedup(
            jnp.asarray(series), jnp.asarray(ts), jnp.asarray(seq),
            jnp.asarray(op), jnp.asarray(valid))
        kept = np.asarray(order)[np.asarray(keep)]
        assert 2 not in kept.tolist() and len(kept) == 2


def make_matrix():
    # 3 series; series 0: samples every 10s; series 1: sparse; series 2: empty
    s0_ts = np.arange(0, 300_000, 10_000, dtype=np.int64)
    s0_v = np.arange(len(s0_ts), dtype=np.float64)  # counter 0,1,2...
    s1_ts = np.array([50_000, 250_000], dtype=np.int64)
    s1_v = np.array([5.0, 2.0])
    series = np.concatenate([np.zeros(len(s0_ts)), np.ones(len(s1_ts))]).astype(np.int32)
    ts = np.concatenate([s0_ts, s1_ts])
    vals = np.concatenate([s0_v, s1_v])
    return SeriesMatrix.build(series, ts, vals, 3)


class TestWindow:
    def test_build(self):
        m = make_matrix()
        assert m.num_series == 3
        assert m.lengths.tolist() == [30, 2, 0]

    def test_avg_sum_count(self):
        m = make_matrix()
        # steps at 60s, 120s; range 60s → window (t-60s, t]
        out, ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            60_000, 60_000, 60_000, op="avg_over_time", nsteps=2)
        # series 0 window (0,60s]: samples at 10..60s → values 1..6 → avg 3.5
        np.testing.assert_allclose(out[0, 0], 3.5)
        # window (60s,120s]: values 7..12 → avg 9.5
        np.testing.assert_allclose(out[0, 1], 9.5)
        assert not bool(ok[2, 0])  # empty series
        out, _ = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            60_000, 60_000, 60_000, op="count_over_time", nsteps=2)
        assert out[0, 0] == 6

    def test_min_max_gather(self):
        m = make_matrix()
        out, ok = range_aggregate_gather(
            m.ts, m.values,
            60_000, 60_000, 60_000, op="max_over_time", nsteps=2, maxw=32)
        np.testing.assert_allclose(out[0, 0], 6.0)
        np.testing.assert_allclose(out[0, 1], 12.0)
        out, _ = range_aggregate_gather(
            m.ts, m.values,
            60_000, 60_000, 60_000, op="min_over_time", nsteps=2, maxw=32)
        np.testing.assert_allclose(out[0, 0], 1.0)

    def test_rate_steady_counter(self):
        m = make_matrix()
        # series 0 increases by 1 every 10s → rate = 0.1/s
        out, ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            100_000, 100_000, 100_000, op="rate", nsteps=2)
        assert bool(ok[0, 0])
        np.testing.assert_allclose(out[0, 0], 0.1, rtol=1e-6)

    def test_increase_with_reset(self):
        ts = np.arange(0, 50_000, 10_000, dtype=np.int64)
        vals = np.array([0.0, 10.0, 20.0, 5.0, 15.0])  # reset at i=3
        m = SeriesMatrix.build(np.zeros(5, np.int32), ts, vals, 1)
        out, ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            40_000, 40_000, 40_000, op="increase", nsteps=1)
        # within (0, 40000]: samples v=10,20,5,15 → adjusted 10,20,25,35
        # raw = 25; extrapolation factor: sampled=30000, durToStart/End=10000/0,
        # avg_dur=10000, threshold=11000 → ext=10000+0 → factor=40/30
        np.testing.assert_allclose(out[0, 0], 25 * (40000 / 30000), rtol=1e-6)

    def test_delta_gauge(self):
        ts = np.arange(0, 50_000, 10_000, dtype=np.int64)
        vals = np.array([10.0, 8.0, 6.0, 4.0, 2.0])
        m = SeriesMatrix.build(np.zeros(5, np.int32), ts, vals, 1)
        out, ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            40_000, 40_000, 40_000, op="delta", nsteps=1)
        np.testing.assert_allclose(out[0, 0], (2.0 - 8.0) * (40000 / 30000), rtol=1e-6)

    def test_changes_resets(self):
        ts = np.arange(0, 60_000, 10_000, dtype=np.int64)
        vals = np.array([1.0, 1.0, 2.0, 1.0, 1.0, 3.0])
        m = SeriesMatrix.build(np.zeros(6, np.int32), ts, vals, 1)
        out, _ = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            50_000, 50_000, 50_001, op="changes", nsteps=1)
        assert out[0, 0] == 3  # 1→2, 2→1, 1→3
        out, _ = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            50_000, 50_000, 50_001, op="resets", nsteps=1)
        assert out[0, 0] == 1

    def test_quantile(self):
        ts = np.arange(0, 40_000, 10_000, dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0, 4.0])
        m = SeriesMatrix.build(np.zeros(4, np.int32), ts, vals, 1)
        out, _ = range_aggregate_gather(
            m.ts, m.values,
            30_000, 30_000, 30_001, op="quantile_over_time", nsteps=1,
            maxw=8, param=0.5)
        np.testing.assert_allclose(out[0, 0], 2.5)

    def test_deriv(self):
        ts = np.arange(0, 50_000, 10_000, dtype=np.int64)
        vals = 2.0 * np.arange(5) + 3.0  # slope 2 per 10s = 0.2/s
        m = SeriesMatrix.build(np.zeros(5, np.int32), ts, vals, 1)
        out, ok = range_aggregate_gather(
            m.ts, m.values,
            40_000, 40_000, 40_001, op="deriv", nsteps=1, maxw=8)
        np.testing.assert_allclose(out[0, 0], 0.2, rtol=1e-5)

    def test_instant_select_lookback(self):
        m = make_matrix()
        vals, ok = instant_select(
            m.ts, m.values,
            55_000, 100_000, 300_000, nsteps=1)
        # series 1 latest sample at 50s (value 5.0) within 5m lookback
        assert bool(ok[1, 0]) and vals[1, 0] == 5.0
        # short lookback (1s) → no point
        vals, ok = instant_select(
            m.ts, m.values,
            55_000, 100_000, 1_000, nsteps=1)
        assert not bool(ok[1, 0])

    def test_idelta_first_last(self):
        ts = np.arange(0, 40_000, 10_000, dtype=np.int64)
        vals = np.array([1.0, 5.0, 2.0, 9.0])
        m = SeriesMatrix.build(np.zeros(4, np.int32), ts, vals, 1)
        args = (m.ts, m.values, m.lengths, 30_000, 30_000, 30_001)
        out, _ = range_aggregate_cumsum(*args, op="idelta", nsteps=1)
        np.testing.assert_allclose(out[0, 0], 7.0)
        out, _ = range_aggregate_cumsum(*args, op="last_over_time", nsteps=1)
        assert out[0, 0] == 9.0
        out, _ = range_aggregate_cumsum(*args, op="first_over_time", nsteps=1)
        assert out[0, 0] == 1.0


class TestReviewRegressions:
    """Regression tests for code-review findings."""

    def test_timestamp_eq_hash_cross_unit(self):
        from greptimedb_tpu.common.time import Timestamp, TimeUnit
        a = Timestamp(1, TimeUnit.SECOND)
        b = Timestamp(1000, TimeUnit.MILLISECOND)
        assert a == b and hash(a) == hash(b)
        assert len({a, b}) == 1

    def test_timestamp_ns_precision(self):
        from greptimedb_tpu.common.time import Timestamp, TimeUnit
        t = Timestamp.from_str("2023-01-02 03:04:05.123456", TimeUnit.NANOSECOND)
        assert t.value % 1_000_000_000 == 123_456_000

    def test_series_matrix_max_len_too_small(self):
        with pytest.raises(ValueError, match="max_len"):
            SeriesMatrix.build(np.zeros(10, np.int32),
                               np.arange(10, dtype=np.int64),
                               np.zeros(10), 1, max_len=4)

    def test_device_arrays_int32_rebase(self):
        base_ts = 1_700_000_000_000
        ts = base_ts + np.arange(0, 50_000, 10_000, dtype=np.int64)
        m = SeriesMatrix.build(np.zeros(5, np.int32), ts, np.arange(5.0), 2)
        rel, vals, lengths, base = m.device_arrays()
        assert rel.dtype == np.int32 and base == base_ts
        assert rel[0, 0] == 0 and rel[0, 4] == 40_000
        # padding sentinel survives as int32 max (still sorts last)
        assert rel[1, 0] == np.iinfo(np.int32).max
        # kernels accept the rebased arrays with rebased query times
        out, ok = range_aggregate_cumsum(
            jnp.asarray(rel), jnp.asarray(vals), jnp.asarray(lengths),
            40_000, 40_000, 40_001, op="sum_over_time", nsteps=1)
        np.testing.assert_allclose(out[0, 0], 10.0)

    def test_first_last_preserve_int_dtype(self):
        import jax
        gids = np.array([0], np.int32)
        mask = np.ones(1, bool)
        ts = np.array([5], np.int64)
        big = np.array([2**60 + 7], np.int64)
        if jax.config.jax_enable_x64:
            (fst,), _ = grouped_aggregate(gids, mask, ts, (big,),
                                          num_groups=2, ops=("first",))
            assert fst.dtype == jnp.int64
            assert int(fst[0]) == 2**60 + 7
        else:
            # production regime: values beyond int32 cannot ride the device
            # silently — the host guard must refuse, not truncate
            with pytest.raises(ValueError, match="rebase"):
                grouped_aggregate(gids, mask, ts, (big,),
                                  num_groups=2, ops=("first",))
        # in-range int values keep an integer dtype end to end
        small = np.array([123456], np.int64)
        (fst,), _ = grouped_aggregate(gids, mask, ts, (small,),
                                      num_groups=2, ops=("first",))
        assert jnp.issubdtype(fst.dtype, jnp.integer)
        assert int(fst[0]) == 123456

    def test_holt_winters(self):
        ts = np.arange(0, 60_000, 10_000, dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
        m = SeriesMatrix.build(np.zeros(6, np.int32), ts, vals, 1)
        out, ok = range_aggregate_gather(
            m.ts, m.values,
            50_000, 50_000, 50_001, op="holt_winters", nsteps=1, maxw=8,
            param=0.5, param2=0.5)
        assert bool(ok[0, 0])
        # perfectly linear data → smoothed value equals the last sample
        np.testing.assert_allclose(out[0, 0], 6.0, rtol=1e-5)

    def test_rate_negative_first_sample_no_zero_cap(self):
        ts = np.arange(0, 30_000, 10_000, dtype=np.int64)
        vals = np.array([-5.0, 5.0, 10.0])
        m = SeriesMatrix.build(np.zeros(3, np.int32), ts, vals, 1)
        out, ok = range_aggregate_cumsum(
            m.ts, m.values, m.lengths,
            30_000, 30_000, 30_001, op="increase", nsteps=1)
        assert bool(ok[0, 0])
        assert float(out[0, 0]) > 0  # not sign-flipped by a negative cap


# ---------------------------------------------------------------------------
# sorted_grouped_aggregate (the scatter-free LSM fast path)
# ---------------------------------------------------------------------------

class TestSortedGroupedAggregate:
    def _mk(self, n=50_000, groups=97, skew=False, seed=3):
        rng = np.random.default_rng(seed)
        if skew:
            raw = rng.zipf(1.5, n) % groups
        else:
            raw = rng.integers(0, groups, n)
        gids = np.sort(raw).astype(np.int32)
        mask = rng.random(n) > 0.15
        ts = np.arange(n, dtype=np.int32)  # sorted within groups by position
        vals = (rng.normal(size=n) * 50).astype(np.float32)
        return gids, mask, ts, vals

    @pytest.mark.parametrize("ops", [
        ("sum", "count", "avg", "min", "max"),
        ("stddev", "variance", "first", "last"),
    ])
    @pytest.mark.parametrize("skew", [False, True])
    def test_matches_scatter_kernel(self, ops, skew):
        from greptimedb_tpu.ops.kernels import (
            grouped_aggregate, sorted_grouped_aggregate)
        groups = 97
        gids, mask, ts, vals = self._mk(groups=groups, skew=skew)
        values = tuple(vals for _ in ops)
        got, counts = sorted_grouped_aggregate(
            gids, mask, ts, values, num_groups=groups, ops=ops)
        want, want_counts = grouped_aggregate(
            gids, mask, ts, values, num_groups=groups, ops=ops)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(want_counts))
        for op, g, w in zip(ops, got, want):
            # both kernels accumulate in f32; differing association orders
            # legitimately diverge ~1e-3 on cancellation-heavy skewed sums
            np.testing.assert_allclose(
                np.asarray(g, np.float64), np.asarray(w, np.float64),
                rtol=2e-3, atol=2e-3, err_msg=f"{op} skew={skew}")

    @pytest.mark.parametrize("ops", [
        ("min", "max"),
        ("first", "last"),
        ("min", "max", "first", "last", "avg"),
    ])
    def test_doubling_kernels_high_cardinality(self, ops):
        """The shift-doubling min/max + argext kernels (seg_len_k set,
        G > the high-card threshold) match the scatter oracle, including
        masked rows, empty groups, and skewed segment lengths."""
        from greptimedb_tpu.ops.kernels import (
            grouped_aggregate, sorted_grouped_aggregate)
        rng = np.random.default_rng(11)
        G = 9000                      # > _SEG_HIGH_CARD_THRESHOLD
        n = 120_000
        raw = np.concatenate([
            rng.integers(0, G, n - 5000),
            np.full(5000, 1234)])     # one fat segment (skew)
        gids = np.sort(raw).astype(np.int32)
        mask = rng.random(n) > 0.2
        ts = rng.integers(0, 1 << 20, n).astype(np.int32)
        vals = (rng.normal(size=n) * 50).astype(np.float32)
        ends = np.cumsum(np.bincount(gids, minlength=G),
                         dtype=np.int64).astype(np.int32)
        from greptimedb_tpu.ops.kernels import seg_len_bucket
        seg_k = seg_len_bucket(
            int(np.diff(ends, prepend=np.int32(0)).max()))
        values = tuple(vals for _ in ops)
        got, counts = sorted_grouped_aggregate(
            gids, mask, ts, values, num_groups=G, ops=ops, ends=ends,
            seg_len_k=seg_k)
        want, want_counts = grouped_aggregate(
            gids, mask, ts, values, num_groups=G, ops=ops)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(want_counts))
        for op, g, w in zip(ops, got, want):
            np.testing.assert_allclose(
                np.asarray(g, np.float64), np.asarray(w, np.float64),
                rtol=2e-3, atol=2e-3, err_msg=op)

    def test_small_and_empty_groups(self):
        from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate
        # groups 0,2 used; 1,3 empty; single-row group
        gids = np.array([0, 0, 0, 2], np.int32)
        mask = np.array([True, True, False, True])
        ts = np.arange(4, dtype=np.int32)
        vals = np.array([1.0, 5.0, 100.0, -3.0], np.float32)
        (s, mn, mx, fst), counts = sorted_grouped_aggregate(
            gids, mask, ts, (vals,) * 4, num_groups=4,
            ops=("sum", "min", "max", "first"))
        np.testing.assert_array_equal(np.asarray(counts), [2, 0, 1, 0])
        np.testing.assert_allclose(np.asarray(s), [6.0, 0.0, -3.0, 0.0])
        assert np.asarray(mn)[0] == 1.0 and np.asarray(mx)[0] == 5.0
        assert np.asarray(mn)[2] == -3.0
        assert np.asarray(fst)[0] == 1.0 and np.asarray(fst)[2] == -3.0
        assert np.isnan(np.asarray(fst)[1])

    def test_col_masks_null_semantics(self):
        from greptimedb_tpu.ops.kernels import (
            grouped_aggregate, sorted_grouped_aggregate)
        rng = np.random.default_rng(5)
        n, groups = 4096, 7
        gids = np.sort(rng.integers(0, groups, n)).astype(np.int32)
        mask = np.ones(n, bool)
        cm = rng.random(n) > 0.5
        ts = np.arange(n, dtype=np.int32)
        vals = rng.normal(size=n).astype(np.float32)
        got, _ = sorted_grouped_aggregate(
            gids, mask, ts, (vals, vals), (cm, np.ones(n, bool)),
            num_groups=groups, ops=("avg", "count"), has_col_masks=True)
        want, _ = grouped_aggregate(
            gids, mask, ts, (vals, vals), (cm, np.ones(n, bool)),
            num_groups=groups, ops=("avg", "count"), has_col_masks=True)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                                   rtol=1e-4)
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))

    def test_first_last_unsorted_ts_within_segment(self):
        # several series collapse into one GROUP BY key → ts NOT sorted
        # within the segment; first/last must still pick by extreme ts
        from greptimedb_tpu.ops.kernels import (
            grouped_aggregate, sorted_grouped_aggregate)
        rng = np.random.default_rng(11)
        n, groups = 5000, 5
        gids = np.sort(rng.integers(0, groups, n)).astype(np.int32)
        ts = rng.permutation(n).astype(np.int32)  # unique → no ties
        mask = rng.random(n) > 0.2
        vals = rng.normal(size=n).astype(np.float32)
        got, _ = sorted_grouped_aggregate(
            gids, mask, ts, (vals, vals), num_groups=groups,
            ops=("first", "last"))
        want, _ = grouped_aggregate(
            gids, mask, ts, (vals, vals), num_groups=groups,
            ops=("first", "last"))
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]))
        np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]))

    def test_block_boundary_segments(self):
        # segments straddling exactly the 1024-block boundaries
        from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate
        B = 1024
        sizes = [B - 1, 1, B, 2 * B - 2, 3, 2 * B + 5]
        gids = np.concatenate([np.full(s, i, np.int32)
                               for i, s in enumerate(sizes)])
        n = len(gids)
        vals = np.random.default_rng(0).normal(size=n).astype(np.float32)
        mask = np.ones(n, bool)
        ts = np.arange(n, dtype=np.int32)
        (s, mn, mx, lst), counts = sorted_grouped_aggregate(
            gids, mask, ts, (vals,) * 4, num_groups=len(sizes),
            ops=("sum", "min", "max", "last"))
        off = 0
        for i, sz in enumerate(sizes):
            seg = vals[off:off + sz]
            np.testing.assert_allclose(np.asarray(s)[i], seg.sum(), rtol=1e-4,
                                       atol=1e-4)
            assert np.asarray(mn)[i] == seg.min()
            assert np.asarray(mx)[i] == seg.max()
            assert np.asarray(lst)[i] == seg[-1]
            off += sz


class TestHighCardinalityPaths:
    """Force num_groups above _SEG_HIGH_CARD_THRESHOLD so the prefix-sum
    and in-block sparse-table paths (not the edge-window path) execute,
    cross-checked against the numpy oracle."""

    def _data(self, n=200_000, groups=20_000, seed=0):
        rng = np.random.default_rng(seed)
        gids = np.sort(rng.integers(0, groups, n)).astype(np.int32)
        ts = rng.integers(0, 1 << 30, n).astype(np.int64)
        vals = (rng.random(n, dtype=np.float32) * 100) - 50
        mask = rng.random(n) > 0.1
        return gids, mask, ts, vals, groups

    def test_sum_min_max_avg_vs_oracle(self):
        from greptimedb_tpu.ops.kernels import (
            _SEG_HIGH_CARD_THRESHOLD, sorted_grouped_aggregate)
        gids, mask, ts, vals, groups = self._data()
        assert groups > _SEG_HIGH_CARD_THRESHOLD
        ops = ("sum", "min", "max", "avg", "count")
        (s, mn, mx, av, ct), counts = sorted_grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            tuple(jnp.asarray(vals) for _ in ops),
            num_groups=groups, ops=ops)
        import pandas as pd
        df = pd.DataFrame({"g": gids[mask], "v": vals[mask]})
        want = df.groupby("g")["v"].agg(["sum", "min", "max", "mean",
                                         "count"])
        got_s, got_mn = np.asarray(s), np.asarray(mn)
        got_mx, got_av = np.asarray(mx), np.asarray(av)
        got_ct = np.asarray(ct)
        for g in want.index[:4000]:
            np.testing.assert_allclose(got_s[g], want.loc[g, "sum"],
                                       rtol=2e-4, atol=1e-3)
            assert got_mn[g] == np.float32(want.loc[g, "min"])
            assert got_mx[g] == np.float32(want.loc[g, "max"])
            np.testing.assert_allclose(got_av[g], want.loc[g, "mean"],
                                       rtol=2e-4, atol=1e-3)
            assert got_ct[g] == want.loc[g, "count"]
        # empty groups: count 0 and min/max at the +/-inf identities
        empty = np.setdiff1d(np.arange(groups), gids[mask])[:50]
        assert (got_ct[empty] == 0).all()
        if len(empty):
            assert np.isposinf(got_mn[empty]).all()
            assert np.isneginf(got_mx[empty]).all()

    def test_segments_spanning_blocks(self):
        """Shapes that hit every decomposition branch: empty, single-row,
        single-block, two-block-no-inner, many-inner-blocks."""
        from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate
        lens = [0, 1, 5, 31, 32, 33, 63, 64, 65, 200, 1024]
        groups = 9000                     # above the threshold
        seg = []
        for g, ln in enumerate(lens):
            seg += [g] * ln
        # the rest of the groups get 0-2 rows
        rng = np.random.default_rng(1)
        extra = np.sort(rng.integers(len(lens), groups, 5000))
        gids = np.concatenate([np.array(seg, np.int32),
                               extra.astype(np.int32)])
        n = len(gids)
        vals = (rng.random(n, dtype=np.float32) * 10) - 5
        mask = np.ones(n, bool)
        ts = np.arange(n, dtype=np.int64)
        (mn, mx), _counts = sorted_grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals), jnp.asarray(vals)),
            num_groups=groups, ops=("min", "max"))
        mn, mx = np.asarray(mn), np.asarray(mx)
        for g in range(len(lens)):
            rows = vals[gids == g]
            if len(rows):
                assert mn[g] == rows.min(), f"min len={lens[g]}"
                assert mx[g] == rows.max(), f"max len={lens[g]}"
        for g in np.unique(extra)[:200]:
            rows = vals[gids == g]
            assert mn[g] == rows.min() and mx[g] == rows.max()

    def test_precomputed_ends_match_device_bounds(self):
        """The host-ends fast path (LSM callers ship run boundaries) must
        agree exactly with the on-device searchsorted bounds."""
        from greptimedb_tpu.ops.kernels import sorted_grouped_aggregate
        rng = np.random.default_rng(9)
        n, groups = 100_000, 11_000
        gids = np.sort(rng.integers(0, groups, n)).astype(np.int32)
        mask = rng.random(n) > 0.2
        ts = np.arange(n, dtype=np.int32)
        vals = rng.normal(size=n).astype(np.float32)
        ends = np.cumsum(np.bincount(gids, minlength=groups),
                         dtype=np.int64).astype(np.int32)
        ops = ("sum", "avg", "min", "max", "count", "first", "last")
        values = tuple(vals for _ in ops)
        got, counts = sorted_grouped_aggregate(
            gids, mask, ts, values, num_groups=groups, ops=ops, ends=ends)
        want, want_counts = sorted_grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            tuple(jnp.asarray(v) for v in values),
            num_groups=groups, ops=ops)
        np.testing.assert_array_equal(np.asarray(counts),
                                      np.asarray(want_counts))
        for op, g, w in zip(ops, got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-5, atol=1e-5, err_msg=op,
                                       equal_nan=True)

    def test_first_last_high_cardinality(self):
        """first/last above the threshold (two-pass argext path) vs a
        pandas oracle, with unsorted ts inside segments and ties."""
        from greptimedb_tpu.ops.kernels import (
            _SEG_HIGH_CARD_THRESHOLD, sorted_grouped_aggregate)
        rng = np.random.default_rng(5)
        n, groups = 120_000, 20_000
        assert groups > _SEG_HIGH_CARD_THRESHOLD
        gids = np.sort(rng.integers(0, groups, n)).astype(np.int32)
        ts = rng.integers(0, 50, n).astype(np.int64)   # many ties
        vals = rng.random(n, dtype=np.float32)
        mask = rng.random(n) > 0.15
        (first, last), _c = sorted_grouped_aggregate(
            jnp.asarray(gids), jnp.asarray(mask), jnp.asarray(ts),
            (jnp.asarray(vals), jnp.asarray(vals)),
            num_groups=groups, ops=("first", "last"))
        first, last = np.asarray(first), np.asarray(last)
        import pandas as pd
        df = pd.DataFrame({"g": gids, "t": ts, "v": vals,
                           "i": np.arange(n)})[mask]
        # oracle: smallest (t, i) / largest (t, i) per group
        fo = df.sort_values(["g", "t", "i"]).groupby("g").first()["v"]
        lo = df.sort_values(["g", "t", "i"]).groupby("g").last()["v"]
        for g in fo.index[:3000]:
            assert first[g] == np.float32(fo.loc[g]), g
            assert last[g] == np.float32(lo.loc[g]), g
