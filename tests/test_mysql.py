"""MySQL wire protocol server tests.

A minimal spec-following client (handshake response 41, COM_QUERY text
protocol, COM_STMT_* binary protocol) drives the server end-to-end —
the same flow the reference exercises via real `mysql` clients in
tests-integration (and the README quick-start monitor-table flow).
"""

import socket
import struct

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.servers.auth import StaticUserProvider
from greptimedb_tpu.servers.mysql import (
    CLIENT_CONNECT_WITH_DB, CLIENT_PLUGIN_AUTH, CLIENT_PROTOCOL_41,
    CLIENT_SECURE_CONNECTION, COM_INIT_DB, COM_PING, COM_QUERY,
    COM_STMT_EXECUTE, COM_STMT_PREPARE, MysqlServer, PacketIO,
    native_password_scramble, lenenc_str, read_lenenc_int, read_lenenc_str)


class MiniMysqlClient:
    """Just enough of the client side of the protocol for tests."""

    def __init__(self, port, user="greptime", password="", database=None):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.io = PacketIO(self.sock)
        self._login(user, password, database)

    def _login(self, user, password, database):
        greeting = self.io.read_packet()
        assert greeting[0] == 10, "expected protocol 10 greeting"
        end = greeting.index(b"\x00", 1)
        self.server_version = greeting[1:end].decode()
        pos = end + 1 + 4
        nonce = greeting[pos:pos + 8]
        pos += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10
        nonce += greeting[pos:pos + 12]
        self.nonce = nonce
        caps = (CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION
                | CLIENT_PLUGIN_AUTH)
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = native_password_scramble(password, nonce)
        body = (struct.pack("<IIB", caps, 1 << 24, 45) + b"\x00" * 23
                + user.encode() + b"\x00"
                + bytes([len(auth)]) + auth)
        if database:
            body += database.encode() + b"\x00"
        body += b"mysql_native_password\x00"
        self.io.write_packet(body)
        resp = self.io.read_packet()
        if resp[0] == 0xFF:
            raise ConnectionRefusedError(self._err_message(resp))
        assert resp[0] == 0x00

    @staticmethod
    def _err_message(packet):
        return packet[9:].decode(errors="replace")

    def _command(self, cmd, payload=b""):
        self.io.reset_seq()
        self.io.write_packet(bytes([cmd]) + payload)

    def ping(self):
        self._command(COM_PING)
        return self.io.read_packet()[0] == 0x00

    def use(self, db):
        self._command(COM_INIT_DB, db.encode())
        assert self.io.read_packet()[0] == 0x00

    def query(self, sql):
        """Returns (column_names, rows) or int affected-rows."""
        self._command(COM_QUERY, sql.encode())
        return self._read_result(binary=False)

    def _read_result(self, binary):
        head = self.io.read_packet()
        if head[0] == 0xFF:
            raise RuntimeError(self._err_message(head))
        if head[0] == 0x00:
            affected, _ = read_lenenc_int(head, 1)
            return affected
        ncols, _ = read_lenenc_int(head, 0)
        names = []
        for _ in range(ncols):
            col = self.io.read_packet()
            pos = 0
            for _ in range(4):                    # def, schema, tbl, org_tbl
                _, pos = read_lenenc_str(col, pos)
            name, pos = read_lenenc_str(col, pos)
            names.append(name.decode())
        assert self.io.read_packet()[0] == 0xFE   # EOF after columns
        rows = []
        while True:
            p = self.io.read_packet()
            if p[0] == 0xFE and len(p) < 9:
                break
            rows.append(self._parse_binary_row(p, ncols) if binary
                        else self._parse_text_row(p, ncols))
        return names, rows

    @staticmethod
    def _parse_text_row(p, ncols):
        row, pos = [], 0
        for _ in range(ncols):
            if p[pos] == 0xFB:
                row.append(None)
                pos += 1
            else:
                v, pos = read_lenenc_str(p, pos)
                row.append(v.decode())
        return row

    @staticmethod
    def _parse_binary_row(p, ncols):
        assert p[0] == 0x00
        nbytes = (ncols + 9) // 8
        bitmap = p[1:1 + nbytes]
        pos = 1 + nbytes
        row = []
        for i in range(ncols):
            if bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                row.append(None)
            else:
                v, pos = read_lenenc_str(p, pos)
                row.append(v.decode())
        return row

    def stmt_prepare(self, sql):
        self._command(COM_STMT_PREPARE, sql.encode())
        p = self.io.read_packet()
        if p[0] == 0xFF:
            raise RuntimeError(self._err_message(p))
        stmt_id = struct.unpack_from("<I", p, 1)[0]
        num_params = struct.unpack_from("<H", p, 7)[0]
        for _ in range(num_params):
            self.io.read_packet()
        if num_params:
            assert self.io.read_packet()[0] == 0xFE
        return stmt_id, num_params

    def stmt_execute(self, stmt_id, params=()):
        body = struct.pack("<IBI", stmt_id, 0, 1)
        if params:
            n = len(params)
            bitmap = bytearray((n + 7) // 8)
            types = b""
            values = b""
            for i, v in enumerate(params):
                if v is None:
                    bitmap[i // 8] |= 1 << (i % 8)
                    types += struct.pack("<H", 6)
                elif isinstance(v, int):
                    types += struct.pack("<H", 8)
                    values += struct.pack("<q", v)
                elif isinstance(v, float):
                    types += struct.pack("<H", 5)
                    values += struct.pack("<d", v)
                else:
                    types += struct.pack("<H", 253)
                    values += lenenc_str(str(v).encode())
            body += bytes(bitmap) + b"\x01" + types + values
        self._command(COM_STMT_EXECUTE, body)
        return self._read_result(binary=True)

    def close(self):
        try:
            self._command(0x01)
            self.sock.close()
        except OSError:
            pass


@pytest.fixture()
def server(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    srv = MysqlServer(fe)
    srv.serve_in_background()
    yield srv
    srv.shutdown()
    fe.shutdown()


@pytest.fixture()
def client(server):
    c = MiniMysqlClient(server.port)
    yield c
    c.close()


class TestMysqlProtocol:
    def test_ping(self, client):
        assert client.ping()

    def test_quickstart_monitor_flow(self, client):
        """README quick-start: create, insert, aggregate (the flow the
        reference's MySQL handler demos, handler.rs:386)."""
        assert client.query(
            "CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME INDEX,"
            " cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))") == 0
        assert client.query(
            "INSERT INTO monitor VALUES ('host1', 1000, 66.6, 1024),"
            " ('host2', 2000, 77.7, 2048), ('host1', 3000, 99.9, 4096)"
        ) == 3
        names, rows = client.query(
            "SELECT host, avg(cpu) AS c FROM monitor GROUP BY host"
            " ORDER BY host")
        assert names == ["host", "c"]
        assert rows == [["host1", "83.25"], ["host2", "77.7"]]

    def test_timestamp_formatting(self, client):
        client.query("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        client.query("INSERT INTO t VALUES (1672531200000, 1.5)")
        _, rows = client.query("SELECT ts, v FROM t")
        assert rows == [["2023-01-01 00:00:00.000", "1.5"]]

    def test_error_packet(self, client):
        with pytest.raises(RuntimeError, match="not found"):
            client.query("SELECT * FROM nope_nothing")

    def test_federated_bootstrap(self, client):
        names, rows = client.query("SELECT @@version_comment")
        assert names == ["@@version_comment"]
        assert "GreptimeDB" in rows[0][0]
        assert client.query("SET NAMES utf8mb4") == 0
        assert client.query("SET autocommit=1") == 0
        names, rows = client.query("SHOW VARIABLES LIKE 'sql_mode'")
        assert names == ["Variable_name", "Value"]
        names, rows = client.query("SELECT database()")
        assert rows == [["public"]]

    def test_use_database(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS otherdb")
        client.use("otherdb")
        _, rows = client.query("SELECT database()")
        assert rows == [["otherdb"]]

    def test_show_and_describe(self, client):
        client.query("CREATE TABLE shown (ts TIMESTAMP TIME INDEX,"
                     " v DOUBLE)")
        names, rows = client.query("SHOW TABLES")
        assert ["shown"] in rows
        names, rows = client.query("DESCRIBE TABLE shown")
        assert any(r[0] == "ts" for r in rows)

    def test_show_processlist_and_kill(self, client):
        """SHOW PROCESSLIST over the wire lists the statement itself;
        KILL of an unknown id is an ER-packet, not a dropped
        connection; COM_PROCESS_KILL takes the same path."""
        names, rows = client.query("SHOW PROCESSLIST")
        assert "Info" in names and "Id" in names
        infos = [r[names.index("Info")] for r in rows]
        assert any("SHOW PROCESSLIST" in (i or "") for i in infos)
        proto = [r[names.index("Protocol")] for r in rows]
        assert "mysql" in proto
        with pytest.raises(RuntimeError, match="no such running"):
            client.query("KILL 424242")
        # wire-level COM_PROCESS_KILL: unknown id → ER packet too
        client._command(0x0C, struct.pack("<I", 424242))
        pkt = client.io.read_packet()
        assert pkt[0] == 0xFF
        assert b"no such running" in pkt
        assert client.ping()                 # connection survives

    def test_prepared_statements(self, client):
        client.query("CREATE TABLE pst (host STRING, ts TIMESTAMP"
                     " TIME INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        stmt, nparams = client.stmt_prepare(
            "INSERT INTO pst (host, ts, cpu) VALUES (?, ?, ?)")
        assert nparams == 3
        assert client.stmt_execute(stmt, ("h1", 1000, 3.25)) == 1
        assert client.stmt_execute(stmt, ("h2", 2000, 4.75)) == 1
        stmt2, _ = client.stmt_prepare(
            "SELECT cpu FROM pst WHERE host = ?")
        names, rows = client.stmt_execute(stmt2, ("h2",))
        assert rows == [["4.75"]]

    def test_handshake_salt_random_printable(self, server):
        # real MySQL servers send a per-connection random salt of printable
        # non-zero bytes: NUL truncates the scramble in libmysqlclient, and
        # a deterministic salt allows auth-response replay
        c1 = MiniMysqlClient(server.port)
        c2 = MiniMysqlClient(server.port)
        for c in (c1, c2):
            assert len(c.nonce) == 20
            assert all(0x21 <= b <= 0x7E for b in c.nonce), c.nonce
        assert c1.nonce != c2.nonce, "salt must differ per connection"
        c1.close()
        c2.close()

    def test_multiple_clients(self, server):
        c1 = MiniMysqlClient(server.port)
        c2 = MiniMysqlClient(server.port)
        c1.query("CREATE TABLE multi (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        c2.query("INSERT INTO multi VALUES (1, 2.0)")
        _, rows = c1.query("SELECT count(*) AS n FROM multi")
        assert rows == [["1"]]
        c1.close()
        c2.close()


class TestMysqlAuth:
    @pytest.fixture()
    def auth_server(self, tmp_path):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        srv = MysqlServer(fe, user_provider=StaticUserProvider(
            {"greptime": "hunter2"}))
        srv.serve_in_background()
        yield srv
        srv.shutdown()
        fe.shutdown()

    def test_good_password(self, auth_server):
        c = MiniMysqlClient(auth_server.port, user="greptime",
                            password="hunter2")
        assert c.ping()
        c.close()

    def test_bad_password(self, auth_server):
        with pytest.raises(ConnectionRefusedError, match="Access denied"):
            MiniMysqlClient(auth_server.port, user="greptime",
                            password="wrong")

    def test_unknown_user(self, auth_server):
        with pytest.raises(ConnectionRefusedError):
            MiniMysqlClient(auth_server.port, user="nobody", password="x")

    def test_connect_with_db(self, auth_server):
        c = MiniMysqlClient(auth_server.port, user="greptime",
                            password="hunter2", database="public")
        _, rows = c.query("SELECT database()")
        assert rows == [["public"]]
        c.close()
