"""Storage engine tests: WAL, memtable, SST, manifest, region lifecycle.

Mirrors reference suites: src/storage/src/wal.rs tests, memtable/tests.rs,
region/tests/{basic,flush,alter,projection}.rs, manifest/region.rs tests.
"""

import os

import numpy as np
import pytest

from greptimedb_tpu.common.time import TimestampRange
from greptimedb_tpu.datatypes import (
    FLOAT64, INT64, STRING, TIMESTAMP_MILLISECOND, ColumnSchema, Schema,
    SemanticType,
)
from greptimedb_tpu.storage import EngineConfig, StorageEngine, WriteBatch
from greptimedb_tpu.storage.object_store import FsObjectStore
from greptimedb_tpu.storage.manifest import RegionManifest
from greptimedb_tpu.storage.wal import Wal


def monitor_schema() -> Schema:
    return Schema([
        ColumnSchema("host", STRING, nullable=False, semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", FLOAT64),
        ColumnSchema("memory", FLOAT64),
    ])


def make_engine(tmp_path, **kwargs) -> StorageEngine:
    return StorageEngine(EngineConfig(data_home=str(tmp_path), **kwargs))


def put_rows(region, hosts, ts, cpu, memory=None):
    wb = WriteBatch(region.version_control.current.schema)
    wb.put({"host": hosts, "ts": ts, "cpu": cpu,
            "memory": memory if memory is not None else [0.0] * len(hosts)})
    return region.write(wb)


class TestWal:
    def test_roundtrip(self, tmp_path):
        wal = Wal(str(tmp_path / "wal"))
        for i in range(1, 6):
            wal.append(i, f"payload-{i}".encode(), schema_version=2)
        got = list(wal.read_from(3))
        assert [(s, v, p.decode()) for s, v, p in got] == [
            (3, 2, "payload-3"), (4, 2, "payload-4"), (5, 2, "payload-5")]
        wal.close()

    def test_torn_tail_tolerated(self, tmp_path):
        wal = Wal(str(tmp_path / "wal"))
        wal.append(1, b"good")
        wal.close()
        # corrupt: append garbage half-record
        segs = [f for f in os.listdir(tmp_path / "wal") if f.endswith(".wal")]
        with open(tmp_path / "wal" / segs[0], "ab") as f:
            f.write(b"\xff\x13\x07")
        wal2 = Wal(str(tmp_path / "wal"))
        got = list(wal2.read_from(0))
        assert len(got) == 1 and got[0][2] == b"good"

    def test_obsolete_deletes_old_segments(self, tmp_path):
        wal = Wal(str(tmp_path / "wal"), segment_bytes=64)
        for i in range(1, 11):
            wal.append(i, b"x" * 100)  # forces one segment per record
        assert len([f for f in os.listdir(tmp_path / "wal")]) == 10
        wal.obsolete(8)
        remaining = sorted(os.listdir(tmp_path / "wal"))
        assert len(remaining) < 10
        got = [s for s, _, _ in wal.read_from(9)]
        assert got == [9, 10]
        wal.close()


class TestManifest:
    def test_log_and_recover(self, tmp_path):
        store = FsObjectStore(str(tmp_path))
        m = RegionManifest(store, "r1/manifest")
        m.save([{"type": "change", "schema": {"v": 1}}])
        m.save([{"type": "edit", "added": ["f1"]}])
        m2 = RegionManifest(store, "r1/manifest")
        state, actions = m2.load()
        assert state is None
        assert [a["type"] for a in actions] == ["change", "edit"]
        # writer resumes past recovered version
        v = m2.save([{"type": "edit", "added": ["f2"]}])
        assert v == 2

    def test_checkpoint_and_gc(self, tmp_path):
        store = FsObjectStore(str(tmp_path))
        m = RegionManifest(store, "r1/manifest", checkpoint_margin=3)
        for i in range(4):
            m.save([{"type": "edit", "i": i}])
        assert m.should_checkpoint()
        m.save_checkpoint({"snapshot": True})
        m.gc()
        state, actions = RegionManifest(store, "r1/manifest").load()
        assert state == {"snapshot": True}
        assert actions == []
        # new actions after checkpoint are replayed
        m.save([{"type": "edit", "i": 99}])
        state, actions = RegionManifest(store, "r1/manifest").load()
        assert state == {"snapshot": True} and actions[0]["i"] == 99


class TestRegionBasic:
    def test_write_and_scan(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a", "b", "a"], [1000, 1000, 2000], [0.1, 0.2, 0.3])
        snap = r.snapshot()
        data = snap.read_merged()
        assert data.num_rows == 3
        # sorted by (series, ts): a@1000, a@2000, b@1000
        hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
        assert hosts == ["a", "a", "b"]
        assert data.ts.tolist() == [1000, 2000, 1000]
        np.testing.assert_allclose(data.fields["cpu"][0], [0.1, 0.3, 0.2])
        eng.close()

    def test_overwrite_same_key(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        put_rows(r, ["a"], [1000], [0.9])
        data = r.snapshot().read_merged()
        assert data.num_rows == 1
        np.testing.assert_allclose(data.fields["cpu"][0], [0.9])
        eng.close()

    def test_delete(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a", "b"], [1000, 1000], [0.1, 0.2])
        wb = WriteBatch(r.version_control.current.schema)
        wb.delete({"host": ["a"], "ts": [1000]})
        r.write(wb)
        data = r.snapshot().read_merged()
        assert data.num_rows == 1
        assert data.series_dict.decode_tag_column(data.series_ids, 0) == ["b"]
        eng.close()

    def test_snapshot_isolation(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        snap = r.snapshot()         # visible seq = 1
        put_rows(r, ["a"], [2000], [0.2])
        assert snap.read_merged().num_rows == 1
        assert r.snapshot().read_merged().num_rows == 2
        eng.close()

    def test_time_range_scan(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"] * 5, [0, 1000, 2000, 3000, 4000],
                 [0.0, 0.1, 0.2, 0.3, 0.4])
        data = r.snapshot().read_merged(time_range=TimestampRange(1000, 3000))
        assert data.ts.tolist() == [1000, 2000]
        eng.close()


class TestFlushRecovery:
    def test_flush_creates_sst_and_scan_merges(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a", "b"], [1000, 1000], [0.1, 0.2])
        files = r.flush()
        assert len(files) == 1 and files[0].num_rows == 2
        assert files[0].time_range == (1000, 1000)
        # post-flush writes overwrite flushed rows through the merge
        put_rows(r, ["a"], [1000], [0.7])
        data = r.snapshot().read_merged()
        assert data.num_rows == 2
        hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
        cpu = dict(zip(hosts, data.fields["cpu"][0]))
        np.testing.assert_allclose(cpu["a"], 0.7)
        eng.close()

    def test_crash_recovery_wal_replay(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        r.flush()
        put_rows(r, ["b"], [2000], [0.2])  # only in WAL + memtable
        # simulate crash: no close/flush; reopen from disk
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert r2 is not None
        data = r2.snapshot().read_merged()
        assert data.num_rows == 2
        hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
        assert sorted(hosts) == ["a", "b"]
        # sequences continue after recovery
        put_rows(r2, ["c"], [3000], [0.3])
        assert r2.snapshot().read_merged().num_rows == 3
        eng2.close()

    def test_series_ids_stable_across_restart(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a", "b", "c"], [1, 1, 1], [0.1, 0.2, 0.3])
        r.flush()
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        # same ids as before restart
        assert r2.series_dict.series.get((0,)) == 0
        assert [r2.series_dict.tag_dicts[0].value(i) for i in range(3)] == \
            ["a", "b", "c"]
        put_rows(r2, ["b", "d"], [2, 2], [0.5, 0.6])
        data = r2.snapshot().read_merged()
        hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
        assert sorted(hosts) == ["a", "b", "b", "c", "d"]
        eng2.close()

    def test_open_missing_region_returns_none(self, tmp_path):
        eng = make_engine(tmp_path)
        assert eng.open_region("nope/r9") is None

    def test_flush_wal_truncation(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        for i in range(5):
            put_rows(r, ["a"], [i * 1000], [float(i)])
        r.flush()
        # reopen: nothing to replay, all rows from SST
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert r2.snapshot().read_merged().num_rows == 5
        assert r2.version_control.committed_sequence == 5
        eng2.close()

    def test_checkpoint_recovery(self, tmp_path):
        eng = make_engine(tmp_path, checkpoint_margin=2)
        r = eng.create_region("t/r0", monitor_schema())
        for i in range(6):
            put_rows(r, ["a"], [i * 1000], [float(i)])
            r.flush()
        eng2 = make_engine(tmp_path, checkpoint_margin=2)
        r2 = eng2.open_region("t/r0")
        assert r2.snapshot().read_merged().num_rows == 6
        eng2.close()


class TestAlter:
    def test_add_column(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        r.flush()
        old = r.version_control.current.schema
        new_schema = Schema(list(old.column_schemas) +
                            [ColumnSchema("disk", FLOAT64)], version=old.version)
        r.alter(new_schema)
        wb = WriteBatch(r.version_control.current.schema)
        wb.put({"host": ["b"], "ts": [2000], "cpu": [0.2], "memory": [1.0],
                "disk": [99.0]})
        r.write(wb)
        data = r.snapshot().read_merged()
        assert data.num_rows == 2
        disk, valid = data.fields["disk"]
        # old SST row reads disk as null; new row has 99.0
        hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
        by_host = {h: (d, v) for h, d, v in zip(hosts, disk, valid)}
        assert by_host["a"][1] == False  # noqa: E712
        assert by_host["b"][0] == 99.0 and bool(by_host["b"][1])
        eng.close()

    def test_alter_survives_restart(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        old = r.version_control.current.schema
        r.alter(Schema(list(old.column_schemas) +
                       [ColumnSchema("disk", FLOAT64)]))
        wb = WriteBatch(r.version_control.current.schema)
        wb.put({"host": ["a"], "ts": [1000], "cpu": [0.1], "memory": [0.5],
                "disk": [42.0]})
        r.write(wb)
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert r2.version_control.current.schema.contains("disk")
        data = r2.snapshot().read_merged()
        assert data.fields["disk"][0].tolist() == [42.0]
        eng2.close()


class TestProjectionAndDrop:
    def test_projection(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1], [2048.0])
        r.flush()
        data = r.snapshot().read_merged(projection=["cpu"])
        assert set(data.fields.keys()) == {"cpu"}
        eng.close()

    def test_drop(self, tmp_path):
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        eng.drop_region("t/r0")
        eng2 = make_engine(tmp_path)
        assert eng2.open_region("t/r0") is None


class TestReviewRegressions:
    def test_create_over_existing_region_rejected(self, tmp_path):
        from greptimedb_tpu.errors import StorageError
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        put_rows(r, ["a"], [1000], [0.1])
        r.flush()
        eng.close()
        eng2 = make_engine(tmp_path)
        with pytest.raises(StorageError, match="already exists"):
            eng2.create_region("t/r0", monitor_schema())
        # open still works and sees the data
        r2 = eng2.open_region("t/r0")
        assert r2.snapshot().read_merged().num_rows == 1
        eng2.close()

    def test_wal_midlog_corruption_aborts_replay(self, tmp_path):
        from greptimedb_tpu.errors import StorageError
        wal = Wal(str(tmp_path / "wal"), segment_bytes=64)
        for i in range(1, 4):
            wal.append(i, b"y" * 100)  # one segment per record
        wal.close()
        segs = sorted(os.listdir(tmp_path / "wal"))
        # corrupt the FIRST segment's payload byte
        p = tmp_path / "wal" / segs[0]
        raw = bytearray(p.read_bytes())
        raw[-1] ^= 0xFF
        p.write_bytes(bytes(raw))
        wal2 = Wal(str(tmp_path / "wal"))
        with pytest.raises(StorageError, match="mid-log"):
            list(wal2.read_from(0))

    def test_nullable_time_index_rejected(self):
        from greptimedb_tpu.datatypes import Schema, ColumnSchema, SemanticType
        from greptimedb_tpu.datatypes import TIMESTAMP_MILLISECOND
        with pytest.raises(ValueError, match="non-nullable"):
            Schema([ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=True,
                                 semantic_type=SemanticType.TIMESTAMP)])

    def test_put_recordbatch_schema_mismatch_rejected(self, tmp_path):
        from greptimedb_tpu.datatypes import (
            RecordBatch, Schema, ColumnSchema, FLOAT64)
        from greptimedb_tpu.errors import InvalidArgumentsError
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        bad_schema = Schema([ColumnSchema("x", FLOAT64)])
        bad = RecordBatch.from_pydict(bad_schema, {"x": [1.0]})
        wb = WriteBatch(r.version_control.current.schema)
        with pytest.raises(InvalidArgumentsError, match="columns"):
            wb.put(bad)
        eng.close()

    def test_i64_guard_without_x64(self):
        import jax
        from greptimedb_tpu.ops.kernels import sort_merge_dedup
        prev = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", False)  # the TPU default
        try:
            ts = np.array([1_700_000_000_000, 1_700_000_000_000 + 2**32],
                          dtype=np.int64)
            with pytest.raises(ValueError, match="rebase"):
                sort_merge_dedup(np.zeros(2, np.int32), ts,
                                 np.arange(2, dtype=np.int64),
                                 np.zeros(2, np.int8), np.ones(2, bool))
        finally:
            jax.config.update("jax_enable_x64", prev)
