"""Per-region read replicas (ISSUE 19).

A leader datanode streams committed WAL records to standby copies of
its regions on other nodes (datanode/replication.py); the balancer's
replica_add/replica_remove op docs drive attach/detach as resumable
state machines; meta's failover_check PROMOTES the most-caught-up
follower when a leader dies — salvaging the dead leader's surviving WAL
records so zero acked rows are lost. These tests drive the whole loop
cooperatively over the shared-data_home deployment shape (one data_home,
node-scoped nodes/<id>/wal dirs) where promotion can reach the dead
leader's WAL.

tests/test_cluster.py holds the multi-process (real kill -9) acceptance
twin; tests/test_balancer.py established the Cluster pump pattern.
"""

import threading
import time

import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.client import LocalDatanodeClient
from greptimedb_tpu.common import failpoint
from greptimedb_tpu.common.failpoint import SimulatedCrash
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import (
    GreptimeError, InvalidArgumentsError, StaleRouteError, UnsupportedError)
from greptimedb_tpu.frontend.distributed import configure_read_replica
from greptimedb_tpu.meta import DatanodeStat, MetaClient, MetaSrv, Peer
from greptimedb_tpu.meta.kv import FileKv
from greptimedb_tpu.meta.service import PROMOTE_PREFIX

from test_balancer import FULL, Cluster, _region0_owner, _setup_table


class ReplCluster(Cluster):
    """Cluster whose datanodes share ONE data_home (node-scoped WAL dirs
    under nodes/<id>/wal, the shared-object-store deployment shape) so a
    promoted follower can fence + salvage a dead leader's WAL."""

    def __init__(self, tmp_path, nodes=(1, 2, 3), kv=None,
                 lease_secs=3600.0, sync_wal=False):
        self._sync_wal = sync_wal
        super().__init__(tmp_path, nodes=nodes, kv=kv,
                         lease_secs=lease_secs)

    def _start_datanode(self, i):
        dn = DatanodeInstance(
            DatanodeOptions(data_home=str(self.tmp_path / "home"),
                            node_id=i, register_numbers_table=False,
                            wal_sync_on_write=self._sync_wal),
            store=self.shared)
        dn.start()
        dn.attach_meta(self.meta)
        self.datanodes[i] = dn
        self.clients[i] = LocalDatanodeClient(dn)
        self.srv.register_datanode(Peer(i, f"dn{i}"))
        self.srv.handle_heartbeat(i)
        return dn


@pytest.fixture()
def cluster(tmp_path):
    failpoint.reset()
    configure_read_replica(mode="leader", max_lag_ms=5000)
    c = ReplCluster(tmp_path)
    yield c
    failpoint.reset()
    configure_read_replica(mode="leader", max_lag_ms=5000)
    c.shutdown()


def _beat_full(c, i, now=None):
    """One stat-bearing heartbeat, the feed behind replicated_seq/lag_ms
    (production: DatanodeInstance.start_heartbeat's full beats)."""
    from greptimedb_tpu.query.stream_exec import region_stat_entries
    dn = c.datanodes[i]
    regions = dn.storage.list_regions()
    entries, rows, nbytes = region_stat_entries(regions.values())
    return c.srv.handle_heartbeat(
        i, DatanodeStat(region_count=len(regions), approximate_rows=rows,
                        approximate_bytes=nbytes, region_stats=entries),
        now=now)


def _add_replica(c, target=None, region=0):
    """ADMIN ADD REPLICA region 0 onto `target` (default: any
    non-leader); returns (leader_id, target_id)."""
    leader = _region0_owner(c)
    if target is None:
        target = next(i for i in c.datanodes if i != leader)
    out = c.fe.do_query(f"ADMIN ADD REPLICA ha {region} TO {target}")[-1]
    assert out.batches, "ADMIN ADD REPLICA returned no op row"
    assert c.pump(), f"replica_add never finished: {c.srv.balancer.ops()}"
    assert c.srv.balancer.done_ops()[-1]["state"] == "done"
    return leader, target


def _r0(c, node):
    """The region-0 Region object hosted on `node`."""
    return c.datanodes[node].catalog.table(CAT, SCH, "ha").regions[0]


def _deliver(c, node):
    """Drain `node`'s meta mailbox (one heartbeat's worth)."""
    resp = c.srv.handle_heartbeat(node)
    for msg in resp.mailbox:
        c.datanodes[node]._handle_mailbox(msg)


def _fail_leader(c, leader):
    """Silence the leader past 2x its lease and run failover."""
    c.hard_kill(leader)
    c.srv._last_seen[leader] = 0.0
    return c.srv.failover_check()


class TestReplicaLifecycle:
    def test_add_replica_bootstraps_standby(self, cluster):
        c = cluster
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        route = c.srv.table_route(FULL)
        rr0 = next(r for r in route.region_routes if r.region_number == 0)
        assert [f.id for f in rr0.followers] == [target]
        assert rr0.leader.id == leader
        assert route.version == 1
        # the standby is fenced for writes but holds the leader's data
        std = _r0(c, target)
        assert std.standby and std.fenced
        lead = _r0(c, leader)
        assert (std.version_control.committed_sequence ==
                lead.version_control.committed_sequence)
        # the leader's shipper is wired for continuous tail shipping
        targets = c.datanodes[leader].replication.targets()
        assert lead.name in targets
        assert len(targets[lead.name]["followers"]) == 1
        # writes through the frontend still ack against the leader only
        c.fe.do_query("INSERT INTO ha VALUES ('h1', 99000, 1.0)")
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == 21

    def test_add_replica_validations(self, cluster):
        c = cluster
        _setup_table(c)
        leader = _region0_owner(c)
        with pytest.raises(GreptimeError, match="leads"):
            c.fe.do_query(f"ADMIN ADD REPLICA ha 0 TO {leader}")
        with pytest.raises(GreptimeError):
            c.fe.do_query("ADMIN ADD REPLICA ha 0 TO 9")   # unregistered
        with pytest.raises(GreptimeError, match="replica"):
            c.fe.do_query("ADMIN REMOVE REPLICA ha 0 FROM 3")
        _, target = _add_replica(c)
        with pytest.raises(GreptimeError, match="already"):
            c.fe.do_query(f"ADMIN ADD REPLICA ha 0 TO {target}")

    def test_remove_replica_detaches_standby(self, cluster):
        c = cluster
        _setup_table(c)
        leader, target = _add_replica(c)
        lead_name = _r0(c, leader).name
        out = c.fe.do_query(
            f"ADMIN REMOVE REPLICA ha 0 FROM {target}")[-1]
        assert out.batches
        assert c.pump(), c.srv.balancer.ops()
        assert c.srv.balancer.done_ops()[-1]["state"] == "done"
        route = c.srv.table_route(FULL)
        rr0 = next(r for r in route.region_routes if r.region_number == 0)
        assert not rr0.followers
        # the standby region is gone from the follower node and the
        # leader's shipper is unwired
        assert lead_name not in c.datanodes[target].storage.list_regions()
        assert lead_name not in c.datanodes[leader].replication.targets()
        # the leader keeps serving
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == 10


class TestContinuousShip:
    def test_wal_tail_ships_and_follower_serves_reads(self, cluster):
        c = cluster
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        lead, std = _r0(c, leader), _r0(c, target)
        vals = ", ".join(f"('h{i % 5}', {50_000 + i}, 2.0)"
                         for i in range(40))
        c.fe.do_query(f"INSERT INTO ha VALUES {vals}")
        c.datanodes[leader].replication.drain(lead.name)
        std = _r0(c, target)        # a gap-refresh may swap the object
        assert (std.version_control.committed_sequence ==
                lead.version_control.committed_sequence)
        # stat beats feed lag tracking; the read router needs them
        for i in c.datanodes:
            _beat_full(c, i)
        c.fe.do_query("SET read_replica = 'follower'")
        try:
            got = c.query_one("SELECT count(*) AS c FROM ha")[0]
            assert got == 60
            # successive single-region scatters rotate over the pool:
            # the follower takes a share of the traffic
            t = c.fe.catalog.table(CAT, SCH, "ha")
            picked = set()
            for _ in range(4):
                for client, regions in t._read_owners_for([0]):
                    assert regions == [0]
                    picked.add(client.node_id)
            assert picked == {leader, target}
        finally:
            c.fe.do_query("SET read_replica = 'leader'")

    def test_follower_gap_refreshes_after_leader_flush(self, cluster):
        c = cluster
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        lead = _r0(c, leader)
        # stall shipping, write + flush on the leader: the WAL segments
        # the follower missed are now obsoleted on the leader side
        c.datanodes[leader].replication.stop()
        vals = ", ".join(f"('h{i % 5}', {60_000 + i}, 3.0)"
                         for i in range(30))
        c.fe.do_query(f"INSERT INTO ha VALUES {vals}")
        lead.flush()
        c.fe.do_query("INSERT INTO ha VALUES ('h1', 70000, 4.0)")
        # the next ship round carries leader_flushed ahead of the
        # standby's manifest view -> it reopens from the shared manifest
        c.datanodes[leader].replication.drain(lead.name)
        std = _r0(c, target)
        assert (std.version_control.committed_sequence ==
                lead.version_control.committed_sequence)
        assert std.standby and std.fenced

    def test_acks_never_wait_on_a_dead_follower(self, cluster):
        c = cluster
        _setup_table(c, rows=10)
        leader, target = _add_replica(c)
        lead = _r0(c, leader)
        c.hard_kill(target)
        # writes ack from the leader's WAL alone; the failed ship is
        # logged and retried, never surfaced to the writer
        before = lead.version_control.committed_sequence
        c.fe.do_query("INSERT INTO ha VALUES ('h2', 80000, 5.0)")
        assert lead.version_control.committed_sequence > before
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == 11

    def test_region_peers_and_cluster_info_feed(self, cluster):
        c = cluster
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        for i in c.datanodes:
            _beat_full(c, i)
        rows = [r for r in c.srv.region_peers()
                if r["table_name"] == FULL and r["region_number"] == 0]
        assert [r["is_leader"] for r in rows] == ["Yes", "No"]
        lead_row, fol_row = rows
        assert lead_row["peer_id"] == leader and lead_row["lag_ms"] == 0
        assert fol_row["peer_id"] == target
        committed = _r0(c, leader).version_control.committed_sequence
        assert lead_row["replicated_seq"] == committed
        assert fol_row["replicated_seq"] == committed  # fully caught up
        assert fol_row["lag_ms"] == 0
        # cluster_info region_count counts LEADER regions only: the
        # standby on `target` adds nothing
        info = {r["peer_id"]: r["region_count"]
                for r in c.srv.cluster_info() if r["peer_id"] > 0}
        assert sum(info.values()) == 2
        route = c.srv.table_route(FULL)
        by_leader = {}
        for rr in route.region_routes:
            by_leader[rr.leader.id] = by_leader.get(rr.leader.id, 0) + 1
        assert info == {i: by_leader.get(i, 0) for i in c.datanodes}


class TestPromotion:
    @pytest.mark.parametrize("sync_wal", [True, False],
                             ids=["sync", "async"])
    def test_leader_death_promotes_with_zero_acked_loss(self, tmp_path,
                                                        sync_wal):
        """The tentpole invariant: kill the leader with an acked,
        UNSHIPPED, UNFLUSHED tail under sync_on_write -> the promoted
        follower salvages the dead leader's WAL and serves every acked
        row exactly once."""
        failpoint.reset()
        c = ReplCluster(tmp_path, sync_wal=sync_wal)
        try:
            _setup_table(c, rows=20)
            c.fe.catalog.table(CAT, SCH, "ha").flush()
            leader, target = _add_replica(c)
            lead = _r0(c, leader)
            c.datanodes[leader].replication.drain(lead.name)
            acked = set(c.scan_keys())
            # stall shipping, then land acked rows ONLY the leader's WAL
            # holds (region 0 hosts: h0..h4)
            c.datanodes[leader].replication.stop()
            for i in range(25):
                key = ("h3", 90_000 + i)
                c.fe.do_query(
                    f"INSERT INTO ha VALUES ('h3', {key[1]}, 7.0)")
                acked.add(key)
            std_seq = _r0(c, target).version_control.committed_sequence
            assert lead.version_control.committed_sequence > std_seq, \
                "test setup: the tail must be unshipped"
            moves = _fail_leader(c, leader)
            assert moves == [{"table": FULL, "region": 0, "from": leader,
                              "to": target, "promoted": True}]
            _deliver(c, target)
            promoted = _r0(c, target)
            assert not promoted.standby and not promoted.fenced
            # zero acked loss, zero duplication
            keys = c.scan_keys()
            assert len(keys) == len(set(keys)), "duplicated rows"
            missing = acked - set(keys)
            assert not missing, f"lost {len(missing)} acked rows"
            # post-promotion liveness: write + read through the new
            # leader
            c.fe.do_query("INSERT INTO ha VALUES ('h0', 95000, 8.0)")
            assert c.query_one("SELECT count(*) AS c FROM ha")[0] == \
                len(acked) + 1
            # manifest references only existing SSTs
            for dn in c.datanodes.values():
                for region in dn.storage.list_regions().values():
                    if region.closed:
                        continue
                    referenced = {f.file_name for f in region.
                                  version_control.current.ssts.all_files()}
                    on_disk = {k.rsplit("/", 1)[-1] for k in
                               c.shared.list(f"{region.name}/sst/")}
                    assert referenced <= on_disk
        finally:
            c.shutdown()

    def test_promotion_picks_most_caught_up_follower(self, tmp_path):
        failpoint.reset()
        c = ReplCluster(tmp_path, nodes=(1, 2, 3, 4))
        try:
            _setup_table(c)
            leader = _region0_owner(c)
            followers = [i for i in c.datanodes if i != leader][:2]
            for f in followers:
                _add_replica(c, target=f)
            route = c.srv.table_route(FULL)
            rname = f"{route.table_id}_{0:010d}"
            # crafted stat beats: follower[1] is further along
            for f, seq in zip(followers, (3, 9)):
                c.srv.handle_heartbeat(f, DatanodeStat(
                    region_count=1, region_stats=[{
                        "region": rname, "rows": 0, "size_bytes": 0,
                        "standby": True, "replicated_seq": seq}]))
            moves = _fail_leader(c, leader)
            assert [m for m in moves if m["region"] == 0][0]["to"] == \
                followers[1]
            rr0 = next(r for r in c.srv.table_route(FULL).region_routes
                       if r.region_number == 0)
            assert rr0.leader.id == followers[1]
            # the slower follower survives as a follower of the new
            # leader
            assert [f.id for f in rr0.followers] == [followers[0]]
        finally:
            c.shutdown()

    def test_resurrected_old_leader_is_fenced(self, cluster):
        c = cluster
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        lead_name = _r0(c, leader).name
        c.datanodes[leader].replication.drain(lead_name)
        _fail_leader(c, leader)
        _deliver(c, target)
        assert not _r0(c, target).standby
        # the old leader comes back from the dead: its WAL dir was
        # fenced by the promotion, so the region reopens write-rejecting
        c.restart_datanode(leader)
        back = _r0(c, leader)
        assert back.fenced and not back.standby
        with pytest.raises(StaleRouteError):
            back.bulk_ingest({"host": ["h1"], "ts": [99_999],
                              "v": [1.0]})
        # a late ship from the deposed leader is ignored by the promoted
        # region (no longer standby)
        out = c.datanodes[target].repl_apply(
            CAT, SCH, "ha", 0,
            [{"seq": 10_000, "payload": None}], leader_flushed=0)
        assert out["standby"] is False and out["replayed"] == 0

    def test_meta_restart_resumes_mid_bootstrap(self, tmp_path):
        """FileKv-backed meta dies mid replica-add; the restarted one
        reloads the op doc and finishes the attach."""
        failpoint.reset()
        kv = FileKv(str(tmp_path / "meta.kv"))
        c = ReplCluster(tmp_path, kv=kv)
        try:
            _setup_table(c)
            leader = _region0_owner(c)
            target = next(i for i in c.datanodes if i != leader)
            c.fe.do_query(f"ADMIN ADD REPLICA ha 0 TO {target}")
            for _ in range(20):
                ops = c.srv.balancer.ops()
                if ops and ops[0]["state"] in ("bootstrap", "attach"):
                    break
                c.pump(rounds=1)
            ops = c.srv.balancer.ops()
            assert ops and ops[0]["state"] in ("bootstrap", "attach"), ops
            c.restart_meta()
            assert c.srv.balancer.ops(), "op lost across meta restart"
            assert c.pump(rounds=30)
            assert c.srv.balancer.done_ops()[-1]["state"] == "done"
            rr0 = next(r for r in c.srv.table_route(FULL).region_routes
                       if r.region_number == 0)
            assert [f.id for f in rr0.followers] == [target]
            assert _r0(c, target).standby
        finally:
            c.shutdown()


class TestReplicationTorture:
    """Satellite: crash/err at every repl_* failpoint — the operation
    resumes (or the ship round retries) and acked rows stay exactly-once
    readable."""

    @pytest.mark.parametrize("action", ["crash", "err"])
    def test_bootstrap_failure_resumes_or_rolls_back(self, tmp_path,
                                                     action, request):
        failpoint.reset()
        request.addfinalizer(failpoint.reset)
        c = ReplCluster(tmp_path)
        request.addfinalizer(c.shutdown)
        _setup_table(c, rows=20)
        leader = _region0_owner(c)
        target = next(i for i in c.datanodes if i != leader)
        c.fe.do_query(f"ADMIN ADD REPLICA ha 0 TO {target}")
        failpoint.configure("repl_bootstrap", action)
        if action == "crash":
            with pytest.raises(SimulatedCrash):
                c.pump(rounds=30)
            # the leader "died" mid-step: restart it from durable state
            failpoint.configure("repl_bootstrap", "off")
            c.hard_kill(leader)
            c.restart_datanode(leader)
            assert c.pump(rounds=40), c.srv.balancer.ops()
            assert c.srv.balancer.done_ops()[-1]["state"] == "done"
        else:
            # err: the step fails its ack; the pre-commit op rolls back
            c.pump(rounds=30)
            final = c.srv.balancer.done_ops()[-1]
            failpoint.configure("repl_bootstrap", "off")
            if final["state"] == "failed":
                # rollback left no follower; a retry succeeds
                rr0 = next(r for r in
                           c.srv.table_route(FULL).region_routes
                           if r.region_number == 0)
                assert not rr0.followers
                c.fe.do_query(f"ADMIN ADD REPLICA ha 0 TO {target}")
                assert c.pump(rounds=40)
                assert c.srv.balancer.done_ops()[-1]["state"] == "done"
        rr0 = next(r for r in c.srv.table_route(FULL).region_routes
                   if r.region_number == 0)
        assert [f.id for f in rr0.followers] == [target]
        lead, std = _r0(c, leader), _r0(c, target)
        c.datanodes[leader].replication.drain(lead.name)
        std = _r0(c, target)
        assert (std.version_control.committed_sequence ==
                lead.version_control.committed_sequence)
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == 20

    @pytest.mark.parametrize("point,action", [
        ("repl_ship", "crash"), ("repl_ship", "err"),
        ("repl_apply", "crash"), ("repl_apply", "err"),
    ])
    def test_ship_failure_reships_exactly_once(self, tmp_path, point,
                                               action, request):
        failpoint.reset()
        request.addfinalizer(failpoint.reset)
        c = ReplCluster(tmp_path)
        request.addfinalizer(c.shutdown)
        _setup_table(c, rows=20)
        leader, target = _add_replica(c)
        lead = _r0(c, leader)
        c.datanodes[leader].replication.stop()   # ship only via drain
        vals = ", ".join(f"('h{i % 5}', {40_000 + i}, 6.0)"
                         for i in range(30))
        c.fe.do_query(f"INSERT INTO ha VALUES {vals}")
        failpoint.configure(point, action)
        shipper = c.datanodes[leader].replication
        if action == "crash":
            with pytest.raises(SimulatedCrash):
                shipper.ship_region(lead.name)
            failpoint.configure(point, "off")
            if point == "repl_apply":
                # the follower died mid-apply: reopen it from its WAL +
                # standby marker
                c.hard_kill(target)
                c.restart_datanode(target)
        else:
            if point == "repl_ship":
                # the err fires before any follower push; the cursor
                # must not advance
                with pytest.raises(GreptimeError):
                    shipper.ship_region(lead.name)
            else:
                # per-follower apply errors are swallowed (at-least-
                # once: the round just doesn't advance the cursor)
                out = shipper.ship_region(lead.name)
                assert out["followers_ok"] == 0 and not out["advanced"]
            failpoint.configure(point, "off")
        shipper.drain(lead.name)
        std = _r0(c, target)
        assert (std.version_control.committed_sequence ==
                lead.version_control.committed_sequence)
        assert std.standby
        # exactly-once on the standby: a raw (pre-dedup) scan shows
        # every (series, ts) key at most once — a re-shipped record
        # applied twice would show here
        raw = std.snapshot().scan()
        raw_keys = list(zip(raw.series_ids.tolist(), raw.ts.tolist()))
        assert len(raw_keys) == len(set(raw_keys)), "double-applied ship"
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == 50

    def test_promote_crash_retries_until_promoted(self, tmp_path,
                                                  request):
        """The repl_promote mail is fire-and-forget; a new leader that
        crashes mid-promote gets the (idempotent) mail again from the
        durable __balancer/promote/ doc."""
        failpoint.reset()
        request.addfinalizer(failpoint.reset)
        c = ReplCluster(tmp_path, sync_wal=True)
        request.addfinalizer(c.shutdown)
        _setup_table(c, rows=20)
        c.fe.catalog.table(CAT, SCH, "ha").flush()
        leader, target = _add_replica(c)
        lead = _r0(c, leader)
        c.datanodes[leader].replication.drain(lead.name)
        acked = set(c.scan_keys())
        c.datanodes[leader].replication.stop()
        for i in range(10):
            key = ("h2", 91_000 + i)
            c.fe.do_query(f"INSERT INTO ha VALUES ('h2', {key[1]}, 9.0)")
            acked.add(key)
        failpoint.configure("repl_promote", "crash")
        moves = _fail_leader(c, leader)
        assert moves and moves[0]["promoted"]
        with pytest.raises(SimulatedCrash):
            _deliver(c, target)
        assert c.srv.kv.range(PROMOTE_PREFIX), \
            "pending promotion doc must survive the crash"
        failpoint.configure("repl_promote", "off")
        # the new leader died mid-promote; reopen it, then the next
        # failover pass re-mails the promotion
        c.hard_kill(target)
        c.restart_datanode(target)
        assert _r0(c, target).standby      # still a standby after crash
        c.srv.failover_check()
        _deliver(c, target)
        promoted = _r0(c, target)
        assert not promoted.standby and not promoted.fenced
        keys = c.scan_keys()
        assert len(keys) == len(set(keys)), "duplicated rows"
        assert not acked - set(keys), "lost acked rows"
        # a confirming stat beat clears the pending doc
        _beat_full(c, target)
        c.srv.failover_check()
        assert not c.srv.kv.range(PROMOTE_PREFIX)
        # duplicate promote mail (pre-confirmation re-send) is a no-op
        c.datanodes[target]._handle_mailbox({
            "type": "repl_promote", "catalog": CAT, "schema": SCH,
            "table": "ha", "region": 0, "old_leader": leader})
        assert c.query_one("SELECT count(*) AS c FROM ha")[0] == \
            len(acked)


class TestStandaloneParity:
    def test_standalone_rejects_replica_controls(self, tmp_path):
        """Satellite: ADMIN ADD/REMOVE REPLICA and SET read_replica get
        the same clean UnsupportedError on a standalone frontend."""
        from greptimedb_tpu.frontend import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "sa"),
            register_numbers_table=False))
        fe = FrontendInstance(dn)
        fe.start()
        try:
            errors = []
            for sql in ("ADMIN ADD REPLICA t 0 TO 2",
                        "ADMIN REMOVE REPLICA t 0 FROM 2",
                        "SET read_replica = 'follower'",
                        "SET replica_max_lag_ms = 100"):
                with pytest.raises(UnsupportedError,
                                   match="distributed") as exc:
                    fe.do_query(sql)
                errors.append(exc.value)
            # parity: every rejection is the same clean error type
            assert {type(e) for e in errors} == {UnsupportedError}
        finally:
            fe.shutdown()

    def test_distributed_accepts_set_read_replica(self, cluster):
        c = cluster
        _setup_table(c)
        c.fe.do_query("SET read_replica = 'follower'")
        c.fe.do_query("SET replica_max_lag_ms = 250")
        from greptimedb_tpu.frontend.distributed import (
            _READ_REPLICA, _REPLICA_MAX_LAG_MS)
        assert _READ_REPLICA[0] == "follower"
        assert _REPLICA_MAX_LAG_MS[0] == 250
        with pytest.raises(InvalidArgumentsError):
            c.fe.do_query("SET read_replica = 'sideways'")
        c.fe.do_query("SET read_replica = 'leader'")
        c.fe.do_query("SET replica_max_lag_ms = 5000")
