"""High-QPS front-door tests (ISSUE 12): admission control over real
wires, WAL group commit, ingest coalescing, and concurrent scan fusion.

The admission gate is load-shedding, not queueing: past the configured
in-flight limit new statements are REJECTED with a typed, retryable
error (HTTP 429 + Retry-After, MySQL 1040 server-busy, PG 53300) while
work already in flight — including work holding WAL group-commit cohort
slots — runs to completion. KILL and SET stay admitted (the operator's
way out), and the self-monitor's own greptime_private writes are
exempt (shedding the observer would blind the operator exactly when
they need the data).
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.common import process_list
from greptimedb_tpu.common.admission import GATE, exempt
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import GreptimeError, OverloadedError
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.servers.coalesce import (
    COALESCER, configure_coalescer, coalescer_settings)
from greptimedb_tpu.storage.wal import (
    Wal, configure_group_commit, group_commit_settings)


@pytest.fixture(autouse=True)
def _reset_front_door_knobs():
    """Admission/coalescer/group-commit state is process-global — every
    test leaves it as it found it."""
    gate_snap = GATE.snapshot()
    gc_snap = group_commit_settings()
    co_snap = coalescer_settings()
    yield
    GATE.configure(max_inflight=gate_snap["max_inflight"],
                   max_queued_bytes=gate_snap["max_queued_bytes"],
                   retry_after_s=gate_snap["retry_after_s"])
    configure_group_commit(enabled=gc_snap[0], max_wait_us=gc_snap[1],
                           max_batch=gc_snap[2])
    configure_coalescer(enabled=co_snap[0], window_ms=co_snap[1])


@pytest.fixture()
def frontend(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    yield fe
    fe.shutdown()


def _scalar(out):
    """First column of the first row of an Output (rows() yields
    iterators)."""
    return list(list(out.batches[0].rows())[0])[0]


def _fill_registry(n):
    """Occupy n in-flight statement slots with live registry entries."""
    return [process_list.REGISTRY.register(f"SELECT {i}", "test", "", "",
                                           None) for i in range(n)]


def _drain(entries):
    for e in entries:
        process_list.REGISTRY.deregister(e)


# ---------------------------------------------------------------------------
# gate semantics (unit level)
# ---------------------------------------------------------------------------

class TestGateUnit:
    def test_disabled_by_default(self):
        assert GATE.snapshot()["max_inflight"] == 0
        GATE.admit_statement("Query")          # no limit: never raises

    def test_rejects_at_limit_and_recovers(self):
        GATE.configure(max_inflight=2)
        entries = _fill_registry(2)
        try:
            with pytest.raises(OverloadedError) as ei:
                GATE.admit_statement("Query")
            assert ei.value.retry_after_s >= 1
            assert ei.value.to_http_status() == 429
        finally:
            _drain(entries)
        GATE.admit_statement("Query")          # slots free: admitted

    def test_kill_and_set_always_admitted(self):
        GATE.configure(max_inflight=1)
        entries = _fill_registry(3)
        try:
            GATE.admit_statement("Kill")
            GATE.admit_statement("SetVariable")
            with pytest.raises(OverloadedError):
                GATE.admit_statement("Query")
        finally:
            _drain(entries)

    def test_exempt_context(self):
        GATE.configure(max_inflight=1)
        entries = _fill_registry(2)
        try:
            with exempt():
                GATE.admit_statement("Query")
                with GATE.admit_ingest(1 << 30):
                    pass
        finally:
            _drain(entries)

    def test_ingest_bytes_reject_and_release(self):
        GATE.configure(max_queued_bytes=100)
        with GATE.admit_ingest(80):
            with pytest.raises(OverloadedError):
                with GATE.admit_ingest(40):
                    pass
        # the 80-byte body drained: the 40-byte one is admitted now
        with GATE.admit_ingest(40):
            pass

    def test_single_oversized_body_admitted_when_idle(self):
        GATE.configure(max_queued_bytes=100)
        with GATE.admit_ingest(500):           # one body IS the queue
            pass


# ---------------------------------------------------------------------------
# over real HTTP: 429 + Retry-After, in-flight work completes
# ---------------------------------------------------------------------------

def _http_sql(port, stmt):
    url = f"http://127.0.0.1:{port}/v1/sql"
    body = urllib.parse.urlencode({"sql": stmt}).encode()
    r = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    try:
        with urllib.request.urlopen(r, timeout=15) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class TestHttpOverload:
    @pytest.fixture()
    def http(self, frontend):
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(frontend, addr="127.0.0.1:0")
        srv.start()
        yield srv
        srv.shutdown()

    def test_reject_with_429_and_retry_after_under_2x_load(self, http,
                                                           frontend):
        """2x the configured limit concurrently: the overflow rejects
        cleanly with Retry-After while every admitted statement
        completes — no collapse, no deadlock."""
        frontend.do_query(
            "CREATE TABLE adm (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))")
        frontend.do_query("INSERT INTO adm VALUES ('a', 1000, 1.0)")
        limit = 2
        GATE.configure(max_inflight=limit, retry_after_s=3)
        entries = _fill_registry(limit)        # the "in-flight" load
        results = []
        try:
            def one():
                results.append(_http_sql(http.port,
                                         "SELECT * FROM adm"))
            threads = [threading.Thread(target=one)
                       for _ in range(2 * limit)]
            [t.start() for t in threads]
            [t.join(timeout=30) for t in threads]
        finally:
            _drain(entries)
        assert len(results) == 2 * limit
        rejected = [r for r in results if r[0] == 429]
        assert rejected, results
        for status, headers, body in rejected:
            assert headers.get("Retry-After") == "3"
            payload = json.loads(body)
            assert payload["code"] == 6001      # RATE_LIMITED
            assert "overloaded" in payload["error"]
        # the gate cleared: the same statement is admitted now and the
        # process did not collapse
        status, _h, _b = _http_sql(http.port, "SELECT * FROM adm")
        assert status == 200

    def test_inflight_work_completes_and_kill_releases_slots(
            self, http, frontend):
        """A slow admitted statement finishes; KILLing it frees its
        admission slot for the next arrival (KILL itself is never
        gated)."""
        frontend.do_query(
            "CREATE TABLE slowt (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))")
        frontend.do_query(
            "INSERT INTO slowt VALUES " + ",".join(
                f"('h{i % 8}', {i * 1000}, {float(i)})"
                for i in range(64)))
        GATE.configure(max_inflight=1)
        release = threading.Event()
        from greptimedb_tpu.query import tpu_exec
        orig = tpu_exec.cached_table_frame

        def gated(table):
            if getattr(table, "name", "") == "slowt":
                release.wait(timeout=20)
            return orig(table)

        tpu_exec.cached_table_frame = gated
        outcome = {}

        def slow_query():
            try:
                outcome["out"] = frontend.do_query(
                    "SELECT host, v FROM slowt WHERE host = 'h1'")
            except GreptimeError as e:
                outcome["err"] = e

        t = threading.Thread(target=slow_query)
        t.start()
        try:
            deadline = time.monotonic() + 10
            while len(process_list.REGISTRY) < 1:
                assert time.monotonic() < deadline, "query never started"
                time.sleep(0.01)
            # the slot is taken: HTTP rejects with 429
            status, headers, _ = _http_sql(http.port,
                                           "SELECT 1 FROM slowt")
            assert status == 429 and "Retry-After" in headers
            # KILL goes THROUGH the full wire path despite the gate
            rows = process_list.REGISTRY.rows()
            assert len(rows) == 1
            status, _h, body = _http_sql(http.port,
                                         f"KILL {rows[0]['id']}")
            assert status == 200, body
            release.set()
            t.join(timeout=20)
            assert not t.is_alive()
            # in-flight work completed (ran to its end or was killed —
            # either way the slot is RELEASED and new work is admitted)
            deadline = time.monotonic() + 10
            while len(process_list.REGISTRY) > 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            status, _h, _b = _http_sql(http.port, "SELECT 1 FROM slowt")
            assert status == 200
        finally:
            release.set()
            tpu_exec.cached_table_frame = orig
            t.join(timeout=5)

    def test_ingest_body_gate_rejects_prometheus_write(self, http):
        from greptimedb_tpu.servers import prometheus as prom_mod
        GATE.configure(max_queued_bytes=64)
        series = [prom_mod.TimeSeries(
            labels={"__name__": "m1", "host": "a"},
            samples=[(1.0, 1000)])]
        body = prom_mod.encode_write_request(series)
        blocker = threading.Event()
        inner = threading.Event()

        # hold one admitted body in flight, then push a second
        def hold():
            with GATE.admit_ingest(60):
                inner.set()
                blocker.wait(timeout=10)

        t = threading.Thread(target=hold)
        t.start()
        assert inner.wait(timeout=5)
        try:
            r = urllib.request.Request(
                f"http://127.0.0.1:{http.port}/v1/prometheus/write",
                data=body, method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=10)
            assert ei.value.code == 429
            assert "Retry-After" in dict(ei.value.headers)
        finally:
            blocker.set()
            t.join(timeout=5)
        # drained: the same body is admitted
        r = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/prometheus/write",
            data=body, method="POST")
        with urllib.request.urlopen(r, timeout=10) as resp:
            assert resp.status == 204


# ---------------------------------------------------------------------------
# over the MySQL wire: clean server-busy error
# ---------------------------------------------------------------------------

class TestMysqlOverload:
    def test_clean_server_busy_error(self, frontend):
        from greptimedb_tpu.servers.mysql import MysqlServer
        from test_mysql import MiniMysqlClient
        srv = MysqlServer(frontend)
        srv.serve_in_background()
        try:
            GATE.configure(max_inflight=1)
            entries = _fill_registry(1)
            try:
                client = MiniMysqlClient(srv.port)
                with pytest.raises(RuntimeError) as ei:
                    client.query("SELECT 1")
                assert "overloaded" in str(ei.value)
                # the connection SURVIVES the rejection (clean error
                # packet, not a dropped socket)
                assert client.ping()
            finally:
                _drain(entries)
            # and recovers once slots free up
            assert client.query("SELECT 1")[1] == [["1"]]
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# monitor exemption
# ---------------------------------------------------------------------------

class TestMonitorExemption:
    def test_self_monitor_writes_pass_a_full_gate(self, frontend):
        """The scraper's greptime_private writes are never shed: a tick
        under a saturated gate still lands rows."""
        GATE.configure(max_inflight=1, max_queued_bytes=16)
        entries = _fill_registry(4)            # far past the limit
        try:
            written = frontend.self_monitor.tick()
            assert written > 0
            assert frontend.self_monitor.stats["last_error"] is None
        finally:
            _drain(entries)
        t = frontend.catalog.table("greptime", "greptime_private",
                                   "node_metrics")
        assert t is not None


# ---------------------------------------------------------------------------
# WAL group commit
# ---------------------------------------------------------------------------

class TestGroupCommit:
    def _concurrent_appends(self, tmp_path, n_threads=6, per=20):
        w = Wal(str(tmp_path), sync_on_write=True)
        errs = []

        def writer(i):
            try:
                for j in range(per):
                    w.append(i * 1000 + j, b"payload-%d-%d" % (i, j))
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_threads)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errs, errs
        return w, n_threads * per

    def test_cohort_shares_fsyncs_and_loses_nothing(self, tmp_path):
        configure_group_commit(enabled=True)
        from greptimedb_tpu.common.telemetry import registry_snapshot
        before = {s[0]: s[2] for s in registry_snapshot()}
        w, n = self._concurrent_appends(tmp_path / "gc")
        after = {s[0]: s[2] for s in registry_snapshot()}
        # every record replays after the concurrent cohort storm
        assert len(list(w.read_from(0))) == n
        w.close()
        fsyncs = after.get("greptime_wal_group_commit_fsyncs_total", 0) \
            - before.get("greptime_wal_group_commit_fsyncs_total", 0)
        records = after.get("greptime_wal_group_commit_records_total", 0) \
            - before.get("greptime_wal_group_commit_records_total", 0)
        assert records == n
        # the whole point: strictly fewer shared fsyncs than records
        assert 0 < fsyncs < n

    def test_off_mode_preserves_per_append_fsync(self, tmp_path):
        configure_group_commit(enabled=False)
        w, n = self._concurrent_appends(tmp_path / "off")
        assert len(list(w.read_from(0))) == n
        w.close()

    def test_failed_group_fsync_fails_every_cohort_member(self, tmp_path):
        """An injected wal_fsync fault during the SHARED fsync must
        surface to every writer whose record it covered — acks must
        never outrun durability."""
        from greptimedb_tpu.common import failpoint as fp
        configure_group_commit(enabled=True, max_wait_us=2000)
        w = Wal(str(tmp_path / "fail"), sync_on_write=True)
        start = threading.Barrier(3)
        errs, oks = [], []

        def writer(i):
            start.wait(timeout=10)
            try:
                w.append(i, b"x" * 16)
                oks.append(i)
            except GreptimeError as e:
                errs.append(e)

        with fp.cfg("wal_fsync", "err"):
            threads = [threading.Thread(target=writer, args=(i,))
                       for i in range(3)]
            [t.start() for t in threads]
            [t.join(timeout=30) for t in threads]
        # with the failpoint armed for the whole storm, nobody acks
        assert not oks and len(errs) == 3, (oks, errs)
        # the WAL recovers: next append + sync succeed
        w.append(99, b"recovered")
        w.sync()
        assert [r[0] for r in w.read_from(99)] == [99]
        w.close()

    def test_knobs_validate(self, frontend):
        with pytest.raises(GreptimeError):
            frontend.do_query("SET wal_group_max_batch = 0")
        frontend.do_query("SET wal_group_commit = 0")
        assert group_commit_settings()[0] is False
        frontend.do_query("SET wal_group_commit = 1")
        frontend.do_query("SET wal_group_max_wait_us = 250")
        frontend.do_query("SET wal_group_max_batch = 64")
        assert group_commit_settings()[1:] == (250, 64)

    def test_region_write_overlaps_group_wait(self, tmp_path):
        """Region-level: concurrent sync_on_write writers through
        Region.write land every row exactly once with group commit on."""
        from torture import TortureRig, make_batch
        configure_group_commit(enabled=True)
        rig = TortureRig(str(tmp_path / "rig"), sync_wal=True)
        rig.create()
        batches = [make_batch(i) for i in range(8)]
        errs = []

        def writer(b):
            try:
                rig.write(b)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(b,))
                   for b in batches]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errs, errs
        got = rig.region.snapshot().read_merged()
        want = {}
        for b in batches:
            want.update(b)
        assert got.num_rows == len(want)
        rig.region.close()


# ---------------------------------------------------------------------------
# ingest coalescing
# ---------------------------------------------------------------------------

class TestCoalescer:
    def test_concurrent_same_shape_requests_merge(self, frontend):
        configure_coalescer(enabled=True, window_ms=25)
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        start = threading.Barrier(5)
        acks, errs = [], []

        def one(i):
            start.wait(timeout=10)
            try:
                n = COALESCER.ingest(
                    frontend, "co_metric",
                    {"ts": [1000 + i], "host": [f"h{i}"], "v": [float(i)]},
                    tag_columns=("host",), timestamp_column="ts", ctx=ctx)
                acks.append(n)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(5)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errs, errs
        assert acks == [1] * 5                 # per-request acks
        out = frontend.do_query("SELECT count(*) FROM co_metric")[0]
        assert _scalar(out) == 5
        from greptimedb_tpu.common.telemetry import registry_snapshot
        snap = {s[0]: s[2] for s in registry_snapshot()}
        assert snap.get(
            "greptime_ingest_coalesce_merged_requests_total", 0) >= 1

    def test_shared_error_reaches_every_member(self, frontend):
        """A cohort whose shared insert fails errors EVERY member —
        none of their rows are durable, none may be acked."""
        configure_coalescer(enabled=True, window_ms=25)
        from greptimedb_tpu.session import QueryContext
        frontend.do_query(
            "CREATE TABLE co_err (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))")
        ctx = QueryContext()
        start = threading.Barrier(3)
        errs = []

        def one(i):
            start.wait(timeout=10)
            try:
                # 'newtag' does not exist and tags cannot be added after
                # create: the shared insert raises for the whole cohort
                COALESCER.ingest(
                    frontend, "co_err",
                    {"ts": [1000 + i], "host": ["a"], "v": [1.0],
                     "newtag": ["x"]},
                    tag_columns=("host", "newtag"),
                    timestamp_column="ts", ctx=ctx)
            except GreptimeError as e:
                errs.append(e)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(3)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert len(errs) == 3, errs
        out = frontend.do_query("SELECT count(*) FROM co_err")[0]
        assert _scalar(out) == 0

    def test_different_shapes_never_share_a_batch(self, frontend):
        """Requests whose column signatures differ stay separate, so a
        request needing a different auto-create shape cannot poison a
        stranger's ack."""
        configure_coalescer(enabled=True, window_ms=25)
        from greptimedb_tpu.session import QueryContext
        ctx = QueryContext()
        start = threading.Barrier(2)
        results = {}

        def narrow():
            start.wait(timeout=10)
            results["narrow"] = COALESCER.ingest(
                frontend, "co_shape",
                {"ts": [1000], "host": ["a"], "v": [1.0]},
                tag_columns=("host",), timestamp_column="ts", ctx=ctx)

        def wide():
            start.wait(timeout=10)
            try:
                results["wide"] = COALESCER.ingest(
                    frontend, "co_shape",
                    {"ts": [2000], "host": ["b"], "v": [2.0],
                     "extra": [7.0]},
                    tag_columns=("host",), timestamp_column="ts", ctx=ctx)
            except GreptimeError as e:
                results["wide_err"] = e

        t1, t2 = threading.Thread(target=narrow), \
            threading.Thread(target=wide)
        t1.start(); t2.start()
        t1.join(timeout=30); t2.join(timeout=30)
        assert results.get("narrow") == 1

    def test_disabled_coalescer_is_passthrough(self, frontend):
        configure_coalescer(enabled=False)
        from greptimedb_tpu.session import QueryContext
        n = COALESCER.ingest(
            frontend, "co_direct", {"ts": [1], "v": [1.0]},
            tag_columns=(), timestamp_column="ts", ctx=QueryContext())
        assert n == 1
        assert COALESCER.pending_batches() == 0

    def test_http_influx_concurrent_writes_coalesce(self, frontend):
        """End to end over HTTP: concurrent line-protocol bodies for one
        measurement still ack 204 each and land every row."""
        from greptimedb_tpu.servers.http import HttpServer
        configure_coalescer(enabled=True, window_ms=25)
        srv = HttpServer(frontend, addr="127.0.0.1:0")
        srv.start()
        try:
            codes = []

            def write(i):
                body = (f"co_http,host=h{i} v={float(i)} "
                        f"{(1000 + i) * 1_000_000}").encode()
                r = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/v1/influxdb/write",
                    data=body, method="POST")
                with urllib.request.urlopen(r, timeout=15) as resp:
                    codes.append(resp.status)

            threads = [threading.Thread(target=write, args=(i,))
                       for i in range(6)]
            [t.start() for t in threads]
            [t.join(timeout=30) for t in threads]
            assert codes == [204] * 6
            out = frontend.do_query("SELECT count(*) FROM co_http")[0]
            assert _scalar(out) == 6
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# concurrent scan fusion
# ---------------------------------------------------------------------------

class TestScanFusion:
    def _setup(self, frontend):
        frontend.do_query(
            "CREATE TABLE fuse (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))")
        frontend.do_query(
            "INSERT INTO fuse VALUES " + ",".join(
                f"('h{i % 4}', {i * 1000}, {i * 0.5})"
                for i in range(200)))
        from greptimedb_tpu.query import tpu_exec
        # pin the device dispatch so the small table takes the resident
        # region path (the fusion site), not the CPU columnar fallback
        self._orig_note = tpu_exec._note_device_query_time
        tpu_exec._note_device_query_time = lambda dt: None
        frontend.do_query("SET tpu_dispatch_min_rows = 1")
        return tpu_exec

    def _teardown(self, tpu_exec):
        tpu_exec._note_device_query_time = self._orig_note
        tpu_exec.TPU_DISPATCH_MIN_ROWS = 131072
        tpu_exec._observed_min_dt[0] = None

    def test_fused_follower_equals_solo_scan(self, frontend):
        """The fusion differential: N concurrent identical scans all
        return exactly the solo answer, with followers adopting the
        leader's pass (counter-asserted), and EXPLAIN ANALYZE naming
        fused-follower."""
        tpu_exec = self._setup(frontend)
        try:
            q = "SELECT host, avg(v) FROM fuse GROUP BY host"
            solo = frontend.do_query(q)[0]
            solo_rows = sorted(
                map(tuple, (r for b in solo.batches for r in b.rows())))
            orig = tpu_exec._moment_frame_for_scan

            def slow(*a, **kw):
                time.sleep(0.2)        # overlap window for the cohort
                return orig(*a, **kw)

            tpu_exec._moment_frame_for_scan = slow
            from greptimedb_tpu.common.telemetry import registry_snapshot
            before = {s[0]: s[2] for s in registry_snapshot()}
            results, errs = [], []

            def one():
                try:
                    out = frontend.do_query(q)[0]
                    results.append(sorted(map(
                        tuple,
                        (r for b in out.batches for r in b.rows()))))
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=one) for _ in range(6)]
            [t.start() for t in threads]
            [t.join(timeout=60) for t in threads]
            tpu_exec._moment_frame_for_scan = orig
            assert not errs, errs
            assert all(r == solo_rows for r in results)
            after = {s[0]: s[2] for s in registry_snapshot()}
            followers = after.get(
                "greptime_scan_fusion_follower_total", 0) - before.get(
                "greptime_scan_fusion_follower_total", 0)
            assert followers >= 1
            # EXPLAIN ANALYZE renders the adopted pass
            tpu_exec._moment_frame_for_scan = slow
            ea_rows = []

            def explain():
                out = frontend.do_query(f"EXPLAIN ANALYZE {q}")[0]
                ea_rows.append(
                    [r for b in out.batches for r in b.rows()])

            threads = [threading.Thread(target=explain)
                       for _ in range(3)]
            [t.start() for t in threads]
            [t.join(timeout=60) for t in threads]
            tpu_exec._moment_frame_for_scan = orig
            fused = [r for rows in ea_rows for r in rows
                     if "fused-follower" in str(r[0])]
            assert fused, ea_rows
        finally:
            self._teardown(tpu_exec)

    def test_write_between_scans_defeats_fusion(self, frontend):
        """Read-your-writes: a scan that starts after a write is acked
        carries a different data-state key and cannot adopt a stale
        pass."""
        tpu_exec = self._setup(frontend)
        try:
            q = "SELECT count(*) FROM fuse"
            out1 = frontend.do_query(q)[0]
            n1 = _scalar(out1)
            frontend.do_query(
                "INSERT INTO fuse VALUES ('h9', 999000, 9.9)")
            out2 = frontend.do_query(q)[0]
            assert _scalar(out2) == n1 + 1
        finally:
            self._teardown(tpu_exec)

    def test_fusion_disabled_by_knob(self, frontend):
        tpu_exec = self._setup(frontend)
        try:
            frontend.do_query("SET scan_fusion = 0")
            assert tpu_exec._FUSION_ENABLED[0] is False
            out = frontend.do_query(
                "SELECT host, max(v) FROM fuse GROUP BY host")[0]
            assert len(list(out.batches[0].rows())) == 4
        finally:
            frontend.do_query("SET scan_fusion = 1")
            self._teardown(tpu_exec)
