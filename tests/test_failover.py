"""Region failover tests: dead datanode's regions reopen elsewhere.

The reference detects failures (phi detector) but leaves the failover
*action* TODO (meta-srv/src/handler/failure_handler/runner.rs:132; RFC
2023-03-08-region-fault-tolerance). Here the action exists: with region
data on a SHARED object store, `MetaSrv.failover_check` re-places dead
nodes' regions on alive ones and mails `open_regions`; the adopting
datanode materializes the table from the meta-stored TableGlobalValue at
its last-flushed state.
"""

import time

import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.client import LocalDatanodeClient
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.distributed import DistInstance
from greptimedb_tpu.meta import MetaClient, MetaSrv, Peer
from greptimedb_tpu.meta.kv import MemKv
from greptimedb_tpu.storage.object_store import FsObjectStore

DDL = """
CREATE TABLE ha (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))
"""


@pytest.fixture()
def cluster(tmp_path):
    """2 datanodes over ONE shared object store (each keeps node-scoped
    control state + a local WAL home)."""
    shared = FsObjectStore(str(tmp_path / "shared_store"))
    srv = MetaSrv(MemKv(), datanode_lease_secs=5.0)
    meta = MetaClient(srv)
    datanodes, clients = {}, {}
    for i in (1, 2):
        dn = DatanodeInstance(
            DatanodeOptions(data_home=str(tmp_path / f"wal{i}"),
                            node_id=i, register_numbers_table=False),
            store=shared)
        dn.start()
        datanodes[i] = dn
        clients[i] = LocalDatanodeClient(dn)
        srv.register_datanode(Peer(i, f"dn{i}"))
        srv.handle_heartbeat(i)
    fe = DistInstance(meta, clients)
    yield fe, datanodes, srv, meta, shared
    for dn in datanodes.values():
        dn.shutdown()


def _beat_regularly(srv, node_id, t0, until, step=1.0):
    t = t0
    while t < until:
        srv.handle_heartbeat(node_id, now=t)
        t += step


class TestFailover:
    def test_regions_move_and_data_survives(self, cluster, tmp_path):
        fe, datanodes, srv, meta, shared = cluster
        fe.do_query(DDL)
        rows = ", ".join(f"('h{i}', {1000+i}, {float(i)})"
                         for i in range(10))
        fe.do_query(f"INSERT INTO ha VALUES {rows}")
        fe.catalog.table(CAT, SCH, "ha").flush()     # durable on shared

        route = srv.table_route("greptime.public.ha")
        owners = {rr.leader.id for rr in route.region_routes}
        assert owners == {1, 2}

        # node 2 dies: node 1 keeps beating, node 2 goes silent
        t0 = time.time()
        _beat_regularly(srv, 1, t0, t0 + 30)
        _beat_regularly(srv, 2, t0, t0 + 3)
        moves = srv.failover_check(now=t0 + 29)
        assert moves and all(m["from"] == 2 and m["to"] == 1
                             for m in moves)

        # the mailbox rides node 1's next heartbeat
        resp = srv.handle_heartbeat(1, now=t0 + 30)
        for msg in resp.mailbox:
            datanodes[1]._handle_mailbox(msg)

        # all regions now on node 1; data readable at last-flushed state
        route = srv.table_route("greptime.public.ha")
        assert {rr.leader.id for rr in route.region_routes} == {1}
        fe2 = DistInstance(meta, {1: LocalDatanodeClient(datanodes[1])})
        out = fe2.do_query("SELECT count(*) AS c, sum(v) AS s FROM ha")[-1]
        row = next(out.batches[0].rows())
        assert row == (10, 45.0)

    def test_unflushed_tail_lost_by_design(self, cluster):
        fe, datanodes, srv, meta, _ = cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO ha VALUES ('h7', 1, 1.0), ('h8', 2, 2.0)")
        t = fe.catalog.table(CAT, SCH, "ha")
        t.flush()
        # this lands only in node WAL/memtable (no flush)
        fe.do_query("INSERT INTO ha VALUES ('h9', 3, 3.0)")

        t0 = time.time()
        _beat_regularly(srv, 1, t0, t0 + 30)
        srv.failover_check(now=t0 + 29)
        resp = srv.handle_heartbeat(1, now=t0 + 30)
        for msg in resp.mailbox:
            datanodes[1]._handle_mailbox(msg)
        fe2 = DistInstance(meta, {1: LocalDatanodeClient(datanodes[1])})
        out = fe2.do_query("SELECT count(*) AS c FROM ha")[-1]
        # flushed rows survive; the unflushed h9 row is gone
        assert next(out.batches[0].rows())[0] == 2

    def test_noop_when_all_alive(self, cluster):
        fe, _, srv, _, _ = cluster
        fe.do_query(DDL)
        t0 = time.time()
        _beat_regularly(srv, 1, t0, t0 + 10)
        _beat_regularly(srv, 2, t0, t0 + 10)
        assert srv.failover_check(now=t0 + 10) == []

    def test_metasrv_restart_grace_period(self, tmp_path):
        """After a metasrv restart, persisted peers have no in-memory
        heartbeat record; the first datanode to heartbeat must NOT trigger
        a mass reassignment of every other (healthy) node's regions —
        persisted peers get a full grace window from process start."""
        from greptimedb_tpu.meta.kv import FileKv
        kv = FileKv(str(tmp_path / "meta.kv"))
        srv1 = MetaSrv(kv, datanode_lease_secs=5.0)
        srv1.register_datanode(Peer(1, "dn1"))
        srv1.register_datanode(Peer(2, "dn2"))
        t0 = time.time()
        srv1.handle_heartbeat(1, now=t0)
        srv1.handle_heartbeat(2, now=t0)
        route = srv1.create_table_route("greptime.public.t", [0, 1], now=t0)
        assert {rr.leader.id for rr in route.region_routes} == {1, 2}
        srv1.put_table_info("greptime.public.t", {"stub": True})
        # "restart": a fresh MetaSrv over the same persisted KV — routes
        # and peers are there, heartbeat history is not
        srv2 = MetaSrv(kv, datanode_lease_secs=5.0)
        assert {p.id for p in srv2.peers()} == {1, 2}
        t1 = srv2._start_time
        srv2.handle_heartbeat(1, now=t1)       # only node 1 beat so far
        # immediately after restart: within grace, node 2 is NOT failed over
        assert srv2.failover_check(now=t1 + 1) == []
        # node 2 heartbeats within the grace window → stays healthy forever
        srv2.handle_heartbeat(2, now=t1 + 2)
        _beat_regularly(srv2, 1, t1, t1 + 15)
        _beat_regularly(srv2, 2, t1, t1 + 15)
        assert srv2.failover_check(now=t1 + 15) == []
        # but a peer that never heartbeats after restart IS failed over
        # once the grace window (2x lease) lapses
        srv3 = MetaSrv(kv, datanode_lease_secs=5.0)
        t2 = srv3._start_time
        _beat_regularly(srv3, 1, t2, t2 + 12)
        moves = srv3.failover_check(now=t2 + 12)
        assert moves and all(m["from"] == 2 and m["to"] == 1 for m in moves)

    def test_no_alive_targets_is_noop(self, cluster):
        fe, _, srv, _, _ = cluster
        fe.do_query(DDL)
        t0 = time.time()
        # both nodes silent
        assert srv.failover_check(now=t0 + 3600) == []

    def test_adopting_node_that_never_saw_the_table(self, tmp_path):
        """A datanode started AFTER the DDL adopts regions purely from
        the meta-stored table info."""
        shared = FsObjectStore(str(tmp_path / "store"))
        srv = MetaSrv(MemKv(), datanode_lease_secs=5.0)
        meta = MetaClient(srv)
        dn1 = DatanodeInstance(
            DatanodeOptions(data_home=str(tmp_path / "wal1"), node_id=1,
                            register_numbers_table=False), store=shared)
        dn1.start()
        srv.register_datanode(Peer(1, "dn1"))
        srv.handle_heartbeat(1)
        fe = DistInstance(meta, {1: LocalDatanodeClient(dn1)})
        fe.do_query("CREATE TABLE solo (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO solo VALUES ('a', 1, 1.5)")
        fe.catalog.table(CAT, SCH, "solo").flush()

        dn3 = DatanodeInstance(
            DatanodeOptions(data_home=str(tmp_path / "wal3"), node_id=3,
                            register_numbers_table=False), store=shared)
        dn3.start()
        srv.register_datanode(Peer(3, "dn3"))
        t0 = time.time()
        _beat_regularly(srv, 3, t0, t0 + 30)
        moves = srv.failover_check(now=t0 + 29)
        assert moves and moves[0]["to"] == 3
        resp = srv.handle_heartbeat(3, now=t0 + 30)
        for msg in resp.mailbox:
            dn3._handle_mailbox(msg)
        fe2 = DistInstance(meta, {3: LocalDatanodeClient(dn3)})
        out = fe2.do_query("SELECT sum(v) AS s FROM solo")[-1]
        assert next(out.batches[0].rows())[0] == 1.5
        dn1.shutdown()
        dn3.shutdown()
