"""SQL window functions (OVER clause) — fallback-engine execution.

Reference behavior: DataFusion's WindowAggExec, reached through
src/query/src/datafusion.rs:61-232; semantics cross-checked against
PostgreSQL for peers (RANGE default frame), NULL handling, and
partition-boundary behavior.
"""

import numpy as np
import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import PlanError
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture(scope="module")
def fe(tmp_path_factory):
    dn = DatanodeInstance(DatanodeOptions(
        data_home=str(tmp_path_factory.mktemp("win")),
        register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    fe.do_query("CREATE TABLE w (host STRING, ts TIMESTAMP TIME INDEX,"
                " k BIGINT, v DOUBLE, PRIMARY KEY(host))")
    fe.do_query(
        "INSERT INTO w VALUES"
        " ('a', 0, 1, 3.0), ('a', 1000, 1, 1.0), ('a', 2000, 2, 4.0),"
        " ('a', 3000, 2, NULL), ('a', 4000, 3, 5.0),"
        " ('b', 0, 1, 10.0), ('b', 1000, 2, 20.0)")
    yield fe
    fe.shutdown()


def rows(fe, sql):
    out = fe.do_query(sql)
    if isinstance(out, list):
        out = out[0]
    rb = out.batches[0]
    cols = [vec.to_pylist() for vec in rb.columns]
    return list(zip(*cols)) if cols else []


def col(fe, sql, idx=-1):
    return [r[idx] for r in rows(fe, sql)]


class TestRanking:
    def test_row_number(self, fe):
        got = col(fe, "SELECT host, ts, row_number() OVER "
                      "(PARTITION BY host ORDER BY ts) FROM w "
                      "ORDER BY host, ts")
        assert got == [1, 2, 3, 4, 5, 1, 2]

    def test_rank_and_dense_rank_ties(self, fe):
        got = rows(fe, "SELECT ts, rank() OVER (ORDER BY k), "
                       "dense_rank() OVER (ORDER BY k) FROM w "
                       "WHERE host = 'a' ORDER BY ts")
        assert [r[1] for r in got] == [1, 1, 3, 3, 5]
        assert [r[2] for r in got] == [1, 1, 2, 2, 3]

    def test_percent_rank_cume_dist(self, fe):
        got = rows(fe, "SELECT ts, percent_rank() OVER (ORDER BY k), "
                       "cume_dist() OVER (ORDER BY k) FROM w "
                       "WHERE host = 'a' ORDER BY ts")
        assert [r[1] for r in got] == [0.0, 0.0, 0.5, 0.5, 1.0]
        assert [r[2] for r in got] == [0.4, 0.4, 0.8, 0.8, 1.0]

    def test_ntile(self, fe):
        got = col(fe, "SELECT ts, ntile(2) OVER (ORDER BY ts) FROM w "
                      "WHERE host = 'a' ORDER BY ts")
        assert got == [1, 1, 1, 2, 2]

    def test_rank_requires_order(self, fe):
        with pytest.raises(PlanError):
            fe.do_query("SELECT rank() OVER () FROM w")


class TestNavigation:
    def test_lag_lead_partition_bounds(self, fe):
        got = rows(fe, "SELECT host, ts, lag(v) OVER "
                       "(PARTITION BY host ORDER BY ts), lead(v, 1, -1.0) "
                       "OVER (PARTITION BY host ORDER BY ts) FROM w "
                       "ORDER BY host, ts")
        lags = [r[2] for r in got]
        leads = [r[3] for r in got]
        assert lags == [None, 3.0, 1.0, 4.0, None, None, 10.0]
        assert leads == [1.0, 4.0, None, 5.0, -1.0, 20.0, -1.0]

    def test_first_last_value(self, fe):
        got = rows(fe, "SELECT host, ts, first_value(v) OVER "
                       "(PARTITION BY host ORDER BY ts), last_value(v) OVER "
                       "(PARTITION BY host ORDER BY ts ROWS BETWEEN "
                       "UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
                       "FROM w ORDER BY host, ts")
        assert [r[2] for r in got] == [3.0] * 5 + [10.0] * 2
        assert [r[3] for r in got] == [5.0] * 5 + [20.0] * 2


class TestAggregates:
    def test_cumulative_sum_skips_nulls(self, fe):
        got = col(fe, "SELECT ts, sum(v) OVER (PARTITION BY host "
                      "ORDER BY ts) FROM w WHERE host = 'a' ORDER BY ts")
        assert got == [3.0, 4.0, 8.0, 8.0, 13.0]

    def test_range_peers_share_frame(self, fe):
        # default frame is RANGE: ties on the order key are peers
        got = col(fe, "SELECT ts, count(*) OVER (ORDER BY k) FROM w "
                      "WHERE host = 'a' ORDER BY ts")
        assert got == [2, 2, 4, 4, 5]

    def test_rows_frame_moving_avg(self, fe):
        got = col(fe, "SELECT ts, avg(v) OVER (ORDER BY ts ROWS BETWEEN "
                      "1 PRECEDING AND CURRENT ROW) FROM w "
                      "WHERE host = 'a' ORDER BY ts")
        assert got[0] == 3.0
        assert got[1] == 2.0
        assert got[2] == 2.5
        assert got[3] == 4.0          # (4, NULL) -> avg over non-null
        assert got[4] == 5.0          # (NULL, 5)

    def test_rows_frame_centered_min(self, fe):
        got = col(fe, "SELECT ts, min(v) OVER (ORDER BY ts ROWS BETWEEN "
                      "1 PRECEDING AND 1 FOLLOWING) FROM w "
                      "WHERE host = 'a' ORDER BY ts")
        assert got == [1.0, 1.0, 1.0, 4.0, 5.0]

    def test_count_star_vs_count_arg(self, fe):
        got = rows(fe, "SELECT ts, count(*) OVER (ORDER BY ts ROWS BETWEEN "
                       "1 PRECEDING AND CURRENT ROW), count(v) OVER "
                       "(ORDER BY ts ROWS BETWEEN 1 PRECEDING AND "
                       "CURRENT ROW) FROM w WHERE host = 'a' ORDER BY ts")
        assert [r[1] for r in got] == [1, 2, 2, 2, 2]
        assert [r[2] for r in got] == [1, 2, 2, 1, 1]

    def test_whole_partition_no_order(self, fe):
        got = col(fe, "SELECT host, sum(v) OVER (PARTITION BY host) FROM w "
                      "ORDER BY host, ts")
        assert got == [13.0] * 5 + [30.0] * 2

    def test_window_over_grouped_query(self, fe):
        got = rows(fe, "SELECT host, sum(v) AS total, rank() OVER "
                       "(ORDER BY sum(v) DESC) FROM w GROUP BY host "
                       "ORDER BY host")
        assert got == [("a", 13.0, 2), ("b", 30.0, 1)]

    def test_expression_of_window(self, fe):
        got = col(fe, "SELECT ts, v - avg(v) OVER (PARTITION BY host) "
                      "FROM w WHERE host = 'b' ORDER BY ts")
        assert got == [-5.0, 5.0]


class TestValidation:
    def test_window_not_allowed_in_where(self, fe):
        with pytest.raises(PlanError):
            fe.do_query("SELECT ts FROM w WHERE "
                        "rank() OVER (ORDER BY ts) = 1")

    def test_order_by_window_alias(self, fe):
        got = rows(fe, "SELECT host, ts, row_number() OVER "
                       "(PARTITION BY host ORDER BY v DESC) AS rn FROM w "
                       "WHERE v IS NOT NULL ORDER BY host, rn")
        assert [r[2] for r in got] == [1, 2, 3, 4, 1, 2]


class TestEdgeCases:
    def test_null_order_keys_sort_last_and_are_peers(self, fe):
        # v is NULL at ts=3000 for host a: NULL sorts last; rank treats
        # NULLs as peers of each other
        got = rows(fe, "SELECT ts, rank() OVER (ORDER BY v) FROM w "
                       "WHERE host = 'a' ORDER BY ts")
        # values: 3,1,4,NULL,5 -> ranks 2,1,3,5,4
        assert [r[1] for r in got] == [2, 1, 3, 5, 4]

    def test_desc_order_nulls_first(self, fe):
        # Postgres default: NULLS FIRST when the order key is DESC
        # (advisor r3: na_position='last' applied regardless of direction)
        got = rows(fe, "SELECT ts, rank() OVER (ORDER BY v DESC) FROM w "
                       "WHERE host = 'a' ORDER BY ts")
        # values by ts: 3,1,4,NULL,5; desc order is NULL,5,4,3,1
        assert [r[1] for r in got] == [4, 5, 3, 1, 2]

    def test_desc_order(self, fe):
        got = col(fe, "SELECT ts, row_number() OVER (ORDER BY v DESC) "
                      "FROM w WHERE host = 'a' AND v IS NOT NULL "
                      "ORDER BY ts")
        # v: 3,1,4,5 -> desc row_numbers 3,4,2,1
        assert got == [3, 4, 2, 1]

    def test_multi_partition_keys(self, fe):
        got = col(fe, "SELECT ts, count(*) OVER (PARTITION BY host, k) "
                      "FROM w ORDER BY host, ts")
        # host a: k=1 twice, k=2 twice, k=3 once; host b: k=1, k=2
        assert got == [2, 2, 2, 2, 1, 1, 1]

    def test_window_with_limit(self, fe):
        got = rows(fe, "SELECT ts, sum(v) OVER (ORDER BY ts) AS s FROM w "
                       "WHERE host = 'a' ORDER BY ts LIMIT 2")
        # LIMIT applies after the window computes over ALL rows
        assert [r[1] for r in got] == [3.0, 4.0]

    def test_window_sees_where_filtered_rows_only(self, fe):
        got = col(fe, "SELECT ts, count(*) OVER () FROM w "
                      "WHERE host = 'a' AND v > 2 ORDER BY ts")
        assert got == [3, 3, 3]     # v in (3,4,5)

    def test_lead_offset_two(self, fe):
        got = col(fe, "SELECT ts, lead(v, 2) OVER (PARTITION BY host "
                      "ORDER BY ts) FROM w WHERE host = 'b' ORDER BY ts")
        assert got == [None, None]

    def test_explain_window_query(self, fe):
        out = fe.do_query("EXPLAIN SELECT row_number() OVER "
                          "(ORDER BY ts) FROM w")
        if isinstance(out, list):
            out = out[0]
        assert out.batches is not None
