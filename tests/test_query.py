"""Query engine tests: fallback executor, TPU fast path, SHOW/DESCRIBE.

The fallback (pandas) and TPU paths are cross-checked on identical data —
the fallback is the oracle, mirroring how the reference validates pushed
scans against DataFusion."""

import math

import numpy as np
import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT, DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.catalog import MemoryCatalogManager
from greptimedb_tpu.datatypes import data_type as dt
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.errors import TableNotFoundError, UnsupportedError
from greptimedb_tpu.mito import MitoEngine
from greptimedb_tpu.query import QueryEngine
from greptimedb_tpu.query import tpu_exec
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql import parse_sql
from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
from greptimedb_tpu.table import CreateTableRequest, NumbersTable


@pytest.fixture(autouse=True)
def _force_tpu_dispatch(monkeypatch):
    """These tests cross-check the TPU path against the fallback on small
    tables; disable the cost-based row threshold so the device path actually
    executes (its dispatch behavior is tested separately below)."""
    monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)


def test_cost_dispatch_small_scan_uses_cpu(tmp_path, monkeypatch):
    """BASELINE config 1 regression: small scans must take the CPU columnar
    path — exact float64 results, no device round-trip latency."""
    monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 131072)
    storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
    mito = MitoEngine(storage)
    cm = MemoryCatalogManager()
    schema = Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
    ])
    t = mito.create_table(CreateTableRequest(
        "monitor", schema, primary_key_indices=[0]))
    cm.register_table(CAT, SCH, "monitor", t)
    t.insert({"host": ["host1", "host2"], "ts": [1000, 1000],
              "cpu": [66.6, 77.7]})
    engine = QueryEngine(cm)
    executed = []
    orig = tpu_exec.region_moment_frames
    monkeypatch.setattr(tpu_exec, "region_moment_frames",
                        lambda *a, **k: (executed.append(1), orig(*a, **k))[1])
    rows = run(engine, "SELECT host, avg(cpu) AS c FROM monitor "
                       "GROUP BY host ORDER BY host").batches[0].to_pylist()
    # float64-exact: 66.6 survives only on the CPU path (device mirror is f32)
    assert [(r["host"], r["c"]) for r in rows] == \
        [("host1", 66.6), ("host2", 77.7)]
    assert executed == [], "small scan took the device path"
    storage.close()


@pytest.fixture()
def world(tmp_path):
    storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
    mito = MitoEngine(storage)
    cm = MemoryCatalogManager()
    schema = Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("region", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
        ColumnSchema("mem", dt.FLOAT64),
    ])
    table = mito.create_table(CreateTableRequest(
        "monitor", schema, primary_key_indices=[0, 1]))
    rng = np.random.default_rng(9)
    n = 4000
    hosts = [f"h{i % 5}" for i in range(n)]
    regions = ["east" if i % 2 else "west" for i in range(n)]
    ts = (np.arange(n) * 250).tolist()          # 0..1000s, 4 per second
    cpu = rng.random(n).round(4).tolist()
    mem = [None if i % 17 == 0 else float(i % 100) for i in range(n)]
    table.insert({"host": hosts, "region": regions, "ts": ts,
                  "cpu": cpu, "mem": mem})
    cm.register_table(CAT, SCH, "monitor", table)
    cm.register_table(CAT, SCH, "numbers", NumbersTable())
    engine = QueryEngine(cm)
    return engine, table, dict(host=hosts, region=regions, ts=ts, cpu=cpu,
                               mem=mem)


def run(engine, sql):
    return engine.execute(parse_sql(sql), QueryContext())


class TestFallback:
    def test_select_star_limit(self, world):
        engine, *_ = world
        out = run(engine, "SELECT * FROM monitor ORDER BY ts LIMIT 3")
        assert out.num_rows == 3
        assert out.schema.names() == ["host", "region", "ts", "cpu", "mem"]

    def test_projection_exprs(self, world):
        engine, *_ = world
        out = run(engine, "SELECT cpu * 100 AS pct, host FROM monitor "
                          "WHERE ts = 0")
        row = out.batches[0].to_pylist()[0]
        assert math.isclose(row["pct"], world[2]["cpu"][0] * 100)

    def test_where_and_order(self, world):
        engine, _, data = world
        out = run(engine, "SELECT ts FROM monitor WHERE host = 'h1' AND "
                          "ts < 10000 ORDER BY ts DESC")
        vals = [r["ts"] for r in out.batches[0].to_pylist()]
        want = sorted((t for h, t in zip(data["host"], data["ts"])
                       if h == "h1" and t < 10000), reverse=True)
        assert vals == want

    def test_numbers(self, world):
        engine, *_ = world
        out = run(engine, "SELECT number FROM numbers ORDER BY number DESC "
                          "LIMIT 5")
        assert [r["number"] for r in out.batches[0].to_pylist()] == \
            [99, 98, 97, 96, 95]

    def test_no_from(self, world):
        engine, *_ = world
        out = run(engine, "SELECT 1 + 1, 'x'")
        row = out.batches[0].to_pylist()[0]
        assert list(row.values()) == [2, "x"]

    def test_case_and_functions(self, world):
        engine, *_ = world
        out = run(engine, """
            SELECT host, CASE WHEN cpu > 0.5 THEN 'hot' ELSE 'cold' END AS t
            FROM monitor WHERE ts = 0""")
        assert out.batches[0].to_pylist()[0]["t"] in ("hot", "cold")
        out = run(engine, "SELECT abs(-3.5), pow(2, 10)")
        row = list(out.batches[0].to_pylist()[0].values())
        assert row == [3.5, 1024.0]

    def test_aggregate_with_expr_group(self, world):
        engine, _, data = world
        # group by an expression the TPU path doesn't take (modulo)
        out = run(engine, """
            SELECT ts % 2 AS par, count(*) AS c FROM monitor GROUP BY par
            ORDER BY par""")
        rows = out.batches[0].to_pylist()
        assert sum(r["c"] for r in rows) == 4000

    def test_table_not_found(self, world):
        engine, *_ = world
        with pytest.raises(TableNotFoundError):
            run(engine, "SELECT * FROM nope")

    def test_distinct(self, world):
        engine, *_ = world
        out = run(engine, "SELECT DISTINCT region FROM monitor ORDER BY region")
        assert [r["region"] for r in out.batches[0].to_pylist()] == \
            ["east", "west"]

    def test_count_distinct(self, world):
        engine, *_ = world
        out = run(engine, "SELECT count(DISTINCT host) AS c FROM monitor")
        assert out.batches[0].to_pylist()[0]["c"] == 5

    def test_having(self, world):
        engine, _, data = world
        out = run(engine, """
            SELECT host, count(*) AS c FROM monitor GROUP BY host
            HAVING count(*) > 100 ORDER BY host""")
        assert all(r["c"] == 800 for r in out.batches[0].to_pylist())

    def test_subquery_from(self, world):
        engine, *_ = world
        out = run(engine, """
            SELECT count(*) AS c FROM
            (SELECT host FROM monitor WHERE ts < 1000) s""")
        assert out.batches[0].to_pylist()[0]["c"] == 4

    def test_correlated_exists_unsupported_error(self, world):
        """An unqualified outer-column reference inside EXISTS surfaces
        the 'correlated ... not supported' taxonomy error, not a raw
        column-not-found."""
        from greptimedb_tpu.errors import UnsupportedError
        engine, *_ = world
        with pytest.raises(UnsupportedError, match="correlated"):
            run(engine, """
                SELECT host FROM monitor m WHERE EXISTS
                (SELECT 1 FROM monitor WHERE host = no_such_col)""")


class TestTpuPath:
    def _oracle(self, engine, sql, monkeypatch):
        """Run the same query with the TPU path disabled."""
        import greptimedb_tpu.query.tpu_exec as tx
        orig = tx.try_execute
        monkeypatch.setattr(tx, "try_execute", lambda *a, **k: None)
        try:
            return run(engine, sql)
        finally:
            monkeypatch.setattr(tx, "try_execute", orig)

    @pytest.mark.parametrize("sql", [
        "SELECT host, avg(cpu) FROM monitor GROUP BY host",
        "SELECT host, region, max(cpu), min(cpu) FROM monitor "
        "GROUP BY host, region",
        "SELECT host, count(*) FROM monitor WHERE ts >= 100000 AND "
        "ts < 500000 GROUP BY host",
        "SELECT host, sum(mem), count(mem) FROM monitor GROUP BY host",
        "SELECT host, date_bin(INTERVAL '1 minute', ts) AS minute, "
        "avg(cpu) FROM monitor GROUP BY host, minute",
        "SELECT avg(cpu), max(mem), count(*) FROM monitor",
        "SELECT host, stddev(cpu) FROM monitor GROUP BY host",
        "SELECT host, first(cpu), last(cpu) FROM monitor GROUP BY host",
        "SELECT region, avg(cpu) FROM monitor WHERE host != 'h0' "
        "GROUP BY region",
        "SELECT host, avg(cpu) FROM monitor WHERE mem > 50 GROUP BY host",
        "SELECT host, avg(cpu) AS a FROM monitor GROUP BY host "
        "HAVING avg(cpu) > 0.4 ORDER BY a DESC LIMIT 3",
    ])
    def test_matches_fallback(self, world, sql, monkeypatch):
        engine, table, _ = world
        a = __import__("greptimedb_tpu.query.planner",
                       fromlist=["analyze"]).analyze(parse_sql(sql))
        plan = tpu_exec.plan_for(table, a, parse_sql(sql))
        assert plan is not None, f"expected TPU plan for: {sql}"
        got = run(engine, sql)
        want = self._oracle(engine, sql, monkeypatch)
        gr = got.batches[0].to_pylist()
        wr = want.batches[0].to_pylist()
        key = lambda r: tuple(str(v) for v in r.values())
        if "ORDER BY" not in sql:
            gr = sorted(gr, key=key)
            wr = sorted(wr, key=key)
        assert len(gr) == len(wr), sql
        for g, w in zip(gr, wr):
            assert list(g) == list(w), sql
            for k in g:
                gv, wv = g[k], w[k]
                if isinstance(gv, float) and isinstance(wv, float):
                    if math.isnan(gv) and math.isnan(wv):
                        continue
                    assert math.isclose(gv, wv, rel_tol=1e-3, abs_tol=1e-4), \
                        (sql, k, gv, wv)
                else:
                    assert gv == wv, (sql, k, gv, wv)

    def test_plan_rejects_unsupported(self, world):
        engine, table, _ = world
        for sql in [
            "SELECT host, percentile(cpu, 50) FROM monitor GROUP BY host",
            "SELECT ts % 2, count(*) FROM monitor GROUP BY 1",
            "SELECT host, avg(abs(cpu)) FROM monitor GROUP BY host",
            # distinct sketches only pay on the distributed pushdown; a
            # LOCAL table keeps the exact fallback (ISSUE 14)
            "SELECT host, count(DISTINCT region) FROM monitor GROUP BY host",
        ]:
            stmt = parse_sql(sql)
            a = __import__("greptimedb_tpu.query.planner",
                           fromlist=["analyze"]).analyze(stmt)
            assert tpu_exec.plan_for(table, a, stmt) is None, sql

    def test_plan_accepts_expression_args(self, world):
        """ISSUE 14: arithmetic agg arguments plan as virtual expression
        moments instead of falling back."""
        engine, table, _ = world
        for sql in [
            "SELECT host, avg(cpu + 1) FROM monitor GROUP BY host",
            "SELECT host, sum(cpu * mem) FROM monitor GROUP BY host",
        ]:
            stmt = parse_sql(sql)
            a = __import__("greptimedb_tpu.query.planner",
                           fromlist=["analyze"]).analyze(stmt)
            plan = tpu_exec.plan_for(table, a, stmt)
            assert plan is not None and plan.field_exprs, sql


class TestShow:
    def test_show_describe(self, world):
        engine, *_ = world
        out = run(engine, "SHOW TABLES")
        names = [r["Tables"] for r in out.batches[0].to_pylist()]
        assert "monitor" in names
        out = run(engine, "SHOW TABLES LIKE 'mon%'")
        assert [r["Tables"] for r in out.batches[0].to_pylist()] == ["monitor"]
        out = run(engine, "DESCRIBE monitor")
        rows = out.batches[0].to_pylist()
        by_col = {r["Column"]: r for r in rows}
        assert by_col["ts"]["Key"] == "TIME INDEX"
        assert by_col["host"]["Semantic Type"] == "TAG"
        assert by_col["cpu"]["Semantic Type"] == "FIELD"
        out = run(engine, "SHOW CREATE TABLE monitor")
        ddl = out.batches[0].to_pylist()[0]["Create Table"]
        assert "TIME INDEX (ts)" in ddl and "PRIMARY KEY (host, region)" in ddl

    def test_explain(self, world):
        engine, *_ = world
        out = run(engine, "EXPLAIN SELECT host, avg(cpu) FROM monitor "
                          "GROUP BY host")
        plan = out.batches[0].to_pylist()[0]["plan"]
        assert "TpuAggregateExec" in plan


class TestReviewRegressions:
    def test_case_on_filtered_frame(self, world):
        # CASE over a WHERE-filtered frame must align with the frame index
        engine, _, data = world
        out = run(engine, """
            SELECT ts, CASE WHEN cpu > 0.5 THEN 'hot' ELSE 'cold' END AS t
            FROM monitor WHERE ts >= 500 AND ts < 1500 ORDER BY ts""")
        rows = out.batches[0].to_pylist()
        assert len(rows) == 4
        for r in rows:
            i = data["ts"].index(r["ts"])
            want = "hot" if data["cpu"][i] > 0.5 else "cold"
            assert r["t"] == want

    def test_constant_projection_empty_result(self, world):
        engine, *_ = world
        out = run(engine, "SELECT 1 AS one FROM monitor WHERE ts < 0")
        assert out.num_rows == 0
        # but SELECT without FROM still yields one row
        assert run(engine, "SELECT 1").num_rows == 1

    def test_fractional_time_bounds_match_fallback(self, world, monkeypatch):
        engine, table, _ = world
        sql = ("SELECT count(*) AS c FROM monitor WHERE ts >= 499.5 "
               "AND ts < 750.5")
        got = run(engine, sql).batches[0].to_pylist()
        import greptimedb_tpu.query.tpu_exec as tx
        monkeypatch.setattr(tx, "try_execute", lambda *a, **k: None)
        want = run(engine, sql).batches[0].to_pylist()
        assert got == want

    def test_unaliased_aggregate_names(self, world):
        engine, *_ = world
        out = run(engine, "SELECT host, avg(cpu) FROM monitor GROUP BY host")
        assert out.schema.names() == ["host", "avg(cpu)"]


def test_alter_on_demand_rejects_new_tags(tmp_path):
    from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
    from greptimedb_tpu.frontend import FrontendInstance
    from greptimedb_tpu.errors import InvalidArgumentsError
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    fe = FrontendInstance(dn)
    fe.start()
    fe.handle_row_insert("up", {"host": ["a"], "greptime_timestamp": [1000],
                                "greptime_value": [1.0]},
                         tag_columns=["host"])
    with pytest.raises(InvalidArgumentsError, match="tag"):
        fe.handle_row_insert(
            "up", {"host": ["a"], "az": ["az1"],
                   "greptime_timestamp": [2000], "greptime_value": [2.0]},
            tag_columns=["host", "az"])
    fe.shutdown()


class TestAdviceRegressions:
    """Regressions for the round-1 advisor findings (ADVICE.md)."""

    def _partitioned(self, tmp_path):
        from greptimedb_tpu.mito import MitoEngine
        from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        mito = MitoEngine(storage)
        stmt = parse_sql("""
            CREATE TABLE p (host STRING, ts TIMESTAMP TIME INDEX,
                            cpu DOUBLE, PRIMARY KEY(host))
            PARTITION BY RANGE COLUMNS (host) (
              PARTITION r0 VALUES LESS THAN ('m'),
              PARTITION r1 VALUES LESS THAN (MAXVALUE))""")
        schema = Schema([
            ColumnSchema("host", dt.STRING, nullable=False,
                         semantic_type=SemanticType.TAG),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("cpu", dt.FLOAT64),
        ])
        t = mito.create_table(CreateTableRequest(
            "p", schema, primary_key_indices=[0], partitions=stmt.partitions))
        cm = MemoryCatalogManager()
        cm.register_table(CAT, SCH, "p", t)
        return QueryEngine(cm), t

    def test_first_last_across_regions_absolute_ts(self, tmp_path):
        # region bases differ: r1's earliest row (ts=50) precedes r0's
        # (ts=100); region-relative min_ts would tie at 0 and pick r0
        engine, t = self._partitioned(tmp_path)
        t.insert({"host": ["alpha", "alpha", "zulu", "zulu"],
                  "ts": [100, 200, 50, 300],
                  "cpu": [111.0, 5.0, 999.0, 7.0]})
        out = run(engine, "SELECT first(cpu) AS f, last(cpu) AS l FROM p")
        row = out.batches[0].to_pylist()[0]
        assert row["f"] == 999.0    # value at absolute earliest ts=50
        assert row["l"] == 7.0      # value at absolute latest ts=300

    def test_fallback_first_without_ts_projection(self, tmp_path, monkeypatch):
        # CPU fallback must project the time index even when the query
        # doesn't reference it, so first/last stay time-ordered. Scan order
        # is series-major (host asc, ts asc): host 'b' holds the earliest
        # row, so unsorted scan order would return 'a's value.
        engine, t = self._partitioned(tmp_path)
        t.insert({"host": ["a", "a", "b", "b"],
                  "ts": [100, 200, 10, 300],
                  "cpu": [111.0, 5.0, 999.0, 7.0]})
        import greptimedb_tpu.query.tpu_exec as tx
        monkeypatch.setattr(tx, "try_execute", lambda *a, **k: None)
        out = run(engine, "SELECT first(cpu) AS f, last(cpu) AS l FROM p")
        row = out.batches[0].to_pylist()[0]
        assert row["f"] == 999.0 and row["l"] == 7.0

    def test_date_trunc_week_monday_aligned(self, world, monkeypatch):
        engine, *_ = world
        from greptimedb_tpu.query.functions import _date_trunc
        # 1970-01-08 (Thursday) truncates to Monday 1970-01-05
        assert _date_trunc("week", [7 * 86_400_000])[0] == 4 * 86_400_000
        # pre-epoch-Monday values floor to the previous Monday
        assert _date_trunc("week", [0])[0] == 4 * 86_400_000 - 604_800_000
        # TPU bucket path agrees with the fallback
        sql = ("SELECT date_trunc('week', ts) AS w, count(*) AS c "
               "FROM monitor GROUP BY w")
        got = run(engine, sql).batches[0].to_pylist()
        import greptimedb_tpu.query.tpu_exec as tx
        monkeypatch.setattr(tx, "try_execute", lambda *a, **k: None)
        want = run(engine, sql).batches[0].to_pylist()
        key = lambda r: r["w"]
        assert sorted(got, key=key) == sorted(want, key=key)


class TestIncrementalScanCache:
    """VERDICT round-1 weakness 5: scan prep must be proportional to new
    data — version bumps merge deltas instead of re-reading the region."""

    def _mk(self, tmp_path):
        from greptimedb_tpu.mito import MitoEngine
        from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        mito = MitoEngine(storage)
        schema = Schema([
            ColumnSchema("host", dt.STRING, nullable=False,
                         semantic_type=SemanticType.TAG),
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("cpu", dt.FLOAT64),
        ])
        t = mito.create_table(CreateTableRequest(
            "inc", schema, primary_key_indices=[0]))
        cm = MemoryCatalogManager()
        cm.register_table(CAT, SCH, "inc", t)
        return QueryEngine(cm), t, storage

    def test_incremental_matches_full(self, tmp_path):
        engine, t, storage = self._mk(tmp_path)
        t.insert({"host": ["a", "b"], "ts": [1, 2], "cpu": [1.0, 2.0]})
        r1 = run(engine, "SELECT host, sum(cpu) AS s FROM inc GROUP BY host")
        t.insert({"host": ["a", "c"], "ts": [3, 4], "cpu": [3.0, 4.0]})
        got = run(engine, "SELECT host, sum(cpu) AS s FROM inc "
                          "GROUP BY host").batches[0].to_pylist()
        cache = tpu_exec.SCAN_CACHE
        tpu_exec.SCAN_CACHE = tpu_exec._ScanCache()   # force full rebuild
        try:
            want = run(engine, "SELECT host, sum(cpu) AS s FROM inc "
                               "GROUP BY host").batches[0].to_pylist()
        finally:
            tpu_exec.SCAN_CACHE = cache
        key = lambda r: r["host"]
        assert sorted(got, key=key) == sorted(want, key=key)
        storage.close()

    def test_update_and_delete_through_delta(self, tmp_path):
        engine, t, storage = self._mk(tmp_path)
        t.insert({"host": ["a", "b"], "ts": [1, 2], "cpu": [1.0, 2.0]})
        run(engine, "SELECT sum(cpu) FROM inc")      # build cache
        t.insert({"host": ["a"], "ts": [1], "cpu": [10.0]})   # overwrite
        t.delete({"host": ["b"], "ts": [2]})
        got = run(engine, "SELECT sum(cpu) AS s FROM inc")
        assert got.batches[0].to_pylist()[0]["s"] == 10.0
        storage.close()

    def test_flush_does_not_reread_ssts(self, tmp_path):
        engine, t, storage = self._mk(tmp_path)
        region = next(iter(t.regions.values()))
        t.insert({"host": ["a"], "ts": [1], "cpu": [1.0]})
        run(engine, "SELECT sum(cpu) FROM inc")      # cache covers seq 1
        t.flush()                                    # rows move to an SST
        reads = []
        orig = region.access_layer.read_sst
        region.access_layer.read_sst = \
            lambda *a, **k: (reads.append(1), orig(*a, **k))[1]
        got = run(engine, "SELECT sum(cpu) AS s FROM inc")
        assert got.batches[0].to_pylist()[0]["s"] == 1.0
        assert reads == [], "flushed-but-covered SST was re-read"
        region.access_layer.read_sst = orig
        storage.close()

    def test_ttl_retraction_rebuilds(self, tmp_path):
        engine, t, storage = self._mk(tmp_path)
        region = next(iter(t.regions.values()))
        region.ttl_ms = 60_000
        now = 1_000_000
        t.insert({"host": ["a", "a"], "ts": [now - 120_000, now],
                  "cpu": [1.0, 2.0]})
        run(engine, "SELECT sum(cpu) FROM inc")      # cache holds both rows
        t.flush()
        region.compact(now_ms=now)                   # TTL drops the old row
        got = run(engine, "SELECT sum(cpu) AS s FROM inc")
        assert got.batches[0].to_pylist()[0]["s"] == 2.0
        storage.close()


def test_incremental_cache_randomized_oracle(tmp_path):
    """Property test: random interleavings of inserts/overwrites/deletes/
    flushes must leave the incremental cache identical to a full rebuild."""
    from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
    from greptimedb_tpu.storage.write_batch import WriteBatch
    rng = np.random.default_rng(7)
    schema = Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
    ])
    storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
    r = storage.create_region("rnd", schema)
    cache = tpu_exec._ScanCache()
    for round_ in range(12):
        n = int(rng.integers(1, 60))
        hosts = [f"h{int(h)}" for h in rng.integers(0, 5, n)]
        ts = rng.integers(0, 200, n).tolist()     # heavy key collisions
        wb = WriteBatch(schema)
        wb.put({"host": hosts, "ts": ts,
                "cpu": rng.random(n).round(3).tolist()})
        r.write(wb)
        if rng.random() < 0.3:
            m = int(rng.integers(1, 10))
            wb = WriteBatch(schema)
            wb.delete({"host": [f"h{int(h)}" for h in rng.integers(0, 5, m)],
                       "ts": rng.integers(0, 200, m).tolist()})
            r.write(wb)
        if rng.random() < 0.4:
            r.flush()
        got = cache.get(r)                        # incremental path
        want = tpu_exec._ScanCache().get(r)       # fresh full rebuild
        assert got.num_rows == want.num_rows, f"round {round_}"
        assert np.array_equal(got.series_ids, want.series_ids)
        assert np.array_equal(got.ts, want.ts)
        gv, _ = got.fields["cpu"]
        wv, _ = want.fields["cpu"]
        assert np.allclose(gv, wv, equal_nan=True), f"round {round_}"
    storage.close()
