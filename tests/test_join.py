"""JOIN execution tests (CPU fallback path).

Reference: joins are DataFusion territory (src/query/src/datafusion.rs);
coverage mirrors typical sqlness join cases — inner/left/right/cross,
multi-key ON, qualified + aliased columns, join + aggregate.
"""

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import PlanError, UnsupportedError
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    f.do_query("CREATE TABLE metrics (host STRING, ts TIMESTAMP TIME"
               " INDEX, cpu DOUBLE, PRIMARY KEY(host))")
    f.do_query("INSERT INTO metrics VALUES ('a', 1000, 1.0),"
               " ('b', 2000, 2.0), ('c', 3000, 3.0)")
    f.do_query("CREATE TABLE meta (host STRING, ts TIMESTAMP TIME INDEX,"
               " dc STRING, PRIMARY KEY(host))")
    f.do_query("INSERT INTO meta VALUES ('a', 1, 'us-east'),"
               " ('b', 1, 'us-west'), ('d', 1, 'eu-1')")
    yield f
    f.shutdown()


def _rows(fe, sql):
    out = fe.do_query(sql)[-1]
    return [tuple(r) for b in out.batches for r in b.rows()]


class TestJoins:
    def test_inner_join(self, fe):
        rows = _rows(fe, "SELECT metrics.host, cpu, dc FROM metrics"
                         " JOIN meta ON metrics.host = meta.host"
                         " ORDER BY metrics.host")
        assert rows == [("a", 1.0, "us-east"), ("b", 2.0, "us-west")]

    def test_left_join_keeps_unmatched(self, fe):
        rows = _rows(fe, "SELECT metrics.host, dc FROM metrics"
                         " LEFT JOIN meta ON metrics.host = meta.host"
                         " ORDER BY metrics.host")
        assert rows == [("a", "us-east"), ("b", "us-west"), ("c", None)]

    def test_right_join(self, fe):
        rows = _rows(fe, "SELECT meta.host, cpu FROM metrics"
                         " RIGHT JOIN meta ON metrics.host = meta.host"
                         " ORDER BY meta.host")
        assert rows == [("a", 1.0), ("b", 2.0), ("d", None)]

    def test_null_keys_never_match(self, fe):
        # SQL: NULL = NULL is not true — pandas merge would match NaN keys
        fe.do_query("CREATE TABLE lt (id STRING, ts TIMESTAMP TIME INDEX,"
                    " k STRING, v DOUBLE, PRIMARY KEY(id))")
        fe.do_query("CREATE TABLE rt (id STRING, ts TIMESTAMP TIME INDEX,"
                    " k STRING, w DOUBLE, PRIMARY KEY(id))")
        fe.do_query("INSERT INTO lt (id, ts, k, v) VALUES"
                    " ('l1', 1, 'x', 1.0), ('l2', 2, NULL, 2.0)")
        fe.do_query("INSERT INTO rt (id, ts, k, w) VALUES"
                    " ('r1', 1, 'x', 10.0), ('r2', 2, NULL, 20.0)")
        rows = _rows(fe, "SELECT lt.id, rt.id FROM lt"
                         " JOIN rt ON lt.k = rt.k")
        assert rows == [("l1", "r1")]      # no NULL-NULL match
        rows = _rows(fe, "SELECT lt.id, rt.id, w FROM lt"
                         " LEFT JOIN rt ON lt.k = rt.k ORDER BY lt.id")
        assert rows == [("l1", "r1", 10.0), ("l2", None, None)]
        rows = _rows(fe, "SELECT lt.id, rt.id FROM lt"
                         " RIGHT JOIN rt ON lt.k = rt.k ORDER BY rt.id")
        assert rows == [("l1", "r1"), (None, "r2")]

    def test_cross_join(self, fe):
        rows = _rows(fe, "SELECT count(*) FROM metrics CROSS JOIN meta")
        assert rows == [(9,)]

    def test_full_outer_join(self, fe):
        rows = _rows(fe, "SELECT metrics.host, meta.host, cpu, dc"
                         " FROM metrics FULL JOIN meta"
                         " ON metrics.host = meta.host")
        assert sorted(rows, key=str) == sorted([
            ("a", "a", 1.0, "us-east"), ("b", "b", 2.0, "us-west"),
            ("c", None, 3.0, None), (None, "d", None, "eu-1")], key=str)

    def test_full_outer_join_null_keys(self, fe):
        fe.do_query("CREATE TABLE fl (id STRING, ts TIMESTAMP TIME INDEX,"
                    " k STRING, PRIMARY KEY(id))")
        fe.do_query("CREATE TABLE fr (id STRING, ts TIMESTAMP TIME INDEX,"
                    " k STRING, PRIMARY KEY(id))")
        fe.do_query("INSERT INTO fl (id, ts, k) VALUES ('l1', 1, 'x'),"
                    " ('l2', 2, NULL)")
        fe.do_query("INSERT INTO fr (id, ts, k) VALUES ('r1', 1, 'x'),"
                    " ('r2', 2, NULL)")
        rows = _rows(fe, "SELECT fl.id, fr.id FROM fl"
                         " FULL JOIN fr ON fl.k = fr.k")
        # NULL keys never match, but full-join preserves both null rows
        assert sorted(rows, key=str) == sorted(
            [("l1", "r1"), ("l2", None), (None, "r2")], key=str)

    def test_aliased_self_join(self, fe):
        rows = _rows(fe, "SELECT l.host, r.host FROM metrics l"
                         " JOIN metrics r ON l.host = r.host"
                         " ORDER BY l.host")
        assert rows == [("a", "a"), ("b", "b"), ("c", "c")]

    def test_join_with_where_and_aggregate(self, fe):
        rows = _rows(fe, "SELECT dc, sum(cpu) AS s FROM metrics"
                         " JOIN meta ON metrics.host = meta.host"
                         " WHERE cpu > 0.5 GROUP BY dc ORDER BY dc")
        assert rows == [("us-east", 1.0), ("us-west", 2.0)]

    def test_non_equi_inner_residual(self, fe):
        rows = _rows(fe, "SELECT metrics.host FROM metrics JOIN meta"
                         " ON metrics.host = meta.host AND cpu > 1.5")
        assert rows == [("b",)]

    def test_join_requires_equality(self, fe):
        with pytest.raises(UnsupportedError, match="equality"):
            fe.do_query("SELECT 1 FROM metrics JOIN meta"
                        " ON metrics.cpu > 1")

    def test_ambiguous_projection_rejected(self, fe):
        from greptimedb_tpu.errors import ColumnNotFoundError
        with pytest.raises(ColumnNotFoundError):
            # 'host' exists on both sides of a self-join: unresolvable
            fe.do_query("SELECT host FROM metrics l"
                        " JOIN metrics r ON l.host = r.host")

    def test_join_subquery(self, fe):
        rows = _rows(fe, "SELECT m.host, t.c FROM metrics m JOIN"
                         " (SELECT host, count(*) AS c FROM meta"
                         "  GROUP BY host) t ON m.host = t.host"
                         " ORDER BY m.host")
        assert rows == [("a", 1), ("b", 1)]
