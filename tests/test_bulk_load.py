"""Bulk load path: WAL-less direct-to-SST ingest and its wiring.

Mirrors the reference's direct part writes + COPY FROM tests
(src/storage/src/region/writer.rs:394-433, operator COPY flows):
correctness vs the WAL+memtable write path, crash-safety around the
manifest commit point, concurrent-write sequence capping, partitioned
routing, COPY FROM / Flight do_put integration, and compressed COPY.
"""

import os
import threading

import numpy as np
import pytest

from greptimedb_tpu.datatypes import (
    FLOAT64, STRING, TIMESTAMP_MILLISECOND, ColumnSchema, Schema,
    SemanticType,
)
from greptimedb_tpu.storage import EngineConfig, StorageEngine, WriteBatch


def monitor_schema() -> Schema:
    return Schema([
        ColumnSchema("host", STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", FLOAT64),
        ColumnSchema("memory", FLOAT64),
    ])


def make_engine(tmp_path, sub="a", **kwargs) -> StorageEngine:
    return StorageEngine(EngineConfig(data_home=str(tmp_path / sub),
                                      **kwargs))


def merged_rows(region):
    data = region.snapshot().read_merged()
    hosts = data.series_dict.decode_tag_column(data.series_ids, 0)
    cpu_d, cpu_v = data.fields["cpu"]
    mem_d, mem_v = data.fields["memory"]
    rows = []
    for i in range(data.num_rows):
        rows.append((
            hosts[i], int(data.ts[i]),
            None if cpu_v is not None and not cpu_v[i] else float(cpu_d[i]),
            None if mem_v is not None and not mem_v[i] else float(mem_d[i]),
        ))
    return sorted(rows)


class TestBulkIngest:
    def test_matches_write_path(self, tmp_path):
        """bulk_ingest produces exactly what write() + flush produces,
        including NULL fields (list-with-None columns) and string tags."""
        eng = make_engine(tmp_path)
        r_w = eng.create_region("t/w", monitor_schema())
        r_b = eng.create_region("t/b", monitor_schema())
        hosts = ["h2", "h0", "h1", "h0"]
        ts = [2000, 1000, 1500, 3000]
        cpu = [0.5, None, 1.5, None]
        mem = [10.0, 20.0, 30.0, 40.0]

        wb = WriteBatch(r_w.schema)
        wb.put({"host": hosts, "ts": ts, "cpu": cpu, "memory": mem})
        r_w.write(wb)
        r_w.flush()

        r_b.bulk_ingest({"host": hosts, "ts": ts, "cpu": cpu,
                         "memory": mem})
        assert merged_rows(r_b) == merged_rows(r_w)
        # bulk went straight to SSTs — nothing buffered
        assert all(mt.num_rows == 0 for mt in
                   r_b.version_control.current.memtables.all_memtables())
        assert len(r_b.version_control.current.ssts.levels[0]) >= 1

    def test_raw_ndarray_fast_path(self, tmp_path):
        """All-ndarray batches (the loader shape) round-trip exactly."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        n = 50_000
        rng = np.random.default_rng(7)
        cols = {
            "host": np.array([f"h{i % 37}" for i in range(n)], dtype=object),
            "ts": np.arange(n, dtype=np.int64) * 100,
            "cpu": rng.random(n),
            "memory": rng.random(n),
        }
        assert r.bulk_ingest(cols) == n
        data = r.snapshot().read_merged()
        assert data.num_rows == n
        # MVCC overwrite across a second bulk batch: same keys win by seq
        r.bulk_ingest({"host": cols["host"][:10], "ts": cols["ts"][:10],
                       "cpu": np.full(10, 9.0), "memory": np.zeros(10)})
        data = r.snapshot().read_merged()
        assert data.num_rows == n
        hosts2 = data.series_dict.decode_tag_column(data.series_ids, 0)
        got = {(h, int(t)): float(c)
               for h, t, c in zip(hosts2, data.ts, data.fields["cpu"][0])}
        for i in range(10):
            assert got[(cols["host"][i], int(cols["ts"][i]))] == 9.0

    def test_survives_reopen(self, tmp_path):
        """Durability without the WAL: SSTs + manifest edit survive a
        crash (no close)."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        r.bulk_ingest({"host": ["a", "b"], "ts": [1000, 2000],
                       "cpu": [1.0, 2.0], "memory": [3.0, 4.0]})
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert merged_rows(r2) == [("a", 1000, 1.0, 3.0),
                                   ("b", 2000, 2.0, 4.0)]

    def test_concurrent_write_sequence_not_skipped(self, tmp_path):
        """A write() landing between bulk_ingest's pre-lock flush and its
        manifest commit must survive replay: flushed_sequence is capped
        below the unflushed write's sequence."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        # seed + flush so the pre-lock flush check sees empty memtables
        r.bulk_ingest({"host": ["a"], "ts": [500],
                       "cpu": [0.1], "memory": [0.2]})
        # simulate the race deterministically: a write sneaks in after
        # the emptiness check (flush becomes a no-op for this call)
        wb = WriteBatch(r.schema)
        wb.put({"host": ["race"], "ts": [999], "cpu": [7.0],
                "memory": [8.0]})
        r.write(wb)
        orig_flush = r.flush
        r.flush = lambda: []          # the gap: bulk sees stale emptiness
        try:
            r.bulk_ingest({"host": ["b"], "ts": [1000],
                           "cpu": [1.0], "memory": [2.0]})
        finally:
            r.flush = orig_flush
        # crash + reopen: WAL replay must still deliver the raced write
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert ("race", 999, 7.0, 8.0) in merged_rows(r2)
        assert ("b", 1000, 1.0, 2.0) in merged_rows(r2)

    def test_crash_before_manifest_leaves_orphans_only(self, tmp_path):
        """A crash between SST write and manifest edit loses the batch
        (never acked) but corrupts nothing: reopen sees the prior state
        and the half-written files are unreferenced orphans."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        r.bulk_ingest({"host": ["a"], "ts": [500],
                       "cpu": [0.1], "memory": [0.2]})
        orig_save = r.manifest.save

        def boom(actions):
            raise RuntimeError("crash before manifest edit")

        r.manifest.save = boom
        with pytest.raises(RuntimeError):
            r.bulk_ingest({"host": ["lost"], "ts": [1000],
                           "cpu": [1.0], "memory": [2.0]})
        r.manifest.save = orig_save
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert merged_rows(r2) == [("a", 500, 0.1, 0.2)]
        # orphan SSTs may exist on disk but none are referenced twice
        referenced = {f.file_name for f in
                      r2.version_control.current.ssts.all_files()}
        assert len(referenced) == 1

    def test_parallel_writers_during_bulk(self, tmp_path):
        """Racing write()s against bulk_ingest never lose acked rows."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        errors = []

        def writer(k):
            try:
                for i in range(20):
                    wb = WriteBatch(r.schema)
                    wb.put({"host": [f"w{k}"], "ts": [10_000 + k * 100 + i],
                            "cpu": [float(i)], "memory": [0.0]})
                    r.write(wb)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for b in range(3):
            r.bulk_ingest({"host": ["bulk"] * 100,
                           "ts": list(range(b * 100, b * 100 + 100)),
                           "cpu": [1.0] * 100, "memory": [2.0] * 100})
        for t in threads:
            t.join()
        assert not errors
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        rows = merged_rows(r2)
        assert len([x for x in rows if x[0] == "bulk"]) == 300
        assert len([x for x in rows if x[0].startswith("w")]) == 60


class TestFrontendBulk:
    @pytest.fixture()
    def fe(self, tmp_path):
        from greptimedb_tpu.datanode import DatanodeOptions
        from greptimedb_tpu.frontend.instance import build_standalone
        inst = build_standalone(DatanodeOptions(
            data_home=str(tmp_path / "fe"), register_numbers_table=False))
        yield inst
        inst.shutdown()

    def _q(self, fe, sql):
        out = fe.do_query(sql)
        return out[0] if isinstance(out, list) else out

    def _create(self, fe):
        fe.do_query("CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME "
                    "INDEX, val DOUBLE, PRIMARY KEY(host))")

    def test_handle_bulk_load_skips_wal(self, fe):
        self._create(fe)
        n = fe.handle_bulk_load("cpu", {
            "host": np.array(["a", "b"], dtype=object),
            "ts": np.array([1000, 2000], dtype=np.int64),
            "val": np.array([1.5, 2.5])})
        assert n == 2
        table = fe.catalog.table("greptime", "public", "cpu")
        region = next(iter(table.regions.values()))
        assert all(mt.num_rows == 0 for mt in
                   region.version_control.current.memtables.all_memtables())
        assert len(region.version_control.current.ssts.levels[0]) == 1
        out = self._q(fe, "SELECT host, val FROM cpu ORDER BY host")
        assert [tuple(r) for b in out.batches for r in b.rows()] == [
            ("a", 1.5), ("b", 2.5)]

    def test_copy_from_routes_through_bulk(self, fe, tmp_path):
        self._create(fe)
        fe.do_query("INSERT INTO cpu VALUES ('a', 1000, 1.5), "
                    "('b', 2000, NULL)")
        path = str(tmp_path / "out.parquet")
        fe.do_query(f"COPY cpu TO '{path}'")
        fe.do_query("CREATE TABLE cpu2 (host STRING, ts TIMESTAMP TIME "
                    "INDEX, val DOUBLE, PRIMARY KEY(host))")
        fe.do_query(f"COPY cpu2 FROM '{path}'")
        out = self._q(fe, "SELECT host, val FROM cpu2 ORDER BY host")
        assert [tuple(r) for b in out.batches for r in b.rows()] == [
            ("a", 1.5), ("b", None)]
        # bulk path: straight to SST, nothing in the memtable
        t2 = fe.catalog.table("greptime", "public", "cpu2")
        region = next(iter(t2.regions.values()))
        assert all(mt.num_rows == 0 for mt in
                   region.version_control.current.memtables.all_memtables())

    @pytest.mark.parametrize("fmt,ext,codec", [
        ("csv", "csv.gz", "gzip"),
        ("csv", "csv.zst", "zstd"),
        ("json", "json.gz", "gzip"),
    ])
    def test_copy_compressed_roundtrip(self, fe, tmp_path, fmt, ext, codec):
        import pyarrow as pa
        self._create(fe)
        fe.do_query("INSERT INTO cpu VALUES ('a', 1000, 1.5), "
                    "('b', 2000, 2.5)")
        path = str(tmp_path / f"out.{ext}")
        fe.do_query(f"COPY cpu TO '{path}' WITH (format='{fmt}')")
        # the file really is compressed (codec magic, not plain text)
        with open(path, "rb") as f:
            head = f.read(4)
        assert head[:2] == b"\x1f\x8b" if codec == "gzip" \
            else head == b"\x28\xb5\x2f\xfd"
        fe.do_query("CREATE TABLE cpu2 (host STRING, ts TIMESTAMP TIME "
                    "INDEX, val DOUBLE, PRIMARY KEY(host))")
        fe.do_query(f"COPY cpu2 FROM '{path}' WITH (format='{fmt}')")
        out = self._q(fe, "SELECT host, val FROM cpu2 ORDER BY host")
        assert [tuple(r) for b in out.batches for r in b.rows()] == [
            ("a", 1.5), ("b", 2.5)]

    def test_compressed_external_table(self, fe, tmp_path):
        import gzip
        fe.datanode.store.write(
            "ext/data.csv.gz",
            gzip.compress(b"host,val\na,1.5\nb,2.5\n"))
        fe.do_query("CREATE EXTERNAL TABLE ext WITH "
                    "(location='ext/data.csv.gz', format='csv')")
        out = self._q(fe, "SELECT host, val FROM ext ORDER BY host")
        assert [tuple(r) for b in out.batches for r in b.rows()] == [
            ("a", 1.5), ("b", 2.5)]


class TestReviewRegressions:
    def test_copy_nullable_timestamp_field(self, tmp_path):
        """A second timestamp-typed FIELD column with NULLs round-trips
        through COPY (to_pylist of raw timestamps yields datetimes the
        validating path cannot cast — ints must be used)."""
        from greptimedb_tpu.datanode import DatanodeOptions
        from greptimedb_tpu.frontend.instance import build_standalone
        fe = build_standalone(DatanodeOptions(
            data_home=str(tmp_path / "fe"), register_numbers_table=False))
        try:
            fe.do_query("CREATE TABLE ev (host STRING, ts TIMESTAMP TIME "
                        "INDEX, seen TIMESTAMP, PRIMARY KEY(host))")
            fe.do_query("INSERT INTO ev VALUES ('a', 1000, 5000), "
                        "('b', 2000, NULL)")
            path = str(tmp_path / "ev.parquet")
            fe.do_query(f"COPY ev TO '{path}'")
            fe.do_query("CREATE TABLE ev2 (host STRING, ts TIMESTAMP TIME "
                        "INDEX, seen TIMESTAMP, PRIMARY KEY(host))")
            fe.do_query(f"COPY ev2 FROM '{path}'")
            out = fe.do_query("SELECT host, seen FROM ev2 ORDER BY host")
            out = out[0] if isinstance(out, list) else out
            rows = [tuple(r) for b in out.batches for r in b.rows()]
            assert rows == [("a", 5000), ("b", None)]
        finally:
            fe.shutdown()

    def test_sequence_not_reissued_after_crash(self, tmp_path):
        """The bulk batch's sequence survives recovery even when
        flushed_sequence was capped below it: a post-restart overwrite
        of a bulk key must win MVCC (never tie on sequence)."""
        eng = make_engine(tmp_path)
        r = eng.create_region("t/r0", monitor_schema())
        r.bulk_ingest({"host": ["a"], "ts": [500],
                       "cpu": [0.1], "memory": [0.2]})
        wb = WriteBatch(r.schema)
        wb.put({"host": ["race"], "ts": [999], "cpu": [7.0],
                "memory": [8.0]})
        r.write(wb)
        orig_flush = r.flush
        r.flush = lambda: []
        try:
            r.bulk_ingest({"host": ["b"], "ts": [1000],
                           "cpu": [1.0], "memory": [2.0]})
        finally:
            r.flush = orig_flush
        bulk_seq = r.version_control.committed_sequence
        # crash + reopen: committed_sequence must not rewind past the
        # bulk batch's (WAL-less) sequence
        eng2 = make_engine(tmp_path)
        r2 = eng2.open_region("t/r0")
        assert r2.version_control.committed_sequence >= bulk_seq
        wb = WriteBatch(r2.schema)
        wb.put({"host": ["b"], "ts": [1000], "cpu": [99.0],
                "memory": [2.0]})
        r2.write(wb)
        assert ("b", 1000, 99.0, 2.0) in merged_rows(r2)

    def test_flight_bulk_load_auto_alter(self, tmp_path):
        """Flight bulk_load matches insert()'s auto create/alter: a new
        field column on an existing table is added, not dropped."""
        import time as _time
        from greptimedb_tpu.client.flight import Database
        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance
        from greptimedb_tpu.servers.flight import FlightFrontendServer

        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        srv = FlightFrontendServer(fe)
        srv.serve_in_background()
        t0 = _time.time()
        while srv.port == 0 and _time.time() - t0 < 10:
            _time.sleep(0.01)
        db = Database(srv.address)
        try:
            n = db.bulk_load("bk", {
                "host": ["a", "b"], "greptime_timestamp": [1000, 2000],
                "val": [1.0, 2.0]}, tag_columns=["host"])
            assert n == 2
            # second load brings a NEW column → auto-ALTER, data kept
            n = db.bulk_load("bk", {
                "host": ["c"], "greptime_timestamp": [3000],
                "val": [3.0], "extra": [42.0]}, tag_columns=["host"])
            assert n == 1
            batches = db.sql("SELECT host, val, extra FROM bk "
                             "ORDER BY host")
            rows = [tuple(r) for b in batches for r in b.rows()]
            assert rows == [("a", 1.0, None), ("b", 2.0, None),
                            ("c", 3.0, 42.0)]
        finally:
            db.close()
            srv.shutdown()
            fe.shutdown()
            dn.shutdown()


class TestDistributedBulk:
    def test_partitioned_routing(self, tmp_path):
        """bulk_load splits rows across regions by the partition rule and
        each datanode ingests WAL-less."""
        from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
        from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MetaClient, MetaSrv, Peer
        from greptimedb_tpu.meta.kv import MemKv

        datanodes, clients = {}, {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
        meta_srv = MetaSrv(MemKv())
        meta = MetaClient(meta_srv)
        for i in (1, 2):
            meta_srv.register_datanode(Peer(i, f"local://{i}"))
        fe = DistInstance(meta, clients)
        fe.do_query("""
CREATE TABLE dist (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                   PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))""")
        hosts = np.array([f"h{i}" for i in range(10)], dtype=object)
        n = fe.handle_bulk_load("dist", {
            "host": hosts,
            "ts": np.arange(10, dtype=np.int64) * 1000,
            "cpu": np.arange(10, dtype=np.float64)})
        assert n == 10
        counts = []
        for dn in datanodes.values():
            t = dn.catalog.table(CAT, SCH, "dist")
            got = sum(b.num_rows for b in t.scan_batches())
            counts.append(got)
            for region in t.regions.values():
                assert all(
                    mt.num_rows == 0 for mt in
                    region.version_control.current.memtables.all_memtables())
        assert sorted(counts) == [5, 5]
        out = fe.do_query("SELECT host, cpu FROM dist ORDER BY host")
        rows = [tuple(r) for b in out[0].batches for r in b.rows()]
        assert rows == [(f"h{i}", float(i)) for i in range(10)]
        for dn in datanodes.values():
            dn.shutdown()
