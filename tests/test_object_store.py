"""S3 object store + LRU disk cache tests.

Mirrors the reference's storage-matrix integration tests
(tests-integration/src/test_util.rs StorageType::{S3, S3WithCache}) using
an in-process mock S3 endpoint, and the cache-policy unit tests
(src/object-store/src/cache_policy.rs).
"""

import http.server
import threading
import urllib.parse

import pytest

from greptimedb_tpu.storage.cache import LruCacheLayer
from greptimedb_tpu.storage.object_store import (
    FsObjectStore, build_object_store)
from greptimedb_tpu.storage.s3 import S3Config, S3Error, S3ObjectStore


class MockS3Handler(http.server.BaseHTTPRequestHandler):
    """Minimal S3 REST semantics over an in-memory dict."""

    store = {}

    def log_message(self, *args):
        pass

    def _key(self):
        return urllib.parse.unquote(self.path.split("?")[0].lstrip("/"))

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        self.store[self._key()] = self.rfile.read(length)
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        query = urllib.parse.parse_qs(parsed.query)
        if "list-type" in query:
            bucket = parsed.path.lstrip("/")
            prefix = query.get("prefix", [""])[0]
            keys = sorted(k[len(bucket) + 1:] for k in self.store
                          if k.startswith(f"{bucket}/{prefix}"))
            body = "<ListBucketResult>"
            for k in keys:
                body += f"<Contents><Key>{k}</Key></Contents>"
            body += "<IsTruncated>false</IsTruncated></ListBucketResult>"
            payload = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_HEAD(self):
        self.send_response(200 if self._key() in self.store else 404)
        self.end_headers()

    def do_DELETE(self):
        self.store.pop(self._key(), None)
        self.send_response(204)
        self.end_headers()


@pytest.fixture()
def mock_s3():
    MockS3Handler.store = {}
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                             MockS3Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


@pytest.fixture()
def s3(mock_s3):
    return S3ObjectStore(S3Config(
        bucket="testbucket", root="greptime", endpoint=mock_s3,
        access_key_id="ak", secret_access_key="sk"))


class TestS3ObjectStore:
    def test_write_read_roundtrip(self, s3):
        s3.write("a/b.txt", b"hello")
        assert s3.read("a/b.txt") == b"hello"

    def test_read_missing_raises(self, s3):
        with pytest.raises(FileNotFoundError):
            s3.read("nope")

    def test_exists_delete(self, s3):
        s3.write("x", b"1")
        assert s3.exists("x")
        s3.delete("x")
        assert not s3.exists("x")
        s3.delete("x")                       # idempotent

    def test_list_prefix(self, s3):
        s3.write("d/1", b"a")
        s3.write("d/2", b"b")
        s3.write("e/3", b"c")
        assert s3.list("d/") == ["d/1", "d/2"]

    def test_delete_dir(self, s3):
        s3.write("dir/a", b"1")
        s3.write("dir/b", b"2")
        s3.delete_dir("dir")
        assert s3.list("dir/") == []

    def test_sigv4_header_shape(self, s3):
        import datetime
        headers = s3._sign("GET", "/b/k", "", "payloadhash",
                           datetime.datetime(2026, 1, 1))
        auth = headers["authorization"]
        assert auth.startswith("AWS4-HMAC-SHA256 Credential=ak/20260101/")
        assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth


class TestLruCacheLayer:
    def test_hit_miss_counting(self, s3, tmp_path):
        cached = LruCacheLayer(s3, str(tmp_path / "cache"))
        cached.write("k", b"v")
        assert cached.read("k") == b"v"      # miss → pull through
        assert cached.read("k") == b"v"      # hit
        assert cached.misses == 1
        assert cached.hits == 1

    def test_eviction_by_capacity(self, s3, tmp_path):
        cached = LruCacheLayer(s3, str(tmp_path / "cache"),
                               capacity_bytes=25)
        for i in range(5):
            cached.write(f"k{i}", bytes(10))
            cached.read(f"k{i}")
        # capacity 25 → at most 2 ten-byte entries survive
        assert len(cached._entries) <= 2
        # evicted keys still readable (from inner)
        assert cached.read("k0") == bytes(10)

    def test_write_invalidates(self, s3, tmp_path):
        cached = LruCacheLayer(s3, str(tmp_path / "cache"))
        cached.write("k", b"old")
        assert cached.read("k") == b"old"
        cached.write("k", b"new")
        assert cached.read("k") == b"new"

    def test_recover_on_start(self, s3, tmp_path):
        cache_dir = str(tmp_path / "cache")
        c1 = LruCacheLayer(s3, cache_dir)
        c1.write("persisted", b"data")
        c1.read("persisted")
        # fresh layer over the same dir recovers the index
        c2 = LruCacheLayer(s3, cache_dir)
        assert "persisted" in c2._entries
        assert c2.read("persisted") == b"data"
        assert c2.hits == 1

    def test_local_path_pulls_through(self, s3, tmp_path):
        cached = LruCacheLayer(s3, str(tmp_path / "cache"))
        cached.write("blob", b"xyz")
        path = cached.local_path("blob")
        assert path is not None
        with open(path, "rb") as f:
            assert f.read() == b"xyz"

    def test_local_path_missing(self, s3, tmp_path):
        cached = LruCacheLayer(s3, str(tmp_path / "cache"))
        assert cached.local_path("ghost") is None


class TestStorageEngineOnS3:
    def test_region_flush_scan_on_s3(self, s3, mock_s3, tmp_path):
        """The full storage engine runs against S3 + cache (reference:
        StorageType::S3WithCache matrix)."""
        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance
        cached = LruCacheLayer(s3, str(tmp_path / "cache"))
        dn = DatanodeInstance(
            DatanodeOptions(data_home=str(tmp_path / "wal"),
                            register_numbers_table=False),
            store=cached)
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        fe.do_query("CREATE TABLE s3t (host STRING, ts TIMESTAMP"
                    " TIME INDEX, v DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO s3t VALUES ('a', 1000, 1.5),"
                    " ('b', 2000, 2.5)")
        t = fe.catalog.table("greptime", "public", "s3t")
        t.flush()
        # SSTs + manifest live in the mock bucket now
        assert any("parquet" in k for k in MockS3Handler.store)
        out = fe.do_query("SELECT sum(v) FROM s3t")[-1]
        assert next(out.batches[0].rows())[0] == 4.0
        fe.shutdown()

    def test_build_object_store_factory(self, mock_s3, tmp_path):
        from greptimedb_tpu.storage.retry import RetryingObjectStore
        fs = build_object_store({"type": "File"}, str(tmp_path / "fs"))
        assert isinstance(fs, RetryingObjectStore)
        assert isinstance(fs.inner, FsObjectStore)
        s3b = build_object_store(
            {"type": "S3", "bucket": "b", "endpoint": mock_s3,
             "cache_path": str(tmp_path / "c")}, "")
        assert isinstance(s3b, LruCacheLayer)
        s3b.write("k", b"v")
        assert s3b.read("k") == b"v"
        with pytest.raises(ValueError):
            build_object_store({"type": "Tape"}, "")
