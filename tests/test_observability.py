"""Engine-wide observability tests (ISSUE 2).

EXPLAIN ANALYZE must render the per-stage breakdown of the execution
that actually ran — differential-checked against the storage-side scan
profiler (`Region.last_scan_profile`), so the two views cannot drift.
Plus: the slow-query log (fires over threshold, silent when disabled)
and the ExecStats collector semantics.
"""

import logging

import numpy as np
import pytest

from greptimedb_tpu.common import exec_stats
from greptimedb_tpu.datanode.instance import (
    DatanodeInstance, DatanodeOptions)
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.query import stream_exec, tpu_exec
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(
        data_home=str(tmp_path / "d"), register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    f.shutdown()


def analyze(fe, sql, ctx):
    """EXPLAIN ANALYZE -> {stage: (rows, files, elapsed_ms, detail)}."""
    out = fe.do_query("EXPLAIN ANALYZE " + sql, ctx)[0]
    rows = {}
    for b in out.batches:
        for stage, r, files, ms, detail in b.rows():
            rows[stage] = (r, files, ms, detail)
    return rows


def _force_device_dispatch(monkeypatch):
    """Defeat both the static and latency-adaptive dispatch floors so a
    tiny test table still takes the device/streamed paths."""
    monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 1)
    monkeypatch.setattr(tpu_exec, "_observed_min_dt", [None])


class TestExplainAnalyzeDifferential:
    def test_streamed_lean_path_matches_profile(self, fe, monkeypatch):
        """A persisted clean bulk region streams via the dedup-skip lean
        path; EXPLAIN ANALYZE must name that path with the same counts
        the region's scan profiler recorded."""
        ctx = QueryContext()
        fe.do_query("CREATE TABLE m (host STRING, ts TIMESTAMP TIME "
                    "INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        table = fe.catalog.table("greptime", "public", "m")
        hosts, per = 4, 300
        ts = np.tile(np.arange(per, dtype=np.int64) * 1000, hosts)
        host = np.repeat(np.array([f"h{i}" for i in range(hosts)]),
                         per).astype(object)
        rng = np.random.default_rng(3)
        table.bulk_load({"host": host, "ts": ts,
                         "cpu": rng.random(hosts * per)})
        region = next(iter(table.regions.values()))
        assert region.last_scan_profile is None
        _force_device_dispatch(monkeypatch)
        monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [1])

        rows = analyze(fe, "SELECT host, avg(cpu) FROM m GROUP BY host",
                       ctx)
        assert "streamed-cold" in rows["dispatch"][3]

        prof = region.last_scan_profile
        assert prof is not None and prof.path == "streamed"
        # the actual path taken: dedup-skip lean slices, zero merged
        assert prof.counters.get("lean_slices", 0) >= 1
        assert prof.counters.get("merged_slices", 0) == 0
        assert prof.counters["dedup_skip_slices"] == \
            prof.counters["lean_slices"]

        # differential: EXPLAIN ANALYZE's stream_scan row carries the
        # SAME row count and path counters the profiler recorded
        ss_rows, _, _, ss_detail = rows["stream_scan"]
        assert ss_rows == prof.rows == hosts * per
        assert f"lean_slices={prof.counters['lean_slices']}" in ss_detail
        assert (f"dedup_skip_slices="
                f"{prof.counters['dedup_skip_slices']}") in ss_detail
        assert "merged_slices" not in ss_detail
        # shared stage vocabulary between the two views
        assert "slice_plan" in rows and "slice_plan" in prof.stages
        assert "decode_reduce" in prof.stages
        # the lean reader reported its decode (rows + files read)
        assert rows["decode"][0] == hosts * per
        assert rows["decode"][1] >= 1

    def test_streamed_merged_path_named(self, fe, monkeypatch):
        """Memtable rows defeat the dedup-skip proof: the same query
        must now be reported as merged, by both views."""
        ctx = QueryContext()
        fe.do_query("CREATE TABLE mm (host STRING, ts TIMESTAMP TIME "
                    "INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO mm VALUES ('a', 1000, 1.0), "
                    "('a', 2000, 2.0), ('b', 1000, 3.0)")
        table = fe.catalog.table("greptime", "public", "mm")
        region = next(iter(table.regions.values()))
        _force_device_dispatch(monkeypatch)
        monkeypatch.setattr(stream_exec, "_STREAM_THRESHOLD_ROWS", [1])

        rows = analyze(fe, "SELECT host, avg(cpu) FROM mm GROUP BY host",
                       ctx)
        assert "streamed-cold" in rows["dispatch"][3]
        prof = region.last_scan_profile
        assert prof.path == "streamed"
        assert prof.counters.get("merged_slices", 0) >= 1
        assert prof.counters.get("lean_slices", 0) == 0
        assert (f"merged_slices={prof.counters['merged_slices']}"
                in rows["stream_scan"][3])

    def test_resident_matches_profile(self, fe, monkeypatch):
        """Device-resident path: EXPLAIN ANALYZE and the profiler agree
        on rows, stages and the scan-cache outcome."""
        ctx = QueryContext()
        fe.do_query("CREATE TABLE r (host STRING, ts TIMESTAMP TIME "
                    "INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO r VALUES ('a', 1000, 1.0), "
                    "('b', 1000, 2.0)")
        table = fe.catalog.table("greptime", "public", "r")
        region = next(iter(table.regions.values()))
        _force_device_dispatch(monkeypatch)

        rows = analyze(fe, "SELECT host, avg(cpu) FROM r GROUP BY host",
                       ctx)
        assert rows["dispatch"][3].startswith("device-resident")
        prof = region.last_scan_profile
        assert prof is not None and prof.path == "resident"
        assert rows["scan_prep"][0] == prof.rows == 2
        assert "scan_prep" in prof.stages and "reduce" in prof.stages
        assert "reduce" in rows
        # cache outcome agrees (first scan of this region: a full build)
        assert prof.counters.get("cache_full") == 1
        assert "cache=full" in rows["scan_prep"][3]

        # second run: exact cache hit, both views say so (reset the
        # adaptive floor the first device query just raised)
        tpu_exec._observed_min_dt[0] = None
        rows = analyze(fe, "SELECT host, avg(cpu) FROM r GROUP BY host",
                       ctx)
        prof = region.last_scan_profile
        assert prof.counters.get("cache_hit") == 1
        assert "cache=hit" in rows["scan_prep"][3]

    def test_cpu_fallback_stages(self, fe):
        ctx = QueryContext()
        fe.do_query("CREATE TABLE c (host STRING, ts TIMESTAMP TIME "
                    "INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO c VALUES ('a', 1000, 1.0), "
                    "('b', 1000, 5.0)")
        rows = analyze(fe, "SELECT host, cpu FROM c WHERE cpu > 2",
                       ctx)
        assert rows["dispatch"][3] == "cpu-fallback"
        assert rows["scan"][0] == 2
        assert rows["filter"][0] == 1          # rows out of the filter
        assert rows["project"][0] == 1
        # plan row carries the logical plan text
        assert "CpuProjectionExec" in rows["plan"][3]


class TestSlowQueryLog:
    def test_fires_over_threshold_and_silent_when_disabled(self, fe,
                                                           caplog):
        ctx = QueryContext()
        fe.do_query("CREATE TABLE s (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        fe.do_query("INSERT INTO s VALUES (1000, 1.0)")
        from greptimedb_tpu.common.telemetry import (
            set_slow_query_threshold_ms)
        try:
            # 1ms: any Python-side SELECT takes longer
            fe.do_query("SET slow_query_threshold_ms = 1")
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.slow_query"):
                fe.do_query("SELECT v FROM s", ctx)
            slow = [r for r in caplog.records
                    if "slow query" in r.getMessage()]
            assert slow, "slow-query log did not fire"
            msg = slow[-1].getMessage()
            assert "trace=" in msg
            assert "SELECT v FROM s" in msg
            assert "stats=[" in msg and "dispatch=" in msg
            assert slow[-1].levelno == logging.WARNING

            # disabled (0 => off): stays silent
            fe.do_query("SET slow_query_threshold_ms = 0")
            caplog.clear()
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.slow_query"):
                fe.do_query("SELECT v FROM s", ctx)
            assert not [r for r in caplog.records
                        if "slow query" in r.getMessage()]
        finally:
            set_slow_query_threshold_ms(None)

    def test_slow_ddl_does_not_report_stale_query_stats(self, fe,
                                                        caplog):
        ctx = QueryContext()
        fe.do_query("CREATE TABLE s2 (ts TIMESTAMP TIME INDEX, "
                    "v DOUBLE)")
        fe.do_query("SELECT 1", ctx)      # leaves ExecStats behind
        from greptimedb_tpu.common.telemetry import (
            set_slow_query_threshold_ms)
        try:
            fe.do_query("SET slow_query_threshold_ms = 1")
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.slow_query"):
                fe.do_query("INSERT INTO s2 VALUES (1, 1.0)", ctx)
            slow = [r for r in caplog.records
                    if "slow query" in r.getMessage()]
            assert slow
            assert "stats=[n/a]" in slow[-1].getMessage()
        finally:
            set_slow_query_threshold_ms(None)


class TestExecStats:
    def test_collect_accumulate_and_render(self):
        with exec_stats.collect() as st:
            exec_stats.record("scan", rows=5, elapsed_s=0.01,
                              cached=True)
            exec_stats.record("scan", rows=3, files=2, lean_slices=1)
            exec_stats.record("scan", lean_slices=2)
            exec_stats.set_dispatch("first")
            exec_stats.set_dispatch("second")    # first wins
        assert exec_stats.current() is None
        s = st.stages["scan"]
        assert s.rows == 8 and s.files == 2
        assert s.detail["lean_slices"] == 3      # numeric details add up
        assert st.dispatch == "first"
        assert st.total_s > 0
        assert "dispatch=first" in st.summary()
        tab = st.rows_table()
        assert tab["stage"][0] == "dispatch"
        assert tab["stage"][-1] == "total"
        assert tab["detail"][0] == "first"

    def test_noop_without_collector(self):
        exec_stats.record("x", rows=1)
        with exec_stats.stage("y"):
            pass
        assert exec_stats.current() is None

    def test_nested_collect_records_into_outer(self):
        with exec_stats.collect() as outer:
            with exec_stats.collect(outer):
                exec_stats.record("inner", rows=1)
        assert outer.stages["inner"].rows == 1

    def test_collector_rides_propagate_into_workers(self):
        from greptimedb_tpu.common.runtime import parallel_map
        with exec_stats.collect() as st:
            parallel_map(
                lambda i: exec_stats.record("worker", rows=i), [1, 2, 3])
        assert st.stages["worker"].rows == 6

    def test_engine_saves_last_exec_stats(self, fe):
        ctx = QueryContext()
        fe.do_query("CREATE TABLE e (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        fe.do_query("INSERT INTO e VALUES (1000, 1.0)")
        fe.do_query("SELECT v FROM e", ctx)
        st = fe.query_engine.last_exec_stats
        assert st is not None
        assert st.dispatch is not None
        assert "scan" in st.stages


class TestExecStatsWire:
    """ISSUE 6: the collector's wire codec (to_dict/absorb) and the
    per-node tree rendering behind distributed EXPLAIN ANALYZE."""

    def test_to_dict_absorb_roundtrip(self):
        src = exec_stats.ExecStats()
        src.record("scan_prep", rows=np.int64(7), files=1,
                   elapsed_s=0.004, cache="hit", pruned=np.int32(3))
        src.set_dispatch("streamed-cold (est_rows=9)")
        src.total_s = 0.01
        import json
        d = json.loads(json.dumps(src.to_dict()))   # must be JSON-safe
        dst = exec_stats.ExecStats()
        dst.absorb(d)
        st = dst.stages["scan_prep"]
        assert st.rows == 7 and st.files == 1
        assert st.detail["cache"] == "hit" and st.detail["pruned"] == 3
        assert dst.dispatch == "streamed-cold (est_rows=9)"
        assert dst.remote_total_ms == pytest.approx(10.0)
        assert dst.node_elapsed_ms() == pytest.approx(10.0)

    def test_absorb_into_active_collector(self):
        with exec_stats.collect() as st:
            exec_stats.absorb_remote(
                {"dispatch": "d", "total_ms": 2.0,
                 "stages": [{"stage": "scan", "rows": 4}]})
        assert st.stages["scan"].rows == 4
        assert st.dispatch == "d"

    def test_record_node_renders_tree(self):
        parent = exec_stats.ExecStats()
        parent.record("dist_scatter", scatter="regions pruned 0/2")
        n2 = exec_stats.ExecStats()
        n2.record("scan_prep", rows=5, elapsed_s=0.002, cache="full")
        n2.record("reduce", rows=5, elapsed_s=0.003)
        n2.set_dispatch("device-resident (scan cache)")
        n1 = exec_stats.ExecStats()
        n1.record("scan_prep", rows=3, elapsed_s=0.001)
        # completion order dn2-then-dn1; rendering must sort by label
        parent.record_node("dn2", n2, wall_ms=9.0)
        parent.record_node("dn1", n1, wall_ms=4.0)
        tab = parent.rows_table()
        stages = tab["stage"]
        i = stages.index("dist_scatter")
        assert stages[i + 1] == "  dn1"
        assert stages[i + 2] == "    scan_prep"
        assert stages[i + 3] == "  dn2"
        assert stages[i + 4] == "    scan_prep"
        assert stages[i + 5] == "    reduce"
        hdr = tab["detail"][i + 3]
        assert "dispatch=device-resident (scan cache)" in hdr
        # in-process sub-collector (no remote total): the round trip IS
        # node work, so node_ms = wall and network_ms = 0
        assert "node_ms=9.00" in hdr and "network_ms=0.00" in hdr
        assert tab["rows"][i + 3] == 5          # node header carries rows
        assert tab["elapsed_ms"][i + 3] == pytest.approx(9.0)
        assert "nodes=dn1:4.0ms,dn2:9.0ms" in parent.summary()

    def test_record_node_label_collision(self):
        parent = exec_stats.ExecStats()
        parent.record_node("dn1", exec_stats.ExecStats(), 1.0)
        parent.record_node("dn1", exec_stats.ExecStats(), 2.0)
        assert list(parent.nodes) == ["dn1", "dn1#2"]

    def test_nodes_render_without_scatter_stage(self):
        parent = exec_stats.ExecStats()
        parent.record_node("dn1", exec_stats.ExecStats(), 1.0)
        stages = parent.rows_table()["stage"]
        assert "  dn1" in stages
        assert stages.index("  dn1") < stages.index("total")


class TestTraceparent:
    def test_roundtrip_inside_span(self):
        from greptimedb_tpu.common import telemetry
        assert telemetry.current_traceparent() is None
        with telemetry.span("outer") as sp:
            header = telemetry.current_traceparent()
            assert header is not None
            trace_id, span_id = telemetry.parse_traceparent(header)
            assert trace_id == sp["trace_id"]
            assert span_id == sp["span_id"]

    def test_remote_context_joins_trace(self):
        from greptimedb_tpu.common import telemetry
        header = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        with telemetry.remote_context(header):
            with telemetry.span("child") as sp:
                assert sp["trace_id"] == "ab" * 16
                assert sp["parent_id"] == "cd" * 8
        assert telemetry.current_span() is None

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-span-01",
        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",       # all-zero trace
        "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",      # non-hex
    ])
    def test_malformed_headers_are_noops(self, bad):
        from greptimedb_tpu.common import telemetry
        assert telemetry.parse_traceparent(bad) is None
        with telemetry.remote_context(bad):
            with telemetry.span("child") as sp:
                assert sp["parent_id"] is None    # fresh trace

    def test_propagate_carries_wire_context_into_workers(self):
        from greptimedb_tpu.common import telemetry
        from greptimedb_tpu.common.runtime import parallel_map
        header = "00-" + "12" * 16 + "-" + "34" * 8 + "-01"
        seen = []
        with telemetry.remote_context(header):
            parallel_map(
                lambda i: seen.append(
                    telemetry.current_span()["trace_id"]), [1, 2, 3])
        assert seen == ["12" * 16] * 3
