"""PromQL parser + engine tests.

Mirrors the reference's test strategy: parser shape tests (the promql-parser
crate's grammar), extrapolated rate/increase golden semantics
(src/promql/src/functions/extrapolate_rate.rs tests), planner behaviors
(src/promql/src/planner.rs:1229-1953 golden plans — here asserted on
results), and Prometheus JSON shaping (src/servers/src/prom.rs:150-400).
"""

import json
import math

import numpy as np
import pytest

from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend import FrontendInstance
from greptimedb_tpu.promql import PromqlEngine, PromqlParseError, parse_promql
from greptimedb_tpu.promql.ast import (
    Aggregate, Binary, Call, NumberLiteral, SubqueryExpr, VectorSelector)
from greptimedb_tpu.promql.parser import parse_duration_ms
from greptimedb_tpu.session import QueryContext
from greptimedb_tpu.sql import parse_sql
from greptimedb_tpu.sql.ast import Tql


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

class TestParser:
    def test_durations(self):
        assert parse_duration_ms("5m") == 300_000
        assert parse_duration_ms("1h30m") == 5_400_000
        assert parse_duration_ms("1.5h") == 5_400_000
        assert parse_duration_ms("10ms") == 10
        assert parse_duration_ms("1y") == 31_536_000_000
        with pytest.raises(PromqlParseError):
            parse_duration_ms("5")
        with pytest.raises(PromqlParseError):
            parse_duration_ms("m")

    def test_selector(self):
        e = parse_promql('cpu{host="a", region=~"us-.*", az!~"z", x!="y"}')
        assert isinstance(e, VectorSelector)
        assert e.metric == "cpu"
        assert [(m.name, m.op, m.value) for m in e.matchers] == [
            ("host", "=", "a"), ("region", "=~", "us-.*"),
            ("az", "!~", "z"), ("x", "!=", "y")]

    def test_matrix_selector_offset(self):
        e = parse_promql("cpu[5m] offset 1m")
        assert e.range_ms == 300_000 and e.offset_ms == 60_000
        e = parse_promql("cpu offset -30s")
        assert e.offset_ms == -30_000

    def test_at_modifier(self):
        e = parse_promql("cpu @ 1609746180")
        assert e.at_ms == 1_609_746_180_000
        assert parse_promql("cpu @ start()").at_ms == "start"
        assert parse_promql("cpu @ end()").at_ms == "end"

    def test_name_matcher_selector(self):
        e = parse_promql('{__name__="cpu", host="a"}')
        assert e.metric == "cpu"

    def test_precedence(self):
        e = parse_promql("1 + 2 * 3")
        assert isinstance(e, Binary) and e.op == "+"
        assert isinstance(e.rhs, Binary) and e.rhs.op == "*"
        # ^ is right-associative and binds tighter than unary minus
        e = parse_promql("2 ^ 3 ^ 2")
        assert e.op == "^" and isinstance(e.rhs, Binary)
        e = parse_promql("a + b or c")
        assert e.op == "or" and e.lhs.op == "+"

    def test_aggregate_forms(self):
        for q in ["sum by (host) (cpu)", "sum(cpu) by (host)"]:
            e = parse_promql(q)
            assert isinstance(e, Aggregate) and e.by == ["host"]
        e = parse_promql("sum without (host, az) (cpu)")
        assert e.without == ["host", "az"]
        e = parse_promql("topk(5, cpu)")
        assert isinstance(e.param, NumberLiteral) and e.param.value == 5
        e = parse_promql("quantile(0.9, cpu)")
        assert e.param.value == 0.9

    def test_binary_modifiers(self):
        e = parse_promql("a / on(host) group_left(extra) b")
        assert e.matching.on == ["host"] and e.matching.group_left
        assert e.matching.include == ["extra"]
        e = parse_promql("a > bool b")
        assert e.return_bool
        e = parse_promql("a and ignoring(x) b")
        assert e.matching.ignoring == ["x"]

    def test_subquery(self):
        e = parse_promql("rate(cpu[5m])[30m:1m]")
        assert isinstance(e, SubqueryExpr)
        assert e.range_ms == 1_800_000 and e.step_ms == 60_000

    def test_literals(self):
        assert parse_promql("0x1F").value == 31.0
        assert parse_promql("1e3").value == 1000.0
        assert parse_promql("-2.5").value == -2.5
        assert math.isinf(parse_promql("Inf").value)
        assert math.isnan(parse_promql("NaN").value)

    def test_errors(self):
        for q in ["", "cpu{", "rate(cpu[5m)", "sum by host (cpu)",
                  "cpu[5]", "1 +", "{}"]:
            with pytest.raises(PromqlParseError):
                parse_promql(q)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

@pytest.fixture()
def fe(tmp_path):
    inst = FrontendInstance(
        DatanodeInstance(DatanodeOptions(data_home=str(tmp_path))))
    inst.start()
    yield inst
    inst.shutdown()


def _mk_cpu(fe, counter=True):
    fe.do_query("CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
                "val DOUBLE, PRIMARY KEY(host))")
    rows = []
    for i in range(60):                 # samples every 10s for 10 min
        rows.append(f"('a', {i * 10_000}, {i * 2.0})")
        rows.append(f"('b', {i * 10_000}, {i * 5.0})")
    fe.do_query("INSERT INTO cpu VALUES " + ",".join(rows))


def _q(fe, promql, start, end, step, instant=False):
    eng = fe.promql_engine()
    return eng.query_to_prom_json(promql, start, end, step, QueryContext(),
                                  instant=instant)


def _series(result, **labels):
    for r in result["result"]:
        if all(r["metric"].get(k) == v for k, v in labels.items()):
            return r
    raise AssertionError(f"series {labels} not in {result['result']}")


class TestEngine:
    def test_instant_vector_lookback(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "cpu", 100_000, 100_000, 1000, instant=True)
        assert out["resultType"] == "vector"
        a = _series(out, host="a")
        assert a["metric"]["__name__"] == "cpu"
        assert a["value"] == [100.0, "20"]
        # beyond the 5m lookback: empty
        out = _q(fe, "cpu", 1_000_000, 1_000_000, 1000, instant=True)
        assert out["result"] == []

    def test_rate_counter(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "rate(cpu[1m])", 300_000, 480_000, 60_000)
        a = _series(out, host="a")
        assert "__name__" not in a["metric"]
        for _, v in a["values"]:
            assert abs(float(v) - 0.2) < 1e-9
        b = _series(out, host="b")
        for _, v in b["values"]:
            assert abs(float(v) - 0.5) < 1e-9

    def test_increase_with_reset(self, fe):
        fe.do_query("CREATE TABLE c2 (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        # counter resets at t=40s: 0,10,20,30,5,15,25 (10s apart)
        vals = [0, 10, 20, 30, 5, 15, 25]
        rows = ",".join(f"({i * 10_000}, {v})" for i, v in enumerate(vals))
        fe.do_query(f"INSERT INTO c2 VALUES {rows}")
        out = _q(fe, "increase(c2[1m])", 60_000, 60_000, 1000, instant=True)
        # window (0,60] holds samples 10..60s (6 samples), reset-adjusted
        # values 10,20,30,35,45,55: raw delta 45 over 50s sampled;
        # extrapolation adds dur_to_start=10s (within the 11s threshold,
        # not zero-capped: dur_to_zero = 50*10/45 = 11.1s) and
        # dur_to_end=0 → 45 * (50+10+0)/50 = 54
        v = float(out["result"][0]["value"][1])
        assert abs(v - 54.0) < 1e-6

    def test_avg_over_time(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "avg_over_time(cpu[1m])", 60_000, 60_000, 1000,
                 instant=True)
        # window (0,60]: host a samples at 10..60s → values 2,4,..,12 avg=7
        a = _series(out, host="a")
        assert abs(float(a["value"][1]) - 7.0) < 1e-9

    def test_min_max_quantile_over_time(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "max_over_time(cpu[1m])", 60_000, 60_000, 1000,
                 instant=True)
        assert float(_series(out, host="b")["value"][1]) == 30.0
        out = _q(fe, "min_over_time(cpu[1m])", 60_000, 60_000, 1000,
                 instant=True)
        assert float(_series(out, host="b")["value"][1]) == 5.0
        out = _q(fe, "quantile_over_time(0.5, cpu[1m])", 60_000, 60_000,
                 1000, instant=True)
        assert float(_series(out, host="a")["value"][1]) == 7.0

    def test_sum_aggregate(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "sum(rate(cpu[1m]))", 300_000, 300_000, 1000,
                 instant=True)
        assert len(out["result"]) == 1
        assert out["result"][0]["metric"] == {}
        assert abs(float(out["result"][0]["value"][1]) - 0.7) < 1e-9

    def test_aggregate_by(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "sum by (host) (cpu)", 100_000, 100_000, 1000,
                 instant=True)
        assert len(out["result"]) == 2
        assert float(_series(out, host="a")["value"][1]) == 20.0

    def test_topk(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "topk(1, cpu)", 100_000, 100_000, 1000, instant=True)
        assert len(out["result"]) == 1
        assert out["result"][0]["metric"]["host"] == "b"

    def test_vector_scalar(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "cpu * 2", 100_000, 100_000, 1000, instant=True)
        assert float(_series(out, host="a")["value"][1]) == 40.0
        # filter comparison
        out = _q(fe, "cpu > 30", 100_000, 100_000, 1000, instant=True)
        assert len(out["result"]) == 1
        assert out["result"][0]["metric"]["host"] == "b"
        # bool comparison
        out = _q(fe, "cpu > bool 30", 100_000, 100_000, 1000, instant=True)
        vals = {r["metric"]["host"]: r["value"][1] for r in out["result"]}
        assert vals == {"a": "0", "b": "1"}

    def test_vector_vector_matching(self, fe):
        _mk_cpu(fe)
        fe.do_query("CREATE TABLE lim (host STRING, ts TIMESTAMP TIME INDEX,"
                    " val DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO lim VALUES ('a', 0, 10.0), ('b', 0, 100.0)")
        out = _q(fe, "cpu / lim", 100_000, 100_000, 1000, instant=True)
        vals = {r["metric"]["host"]: float(r["value"][1])
                for r in out["result"]}
        assert vals == {"a": 2.0, "b": 0.5}

    def test_set_ops(self, fe):
        _mk_cpu(fe)
        out = _q(fe, 'cpu and cpu{host="a"}', 100_000, 100_000, 1000,
                 instant=True)
        assert len(out["result"]) == 1
        out = _q(fe, 'cpu unless cpu{host="a"}', 100_000, 100_000, 1000,
                 instant=True)
        assert out["result"][0]["metric"]["host"] == "b"
        out = _q(fe, 'cpu{host="a"} or cpu', 100_000, 100_000, 1000,
                 instant=True)
        assert len(out["result"]) == 2

    def test_scalar_and_functions(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "42", 100_000, 100_000, 1000, instant=True)
        assert out["resultType"] == "scalar" and out["result"][1] == "42"
        out = _q(fe, "3 * scalar(cpu{host=\"a\"})", 100_000, 100_000,
                 1000, instant=True)
        assert out["result"][1] == "60"
        out = _q(fe, "abs(0 - cpu)", 100_000, 100_000, 1000, instant=True)
        assert float(_series(out, host="a")["value"][1]) == 20.0
        out = _q(fe, "clamp_max(cpu, 25)", 100_000, 100_000, 1000,
                 instant=True)
        assert float(_series(out, host="b")["value"][1]) == 25.0

    def test_absent(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "absent(nosuch)", 100_000, 100_000, 1000, instant=True)
        assert out["result"][0]["value"][1] == "1"
        out = _q(fe, "absent(cpu)", 100_000, 100_000, 1000, instant=True)
        assert out["result"] == []

    def test_histogram_quantile(self, fe):
        fe.do_query("CREATE TABLE hist (le STRING, ts TIMESTAMP TIME INDEX,"
                    " val DOUBLE, PRIMARY KEY(le))")
        # cumulative buckets: 0.1→10, 0.5→60, +Inf→100
        fe.do_query("INSERT INTO hist VALUES ('0.1', 0, 10), "
                    "('0.5', 0, 60), ('+Inf', 0, 100)")
        out = _q(fe, "histogram_quantile(0.5, hist)", 1000, 1000, 1000,
                 instant=True)
        v = float(out["result"][0]["value"][1])
        # rank 50 lands in (0.1, 0.5]: 0.1 + 0.4*(50-10)/(60-10) = 0.42
        assert abs(v - 0.42) < 1e-9

    def test_label_replace(self, fe):
        _mk_cpu(fe)
        out = _q(fe, 'label_replace(cpu, "h2", "$1-x", "host", "(.*)")',
                 100_000, 100_000, 1000, instant=True)
        assert _series(out, host="a")["metric"]["h2"] == "a-x"

    def test_offset(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "cpu offset 1m", 160_000, 160_000, 1000, instant=True)
        # value at 100s (160 - 60)
        assert float(_series(out, host="a")["value"][1]) == 20.0

    def test_range_query_json_shape(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "cpu", 0, 120_000, 60_000)
        assert out["resultType"] == "matrix"
        a = _series(out, host="a")
        assert a["values"][0][0] == 0.0
        assert len(a["values"]) == 3

    def test_raw_matrix_instant(self, fe):
        _mk_cpu(fe)
        out = _q(fe, "cpu[30s]", 60_000, 60_000, 1000, instant=True)
        assert out["resultType"] == "matrix"
        a = _series(out, host="a")
        assert [v for _, v in a["values"]] == ["8", "10", "12"]


class TestExtrapolationGolden:
    """Extrapolated-rate semantics (reference:
    src/promql/src/functions/extrapolate_rate.rs, prometheus
    extrapolatedRate). The reference's unit tests feed hand-built 2-sample
    windows straight into the UDF; through a real aligned-grid query the
    same counter (value t at ts=t ms, 1..9) gives these hand-derived
    goldens for increase(g[5ms]) at steps 2..9:

    - t=2: window (-3,2] = samples {1,2}: raw=1, sampled=1, avg_dur=1,
      threshold=1.1; dur_to_start=4 but zero-capped to sampled*first/raw=1
      (<1.1 → take it), dur_to_end=0 → factor (1+1+0)/1 = 2 → 2.0
    - t=3: samples {1..3}: raw=2, sampled=2, zero-cap 2*1/2=1 → factor
      (2+1)/2 = 1.5 → 3.0; t=4 → 4/3 factor → 4.0; t=5 → 5/4 → 5.0
    - t≥6: 5-sample windows with dur_to_start=1 (<1.1): factor 5/4 → 5.0
    """

    def test_increase_normal_input(self, fe):
        fe.do_query("CREATE TABLE g (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        rows = ",".join(f"({t}, {float(t)})" for t in range(1, 10))
        fe.do_query(f"INSERT INTO g VALUES {rows}")
        eng = fe.promql_engine()
        val, steps = eng.query_range("increase(g[5ms])", 2, 9, 1,
                                     QueryContext())
        got = [round(float(v), 6) for v in val.values[0]]
        assert list(steps) == list(range(2, 10))
        assert got == [2.0, 3.0, 4.0, 5.0, 5.0, 5.0, 5.0, 5.0]

    def test_increase_counter_reset(self, fe):
        fe.do_query("CREATE TABLE g2 (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        # reference increase_counter_reset: this series must behave exactly
        # like the uninterrupted 1..9 counter after reset adjustment
        vals = [1.0, 2.0, 3.0, 4.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        rows = ",".join(f"({t + 1}, {v})" for t, v in enumerate(vals))
        fe.do_query(f"INSERT INTO g2 VALUES {rows}")
        eng = fe.promql_engine()
        val, _ = eng.query_range("increase(g2[5ms])", 2, 9, 1,
                                 QueryContext())
        got = [round(float(v), 6) for v in val.values[0]]
        assert got == [2.0, 3.0, 4.0, 5.0, 5.0, 5.0, 5.0, 5.0]

    def test_rate_is_increase_per_second(self, fe):
        fe.do_query("CREATE TABLE g3 (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        rows = ",".join(f"({t * 1000}, {float(t)})" for t in range(10))
        fe.do_query(f"INSERT INTO g3 VALUES {rows}")
        eng = fe.promql_engine()
        inc, _ = eng.query_range("increase(g3[5s])", 9000, 9000, 1000,
                                 QueryContext())
        rate, _ = eng.query_range("rate(g3[5s])", 9000, 9000, 1000,
                                  QueryContext())
        assert abs(float(inc.values[0][0]) -
                   5.0 * float(rate.values[0][0])) < 1e-9

    def test_delta_gauge(self, fe):
        fe.do_query("CREATE TABLE g4 (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        # gauge going down — delta must not apply counter correction
        rows = ",".join(f"({t * 1000}, {10.0 - t})" for t in range(6))
        fe.do_query(f"INSERT INTO g4 VALUES {rows}")
        eng = fe.promql_engine()
        val, _ = eng.query_range("delta(g4[5s])", 5000, 5000, 1000,
                                 QueryContext())
        assert float(val.values[0][0]) == -5.0


class TestTql:
    def test_tql_eval_via_sql(self, fe):
        _mk_cpu(fe)
        out = fe.do_query(
            "TQL EVAL (300, 480, '60s') rate(cpu[1m])")[-1]
        rows = out.batches[0].to_pylist()
        assert len(rows) == 8            # 2 hosts × 4 steps
        hosts = {r["host"] for r in rows}
        assert hosts == {"a", "b"}
        assert all(abs(r["value"] - (0.2 if r["host"] == "a" else 0.5))
                   < 1e-9 for r in rows)

    def test_tql_parse_roundtrip(self):
        stmt = parse_sql("TQL EVAL (0, 100, '15s') sum(rate(x[5m]))")
        assert isinstance(stmt, Tql)
        assert stmt.query.strip().startswith("sum")


class TestMultiRegion:
    def test_promql_over_partitioned_table(self, fe):
        fe.do_query("""
            CREATE TABLE pm (host STRING, ts TIMESTAMP TIME INDEX,
                             val DOUBLE, PRIMARY KEY(host))
            PARTITION BY RANGE COLUMNS (host) (
              PARTITION r0 VALUES LESS THAN ('m'),
              PARTITION r1 VALUES LESS THAN (MAXVALUE))""")
        rows = []
        for i in range(30):
            rows.append(f"('alpha', {i * 10_000}, {i * 1.0})")
            rows.append(f"('zulu', {i * 10_000}, {i * 3.0})")
        fe.do_query("INSERT INTO pm VALUES " + ",".join(rows))
        out = _q(fe, "rate(pm[1m])", 120_000, 240_000, 60_000)
        a = _series(out, host="alpha")
        z = _series(out, host="zulu")
        for _, v in a["values"]:
            assert abs(float(v) - 0.1) < 1e-9
        for _, v in z["values"]:
            assert abs(float(v) - 0.3) < 1e-9


class TestReviewRegressions:
    """Round-2 inline review findings."""

    def test_unary_minus_binds_looser_than_pow(self):
        e = parse_promql("-1^2")
        # -(1^2) = -1, not (-1)^2
        from greptimedb_tpu.promql.ast import Unary
        assert isinstance(e, Unary) or (
            isinstance(e, NumberLiteral) and e.value == -1)
        e = parse_promql("-2*3")
        assert isinstance(e, Binary) and e.op == "*"
        assert e.lhs.value == -2.0

    def test_irate_and_timestamp_at_realistic_epoch(self, fe):
        base = 1_700_000_000_000          # Nov 2023, epoch ms
        fe.do_query("CREATE TABLE ep (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        rows = ",".join(f"({base + i * 15_000}, {i * 3.0})"
                        for i in range(20))
        fe.do_query(f"INSERT INTO ep VALUES {rows}")
        eng = fe.promql_engine()
        t = (base + 19 * 15_000) // 1000
        out = eng.query_to_prom_json("irate(ep[1m])", t * 1000, t * 1000,
                                     1000, QueryContext(), instant=True)
        # 3 per 15s = 0.2/s; float32 epoch seconds would return empty/0
        assert out["result"], "irate returned empty at realistic epoch"
        assert abs(float(out["result"][0]["value"][1]) - 0.2) < 1e-3
        out = eng.query_to_prom_json("timestamp(ep)", t * 1000, t * 1000,
                                     1000, QueryContext(), instant=True)
        got = float(out["result"][0]["value"][1])
        assert abs(got - t) < 1.0         # was off by up to ~128s

    def test_absent_selector_labels(self, fe):
        _mk_cpu(fe)
        out = _q(fe, 'absent(nosuch{job="api", host=~"h.*"})',
                 100_000, 100_000, 1000, instant=True)
        assert out["result"][0]["metric"] == {"job": "api"}


class TestReviewRegressions2:
    def test_irate_counter_reset(self, fe):
        fe.do_query("CREATE TABLE ir (ts TIMESTAMP TIME INDEX, val DOUBLE)")
        # counter resets between the last two samples: prometheus uses the
        # last value alone (0.5/s), not a huge negative rate
        fe.do_query("INSERT INTO ir VALUES (0, 100000), (10000, 100005), "
                    "(20000, 5)")
        out = _q(fe, "irate(ir[1m])", 20_000, 20_000, 1000, instant=True)
        v = float(out["result"][0]["value"][1])
        assert abs(v - 0.5) < 1e-6

    def test_invalid_regex_is_query_error(self, fe):
        _mk_cpu(fe)
        with pytest.raises(PromqlParseError):
            _q(fe, 'cpu{host=~"["}', 0, 0, 1000, instant=True)

    def test_invalid_duration_is_greptime_error(self):
        from greptimedb_tpu.common.time import parse_prom_duration
        from greptimedb_tpu.errors import GreptimeError
        with pytest.raises(GreptimeError):
            parse_prom_duration("abc")


class TestTqlExplain:
    def test_explain_plan_tree(self, fe):
        _mk_cpu(fe)
        out = fe.do_query("TQL EXPLAIN (0, 60, '1m')"
                          " sum by (host) (rate(cpu[1m]))")[-1]
        plan = out.batches[0].to_pydict()["plan"][0]
        assert "PromAggregate: sum by (host)" in plan
        assert "PromCall: rate" in plan
        assert "PromSeriesScan: cpu[60000ms]" in plan

    def test_analyze_reports_stats(self, fe):
        _mk_cpu(fe)
        out = fe.do_query("TQL ANALYZE (0, 100, '10s') cpu")[-1]
        doc = out.batches[0].to_pydict()
        assert doc["plan_type"] == ["logical_plan", "analyze"]
        assert "elapsed" in doc["plan"][1] and "series: 2" in doc["plan"][1]


class TestStreamedColdSelect:
    """Satellite (ISSUE 3): PromQL range selectors take the streamed cold
    path — a window-bounded host read that never enters the scan cache —
    when the region exceeds the stream threshold, with identical answers
    to the resident path."""

    def test_streamed_matches_resident(self, fe):
        from greptimedb_tpu.query import stream_exec, tpu_exec
        _mk_cpu(fe)
        table = fe.catalog.table("greptime", "public", "cpu")
        region = next(iter(table.regions.values()))
        saved = stream_exec.stream_threshold_rows()
        try:
            # resident baseline (threshold far above the 120 rows)
            stream_exec.configure_streaming(threshold_rows=10_000_000)
            assert not tpu_exec.region_streams_cold(region)
            resident = _q(fe, "rate(cpu[1m])", 300_000, 480_000, 60_000)
            inst_res = _q(fe, "cpu", 100_000, 100_000, 1000, instant=True)
            # force the cold path and evict any residency
            stream_exec.configure_streaming(threshold_rows=1)
            tpu_exec.SCAN_CACHE._entries.clear()
            assert tpu_exec.region_streams_cold(region)
            streamed = _q(fe, "rate(cpu[1m])", 300_000, 480_000, 60_000)
            inst_str = _q(fe, "cpu", 100_000, 100_000, 1000, instant=True)
            assert streamed == resident
            assert inst_str == inst_res
            # the cold read must not have populated the scan cache
            assert tpu_exec.SCAN_CACHE.resident_bytes() == 0
        finally:
            stream_exec.configure_streaming(threshold_rows=saved)

    def test_streamed_reads_only_window(self, fe):
        from greptimedb_tpu.query import stream_exec, tpu_exec
        from greptimedb_tpu.session import QueryContext
        from greptimedb_tpu.promql.parser import parse_promql
        _mk_cpu(fe)                      # 60 samples / host, 10s apart
        saved = stream_exec.stream_threshold_rows()
        try:
            stream_exec.configure_streaming(threshold_rows=1)
            tpu_exec.SCAN_CACHE._entries.clear()
            eng = fe.promql_engine()
            sel = parse_promql("cpu[1m]")
            selection = eng.select(sel, 100_000, 160_000, QueryContext())
            # window-bounded: 7 samples/host in [100s, 160s], not 60
            total = int(np.sum(selection.matrix.lengths))
            assert total == 2 * 7
        finally:
            stream_exec.configure_streaming(threshold_rows=saved)
