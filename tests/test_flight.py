"""Arrow Flight data plane: datanode server/client + frontend Database.

Mirrors the reference's gRPC/Flight integration tests
(tests-integration/tests/grpc.rs): insert + query round-trip over real
sockets, distributed DDL/insert/query with Flight as the router↔worker
transport (client/src/database.rs do_get path).
"""

import time

import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.client.flight import Database, FlightDatanodeClient
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.distributed import DistInstance
from greptimedb_tpu.frontend.instance import build_standalone
from greptimedb_tpu.meta import MetaClient, MetaSrv, Peer
from greptimedb_tpu.meta.kv import MemKv
from greptimedb_tpu.servers.flight import (
    FlightDatanodeServer, FlightFrontendServer)


def _wait_port(server, timeout=10.0):
    t0 = time.time()
    while server.port == 0 and time.time() - t0 < timeout:
        time.sleep(0.01)
    assert server.port != 0


@pytest.fixture()
def flight_cluster(tmp_path):
    """2 datanodes behind Flight servers + meta + DistInstance with
    FlightDatanodeClients: the in-process distributed topology promoted
    onto real sockets."""
    datanodes, servers, clients = {}, {}, {}
    for i in (1, 2):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        srv = FlightDatanodeServer(dn)
        srv.serve_in_background()
        _wait_port(srv)
        datanodes[i] = dn
        servers[i] = srv
        clients[i] = FlightDatanodeClient(srv.address, node_id=i)
    meta_srv = MetaSrv(MemKv())
    meta = MetaClient(meta_srv)
    for i, dn in datanodes.items():
        meta_srv.register_datanode(Peer(i, servers[i].address))
        dn.start_heartbeat(meta, interval_s=3600)
    fe = DistInstance(meta, clients)
    yield fe, datanodes, clients
    for c in clients.values():
        c.close()
    for s in servers.values():
        s.shutdown()
    for dn in datanodes.values():
        dn.shutdown()


DDL = """
CREATE TABLE dist (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                   PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))
"""


class TestFlightDatanodePlane:
    def test_ping(self, flight_cluster):
        _, _, clients = flight_cluster
        assert clients[1].ping() == 1
        assert clients[2].ping() == 2

    def test_ddl_insert_query_roundtrip(self, flight_cluster):
        fe, datanodes, _ = flight_cluster
        fe.do_query(DDL)
        hosts = [f"h{i}" for i in range(10)]
        rows = []
        for h in hosts:
            for k in range(5):
                rows.append(f"('{h}', {1000 + k}, {float(ord(h[1]) - 48)})")
        n = fe.do_query(
            "INSERT INTO dist (host, ts, cpu) VALUES " + ",".join(rows))
        assert n[0].affected_rows == 50

        # rows actually split across the two datanodes over the wire
        counts = []
        for dn in datanodes.values():
            t = dn.catalog.table(CAT, SCH, "dist")
            got = sum(b.num_rows for b in t.scan_batches())
            counts.append(got)
        assert sorted(counts) == [25, 25]

        # aggregate pushdown over Flight: moments stream back as frames
        out = fe.do_query(
            "SELECT host, avg(cpu) AS c FROM dist GROUP BY host ORDER BY host")
        got = {r[0]: r[1] for b in out[0].batches for r in b.rows()}
        assert got == {h: float(ord(h[1]) - 48) for h in hosts}

    def test_scan_over_wire(self, flight_cluster):
        fe, _, clients = flight_cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist (host, ts, cpu) VALUES "
                    "('h1', 1000, 1.5), ('h8', 1000, 8.5)")
        b1 = clients[1].scan_batches(CAT, SCH, "dist")
        b2 = clients[2].scan_batches(CAT, SCH, "dist")
        vals = sorted(r[2] for bs in (b1, b2) for b in bs for r in b.rows())
        assert vals == [1.5, 8.5]

    def test_describe_and_hydrate(self, flight_cluster):
        """Frontend restart: a fresh DistInstance rebuilds DistTables from
        meta routes + wire describe_table."""
        fe, _, clients = flight_cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist (host, ts, cpu) VALUES "
                    "('h1', 1000, 1.0), ('h9', 1000, 9.0)")
        fe2 = DistInstance(fe.meta, clients)
        out = fe2.do_query("SELECT avg(cpu) AS a FROM dist")
        assert out[0].batches[0].rows().__next__()[0] == 5.0

    def test_flush_and_drop(self, flight_cluster):
        fe, datanodes, _ = flight_cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist (host, ts, cpu) VALUES "
                    "('h1', 1000, 1.0)")
        table = fe.catalog.table(CAT, SCH, "dist")
        table.flush()
        fe.do_query("DROP TABLE dist")
        for dn in datanodes.values():
            assert dn.catalog.table(CAT, SCH, "dist") is None

    def test_error_surfaces(self, flight_cluster):
        from greptimedb_tpu.errors import GreptimeError
        _, _, clients = flight_cluster
        with pytest.raises(GreptimeError):
            clients[1].write_region(CAT, SCH, "missing", 0,
                                    {"ts": [1], "v": [1.0]})


class TestDatabaseClient:
    @pytest.fixture()
    def standalone(self, tmp_path):
        from greptimedb_tpu.frontend.instance import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "data"),
            register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        srv = FlightFrontendServer(fe)
        srv.serve_in_background()
        _wait_port(srv)
        db = Database(srv.address)
        yield db
        db.close()
        srv.shutdown()
        fe.shutdown()

    def test_quickstart_flow(self, standalone):
        db = standalone
        assert db.sql(
            "CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME INDEX,"
            " cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))") == 0
        n = db.sql("INSERT INTO monitor VALUES "
                   "('host1', 1000, 66.6, 1024), "
                   "('host2', 1000, 77.7, 2048)")
        assert n == 2
        batches = db.sql("SELECT host, avg(cpu) AS c FROM monitor "
                         "GROUP BY host ORDER BY host")
        rows = [r for b in batches for r in b.rows()]
        assert rows == [("host1", 66.6), ("host2", 77.7)]

    def test_row_insert_auto_create(self, standalone):
        db = standalone
        n = db.insert("autotab",
                      {"host": ["a", "b"], "greptime_timestamp": [1, 2],
                       "val": [1.0, 2.0]},
                      tag_columns=["host"])
        assert n == 2
        batches = db.sql("SELECT count(val) AS n FROM autotab")
        assert next(batches[0].rows())[0] == 2


class TestDoPutTracePropagation:
    """Regression (greptlint GL07): client/flight._put has always sent
    the caller's traceparent inside the descriptor command, but the
    server's do_put dropped it — bulk writes detached from the client's
    trace while queries (do_get) joined it."""

    def test_do_put_joins_client_trace(self, tmp_path):
        from greptimedb_tpu.common import telemetry
        from greptimedb_tpu.frontend.instance import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "data"),
            register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        srv = FlightFrontendServer(fe)
        srv.serve_in_background()
        _wait_port(srv)
        db = Database(srv.address)
        try:
            server_side = []
            orig = fe.handle_row_insert

            def spy(*args, **kwargs):
                # runs on the Flight handler thread: what trace is live?
                server_side.append(telemetry.current_traceparent())
                return orig(*args, **kwargs)

            fe.handle_row_insert = spy
            with telemetry.span("client-bulk-write"):
                client_tp = telemetry.current_traceparent()
                n = db.insert(
                    "traced_tab",
                    {"host": ["a"], "greptime_timestamp": [1],
                     "val": [1.0]}, tag_columns=["host"])
            assert n == 1
            assert server_side and server_side[0] is not None, \
                "do_put handler ran without a trace context"
            client_trace = telemetry.parse_traceparent(client_tp)[0]
            server_trace = telemetry.parse_traceparent(server_side[0])[0]
            assert server_trace == client_trace
        finally:
            db.close()
            srv.shutdown()
            fe.shutdown()
