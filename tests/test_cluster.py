"""Multi-host cluster topology tests.

Two levels, mirroring the reference's distributed coverage:
- in-process, real sockets: FlightMetaServer/Client + Flight datanodes +
  PeerClientRegistry (tests-integration style)
- true multi-process: metasrv + 2 datanodes + frontend spawned via the
  CLI role subcommands, driven over HTTP (the greptime cluster quick
  start flow).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT
from greptimedb_tpu import DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.distributed import DistInstance
from greptimedb_tpu.meta import MetaSrv, Peer
from greptimedb_tpu.meta.flight import (
    FlightMetaClient, FlightMetaServer, PeerClientRegistry)
from greptimedb_tpu.meta.kv import FileKv, MemKv
from greptimedb_tpu.servers.flight import FlightDatanodeServer

DDL = """
CREATE TABLE dist (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                   PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE))
"""


def _wait_port(server, timeout=10.0):
    t0 = time.time()
    while server.port == 0 and time.time() - t0 < timeout:
        time.sleep(0.01)
    assert server.port != 0


class TestFileKv:
    def test_snapshot_roundtrip(self, tmp_path):
        path = str(tmp_path / "kv.json")
        kv = FileKv(path)
        kv.put("a", b"1")
        kv.incr("seq")
        assert FileKv(path).get("a") == b"1"
        assert FileKv(path).incr("seq") == 2

    def test_cas_persists(self, tmp_path):
        path = str(tmp_path / "kv.json")
        kv = FileKv(path)
        assert kv.compare_and_put("k", None, b"v")
        assert not FileKv(path).compare_and_put("k", None, b"w")


class TestWireMetaCluster:
    @pytest.fixture()
    def cluster(self, tmp_path):
        meta_srv = MetaSrv(MemKv())
        meta_server = FlightMetaServer(meta_srv)
        meta_server.serve_in_background()
        _wait_port(meta_server)
        meta = FlightMetaClient(meta_server.address)

        datanodes, servers = {}, {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            srv = FlightDatanodeServer(dn)
            srv.serve_in_background()
            _wait_port(srv)
            meta.register(Peer(i, srv.address))
            dn.start_heartbeat(meta, interval_s=3600)
            datanodes[i] = dn
            servers[i] = srv
        fe = DistInstance(meta, PeerClientRegistry(meta))
        yield fe, datanodes
        for s in servers.values():
            s.shutdown()
        for dn in datanodes.values():
            dn.shutdown()
        meta.close()
        meta_server.shutdown()

    def test_ddl_insert_query_over_wire_meta(self, cluster):
        fe, datanodes = cluster
        fe.do_query(DDL)
        rows = ", ".join(f"('h{i}', {1000+i}, {float(i)})"
                         for i in range(10))
        n = fe.do_query(f"INSERT INTO dist VALUES {rows}")[-1]
        assert n.affected_rows == 10
        counts = sorted(
            sum(b.num_rows for b in
                dn.catalog.table(CAT, SCH, "dist").scan_batches())
            for dn in datanodes.values())
        assert counts == [5, 5]
        out = fe.do_query("SELECT count(*) AS c FROM dist")[-1]
        assert next(out.batches[0].rows())[0] == 10

    def test_registry_resolves_lazily(self, cluster):
        fe, _ = cluster
        fe.do_query(DDL)
        fe.do_query("INSERT INTO dist VALUES ('h1', 1, 1.0)")
        # a fresh frontend with an EMPTY registry must dial peers on
        # demand from meta state alone
        fe2 = DistInstance(fe.meta, PeerClientRegistry(fe.meta))
        out = fe2.do_query("SELECT sum(cpu) AS s FROM dist")[-1]
        assert next(out.batches[0].rows())[0] == 1.0


HASH_DDL = """
CREATE TABLE obs (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                  PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 8
"""


class TestClusterObservability:
    """ISSUE 6: one trace id per statement across processes, per-node
    EXPLAIN ANALYZE over the wire, and the cluster_info health view."""

    @pytest.fixture()
    def wire_cluster(self, tmp_path):
        meta_srv = MetaSrv(MemKv())
        meta_server = FlightMetaServer(meta_srv)
        meta_server.serve_in_background()
        _wait_port(meta_server)
        meta = FlightMetaClient(meta_server.address)
        datanodes, servers = {}, {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            srv = FlightDatanodeServer(dn)
            srv.serve_in_background()
            _wait_port(srv)
            meta.register(Peer(i, srv.address))
            dn.start_heartbeat(meta, interval_s=3600)
            datanodes[i] = dn
            servers[i] = srv
        fe = DistInstance(meta, PeerClientRegistry(meta))
        fe.do_query(HASH_DDL)
        rows = ", ".join(f"('h{i % 4}', {1000 + i}, {float(i)})"
                         for i in range(24))
        fe.do_query(f"INSERT INTO obs VALUES {rows}")
        yield fe, meta_srv, datanodes
        for s in servers.values():
            s.shutdown()
        for dn in datanodes.values():
            dn.shutdown()
        meta.close()
        meta_server.shutdown()

    def test_one_trace_id_across_frontend_and_datanodes(
            self, wire_cluster, caplog):
        """Satellite 1: after wire propagation, a slow distributed
        statement logs the SAME trace id on the frontend and on every
        datanode it touched (datanodes used to mint their own)."""
        import logging

        from greptimedb_tpu.common.telemetry import (
            set_slow_query_threshold_ms)
        fe, _, _ = wire_cluster
        set_slow_query_threshold_ms(1)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="greptimedb_tpu.slow_query"):
                fe.do_query(
                    "SELECT host, count(*) AS c FROM obs GROUP BY host")
        finally:
            set_slow_query_threshold_ms(None)
        import re

        def traces(needle):
            return {re.search(r"trace=(\S+)", r.getMessage()).group(1)
                    for r in caplog.records
                    if needle in r.getMessage()}
        fe_traces = traces("slow query:")
        dn_traces = traces("slow datanode op:")
        assert len(fe_traces) == 1, caplog.text
        assert dn_traces, "datanode side must log the slow op too"
        assert dn_traces == fe_traces, \
            f"trace ids diverged: fe={fe_traces} dn={dn_traces}"
        # a bare 32-hex trace id, not a whole traceparent header
        assert "-" not in next(iter(fe_traces))

    def _analyze_rows(self, fe, sql):
        out = fe.do_query("EXPLAIN ANALYZE " + sql)[-1]
        return [r for b in out.batches for r in b.to_pylist()]

    def test_per_node_tree_sums_to_standalone(self, wire_cluster,
                                              tmp_path):
        """Satellite 3 (wire-level differential): the per-node stage
        rows of a distributed EXPLAIN ANALYZE sum — rows scanned across
        nodes — to the standalone run of the same query on the same
        data."""
        from greptimedb_tpu.frontend.instance import FrontendInstance
        fe, _, _ = wire_cluster
        sql = "SELECT host, count(*) AS c FROM obs GROUP BY host"
        rows = self._analyze_rows(fe, sql)
        node_rows = [r for r in rows
                     if r["stage"].startswith("  dn")
                     and not r["stage"].startswith("    ")]
        assert len(node_rows) == 2, [r["stage"] for r in rows]
        for r in node_rows:
            assert "dispatch=" in r["detail"]
            assert "network_ms=" in r["detail"]
        scan_rows = [r for r in rows if r["stage"] == "    scan_prep"]
        assert scan_rows, "per-node scan stages must cross the wire"
        dist_scanned = sum(r["rows"] for r in scan_rows)

        # standalone twin on identical data
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "solo"),
            register_numbers_table=False))
        dn.start()
        solo = FrontendInstance(dn)
        solo.start()
        try:
            solo.do_query(
                "CREATE TABLE obs (host STRING, ts TIMESTAMP TIME INDEX,"
                " cpu DOUBLE, PRIMARY KEY(host))")
            vals = ", ".join(f"('h{i % 4}', {1000 + i}, {float(i)})"
                             for i in range(24))
            solo.do_query(f"INSERT INTO obs VALUES {vals}")
            solo_rows = self._analyze_rows(solo, sql)
        finally:
            solo.shutdown()
        solo_scanned = next(
            r["rows"] for r in solo_rows
            if r["stage"] in ("scan_prep", "scan", "decode_reduce"))
        assert dist_scanned == solo_scanned == 24

    def test_cluster_info_lease_flip_on_dead_datanode(self, wire_cluster):
        """Acceptance: all nodes alive with region counts; a datanode
        that stops heartbeating flips to expired within the lease
        window (probed with an explicit `now` — no wall-clock sleeps)."""
        fe, meta_srv, _ = wire_cluster
        out = fe.do_query(
            "SELECT peer_type, lease_state, region_count FROM "
            "information_schema.cluster_info ORDER BY peer_id")[-1]
        got = [tuple(r) for b in out.batches for r in b.rows()]
        assert got[0][:2] == ("metasrv", "leader")
        assert [g[:2] for g in got[1:]] == [("datanode", "alive")] * 2
        assert sum(g[2] for g in got[1:]) == 8     # all routed regions
        # dn2 ingests hot right up to its death...
        import time as _time
        from greptimedb_tpu.meta import DatanodeStat
        t0 = _time.time()
        meta_srv.handle_heartbeat(
            2, DatanodeStat(approximate_rows=1000), now=t0)
        meta_srv.handle_heartbeat(
            2, DatanodeStat(approximate_rows=3000), now=t0 + 2)
        hot = {n["peer_id"]: n for n in meta_srv.cluster_info(now=t0 + 2)}
        assert hot[2]["ingest_rate_rps"] > 0
        # ...then goes silent: one lease window later the view says
        # expired
        later = t0 + 2 + meta_srv.datanode_lease_secs + 1
        meta_srv.handle_heartbeat(1, now=later)    # dn1 keeps beating
        info = {n["peer_id"]: n for n in meta_srv.cluster_info(now=later)}
        assert info[1]["lease_state"] == "alive"
        assert info[2]["lease_state"] == "expired"
        assert info[2]["region_count"] == 4        # placement unchanged
        # a dead node is not ingesting: its last-known rate must not
        # read as cluster heat forever (rows stay — they are cumulative)
        assert info[2]["ingest_rate_rps"] == 0.0
        assert info[2]["approximate_rows"] == 3000

    def test_heartbeat_stats_feed_cluster_info(self, wire_cluster):
        """A stat-bearing heartbeat surfaces rows + per-region stats in
        the view, and consecutive reports yield an ingest rate."""
        fe, meta_srv, datanodes = wire_cluster
        import json as _json
        import time as _time
        from greptimedb_tpu.meta import DatanodeStat
        t0 = _time.time()
        meta_srv.handle_heartbeat(1, DatanodeStat(
            region_count=4, approximate_rows=1000,
            region_stats=[{"region": "r", "rows": 1000}]), now=t0)
        meta_srv.handle_heartbeat(1, DatanodeStat(
            region_count=4, approximate_rows=3000,
            region_stats=[{"region": "r", "rows": 3000}]), now=t0 + 2)
        info = {n["peer_id"]: n
                for n in meta_srv.cluster_info(now=t0 + 2)}
        assert info[1]["approximate_rows"] == 3000
        assert info[1]["ingest_rate_rps"] == pytest.approx(1000.0)
        assert _json.loads(info[1]["region_stats"]) == [
            {"region": "r", "rows": 3000}]


@pytest.mark.slow
class TestMultiProcessCluster:
    def _spawn(self, *argv, env):
        return subprocess.Popen(
            [sys.executable, "-m", "greptimedb_tpu.cmd.main", *argv],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def _http(self, port, sql, timeout=60):
        data = urllib.parse.urlencode({"sql": sql}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/sql", data=data)
        return json.load(urllib.request.urlopen(req, timeout=timeout))

    def _wait_tcp(self, port, proc, timeout=90):
        import socket
        t0 = time.time()
        while time.time() - t0 < timeout:
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                raise AssertionError(f"process died:\n{out[-3000:]}")
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=1).close()
                return
            except OSError:
                time.sleep(0.3)
        raise AssertionError(f"port {port} never came up")

    def test_cluster_quickstart(self, tmp_path):
        import socket

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        meta_p, dn1_p, dn2_p, http_p = (free_port() for _ in range(4))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = []
        try:
            procs.append(self._spawn(
                "metasrv", "start", "--bind-addr", f"127.0.0.1:{meta_p}",
                "--store", str(tmp_path / "kv.json"), env=env))
            self._wait_tcp(meta_p, procs[0])
            for i, port in ((1, dn1_p), (2, dn2_p)):
                procs.append(self._spawn(
                    "datanode", "start", "--node-id", str(i),
                    "--rpc-addr", f"127.0.0.1:{port}",
                    "--metasrv-addr", f"127.0.0.1:{meta_p}",
                    "--data-home", str(tmp_path / f"dn{i}"), env=env))
            self._wait_tcp(dn1_p, procs[1])
            self._wait_tcp(dn2_p, procs[2])
            procs.append(self._spawn(
                "frontend", "start",
                "--metasrv-addr", f"127.0.0.1:{meta_p}",
                "--http-addr", f"127.0.0.1:{http_p}", env=env))
            self._wait_tcp(http_p, procs[3])

            resp = self._http(http_p, DDL)
            assert resp["code"] == 0, resp
            rows = ", ".join(f"('h{i}', {1000+i}, {float(i)})"
                             for i in range(10))
            resp = self._http(http_p, f"INSERT INTO dist VALUES {rows}")
            assert resp["code"] == 0, resp
            assert resp["output"][0]["affectedrows"] == 10
            resp = self._http(
                http_p, "SELECT host, cpu FROM dist ORDER BY host")
            assert resp["code"] == 0, resp
            got = resp["output"][0]["records"]["rows"]
            assert len(got) == 10
            assert got[0][0] == "h0"
            resp = self._http(http_p, "SELECT sum(cpu) FROM dist")
            assert resp["output"][0]["records"]["rows"] == [[45.0]]
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


@pytest.mark.slow
class TestDurableTraceCluster:
    """ISSUE 15 acceptance drive: a REAL 4-datanode cluster (separate
    processes). A deliberately slow distributed query finishes; long
    after, ADMIN SHOW TRACE reassembles its full cross-node waterfall
    from greptime_private.trace_spans — frontend AND all touched
    datanodes under one trace id. A fast query leaves no spans, a
    KILLed query is always retained, and background_jobs shows
    datanode-side flush/compaction work with its region."""

    _spawn = TestMultiProcessCluster._spawn
    _http = TestMultiProcessCluster._http
    _wait_tcp = TestMultiProcessCluster._wait_tcp

    def _sql(self, port, sql, timeout=60):
        resp = self._http(port, sql, timeout=timeout)
        assert resp["code"] == 0, resp
        return resp

    def _rows(self, port, sql):
        out = self._sql(port, sql)["output"][0]
        return out.get("records", {}).get("rows", [])

    def test_cross_node_waterfall_survives_the_query(self, tmp_path):
        import socket
        import threading

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        meta_p, http_p = free_port(), free_port()
        dn_ports = {i: free_port() for i in (1, 2, 3, 4)}
        # tail-sampling pinned for determinism: ONLY slow/error/killed/
        # balancer traces retain (no head-sample noise). 300ms keeps
        # ordinary statements fast; the "deliberately slow" query gets
        # its slowness injected via the dist_rpc delay failpoint
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   GREPTIME_TRACE_SAMPLE_RATIO="0",
                   GREPTIME_SLOW_QUERY_MS="300")
        procs = []
        try:
            procs.append(self._spawn(
                "metasrv", "start", "--bind-addr", f"127.0.0.1:{meta_p}",
                "--store", str(tmp_path / "kv.json"), env=env))
            self._wait_tcp(meta_p, procs[0])
            for i, port in dn_ports.items():
                procs.append(self._spawn(
                    "datanode", "start", "--node-id", str(i),
                    "--rpc-addr", f"127.0.0.1:{port}",
                    "--metasrv-addr", f"127.0.0.1:{meta_p}",
                    # one shared data home (the elastic deployment
                    # shape) so the migrate half of the drive can hand
                    # a region between nodes; WAL/fence state is
                    # node-scoped inside it
                    "--data-home", str(tmp_path / "shared"), env=env))
            for i, port in dn_ports.items():
                self._wait_tcp(port, procs[i])
            procs.append(self._spawn(
                "frontend", "start",
                "--metasrv-addr", f"127.0.0.1:{meta_p}",
                "--http-addr", f"127.0.0.1:{http_p}", env=env))
            self._wait_tcp(http_p, procs[-1])

            self._sql(http_p, """
CREATE TABLE tr (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 8""")
            for b in range(4):
                vals = ", ".join(
                    f"('h{j % 40}', {100_000 + b * 1000 + j}, {float(j)})"
                    for j in range(500))
                self._sql(http_p, f"INSERT INTO tr VALUES {vals}")

            # --- the deliberately slow distributed query: every dist
            # RPC pays an injected 400ms hop, so the statement clears
            # the 300ms slow threshold deterministically ---
            self._sql(http_p, "SET failpoint_dist_rpc = 'delay(400)'")
            rows = self._rows(http_p, "SELECT host, avg(v), count(*) "
                                      "FROM tr GROUP BY host")
            assert len(rows) == 40
            self._sql(http_p, "SET failpoint_dist_rpc = 'off'")

            # the query is DONE. Reassemble its waterfall from the
            # durable store: the SHOW TRACE ping piggybacks verdicts to
            # every datanode and collects their buffered spans
            wf = self._rows(http_p, "ADMIN SHOW TRACE 'last'")
            spans = [r[0].strip() for r in wf]
            nodes = {r[1] for r in wf}
            assert any("execute_stmt" in s for s in spans)
            assert "frontend" in nodes
            touched = {n for n in nodes if n.startswith("dn")}
            assert touched == {"dn1", "dn2", "dn3", "dn4"}, nodes
            # one trace id across every process: the stored rows agree
            tid_rows = self._rows(
                http_p, "SELECT DISTINCT trace_id FROM "
                        "information_schema.trace_spans WHERE "
                        "span_name IN ('dn_region_moments', 'dn_scan')")
            assert len(tid_rows) == 1
            tid = tid_rows[0][0]
            node_rows = self._rows(
                http_p, f"SELECT DISTINCT node FROM information_schema"
                        f".trace_spans WHERE trace_id = '{tid}'")
            got_nodes = {r[0] for r in node_rows}
            assert {"frontend", "dn1", "dn2", "dn3", "dn4"} <= got_nodes

            # --- a fast query leaves no spans ---
            before = self._rows(http_p, "SELECT count(*) FROM "
                                        "information_schema.trace_spans"
                                        )[0][0]
            self._sql(http_p, "SELECT 1")
            time.sleep(0.2)
            after = self._rows(http_p, "SELECT count(*) FROM "
                                       "information_schema.trace_spans"
                                       )[0][0]
            assert after == before   # nothing new from SELECT 1

            # --- a KILLed query is always retained ---
            self._sql(http_p, "SET failpoint_dist_rpc = 'delay(2000)'")
            killed = {}

            def victim():
                try:
                    self._http(http_p,
                               "SELECT host, sum(v) FROM tr "
                               "GROUP BY host", timeout=120)
                except Exception as e:  # noqa: BLE001
                    killed["err"] = e
            t = threading.Thread(target=victim)
            t.start()
            pid = None
            t0 = time.time()
            while pid is None and time.time() - t0 < 30:
                for r in self._rows(http_p,
                                    "SELECT id, query FROM "
                                    "information_schema.processes"):
                    if "sum(v)" in r[1]:
                        pid = r[0]
                time.sleep(0.1)
            assert pid is not None, "victim never registered"
            self._sql(http_p, f"KILL {pid}")
            t.join(60)
            self._sql(http_p, "SET failpoint_dist_rpc = 'off'")
            cancelled = self._rows(
                http_p, "SELECT count(*) FROM information_schema."
                        "trace_spans WHERE status = 'cancelled'")
            assert cancelled[0][0] >= 1

            # --- background_jobs shows datanode work with regions ---
            self._sql(http_p, "ADMIN FLUSH TABLE tr")
            jobs = self._rows(
                http_p, "SELECT kind, region, node, state FROM "
                        "information_schema.background_jobs "
                        "WHERE kind = 'flush'")
            assert jobs, "no flush jobs visible cluster-wide"
            assert any(r[2].startswith("dn") and r[1] for r in jobs)

            # --- balancer op steps: jobs on the METASRV process are
            # merged into the view, and the op's trace (always
            # retained) lands in trace_spans via the meta-RPC export ---
            owner = self._rows(
                http_p, "SELECT peer_id FROM information_schema."
                        "region_peers WHERE region_number = 0")[0][0]
            target = next(i for i in (1, 2, 3, 4) if i != owner)
            self._sql(http_p,
                      f"ADMIN MIGRATE REGION tr 0 TO {target}")
            t0 = time.time()
            bal = []
            while time.time() - t0 < 60:
                bal = self._rows(
                    http_p, "SELECT kind, node, state FROM "
                            "information_schema.background_jobs "
                            "WHERE kind = 'balancer_op'")
                if any(r[1] == "metasrv" for r in bal):
                    break
                time.sleep(0.5)
            assert any(r[1] == "metasrv" for r in bal), bal
            t0 = time.time()
            stored = []
            while time.time() - t0 < 60 and not stored:
                stored = self._rows(
                    http_p, "SELECT count(*) FROM information_schema."
                            "trace_spans WHERE node = 'metasrv' AND "
                            "span_name = 'job_balancer_op'")
                if stored and stored[0][0] > 0:
                    break
                stored = []
                time.sleep(0.5)
            assert stored and stored[0][0] > 0, \
                "metasrv balancer trace never reached trace_spans"
        finally:
            for p in procs:
                p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


@pytest.mark.slow
class TestElasticCluster:
    """ISSUE 9 acceptance drive: a REAL 4-datanode cluster (separate
    processes over a shared object store) under sustained ingest —
    ADMIN MIGRATE REGION completes with zero acked-row loss/duplication,
    kill -9 of a datanode triggers automatic re-placement while queries
    keep answering, and region_peers/cluster_info reflect it all."""

    _spawn = TestMultiProcessCluster._spawn
    _http = TestMultiProcessCluster._http
    _wait_tcp = TestMultiProcessCluster._wait_tcp

    def _sql(self, port, sql, timeout=60):
        resp = self._http(port, sql, timeout=timeout)
        assert resp["code"] == 0, resp
        return resp

    def _rows(self, port, sql):
        return self._sql(port, sql)["output"][0]["records"]["rows"]

    def _wait_until(self, fn, timeout=60, what="condition"):
        t0 = time.time()
        last = None
        while time.time() - t0 < timeout:
            try:
                last = fn()
                if last:
                    return last
            except Exception as e:  # noqa: BLE001 — polled condition
                last = e            # may race server restarts
            time.sleep(0.5)
        raise AssertionError(f"{what} never held (last={last!r})")

    def test_migrate_and_kill_under_ingest(self, tmp_path):
        import socket
        import threading

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        meta_p, http_p = free_port(), free_port()
        dn_ports = {i: free_port() for i in (1, 2, 3, 4)}
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        shared_home = str(tmp_path / "shared")
        procs, dn_procs = [], {}
        try:
            procs.append(self._spawn(
                "metasrv", "start", "--bind-addr", f"127.0.0.1:{meta_p}",
                "--store", str(tmp_path / "kv.json"),
                "--failover-interval", "0.5",
                "--datanode-lease-secs", "2", env=env))
            self._wait_tcp(meta_p, procs[0])
            for i, port in dn_ports.items():
                p = self._spawn(
                    "datanode", "start", "--node-id", str(i),
                    "--rpc-addr", f"127.0.0.1:{port}",
                    "--metasrv-addr", f"127.0.0.1:{meta_p}",
                    "--heartbeat-interval", "0.5",
                    # ONE shared data home = shared object store; WAL +
                    # control state are node-scoped inside it
                    "--data-home", shared_home, env=env)
                procs.append(p)
                dn_procs[i] = p
            for i, port in dn_ports.items():
                self._wait_tcp(port, dn_procs[i])
            procs.append(self._spawn(
                "frontend", "start",
                "--metasrv-addr", f"127.0.0.1:{meta_p}",
                "--http-addr", f"127.0.0.1:{http_p}", env=env))
            self._wait_tcp(http_p, procs[-1])

            self._sql(http_p, """
CREATE TABLE el (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h3'),
  PARTITION r1 VALUES LESS THAN ('h6'),
  PARTITION r2 VALUES LESS THAN ('h9'),
  PARTITION r3 VALUES LESS THAN (MAXVALUE))""")

            acked = set()
            acked_lock = threading.Lock()
            stop = threading.Event()

            def ingest():
                n = 0
                while not stop.is_set():
                    n += 1
                    batch = [(f"h{j}", 10_000 + n * 10 + j)
                             for j in range(10)]
                    vals = ", ".join(f"('{h}', {ts}, 1.0)"
                                     for h, ts in batch)
                    try:
                        self._sql(http_p,
                                  f"INSERT INTO el VALUES {vals}",
                                  timeout=30)
                        with acked_lock:
                            acked.update(batch)
                    except Exception:  # noqa: BLE001 — unacked writes
                        pass           # are legal during the fault
                    time.sleep(0.05)

            t = threading.Thread(target=ingest, daemon=True)
            t.start()
            try:
                # --- ADMIN MIGRATE under sustained ingest ---
                peers = self._rows(
                    http_p,
                    "SELECT region_number, peer_id FROM "
                    "information_schema.region_peers")
                assert len(peers) == 4
                src = next(p for r, p in peers if r == 0)
                dst = next(i for i in (1, 2, 3, 4) if i != src)
                out = self._rows(
                    http_p, f"ADMIN MIGRATE REGION el 0 TO {dst}")
                assert out[0][1] == "migrate"
                self._wait_until(
                    lambda: [r for r in self._rows(
                        http_p,
                        "SELECT region_number, peer_id, operation FROM "
                        "information_schema.region_peers")
                        if r[0] == 0][0][1] == dst and
                    [r for r in self._rows(
                        http_p,
                        "SELECT region_number, operation FROM "
                        "information_schema.region_peers")
                        if r[0] == 0][0][1] is None,
                    what="migration commit")

                # --- kill -9 a datanode hosting region 3 ---
                placement = {r[0]: r[1] for r in self._rows(
                    http_p,
                    "SELECT region_number, peer_id FROM "
                    "information_schema.region_peers")}
                victim = placement[3]
                victim_regions = [rn for rn, p in placement.items()
                                  if p == victim]
                dn_procs[victim].kill()      # SIGKILL, no shutdown
                self._wait_until(
                    lambda: all(
                        r[1] != victim for r in self._rows(
                            http_p,
                            "SELECT region_number, peer_id FROM "
                            "information_schema.region_peers")),
                    timeout=90, what="automatic re-placement")
                # cluster_info marks the victim non-alive
                states = {r[0]: r[1] for r in self._rows(
                    http_p,
                    "SELECT peer_id, lease_state FROM "
                    "information_schema.cluster_info")}
                assert states[victim] in ("expired", "suspect",
                                          "unknown")
                # queries answer on the re-placed layout
                assert self._rows(
                    http_p, "SELECT count(*) FROM el")[0][0] > 0
            finally:
                stop.set()
                t.join(timeout=60)

            # --- integrity: every acked row exactly once ---
            # Rows that ACKED on the victim but lived only in its WAL
            # are the documented failover loss domain (RFC region-fault-
            # tolerance: re-adoption is at last-flushed state), so the
            # check excludes the victim-hosted ranges; every OTHER
            # region's acked rows must be present exactly once.
            RANGES = {0: (None, "h3"), 1: ("h3", "h6"),
                      2: ("h6", "h9"), 3: ("h9", None)}

            def in_victim(key):
                h = key[0]
                return any(
                    (lo is None or h >= lo) and (hi is None or h < hi)
                    for lo, hi in (RANGES[rn] for rn in victim_regions))

            def settled():
                rows = self._rows(http_p, "SELECT host, ts FROM el")
                keys = [tuple(r) for r in rows]
                assert len(keys) == len(set(keys)), "duplicated rows"
                with acked_lock:
                    missing = {k for k in acked - set(keys)
                               if not in_victim(k)}
                return not missing

            self._wait_until(settled, timeout=60,
                             what="acked-row integrity")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


@pytest.mark.slow
class TestReplicaCluster:
    """ISSUE 19 acceptance drive: a REAL 4-datanode cluster (separate
    processes over a shared object store, WAL fsync-on-ack). ADMIN ADD
    REPLICA attaches a continuously-replicated follower; kill -9 of the
    region leader under sustained acked sync ingest promotes the
    caught-up follower with ZERO acked-row loss/duplication, and
    SET read_replica reads answer before and after the promotion."""

    _spawn = TestMultiProcessCluster._spawn
    _http = TestMultiProcessCluster._http
    _wait_tcp = TestMultiProcessCluster._wait_tcp
    _sql = TestElasticCluster._sql
    _rows = TestElasticCluster._rows
    _wait_until = TestElasticCluster._wait_until

    def test_kill_leader_under_sync_ingest_zero_acked_loss(
            self, tmp_path):
        import socket
        import threading

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        LEASE_S = 2.0
        meta_p, http_p = free_port(), free_port()
        dn_ports = {i: free_port() for i in (1, 2, 3, 4)}
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        shared_home = str(tmp_path / "shared")
        procs, dn_procs = [], {}
        try:
            procs.append(self._spawn(
                "metasrv", "start", "--bind-addr", f"127.0.0.1:{meta_p}",
                "--store", str(tmp_path / "kv.json"),
                "--failover-interval", "0.5",
                "--datanode-lease-secs", str(LEASE_S), env=env))
            self._wait_tcp(meta_p, procs[0])
            for i, port in dn_ports.items():
                p = self._spawn(
                    "datanode", "start", "--node-id", str(i),
                    "--rpc-addr", f"127.0.0.1:{port}",
                    "--metasrv-addr", f"127.0.0.1:{meta_p}",
                    "--heartbeat-interval", "0.5",
                    # fsync before every ack: an acked row is durable in
                    # the leader's node-scoped WAL on the shared home,
                    # where promotion salvage can reach it after SIGKILL
                    "--wal-sync-on-write",
                    "--data-home", shared_home, env=env)
                procs.append(p)
                dn_procs[i] = p
            for i, port in dn_ports.items():
                self._wait_tcp(port, dn_procs[i])
            procs.append(self._spawn(
                "frontend", "start",
                "--metasrv-addr", f"127.0.0.1:{meta_p}",
                "--http-addr", f"127.0.0.1:{http_p}", env=env))
            self._wait_tcp(http_p, procs[-1])

            self._sql(http_p, """
CREATE TABLE rt (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))""")

            def placement():
                return {
                    (r[0], r[1]): (r[2], r[3]) for r in self._rows(
                        http_p,
                        "SELECT peer_id, is_leader, status, "
                        "replicated_seq FROM "
                        "information_schema.region_peers WHERE "
                        "table_name = 'greptime.public.rt'")}

            leader = next(p for (p, is_l) in placement() if is_l == "Yes")
            follower = next(i for i in (1, 2, 3, 4) if i != leader)
            self._sql(http_p,
                      f"ADMIN ADD REPLICA rt 0 TO {follower}")
            self._wait_until(
                lambda: placement().get((follower, "No"),
                                        ("", None))[0] == "ALIVE",
                what="replica bootstrap")

            # bounded-staleness replica reads answer BEFORE promotion
            self._sql(http_p, "SET read_replica = 'follower'")
            self._sql(http_p, "SET replica_max_lag_ms = 60000")
            self._sql(http_p, "INSERT INTO rt VALUES ('h0', 1000, 1.0)")
            self._wait_until(
                lambda: all(
                    self._rows(http_p,
                               "SELECT count(*) FROM rt")[0][0] >= 1
                    for _ in range(4)),
                what="replica-mode reads before promotion")

            acked = set()
            acked_lock = threading.Lock()
            stop = threading.Event()

            def ingest():
                n = 0
                while not stop.is_set():
                    n += 1
                    batch = [(f"h{j}", 10_000 + n * 10 + j)
                             for j in range(10)]
                    vals = ", ".join(f"('{h}', {ts}, 1.0)"
                                     for h, ts in batch)
                    try:
                        self._sql(http_p,
                                  f"INSERT INTO rt VALUES {vals}",
                                  timeout=30)
                        with acked_lock:
                            acked.update(batch)
                    except Exception:  # noqa: BLE001 — unacked writes
                        pass           # are legal during the fault
                    time.sleep(0.05)

            t = threading.Thread(target=ingest, daemon=True)
            t.start()
            try:
                # let acked sync writes accumulate on the leader, with
                # the shipper streaming them to the follower
                self._wait_until(
                    lambda: len(acked) >= 100,
                    what="sustained acked ingest")
                t_kill = time.time()
                dn_procs[leader].kill()       # SIGKILL, no shutdown
                # meta detects the lost lease and promotes the (only,
                # hence most-caught-up) follower via the atomic
                # route-commit path; queries keep answering throughout
                self._wait_until(
                    lambda: placement().get((follower, "Yes"),
                                            ("", None))[0] == "ALIVE",
                    timeout=60, what="follower promotion")
                handoff_s = time.time() - t_kill
                # detection is bounded by the lease window; the full
                # handoff adds salvage/replay + heartbeat cadence slack
                assert handoff_s < 10 * LEASE_S, handoff_s
                # replica-mode reads still answer AFTER promotion (the
                # pool degrades to the new leader when no follower is
                # attached)
                assert self._rows(
                    http_p, "SELECT count(*) FROM rt")[0][0] > 0
            finally:
                stop.set()
                t.join(timeout=60)

            # post-promotion liveness: new writes ack through the
            # promoted leader
            self._sql(http_p,
                      "INSERT INTO rt VALUES ('h_post', 99000, 1.0)")

            # --- integrity: EVERY acked row exactly once — the kill -9
            # loss domain is empty because acks waited on fsync and
            # promotion salvaged the dead leader's WAL tail ---
            self._sql(http_p, "SET read_replica = 'leader'")

            def settled():
                rows = self._rows(http_p, "SELECT host, ts FROM rt")
                keys = [tuple(r) for r in rows]
                assert len(keys) == len(set(keys)), "duplicated rows"
                with acked_lock:
                    missing = acked - set(keys)
                return not missing

            self._wait_until(settled, timeout=60,
                             what="zero acked-row loss")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestDistributedIngest:
    """Auto create/alter ingest through a distributed frontend (the
    HTTP/Influx/OpenTSDB handler path on a cluster router)."""

    @pytest.fixture()
    def fe(self, tmp_path):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.meta import MetaClient
        datanodes, clients = {}, {}
        srv = MetaSrv(MemKv())
        meta = MetaClient(srv)
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        yield fe
        for dn in datanodes.values():
            dn.shutdown()

    def test_auto_create_and_insert(self, fe):
        n = fe.handle_row_insert(
            "autodist",
            {"host": ["a", "b"], "greptime_timestamp": [1, 2],
             "v": [1.0, 2.0]}, tag_columns=["host"])
        assert n == 2
        out = fe.do_query("SELECT count(*) AS c FROM autodist")[-1]
        assert next(out.batches[0].rows())[0] == 2

    def test_auto_alter_adds_field(self, fe):
        fe.handle_row_insert(
            "evolving", {"host": ["a"], "greptime_timestamp": [1],
                         "v": [1.0]}, tag_columns=["host"])
        n = fe.handle_row_insert(
            "evolving", {"host": ["a"], "greptime_timestamp": [2],
                         "v": [2.0], "extra": [7.5]}, tag_columns=["host"])
        assert n == 1
        out = fe.do_query("SELECT sum(extra) AS s FROM evolving")[-1]
        assert next(out.batches[0].rows())[0] == 7.5

    def test_new_tag_rejected(self, fe):
        from greptimedb_tpu.errors import InvalidArgumentsError
        fe.handle_row_insert(
            "tagged", {"host": ["a"], "greptime_timestamp": [1],
                       "v": [1.0]}, tag_columns=["host"])
        with pytest.raises(InvalidArgumentsError, match="tag"):
            fe.handle_row_insert(
                "tagged", {"host": ["a"], "dc": ["x"],
                           "greptime_timestamp": [2], "v": [2.0]},
                tag_columns=["host", "dc"])


class TestDistributedLockAndElection:
    """Reference: meta-srv/src/lock/ + election/etcd.rs — KV-lease based."""

    def test_lock_mutual_exclusion(self):
        from greptimedb_tpu.meta.lock import DistributedLock
        kv = MemKv()
        a = DistributedLock(kv, "ddl", holder="a")
        b = DistributedLock(kv, "ddl", holder="b")
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.try_acquire()            # re-entrant renewal
        a.release()
        assert b.try_acquire()

    def test_expired_lease_taken_over(self):
        from greptimedb_tpu.meta.lock import DistributedLock
        kv = MemKv()
        a = DistributedLock(kv, "x", holder="a", lease_secs=5)
        b = DistributedLock(kv, "x", holder="b", lease_secs=5)
        t0 = time.time()
        assert a.try_acquire(now=t0)
        assert not b.try_acquire(now=t0 + 2)
        assert b.try_acquire(now=t0 + 6)  # a's lease expired
        assert a.holder_of(now=t0 + 7) == "b"

    def test_stale_release_does_not_break_new_holder(self):
        # release() must be compare-and-delete: after a's lease expires and
        # b takes over, a's late release must NOT delete b's lock
        from greptimedb_tpu.meta.lock import DistributedLock
        kv = MemKv()
        a = DistributedLock(kv, "x", holder="a", lease_secs=5)
        b = DistributedLock(kv, "x", holder="b", lease_secs=5)
        t0 = time.time()
        assert a.try_acquire(now=t0)
        assert b.try_acquire(now=t0 + 6)   # takeover after expiry
        assert not a.release()             # stale holder: no-op
        assert b.holder_of(now=t0 + 7) == "b"

    def test_compare_and_delete_atomicity(self):
        kv = MemKv()
        kv.put("k", b"v1")
        assert not kv.compare_and_delete("k", b"other")
        assert kv.get("k") == b"v1"
        assert kv.compare_and_delete("k", b"v1")
        assert kv.get("k") is None

    def test_context_manager(self):
        from greptimedb_tpu.meta.lock import DistributedLock
        kv = MemKv()
        with DistributedLock(kv, "cm", holder="a") as lock:
            assert lock.holder_of() == "a"
        assert DistributedLock(kv, "cm", holder="b").try_acquire()

    def test_election_single_leader(self):
        from greptimedb_tpu.meta.lock import Election
        kv = MemKv()
        e1 = Election(kv, "meta-1")
        e2 = Election(kv, "meta-2")
        assert e1.campaign_once()
        assert not e2.campaign_once()
        assert e1.is_leader and not e2.is_leader
        assert e2.leader() == "meta-1"

    def test_election_failover_on_lease_expiry(self):
        from greptimedb_tpu.meta.lock import Election
        kv = MemKv()
        e1 = Election(kv, "meta-1", lease_secs=5)
        e2 = Election(kv, "meta-2", lease_secs=5)
        t0 = time.time()
        assert e1.campaign_once(now=t0)
        # leader dies; challenger wins after the lease lapses
        assert not e2.campaign_once(now=t0 + 2)
        assert e2.campaign_once(now=t0 + 6)
        assert e2.leader() == "meta-2"
