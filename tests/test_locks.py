"""Lock-order race detector (common/locks.py): the runtime half of
greptlint. The ABBA tests MUST fail if the detector's raise is removed —
they are the proof the detector detects — and the storage concurrency
scenario proves it stays quiet on the real flush+scan+compaction
interleavings (no false positives on code we ship).
"""

import concurrent.futures
import subprocess
import sys
import threading

import pytest

from greptimedb_tpu.common import locks
from greptimedb_tpu.common.locks import (IoUnderLockError, LockOrderError,
                                         TrackedLock, TrackedRLock)


@pytest.fixture(autouse=True)
def _fresh_graph():
    """Lock-order edges are global by design (cross-test accumulation is
    how real inversions surface); these tests seed their own unique lock
    classes, so isolate them from each other."""
    locks.reset_graph()
    yield
    locks.reset_graph()


class TestAbbaDetection:
    def test_abba_cycle_raises_instead_of_deadlocking(self):
        a = TrackedLock("t.abba_a", force=True)
        b = TrackedLock("t.abba_b", force=True)

        def leg_one():                  # establishes the order a -> b
            with a:
                with b:
                    pass

        t = threading.Thread(target=leg_one)
        t.start()
        t.join()
        assert "t.abba_b" in locks.order_edges().get("t.abba_a", set())

        with pytest.raises(LockOrderError, match="cycle"):
            with b:
                with a:                 # inverse order: ABBA
                    pass

    def test_error_names_both_sides_and_prior_stack(self):
        a = TrackedLock("t.named_a", force=True)
        b = TrackedLock("t.named_b", force=True)
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError) as ei:
            with b:
                with a:
                    pass
        msg = str(ei.value)
        assert "t.named_a" in msg and "t.named_b" in msg
        assert "first seen at" in msg   # the acquisition that set the order

    def test_transitive_cycle_through_third_lock(self):
        a = TrackedLock("t.tri_a", force=True)
        b = TrackedLock("t.tri_b", force=True)
        c = TrackedLock("t.tri_c", force=True)
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(LockOrderError):   # c -> a closes a->b->c->a
            with c:
                with a:
                    pass

    def test_two_instances_of_same_class_nested_raises(self):
        r1 = TrackedLock("t.same_class", force=True)
        r2 = TrackedLock("t.same_class", force=True)
        with pytest.raises(LockOrderError, match="same"):
            with r1:
                with r2:
                    pass

    def test_consistent_order_never_raises(self):
        a = TrackedLock("t.ok_a", force=True)
        b = TrackedLock("t.ok_b", force=True)
        for _ in range(3):
            with a:
                with b:
                    pass


class TestLockProtocol:
    def test_rlock_reentry_is_fine(self):
        r = TrackedRLock("t.rlock", force=True)
        with r:
            with r:
                assert locks.held_locks().count("t.rlock") == 2

    def test_nonreentrant_self_reacquire_raises_not_deadlocks(self):
        lk = TrackedLock("t.self_dead", force=True)
        with lk:
            with pytest.raises(LockOrderError, match="re-acquired"):
                lk.acquire()

    def test_try_acquire_records_no_order_edge(self):
        """Non-blocking acquisition cannot deadlock, so it must not
        poison the order graph."""
        a = TrackedLock("t.try_a", force=True)
        b = TrackedLock("t.try_b", force=True)
        with a:
            assert b.acquire(blocking=False)
            b.release()
        assert "t.try_b" not in locks.order_edges().get("t.try_a", set())
        with b:                          # inverse order is still legal
            with a:
                pass

    def test_release_supports_non_lifo(self):
        a = TrackedLock("t.lifo_a", force=True)
        b = TrackedLock("t.lifo_b", force=True)
        a.acquire()
        b.acquire()
        a.release()                      # out of order
        assert locks.held_locks() == ["t.lifo_b"]
        b.release()
        assert locks.held_locks() == []


class TestIoUnderLock:
    def test_io_failpoint_site_under_memory_lock_raises(self):
        from greptimedb_tpu.common import failpoint as fp
        lk = TrackedLock("t.mem_only", io_ok=False, force=True)
        with lk:
            with pytest.raises(IoUnderLockError, match="objstore_read"):
                fp.fires("objstore_read")

    def test_io_ok_lock_permits_io_sites(self):
        from greptimedb_tpu.common import failpoint as fp
        lk = TrackedLock("t.io_fine", io_ok=True, force=True)
        with lk:
            fp.fires("objstore_read")    # no raise

    def test_non_io_site_is_ignored(self):
        from greptimedb_tpu.common import failpoint as fp
        lk = TrackedLock("t.mem_only2", io_ok=False, force=True)
        with lk:
            fp.fires("manifest_commit")  # metadata site, not blocking I/O


class TestInactiveMode:
    def test_disabled_factory_returns_raw_lock(self):
        """GREPTIME_LOCK_CHECK=0 ⇒ plain threading primitives, nothing
        wrapped — production pays zero per-acquire cost (bench.py
        asserts the ns differential)."""
        code = (
            "from greptimedb_tpu.common.locks import TrackedLock, "
            "TrackedRLock, enabled\n"
            "import threading\n"
            "assert not enabled()\n"
            "assert type(TrackedLock('x')) is type(threading.Lock())\n"
            "assert type(TrackedRLock('x')) is type(threading.RLock())\n"
            "print('RAW_OK')\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120, env={"GREPTIME_LOCK_CHECK": "0", "PATH": "/usr/bin",
                              "JAX_PLATFORMS": "cpu"})
        assert "RAW_OK" in proc.stdout, proc.stderr

    def test_enabled_under_pytest(self):
        assert locks.enabled()           # auto-on: pytest in sys.modules


class TestNoFalsePositivesOnStorage:
    """The detector wraps ~10 real storage locks; the flush+scan+
    compaction interleaving from tests/test_concurrency.py must run
    clean — a detector that cries wolf gets turned off."""

    def test_flush_scan_compact_interleaving_is_clean(self, tmp_path):
        from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                      DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance

        assert locks.enabled()
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False,
            flush_size_bytes=64 * 1024))   # tiny: flushes trigger mid-test
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        try:
            fe.do_query("CREATE TABLE lk (host STRING, ts TIMESTAMP TIME"
                        " INDEX, v DOUBLE, PRIMARY KEY(host))")
            stop = threading.Event()
            errors = []

            def writer():
                try:
                    for i in range(200):
                        fe.do_query(f"INSERT INTO lk VALUES"
                                    f" ('h{i % 4}', {i}, {float(i)})")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def reader():
                try:
                    while not stop.is_set():
                        fe.do_query("SELECT count(*) FROM lk")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def flusher():
                t = fe.catalog.table("greptime", "public", "lk")
                try:
                    while not stop.is_set():
                        t.flush()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            with concurrent.futures.ThreadPoolExecutor(3) as pool:
                w = pool.submit(writer)
                pool.submit(reader)
                pool.submit(flusher)
                w.result(timeout=120)
                stop.set()
            bad = [e for e in errors if isinstance(e, LockOrderError)]
            assert not bad, f"false positive on real storage path: {bad}"
            assert not errors, errors
            out = fe.do_query("SELECT count(*) FROM lk")[-1]
            assert next(out.batches[0].rows())[0] == 200
        finally:
            fe.shutdown()

    def test_storage_locks_are_tracked_under_pytest(self, tmp_path):
        """The swap-in is live: a freshly built engine's locks are
        _Tracked instances, named, and the writer lock is reentrant."""
        from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine

        eng = StorageEngine(EngineConfig(data_home=str(tmp_path / "s")))
        assert isinstance(eng._lock, locks._Tracked)
        assert eng._lock.name == "storage.engine"


class TestConditionProtocol:
    """Regression: LocalScheduler builds threading.Condition over its
    (now tracked) lock; without _is_owned/_release_save/_acquire_restore
    on _Tracked, Condition's acquire(False) fallback misreads the owner
    probing its own non-reentrant lock as a self-deadlock — every
    background worker died at _wake.wait()."""

    def test_condition_wait_notify_over_tracked_lock(self):
        lk = TrackedLock("t.cond", io_ok=False, force=True)
        cond = threading.Condition(lk)
        ready = []

        def consumer():
            with cond:
                while not ready:
                    cond.wait(timeout=10)
                ready.append("consumed")

        t = threading.Thread(target=consumer)
        t.start()
        import time
        time.sleep(0.05)                 # let the consumer park in wait()
        with cond:
            ready.append("produced")
            cond.notify()
        t.join(timeout=10)
        assert not t.is_alive()
        assert ready == ["produced", "consumed"]

    def test_wait_releases_held_bookkeeping(self):
        """While parked in cond.wait() the thread must not count as
        holding the lock (the IO check and order graph read that list)."""
        lk = TrackedLock("t.cond_held", force=True)
        cond = threading.Condition(lk)
        observed = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                observed.append(list(locks.held_locks()))

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.05)
        with cond:                       # acquirable ⇒ waiter released it
            cond.notify()
        t.join(timeout=10)
        assert observed == [["t.cond_held"]]   # reacquired after wait
        assert locks.held_locks() == []

    def test_condition_over_tracked_rlock(self):
        lk = TrackedRLock("t.cond_r", force=True)
        cond = threading.Condition(lk)
        with cond:
            with lk:                     # re-entry while conditioned
                pass
            assert not cond.wait(timeout=0.01)  # times out, then restores
            assert locks.held_locks() == ["t.cond_r"]
        assert locks.held_locks() == []

    def test_scheduler_background_jobs_run_under_detector(self):
        """End to end: the real LocalScheduler (Condition over a tracked
        lock) still runs jobs with the detector on."""
        from greptimedb_tpu.storage.scheduler import LocalScheduler
        assert locks.enabled()
        s = LocalScheduler(max_inflight=2, name="lk-test")
        try:
            hs = [s.submit(f"j{i}", lambda i=i: i * i) for i in range(4)]
            assert [h.wait(10) for h in hs] == [0, 1, 4, 9]
        finally:
            s.stop()
