"""Live process list + cooperative KILL tests (ISSUE 8).

Covers the active-statement registry (common/process_list.py), its SQL
surfaces (SHOW PROCESSLIST, information_schema.processes, KILL), live
resource totals off the running statement's ExecStats collector, and
the cancellation contract: a killed streamed scan or dist scatter
terminates at the next batch boundary AND releases its pool slots (no
orphan futures), while killing an unknown/finished id is a clean user
error.
"""

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.common import failpoint, process_list
from greptimedb_tpu.common.process_list import ProcessRegistry
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import InvalidArgumentsError, QueryCancelledError
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.query.stream_exec import (configure_streaming,
                                              stream_threshold_rows)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.reset()
    yield
    failpoint.reset()


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    frontend = FrontendInstance(dn)
    frontend.start()
    yield frontend
    frontend.shutdown()


def _pydict(fe, sql):
    out = fe.do_query(sql)[-1]
    return out.batches[0].to_pydict()


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_track_register_deregister(self):
        reg = ProcessRegistry(node="test")
        with process_list.track("SELECT 1", protocol="mysql",
                                trace_id="abc") as entry:
            assert process_list.current() is entry
            # the global registry is separate from this local one; check
            # the entry row shape off the entry itself
            row = entry.row()
            assert row["query"] == "SELECT 1"
            assert row["protocol"] == "mysql"
            assert row["state"] == "running"
            assert row["trace_id"] == "abc"
        assert process_list.current() is None
        assert len(reg) == 0

    def test_kill_unknown_id_clean_error(self):
        reg = ProcessRegistry()
        with pytest.raises(InvalidArgumentsError, match="no such running"):
            reg.kill(424242)

    def test_kill_trips_check_cancelled(self):
        reg = ProcessRegistry()
        entry = reg.register("SELECT slow", "http", "", "", None)
        with process_list.install(entry):
            process_list.check_cancelled()          # not yet
            reg.kill(entry.id)
            assert entry.state() == "cancelling"
            with pytest.raises(QueryCancelledError):
                process_list.check_cancelled()
        reg.deregister(entry)
        # killing it AGAIN after it finished: clean error, not a crash
        with pytest.raises(InvalidArgumentsError):
            reg.kill(entry.id)

    def test_check_cancelled_noop_outside_statement(self):
        process_list.check_cancelled()              # no tracked statement

    def test_propagate_carries_entry_into_workers(self):
        """telemetry.propagate must carry the process entry, so a KILL
        is observable from pool workers too."""
        from greptimedb_tpu.common.runtime import parallel_map
        reg = ProcessRegistry()
        entry = reg.register("SELECT fanout", "http", "", "", None)
        reg.kill(entry.id)
        with process_list.install(entry):
            with pytest.raises(QueryCancelledError):
                list(parallel_map(
                    lambda _: process_list.check_cancelled(), [1, 2],
                    max_workers=2))
        reg.deregister(entry)


# ---------------------------------------------------------------------------
# SQL surfaces
# ---------------------------------------------------------------------------

class TestSqlSurfaces:
    def test_show_processlist_shows_itself(self, fe):
        d = _pydict(fe, "SHOW PROCESSLIST")
        assert "SHOW PROCESSLIST" in d["Info"]
        i = d["Info"].index("SHOW PROCESSLIST")
        assert d["State"][i] == "running"
        assert d["Protocol"][i] == "http"
        assert d["Trace_id"][i]

    def test_show_full_processlist_truncation(self, fe):
        filler = ", ".join(["1"] * 200)
        d = _pydict(fe, f"SHOW PROCESSLIST -- {filler}")
        row = next(q for q in d["Info"] if q.startswith("SHOW"))
        assert len(row) == 100                      # truncated
        d = _pydict(fe, f"SHOW FULL PROCESSLIST -- {filler}")
        row = next(q for q in d["Info"] if q.startswith("SHOW"))
        assert len(row) > 100                       # full text

    def test_information_schema_processes(self, fe):
        d = _pydict(fe, "SELECT id, node, query, protocol, state, "
                        "elapsed_ms, rows_scanned, bytes_read, rpcs "
                        "FROM information_schema.processes")
        assert len(d["id"]) == 1
        assert "information_schema.processes" in d["query"][0]
        assert d["state"] == ["running"]
        assert d["elapsed_ms"][0] >= 0.0

    def test_kill_unknown_id_via_sql(self, fe):
        with pytest.raises(InvalidArgumentsError, match="KILL 424242"):
            fe.do_query("KILL 424242")
        with pytest.raises(InvalidArgumentsError):
            fe.do_query("KILL QUERY 424242")        # MySQL spelling

    def test_kill_parse_errors(self, fe):
        from greptimedb_tpu.sql.parser import ParserError
        with pytest.raises(ParserError):
            fe.do_query("KILL abc")


# ---------------------------------------------------------------------------
# cooperative cancellation: streamed cold scan
# ---------------------------------------------------------------------------

class TestKillStreamedScan:
    @pytest.fixture()
    def slow_scan_fe(self, fe):
        fe.do_query(
            "CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host))")
        table = fe.catalog.table("greptime", "public", "cpu")
        per = 20_000
        for chunk in range(10):   # 10 SSTs → many streamed slices
            ts = np.arange(per, dtype=np.int64) * 1000 \
                + chunk * per * 1000
            host = np.repeat(
                np.array([f"h{i}" for i in range(20)]),
                per // 20).astype(object)
            table.bulk_load({"host": host, "ts": ts,
                             "v": np.random.default_rng(chunk).random(per)})
        from greptimedb_tpu.query import stream_exec
        saved = stream_threshold_rows()
        saved_slice = stream_exec._SLICE_ROWS[0]
        # small slices: the scan must cross MANY batch boundaries so the
        # cooperative cancellation check has somewhere to fire
        configure_streaming(threshold_rows=1000, slice_rows=5000)
        yield fe
        configure_streaming(threshold_rows=saved, slice_rows=saved_slice)

    def test_kill_terminates_within_one_slice(self, slow_scan_fe):
        fe = slow_scan_fe
        fe.do_query("SET failpoint_stream_slice = 'delay(150)'")
        outcome = []

        def run():
            try:
                fe.do_query("SELECT host, max(v) FROM cpu GROUP BY host")
                outcome.append("completed")
            except QueryCancelledError:
                outcome.append("cancelled")

        t = threading.Thread(target=run)
        t.start()
        pid = live = None
        for _ in range(400):                 # await live progress facts
            rows = [r for r in process_list.REGISTRY.rows()
                    if "GROUP BY" in r["query"]]
            if rows and rows[0]["bytes_read"] > 0:
                pid, live = rows[0]["id"], rows[0]
                break
            time.sleep(0.01)
        assert pid is not None, "query never appeared in the registry"
        assert live["state"] == "running"
        t0 = time.perf_counter()
        fe.do_query(f"KILL {pid}")
        t.join(timeout=15)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        assert outcome == ["cancelled"], outcome
        # one slice boundary = one 150ms delay (+ slack for a slow box)
        assert elapsed_ms < 5000, f"took {elapsed_ms:.0f}ms after KILL"
        # gone from the view, and the id is now a clean error
        assert not any(r["id"] == pid
                       for r in process_list.REGISTRY.rows())
        with pytest.raises(InvalidArgumentsError):
            fe.do_query(f"KILL {pid}")

    def test_live_rows_scanned_progress(self, slow_scan_fe):
        """Acceptance: a slow query shows LIVE rows-scanned counts in
        the processes view while it runs, not only at the end."""
        fe = slow_scan_fe
        fe.do_query("SET failpoint_stream_slice = 'delay(100)'")
        seen = []

        def run():
            try:
                fe.do_query("SELECT host, max(v) FROM cpu GROUP BY host")
            except QueryCancelledError:
                pass

        t = threading.Thread(target=run)
        t.start()
        pid = None
        try:
            for _ in range(600):
                rows = [r for r in process_list.REGISTRY.rows()
                        if "GROUP BY" in r["query"]]
                if rows:
                    pid = rows[0]["id"]
                    if rows[0]["rows_scanned"] > 0:
                        seen.append(rows[0]["rows_scanned"])
                        break
                time.sleep(0.01)
        finally:
            if pid is not None:
                try:
                    process_list.REGISTRY.kill(pid)
                except InvalidArgumentsError:
                    pass
            t.join(timeout=15)
        assert seen and seen[0] > 0

    def test_killed_scan_releases_stream_workers(self, slow_scan_fe):
        """After a kill, the per-scan transient pool must wind down (the
        prefetched slice futures are cancelled in the loop's finally) —
        the scan thread joins promptly instead of draining every
        remaining prefetched slice."""
        fe = slow_scan_fe
        fe.do_query("SET failpoint_stream_slice = 'delay(200)'")
        t = threading.Thread(
            target=lambda: pytest.raises(
                QueryCancelledError,
                fe.do_query, "SELECT host, max(v) FROM cpu GROUP BY host"))
        t.start()
        for _ in range(400):
            rows = [r for r in process_list.REGISTRY.rows()
                    if "GROUP BY" in r["query"]]
            if rows and rows[0]["bytes_read"] > 0:
                process_list.REGISTRY.kill(rows[0]["id"])
                break
            time.sleep(0.01)
        t0 = time.perf_counter()
        t.join(timeout=20)
        assert not t.is_alive()
        # 10 SSTs × 200ms ≈ 2s serial drain; a prompt exit proves the
        # queued prefetches were cancelled, not awaited
        assert (time.perf_counter() - t0) < 2.0


# ---------------------------------------------------------------------------
# cooperative cancellation: distributed scatter-gather
# ---------------------------------------------------------------------------

class TestKillDistScatter:
    @pytest.fixture()
    def cluster(self, tmp_path):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(srv)
        datanodes, clients = {}, {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        fe.do_query(
            "CREATE TABLE hashed (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host)) "
            "PARTITION BY HASH (host) PARTITIONS 8")
        fe.do_query("INSERT INTO hashed VALUES " + ", ".join(
            f"('h{i}', {1000 + i}, 1.0)" for i in range(64)))
        yield fe
        for dn in datanodes.values():
            dn.shutdown()

    def test_kill_in_flight_scatter_releases_pool(self, cluster):
        from greptimedb_tpu.common.runtime import (configure_dist_fanout,
                                                   dist_fanout,
                                                   dist_runtime)
        fe = cluster
        saved = dist_fanout()
        # serial fan-out: the second datanode's RPC sits QUEUED in the
        # shared dist pool while the first one crawls — exactly the
        # orphan-future shape the gather's finally must cancel
        configure_dist_fanout(1)
        failpoint.configure("dist_rpc", "delay(400)")
        outcome = []

        def run():
            try:
                fe.do_query("SELECT host, max(v) FROM hashed "
                            "GROUP BY host")
                outcome.append("completed")
            except QueryCancelledError:
                outcome.append("cancelled")

        t = threading.Thread(target=run)
        t.start()
        try:
            pid = None
            for _ in range(400):
                rows = [r for r in process_list.REGISTRY.rows()
                        if "GROUP BY" in r["query"]]
                if rows:
                    pid = rows[0]["id"]
                    break
                time.sleep(0.01)
            assert pid is not None
            time.sleep(0.1)            # first RPC in flight, second queued
            fe.do_query(f"KILL {pid}")
            t.join(timeout=15)
        finally:
            failpoint.configure("dist_rpc", None)
            configure_dist_fanout(saved)
        assert outcome == ["cancelled"], outcome
        # no orphan futures left occupying the shared dist pool: the
        # queue drains and fresh work gets a slot immediately
        pool = dist_runtime()
        deadline = time.time() + 5
        while pool._work_queue.qsize() and time.time() < deadline:
            time.sleep(0.02)
        assert pool._work_queue.qsize() == 0
        t0 = time.perf_counter()
        pool.submit(lambda: None).result(timeout=5)
        assert (time.perf_counter() - t0) < 1.0

    def test_dist_processes_view_counts_rpcs(self, cluster):
        fe = cluster
        fe.do_query("SELECT host, max(v) FROM hashed GROUP BY host")
        st = fe.query_engine.last_exec_stats
        assert st is not None and st.totals()["rpcs"] >= 1

    def test_dist_frontend_names_the_node(self, cluster):
        """A cluster frontend labels its processes rows 'frontend', so a
        multi-frontend operator can tell which process owns a statement
        (KILL is per-process) — and a standalone built later relabels."""
        d = cluster.do_query(
            "SELECT node FROM information_schema.processes"
        )[-1].batches[0].to_pydict()
        assert d["node"] == ["frontend"]


# ---------------------------------------------------------------------------
# satellite: SET unification across frontends
# ---------------------------------------------------------------------------

class TestSetVariableUnified:
    """`SET` of an unknown variable must behave IDENTICALLY on the
    standalone and distributed frontends: both route through
    apply_set_variable, so both raise the same InvalidArgumentsError,
    and both silently accept the wire-client compat boilerplate."""

    @pytest.fixture()
    def dist_fe(self, tmp_path):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(srv)
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "dn1"), node_id=1,
            register_numbers_table=False))
        dn.start()
        srv.register_datanode(Peer(1, "dn1"))
        srv.handle_heartbeat(1)
        frontend = DistInstance(meta, {1: LocalDatanodeClient(dn)})
        yield frontend
        dn.shutdown()

    @pytest.mark.parametrize("which", ["standalone", "distributed"])
    def test_unknown_variable_errors_identically(self, which, fe,
                                                 dist_fe):
        target = fe if which == "standalone" else dist_fe
        with pytest.raises(InvalidArgumentsError,
                           match="unknown session variable"):
            target.do_query("SET slow_query_treshold_ms = 5")  # typo'd

    @pytest.mark.parametrize("which", ["standalone", "distributed"])
    def test_compat_and_known_knobs_accepted(self, which, fe, dist_fe):
        target = fe if which == "standalone" else dist_fe
        target.do_query("SET autocommit = 1")            # client compat
        target.do_query("SET extra_float_digits = 3")    # pg compat
        target.do_query("SET slow_query_threshold_ms = 0")   # real knob
        target.do_query("SET self_monitor_retention_ms = 3600000")
        from greptimedb_tpu.monitor.scraper import (configure_retention,
                                                    retention_ms)
        assert retention_ms() == 3600000
        configure_retention(7 * 24 * 3600 * 1000)
