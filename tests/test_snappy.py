"""Snappy codec tests: native C++ compressor + Python fallback parity.

The Prometheus remote R/W path depends on this codec (reference uses the
snappy crate); both implementations must read each other's output.
"""

import numpy as np
import pytest

from greptimedb_tpu.utils import snappy


CASES = [
    b"",
    b"a",
    b"abcabcabcabc",
    b"hello world " * 1000,
    bytes(np.random.default_rng(0).integers(0, 256, 50_000,
                                            dtype=np.uint8)),
    b"\x00" * 100_000,
]


@pytest.mark.parametrize("raw", CASES, ids=range(len(CASES)))
def test_roundtrip(raw):
    assert snappy.decompress(snappy.compress(raw)) == raw


@pytest.mark.parametrize("raw", CASES, ids=range(len(CASES)))
def test_cross_implementation(raw):
    # python decoder reads native output; native decoder reads
    # literal-only python output
    assert snappy._py_decompress(snappy.compress(raw)) == raw
    assert snappy.decompress(snappy._py_compress(raw)) == raw


def test_compression_actually_compresses():
    if snappy._load() is None:
        pytest.skip("native snappy unavailable")
    raw = b"time series data " * 4096
    assert len(snappy.compress(raw)) < len(raw) // 5


def test_corrupt_input_rejected():
    with pytest.raises(ValueError):
        snappy.decompress(b"\x20\x0f\xff\xff\xff")


def test_remote_write_roundtrip():
    """End-to-end through the Prometheus codec helpers: the native
    compressor's output decodes back to the same series."""
    from greptimedb_tpu.servers.prometheus import (
        TimeSeries, decode_write_request, encode_write_request)
    series = [TimeSeries(
        labels={"__name__": "cpu_usage", "host": "h1"},
        samples=[(1.5, 1000), (2.5, 2000)])]
    body = encode_write_request(series)
    got = decode_write_request(body)
    assert got == series
