CREATE TABLE dist_gb (host STRING, n BIGINT, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host, n)) PARTITION BY RANGE COLUMNS (n) (PARTITION p0 VALUES LESS THAN (10), PARTITION p1 VALUES LESS THAN (MAXVALUE));

INSERT INTO dist_gb VALUES ('a', 1, 1000, 1.0), ('a', 15, 2000, 2.0), ('b', 2, 3000, 3.0), ('b', 20, 4000, 4.0), ('a', 5, 5000, 5.0);

SELECT host, count(*), sum(v), avg(v) FROM dist_gb GROUP BY host ORDER BY host;

SELECT host, max(v) FROM dist_gb GROUP BY host HAVING max(v) > 3.5 ORDER BY host;

DROP TABLE dist_gb;
