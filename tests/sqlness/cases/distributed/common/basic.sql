CREATE TABLE dist_basic (n BIGINT, ts TIMESTAMP TIME INDEX, row_id BIGINT) PARTITION BY RANGE COLUMNS (n) (PARTITION r0 VALUES LESS THAN (5), PARTITION r1 VALUES LESS THAN (9), PARTITION r2 VALUES LESS THAN (MAXVALUE));

INSERT INTO dist_basic VALUES (1, 1000, 1), (2, 2000, 2), (3, 3000, 3), (5, 5000, 5), (6, 6000, 6), (8, 8000, 8), (9, 9000, 9), (10, 10000, 10);

SELECT * FROM dist_basic ORDER BY n;

SELECT count(*), sum(n), avg(n), min(n), max(n) FROM dist_basic;

SELECT n FROM dist_basic WHERE n > 5 ORDER BY n;

SELECT count(*) FROM dist_basic WHERE n < 9;

DELETE FROM dist_basic WHERE n = 6;

SELECT count(*), sum(n) FROM dist_basic;

DROP TABLE dist_basic;
