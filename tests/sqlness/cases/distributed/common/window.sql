CREATE TABLE dist_win (host STRING, n BIGINT, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host, n)) PARTITION BY RANGE COLUMNS (n) (PARTITION w0 VALUES LESS THAN (10), PARTITION w1 VALUES LESS THAN (MAXVALUE));

INSERT INTO dist_win VALUES ('a', 1, 1000, 5.0), ('a', 15, 2000, 3.0), ('b', 2, 3000, 8.0), ('b', 20, 4000, 1.0);

SELECT host, ts, v, row_number() OVER (PARTITION BY host ORDER BY ts) AS rn, sum(v) OVER (PARTITION BY host ORDER BY ts) AS cs FROM dist_win ORDER BY host, ts;

SELECT host, sum(v) AS total, rank() OVER (ORDER BY sum(v) DESC) AS rk FROM dist_win GROUP BY host ORDER BY host;

DROP TABLE dist_win;
