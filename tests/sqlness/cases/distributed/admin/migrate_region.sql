-- ADMIN MIGRATE REGION: elastic region movement between datanodes.
-- The op is async (op_id tracks it); the runner pumps the balancer to
-- completion after each statement, so placement below is settled.
CREATE TABLE mig (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                  PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE));

INSERT INTO mig VALUES ('h1', 1000, 1.0), ('h3', 1001, 2.0),
                       ('h7', 1002, 3.0), ('h9', 1003, 4.0);

-- region 0 starts on dn1 (load-based placement): move it to dn2
ADMIN MIGRATE REGION mig 0 TO 2;

-- zero acked rows lost or duplicated by the move
SELECT count(*) AS c, sum(v) AS s FROM mig;

-- placement reflects the migration; no operation is left in flight
SELECT table_name, region_number, peer_id, is_leader, status, operation
FROM information_schema.region_peers;

-- writes route to the new owner transparently
INSERT INTO mig VALUES ('h2', 1004, 5.0);

SELECT count(*) AS c FROM mig WHERE host < 'h5';

-- unknown region / unknown table / no-op target are clean errors
ADMIN MIGRATE REGION mig 7 TO 2;

ADMIN MIGRATE REGION nope 0 TO 2;

ADMIN MIGRATE REGION mig 1 TO 2;

-- everything ended up on dn2: REBALANCE moves one region back
ADMIN REBALANCE;

SELECT table_name, region_number, peer_id FROM
information_schema.region_peers;

DROP TABLE mig;
