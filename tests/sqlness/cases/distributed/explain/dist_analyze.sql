-- Per-node EXPLAIN ANALYZE tree (ISSUE 6): datanode-side ExecStats
-- cross the RPC boundary and merge under the dist_scatter line — one
-- block per node naming its actual dispatch, rows/files per stage, and
-- the node-elapsed vs network split. elapsed_ms / node_ms / network_ms
-- are wall clock and normalized by the runner.

CREATE TABLE dist_analyze (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    cpu DOUBLE,
    mem DOUBLE,
    PRIMARY KEY(host)
)
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h2'),
  PARTITION r1 VALUES LESS THAN ('h4'),
  PARTITION r2 VALUES LESS THAN ('h6'),
  PARTITION r3 VALUES LESS THAN (MAXVALUE));

INSERT INTO dist_analyze VALUES
    ('h0', 1000, 10.0, 1.0),
    ('h1', 2000, 20.0, 2.0),
    ('h2', 1000, 30.0, 3.0),
    ('h3', 3000, 40.0, 4.0),
    ('h5', 4000, 50.0, 5.0),
    ('h7', 5000, 60.0, 6.0);

-- cold full fan-out: all 4 regions survive, both datanodes of the
-- 2-node sqlness cluster answer — each gets its own stage block with
-- per-node row counts that sum to the 6 rows inserted
EXPLAIN ANALYZE SELECT host, avg(cpu), max(mem) FROM dist_analyze GROUP BY host;

-- range rule prunes to one region -> a single node block remains, and
-- its scan rows are exactly that region's share
EXPLAIN ANALYZE SELECT host, count(*) AS c FROM dist_analyze WHERE host >= 'h6' GROUP BY host;

DROP TABLE dist_analyze;
