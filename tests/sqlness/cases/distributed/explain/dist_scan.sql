-- Distributed EXPLAIN / EXPLAIN ANALYZE goldens (ISSUE 5): the pruned
-- parallel scatter-gather names its decision — regions pruned a/b,
-- fan-out=k — identically in the plan text and in the executed
-- dist_scatter stage; slowest_node_ms is wall clock and normalized by
-- the runner.

CREATE TABLE dist_scan (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    cpu DOUBLE,
    PRIMARY KEY(host)
)
PARTITION BY HASH (host) PARTITIONS 8;

INSERT INTO dist_scan VALUES
    ('h1', 1000, 10.0),
    ('h1', 2000, 20.0),
    ('h2', 1000, 30.0),
    ('h3', 4000, 40.0);

-- tag-point query: the hash rule prunes 7 of 8 regions, so exactly one
-- datanode (the one owning h1's region) is contacted
EXPLAIN SELECT host, avg(cpu) FROM dist_scan WHERE host = 'h1' GROUP BY host;

-- unfiltered group-by first (cold: every region scan-caches as `full`):
-- nothing prunes, the scatter fans out to both datanodes of the 2-node
-- sqlness cluster
EXPLAIN ANALYZE SELECT host, count(*) AS c FROM dist_scan GROUP BY host;

-- the pruned point query now runs warm (cache=hit on its one region)
EXPLAIN ANALYZE SELECT host, avg(cpu) FROM dist_scan WHERE host = 'h1' GROUP BY host;

-- SET dist_fanout = 1 serializes the scatter (differential/debug knob);
-- answers and pruning are identical, only concurrency changes
SET dist_fanout = 1;

EXPLAIN ANALYZE SELECT host, count(*) AS c FROM dist_scan GROUP BY host;

SET dist_fanout = 8;

DROP TABLE dist_scan;
