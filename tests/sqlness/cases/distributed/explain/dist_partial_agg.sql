-- Distributed aggregation v2 goldens (ISSUE 14): count(DISTINCT),
-- approx_distinct / approx_percentile / median and expression agg
-- arguments push SKETCH/moment partials down to the datanodes, and the
-- cost-based scatter planner renders its choice (with row estimates)
-- identically in EXPLAIN and EXPLAIN ANALYZE.

CREATE TABLE dpa (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    n BIGINT,
    PRIMARY KEY(host)
)
PARTITION BY HASH (host) PARTITIONS 8;

INSERT INTO dpa VALUES
    ('h1', 1000, 1.0, 1),
    ('h1', 2000, 2.0, 1),
    ('h1', 3000, 2.0, 2),
    ('h1', 4000, NULL, 2),
    ('h2', 1000, 5.0, 3),
    ('h2', 2000, NULL, 3),
    ('h3', 4000, 7.5, 4);

-- exact-set distinct partials: small per-group sets stay EXACT
SELECT host, count(DISTINCT v) AS cd, count(DISTINCT n) AS cn
FROM dpa GROUP BY host ORDER BY host;

EXPLAIN SELECT host, count(DISTINCT v) AS cd FROM dpa GROUP BY host;

-- expression agg arguments moment per-region before folding
SELECT host, sum(v * 2) AS s, avg(v + n) AS av
FROM dpa GROUP BY host ORDER BY host;

-- the approx family (documented bounds; tiny sets are exact)
SELECT host, approx_distinct(v) AS ad, approx_percentile(v, 50) AS p50
FROM dpa GROUP BY host ORDER BY host;

SELECT median(v) AS m FROM dpa;

-- SET exact_distinct = 1 refuses the sketch path: raw rows, exact at
-- any cardinality
SET exact_distinct = 1;

EXPLAIN SELECT host, count(DISTINCT v) AS cd FROM dpa GROUP BY host;

SET exact_distinct = 0;

-- EXPLAIN ANALYZE: the finalize stage reports partial frames, partial
-- wire bytes, and sketch-vs-exact per aggregate
EXPLAIN ANALYZE SELECT host, count(DISTINCT v) AS cd, sum(v) AS s
FROM dpa GROUP BY host;

-- approx aggregates cannot materialize into a flow sink (hint, like avg)
CREATE FLOW bad_flow AS SELECT host,
    date_bin(INTERVAL '1 minute', ts) AS tb, approx_distinct(v) AS d
FROM dpa GROUP BY host, tb;

DROP TABLE dpa;
