-- information_schema.cluster_info (ISSUE 6): the meta service's
-- heartbeat-collected health view as a queryable table — node id, role,
-- address, lease state, last-seen, route-derived region counts, and the
-- heartbeat-reported ingest stats. peer_addr / last_seen_ms are
-- normalized by the runner.

SELECT peer_id, peer_type, peer_addr, lease_state, last_seen_ms, region_count
FROM information_schema.cluster_info ORDER BY peer_id;

-- region placement shows up in the view as soon as the route exists
-- (counts come from meta's routes, not from the next heartbeat)
CREATE TABLE ci_demo (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    cpu DOUBLE,
    PRIMARY KEY(host)
)
PARTITION BY HASH (host) PARTITIONS 8;

SELECT peer_id, peer_type, lease_state, region_count, approximate_rows
FROM information_schema.cluster_info ORDER BY peer_id;

DROP TABLE ci_demo;
