-- information_schema.region_peers: placement + in-flight balancer ops.
CREATE TABLE rp (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE));

INSERT INTO rp VALUES ('h1', 1000, 1.0), ('h2', 1001, 2.0),
                      ('h6', 1002, 3.0), ('h7', 1003, 4.0),
                      ('h8', 1004, 5.0);

SELECT table_name, region_number, peer_id, is_leader, status,
       route_version, operation
FROM information_schema.region_peers;

-- split the hot upper region at a chosen boundary: the parent region is
-- replaced by two children and the partition rule refines in place
ADMIN SPLIT REGION rp 1 AT 'h7';

SELECT table_name, region_number, peer_id, is_leader, status,
       route_version, operation
FROM information_schema.region_peers;

-- the refined rule round-trips through the codec and renders correctly
SHOW CREATE TABLE rp;

-- reads and writes keep answering across the refined layout
SELECT count(*) AS c, sum(v) AS s FROM rp;

SELECT count(*) AS c FROM rp WHERE host >= 'h7';

INSERT INTO rp VALUES ('h9', 1005, 6.0);

SELECT count(*) AS c FROM rp WHERE host >= 'h7';

-- a hash-partitioned table cannot split one bucket
CREATE TABLE rph (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                  PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 4;

ADMIN SPLIT REGION rph 0;

-- splitting at a value outside the region's range is a clean error
ADMIN SPLIT REGION rp 0 AT 'h6';

-- attach a read replica: region 0's leader streams its WAL tail to a
-- standby on dn2, and region_peers grows a follower row (this env's
-- cooperative heartbeats carry no region stats, so the seq/lag columns
-- stay at their no-telemetry defaults)
ADMIN ADD REPLICA rp 0 TO 2;

SELECT table_name, region_number, peer_id, is_leader, status,
       replicated_seq, lag_ms
FROM information_schema.region_peers
WHERE table_name = 'greptime.public.rp' AND region_number = 0;

-- follower regions never count toward cluster_info region_count
SELECT peer_id, region_count FROM information_schema.cluster_info
WHERE peer_type = 'datanode' ORDER BY peer_id;

-- a replica cannot stack on the leader, nor attach twice
ADMIN ADD REPLICA rp 0 TO 1;

ADMIN ADD REPLICA rp 0 TO 2;

-- detach: the follower row disappears and the standby region drops
ADMIN REMOVE REPLICA rp 0 FROM 2;

SELECT table_name, region_number, peer_id, is_leader, status
FROM information_schema.region_peers
WHERE table_name = 'greptime.public.rp' AND region_number = 0;

DROP TABLE rp;

DROP TABLE rph;
