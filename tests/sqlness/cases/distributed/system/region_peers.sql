-- information_schema.region_peers: placement + in-flight balancer ops.
CREATE TABLE rp (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                 PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h5'),
  PARTITION r1 VALUES LESS THAN (MAXVALUE));

INSERT INTO rp VALUES ('h1', 1000, 1.0), ('h2', 1001, 2.0),
                      ('h6', 1002, 3.0), ('h7', 1003, 4.0),
                      ('h8', 1004, 5.0);

SELECT table_name, region_number, peer_id, is_leader, status,
       route_version, operation
FROM information_schema.region_peers;

-- split the hot upper region at a chosen boundary: the parent region is
-- replaced by two children and the partition rule refines in place
ADMIN SPLIT REGION rp 1 AT 'h7';

SELECT table_name, region_number, peer_id, is_leader, status,
       route_version, operation
FROM information_schema.region_peers;

-- the refined rule round-trips through the codec and renders correctly
SHOW CREATE TABLE rp;

-- reads and writes keep answering across the refined layout
SELECT count(*) AS c, sum(v) AS s FROM rp;

SELECT count(*) AS c FROM rp WHERE host >= 'h7';

INSERT INTO rp VALUES ('h9', 1005, 6.0);

SELECT count(*) AS c FROM rp WHERE host >= 'h7';

-- a hash-partitioned table cannot split one bucket
CREATE TABLE rph (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE,
                  PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 4;

ADMIN SPLIT REGION rph 0;

-- splitting at a value outside the region's range is a clean error
ADMIN SPLIT REGION rp 0 AT 'h6';

DROP TABLE rp;

DROP TABLE rph;
