CREATE TABLE wf (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

INSERT INTO wf VALUES ('a', 0, 3.0), ('a', 1000, 1.0), ('a', 2000, 4.0), ('b', 0, 10.0), ('b', 1000, 20.0), ('b', 2000, 20.0);

SELECT host, ts, v, row_number() OVER (PARTITION BY host ORDER BY ts) AS rn FROM wf ORDER BY host, ts;

SELECT host, ts, v, rank() OVER (PARTITION BY host ORDER BY v) AS rk, dense_rank() OVER (PARTITION BY host ORDER BY v) AS dr FROM wf ORDER BY host, ts;

SELECT host, ts, lag(v) OVER (PARTITION BY host ORDER BY ts) AS pv, lead(v, 1, -1.0) OVER (PARTITION BY host ORDER BY ts) AS nv FROM wf ORDER BY host, ts;

SELECT host, ts, sum(v) OVER (PARTITION BY host ORDER BY ts) AS cs FROM wf ORDER BY host, ts;

SELECT host, ts, avg(v) OVER (PARTITION BY host ORDER BY ts ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS mv FROM wf ORDER BY host, ts;

SELECT host, ts, first_value(v) OVER (PARTITION BY host ORDER BY ts) AS fv, last_value(v) OVER (PARTITION BY host ORDER BY ts ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS lv FROM wf ORDER BY host, ts;

SELECT host, sum(v) AS total, rank() OVER (ORDER BY sum(v) DESC) AS rk FROM wf GROUP BY host ORDER BY host;

SELECT host, ts, count(*) OVER (PARTITION BY host) AS c FROM wf ORDER BY host, ts;

DROP TABLE wf;
