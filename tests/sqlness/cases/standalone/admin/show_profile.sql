-- ADMIN SHOW PROFILE (ISSUE 17): the continuous profiler's tree
-- surface. Sampling is wall-clock driven, so this golden sticks to the
-- deterministic surfaces — knob plumbing, validation, and the two
-- not-found paths; the sampled tree itself is asserted by
-- tests/test_profiler.py. The runner resets the profiling knobs per
-- case and normalizes sample counts / stack hashes.

SELECT count(*) FROM information_schema.profile_samples;

ADMIN SHOW PROFILE 'last';

ADMIN SHOW PROFILE 'f00dfeedf00dfeedf00dfeedf00dfeed';

SET profiling = 1;

SET profile_hz = 250;

SET profile_hz = 0.5;

SET profile_hz = 99999;

SET profile_hz = 'fast';

SET profile_retention_ms = 3600000;

SET profiling = 0;
