-- ADMIN SHOW TRACE (ISSUE 15): the durable trace store's waterfall
-- surface. With trace_sample_ratio = 1 every trace is retained, so
-- 'last' renders the immediately preceding statement's stored spans;
-- at ratio 0 a fast statement leaves nothing. Volatile columns
-- (timings) are normalized by the runner.

SET trace_sample_ratio = 1;

SELECT 1;

ADMIN SHOW TRACE 'last';

SET trace_sample_ratio = 0;

ADMIN SHOW TRACE 'f00dfeedf00dfeedf00dfeedf00dfeed';

SET trace_sample_ratio = 0.01;
