CREATE TABLE counter_metric (host STRING, ts TIMESTAMP TIME INDEX, val DOUBLE, PRIMARY KEY(host));

INSERT INTO counter_metric VALUES
    ('web1', 0, 0.0), ('web1', 15000, 15.0), ('web1', 30000, 30.0),
    ('web1', 45000, 45.0), ('web1', 60000, 60.0), ('web1', 75000, 75.0),
    ('web1', 90000, 105.0), ('web1', 105000, 135.0), ('web1', 120000, 165.0);

TQL EVAL (120, 120, '1m') rate(counter_metric[1m]);

TQL EVAL (120, 120, '1m') increase(counter_metric[1m]);

TQL EVAL (120, 120, '1m') delta(counter_metric[1m]);

TQL EVAL (120, 120, '1m') idelta(counter_metric[1m]);

TQL EVAL (120, 120, '1m') max_over_time(counter_metric[1m]);

TQL EVAL (120, 120, '1m') count_over_time(counter_metric[1m]);

TQL EVAL (120, 120, '1m') quantile_over_time(0.5, counter_metric[1m]);

TQL EVAL (120, 120, '1m') changes(counter_metric[2m]);

TQL EVAL (120, 120, '1m') resets(counter_metric[2m]);

DROP TABLE counter_metric;
