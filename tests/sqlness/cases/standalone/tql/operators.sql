CREATE TABLE reqs (host STRING, path STRING, ts TIMESTAMP TIME INDEX, val DOUBLE, PRIMARY KEY(host, path));

INSERT INTO reqs VALUES
    ('a', '/x', 0, 1.0), ('a', '/y', 0, 2.0),
    ('b', '/x', 0, 4.0), ('b', '/y', 0, 8.0);

TQL EVAL (0, 0, '5m') sum(reqs);

TQL EVAL (0, 0, '5m') sum by (host) (reqs);

TQL EVAL (0, 0, '5m') sum without (host) (reqs);

TQL EVAL (0, 0, '5m') max by (path) (reqs);

TQL EVAL (0, 0, '5m') topk(1, reqs);

TQL EVAL (0, 0, '5m') reqs{host="a"};

TQL EVAL (0, 0, '5m') reqs{host=~"a|b", path="/x"};

TQL EVAL (0, 0, '5m') reqs * 2 + 1;

DROP TABLE reqs;
