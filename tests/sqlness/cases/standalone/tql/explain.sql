CREATE TABLE cpu_seconds (host STRING, ts TIMESTAMP TIME INDEX, val DOUBLE, PRIMARY KEY(host));

INSERT INTO cpu_seconds VALUES
    ('web1', 0, 1.0), ('web1', 60000, 7.0), ('web1', 120000, 13.0),
    ('web2', 0, 2.0), ('web2', 60000, 12.0), ('web2', 120000, 22.0);

TQL EXPLAIN (0, 120, '60s') sum by (host) (rate(cpu_seconds[1m]));

SET tpu_dispatch_min_rows = 0;

TQL EXPLAIN (0, 120, '60s') sum by (host) (rate(cpu_seconds[1m]));

TQL EXPLAIN (0, 120, '60s') avg(cpu_seconds);

TQL EXPLAIN (0, 120, '60s') topk(1, cpu_seconds);

SET tpu_dispatch_min_rows = 131072;

DROP TABLE cpu_seconds;
