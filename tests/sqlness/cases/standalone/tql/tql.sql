CREATE TABLE http_requests (host STRING, ts TIMESTAMP TIME INDEX, val DOUBLE, PRIMARY KEY(host));

INSERT INTO http_requests VALUES
    ('web1', 0, 1.0), ('web1', 5000, 2.0), ('web1', 10000, 3.0),
    ('web2', 0, 10.0), ('web2', 5000, 20.0), ('web2', 10000, 30.0);

TQL EVAL (0, 10, '5s') http_requests;

TQL EVAL (0, 10, '5s') sum(http_requests);

TQL EVAL (10, 10, '5s') avg_over_time(http_requests[10s]);

DROP TABLE http_requests;
