CREATE TABLE dv (host STRING, ts TIMESTAMP TIME INDEX, a DOUBLE DEFAULT 9.5, b BIGINT DEFAULT 7, c STRING, PRIMARY KEY(host));

INSERT INTO dv (host, ts) VALUES ('x', 1000);

INSERT INTO dv (host, ts, a, c) VALUES ('y', 2000, 1.25, 'set');

INSERT INTO dv VALUES ('z', 3000, NULL, NULL, NULL);

SELECT host, a, b, c FROM dv ORDER BY host;

SELECT host, count(a), count(b), count(c) FROM dv GROUP BY host ORDER BY host;

DROP TABLE dv;
