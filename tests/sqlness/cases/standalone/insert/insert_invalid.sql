-- Error-path goldens: invalid inserts must fail with stable, rendered
-- errors — not partial writes (ISSUE 1 satellite).

CREATE TABLE invalid_insert_t (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    cpu DOUBLE,
    PRIMARY KEY(host)
);

-- unknown column
INSERT INTO invalid_insert_t (host, ts, nope) VALUES ('h1', 1000, 1.0);

-- arity mismatch: more values than columns
INSERT INTO invalid_insert_t VALUES ('h1', 1000, 1.0, 2.0);

-- type mismatch: string into DOUBLE
INSERT INTO invalid_insert_t VALUES ('h1', 1000, 'not-a-number');

-- missing the time index value
INSERT INTO invalid_insert_t (host, cpu) VALUES ('h1', 1.0);

-- unknown table
INSERT INTO no_such_table VALUES ('h1', 1000, 1.0);

-- nothing must have landed from the failed statements
SELECT count(*) FROM invalid_insert_t;

DROP TABLE invalid_insert_t;
