CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host));

INSERT INTO monitor (host, ts, cpu, memory) VALUES ('host1', 1000, 1.5, 100);

INSERT INTO monitor (host, ts, cpu) VALUES ('host2', 2000, 2.5);

INSERT INTO monitor VALUES ('host3', 3000, 3.5, 300), ('host4', 4000, 4.5, 400);

INSERT INTO monitor (ts, cpu) VALUES (5000, 5.5);

SELECT * FROM monitor ORDER BY ts;

INSERT INTO monitor (host, ts, nope) VALUES ('x', 1, 1);

INSERT INTO monitor (host, ts, cpu) VALUES ('h', 1);

DROP TABLE monitor;
