-- information_schema.cluster_info on a standalone frontend: no meta
-- service, so the view synthesizes one row for the local process with
-- live region facts (last_seen_ms is normalized by the runner).

CREATE TABLE ci_local (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                       PRIMARY KEY(host));

INSERT INTO ci_local VALUES ('a', 1000, 1.0), ('b', 2000, 2.0),
                            ('c', 3000, 3.0);

SELECT peer_id, peer_type, lease_state, region_count, approximate_rows,
       region_stats
FROM information_schema.cluster_info;

DROP TABLE ci_local;
