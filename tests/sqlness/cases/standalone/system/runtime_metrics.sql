-- information_schema.runtime_metrics (ISSUE 2): the prometheus registry
-- plus live engine gauges, queryable over SQL exactly like /metrics.
-- Counter/timer VALUES are run-dependent, so the goldens select either
-- deterministic engine gauges or name/kind only.

CREATE TABLE rm (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

INSERT INTO rm VALUES ('a', 1000, 1.0), ('b', 2000, 2.0);

-- engine gauges are synthesized from live region state: deterministic
-- on a fresh environment (1 region, 2 memtable rows, no SSTs yet)
SELECT metric_name, labels, value
    FROM information_schema.runtime_metrics
    WHERE metric_name IN ('greptime_region_count',
                          'greptime_region_memtable_rows',
                          'greptime_region_sst_files')
    ORDER BY metric_name;

-- the statement timer the frontend records for every statement is
-- exported under the same name /metrics renders
SELECT metric_name, kind
    FROM information_schema.runtime_metrics
    WHERE metric_name = 'greptime_stmt_execute_seconds_count';

DROP TABLE rm;
