-- information_schema.failpoints (ISSUE 4): the fault-injection registry
-- is queryable over SQL, and SET failpoint_<name> arms/disarms a point
-- (same registry as GREPTIME_FAILPOINTS and /v1/admin/failpoints).

SET failpoint_flush_commit = 'err';

SELECT name, action, hits, fires FROM information_schema.failpoints
    WHERE name = 'flush_commit';

-- a zero-millisecond delay is observable only through its counters:
-- each WAL append below evaluates the armed point once
SET failpoint_wal_append = 'delay(0)';

CREATE TABLE fpt (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

INSERT INTO fpt VALUES ('a', 1000, 1.0), ('b', 2000, 2.0);

INSERT INTO fpt VALUES ('c', 3000, 3.0);

SELECT name, action, hits, fires FROM information_schema.failpoints
    WHERE name LIKE 'wal_%' ORDER BY name;

-- the SST secondary-index crash/degrade points (ISSUE 13): write sits
-- between the SST data write and the sidecar publish, read degrades a
-- consult to stats-only pruning
SELECT name, action FROM information_schema.failpoints
    WHERE name LIKE 'sst_index%' ORDER BY name;

-- the continuous profiler's flush point (ISSUE 17) registers at import
SELECT name, action FROM information_schema.failpoints
    WHERE name LIKE 'profiler_%' ORDER BY name;

-- NxM one-in-N arming renders verbatim
SET failpoint_objstore_read = '1x3*err(transient)';

SELECT name, action FROM information_schema.failpoints
    WHERE name = 'objstore_read';

-- malformed actions are rejected, not armed
SET failpoint_objstore_read = 'explode';

SET failpoint_flush_commit = 'off';

SET failpoint_wal_append = 'off';

SET failpoint_objstore_read = 'off';

SELECT count(*) FROM information_schema.failpoints
    WHERE action IS NOT NULL;

DROP TABLE fpt;
