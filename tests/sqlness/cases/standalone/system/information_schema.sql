CREATE TABLE monitored (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE, PRIMARY KEY(host));

SELECT table_name, table_schema, engine FROM information_schema.tables WHERE table_schema = 'public' ORDER BY table_name;

SELECT column_name, data_type, semantic_type FROM information_schema.columns WHERE table_name = 'monitored' ORDER BY column_name;

SELECT count(*) FROM information_schema.columns WHERE table_schema = 'public' AND table_name = 'numbers';

DROP TABLE monitored;
