-- information_schema.background_jobs (ISSUE 15): background work —
-- flush, compaction, TTL sweeps, flow folds, balancer steps, WAL
-- group commits — registers live rows with region/table attribution
-- plus a last-N completed ring with durations and outcomes. Volatile
-- columns (job_id/trace_id/start_ms/duration_ms) are normalized by
-- the runner.

CREATE TABLE bj (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

INSERT INTO bj VALUES ('a', 1000, 1.0), ('b', 2000, 2.0);

ADMIN FLUSH TABLE bj;

INSERT INTO bj VALUES ('a', 3000, 3.0), ('b', 4000, 4.0);

ADMIN FLUSH TABLE bj;

ADMIN COMPACT TABLE bj;

-- two flushes and one compaction, all done, none failed; every row
-- names its region and carries a trace id into the durable trace store
SELECT kind, region, state, error
FROM information_schema.background_jobs
WHERE kind IN ('flush', 'compaction')
ORDER BY kind, job_id;

SELECT count(*) FROM information_schema.background_jobs
WHERE kind IN ('flush', 'compaction') AND trace_id != '';
