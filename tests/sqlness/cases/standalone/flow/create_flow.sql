-- Continuous rollup flows (ISSUE 3): CREATE/SHOW/DROP FLOW lifecycle +
-- error cases. The `watermark` column is wall-advancing state and is
-- normalized by the runner; rows_folded is deterministic because the
-- only fold here is the rollup-rewritten SELECT's refresh.

CREATE TABLE cpu_flow (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

INSERT INTO cpu_flow VALUES
    ('a', 0, 1.0), ('a', 30000, 3.0), ('a', 60000, 5.0),
    ('a', 90000, 7.0), ('b', 0, 10.0), ('b', 30000, 30.0),
    ('b', 60000, 50.0), ('b', 90000, 70.0);

CREATE FLOW cpu_flow_1m AS
    SELECT host, date_bin(INTERVAL '1 minute', ts) AS b,
           sum(v) AS v_sum, count(v) AS v_cnt
    FROM cpu_flow GROUP BY host, b;

SHOW FLOWS;

-- the sink is an ordinary table
SHOW TABLES LIKE 'cpu_flow_1m';

-- a compatible coarser query is served via the rollup (and its refresh
-- folds the pending rows first, advancing the watermark)
SELECT host, date_bin(INTERVAL '2 minutes', ts) AS b, sum(v), count(v), avg(v)
FROM cpu_flow GROUP BY host, b ORDER BY host, b;

-- the sink now holds one row per (host, minute)
SELECT host, ts, v_sum, v_cnt FROM cpu_flow_1m ORDER BY host, ts;

SHOW FLOWS;

-- error: non-derivable aggregate
CREATE FLOW bad_agg AS
    SELECT stddev(v) FROM cpu_flow
    GROUP BY date_bin(INTERVAL '1 minute', ts);

-- error: zero stride
CREATE FLOW bad_stride AS
    SELECT sum(v) FROM cpu_flow
    GROUP BY date_bin(INTERVAL '0 minutes', ts);

-- error: no time bucket at all
CREATE FLOW bad_groups AS
    SELECT host, sum(v) FROM cpu_flow GROUP BY host;

-- error: duplicate flow
CREATE FLOW cpu_flow_1m AS
    SELECT sum(v) AS v_sum FROM cpu_flow
    GROUP BY date_bin(INTERVAL '1 minute', ts);

DROP FLOW cpu_flow_1m;

SHOW FLOWS;

DROP FLOW cpu_flow_1m;

DROP FLOW IF EXISTS cpu_flow_1m;

-- avg flows are accepted: every fold recomputes whole buckets from the
-- source rows, so the stored avg is exact (never an avg of avgs)
CREATE FLOW cpu_flow_avg AS
    SELECT host, date_bin(INTERVAL '1 minute', ts) AS b,
           avg(v) AS v_avg, sum(v) AS v_sum, count(v) AS v_cnt
    FROM cpu_flow GROUP BY host, b;

-- this avg query is served from the rollup; its refresh folds the
-- pending source rows, storing the exact per-bucket avg in the sink
SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, avg(v)
FROM cpu_flow GROUP BY host, b ORDER BY host, b;

SELECT host, ts, v_avg FROM cpu_flow_avg ORDER BY host, ts;

DROP FLOW cpu_flow_avg;

DROP TABLE cpu_flow_avg;

DROP TABLE cpu_flow_1m;

DROP TABLE cpu_flow;
