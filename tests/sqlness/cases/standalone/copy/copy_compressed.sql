CREATE TABLE csrc (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

INSERT INTO csrc VALUES ('a', 1000, 1.5), ('b', 2000, NULL), ('c', 3000, 3.5);

COPY csrc TO '/tmp/sqlness_copy_comp.csv.gz' WITH (format='csv');

CREATE TABLE cdst (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

COPY cdst FROM '/tmp/sqlness_copy_comp.csv.gz' WITH (format='csv');

SELECT host, v FROM cdst ORDER BY host;

COPY csrc TO '/tmp/sqlness_copy_comp.json.zst' WITH (format='json', compression='zstd');

CREATE TABLE jdst (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

COPY jdst FROM '/tmp/sqlness_copy_comp.json.zst' WITH (format='json');

SELECT host, v FROM jdst ORDER BY host;

DROP TABLE csrc;

DROP TABLE cdst;

DROP TABLE jdst;
