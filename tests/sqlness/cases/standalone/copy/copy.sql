CREATE TABLE src (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

INSERT INTO src VALUES ('a', 1000, 1.5), ('b', 2000, 2.5);

COPY src TO '/tmp/sqlness_copy_out.parquet' WITH (format='parquet');

CREATE TABLE dst (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

COPY dst FROM '/tmp/sqlness_copy_out.parquet' WITH (format='parquet');

SELECT * FROM dst ORDER BY ts;

DROP TABLE src;

DROP TABLE dst;
