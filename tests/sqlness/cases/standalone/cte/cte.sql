CREATE TABLE m (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1000, 1.0), ('a', 2000, 3.0), ('b', 1000, 5.0), ('b', 2000, 7.0);

WITH hot AS (SELECT host, avg(cpu) AS c FROM m GROUP BY host) SELECT * FROM hot ORDER BY host;

WITH hot AS (SELECT host, avg(cpu) AS c FROM m GROUP BY host) SELECT max(c) FROM hot;

WITH hot AS (SELECT host, avg(cpu) AS c FROM m GROUP BY host) SELECT x.host, x.c + y.c AS s FROM hot x JOIN hot y ON x.host = y.host ORDER BY x.host;

WITH a(h, c) AS (SELECT host, avg(cpu) FROM m GROUP BY host), b AS (SELECT h FROM a WHERE c > 3) SELECT * FROM b;

WITH hot AS (SELECT host FROM m WHERE cpu > 6) SELECT count(*) FROM hot;

WITH u AS (SELECT host FROM m WHERE cpu < 2 UNION ALL SELECT host FROM m WHERE cpu > 6) SELECT host FROM u ORDER BY host;

WITH lim AS (SELECT host, cpu FROM m ORDER BY cpu DESC LIMIT 2) SELECT host, cpu FROM lim ORDER BY host, cpu;

WITH RECURSIVE r AS (SELECT 1) SELECT * FROM r;

WITH dup AS (SELECT 1), dup AS (SELECT 2) SELECT * FROM dup;

DROP TABLE m;
