CREATE TABLE m (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE, PRIMARY KEY(host));

INSERT INTO m VALUES ('a', 1000, 1.0), ('b', 2000, 2.0), ('c', 3000, 3.0);

CREATE TABLE info (host STRING, ts TIMESTAMP TIME INDEX, dc STRING, PRIMARY KEY(host));

INSERT INTO info VALUES ('a', 1, 'east'), ('b', 1, 'west'), ('d', 1, 'eu');

SELECT m.host, cpu, dc FROM m JOIN info ON m.host = info.host ORDER BY m.host;

SELECT m.host, dc FROM m LEFT JOIN info ON m.host = info.host ORDER BY m.host;

SELECT dc, sum(cpu) FROM m JOIN info ON m.host = info.host GROUP BY dc ORDER BY dc;

SELECT count(*) FROM m CROSS JOIN info;

DROP TABLE m;

DROP TABLE info;
