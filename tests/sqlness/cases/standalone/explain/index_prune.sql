-- Per-SST secondary index pruning (ISSUE 13): point/IN tag predicates
-- resolve to series-id sets through the series dictionary, and the scan
-- planner drops whole SST files through their bloom sidecars before any
-- parquet footer is opened. The prune stage reports files pruned by
-- index as index_files_pruned / index_files_checked; the elapsed_ms
-- column is normalized by the runner.

CREATE TABLE idx_prune (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

-- three flushed SSTs whose sid RANGES overlap (h4 appears in every
-- batch) but whose sid SETS differ — the layout coarse min/max stats
-- cannot prune and the bloom can
INSERT INTO idx_prune VALUES ('h1', 1000, 1.0), ('h4', 1500, 4.0);

ADMIN FLUSH TABLE idx_prune;

INSERT INTO idx_prune VALUES ('h2', 2000, 2.0), ('h4', 2500, 4.5);

ADMIN FLUSH TABLE idx_prune;

INSERT INTO idx_prune VALUES ('h3', 3000, 3.0), ('h4', 3500, 5.0);

ADMIN FLUSH TABLE idx_prune;

-- pin the dispatch floor (also resets the latency-adaptive floor) so
-- the point query takes the device path, not cpu-small-scan
SET tpu_dispatch_min_rows = 1;

-- host='h2' lives only in the second SST: the first file is dropped by
-- its sid range, the third by its bloom (its range covers h2's sid but
-- its sid set does not) — files pruned by index 2/3
EXPLAIN ANALYZE SELECT host, max(v) FROM idx_prune
    WHERE host = 'h2' GROUP BY host;

-- the differential kill switch: SET sst_index = 0 restores the
-- stats-only read path (no file pruning tier, resident scan cache)
SET sst_index = 0;

SELECT host, max(v) FROM idx_prune WHERE host = 'h2' GROUP BY host;

SET sst_index = 1;

-- IN(...) resolves to a multi-sid candidate set the same way
SELECT host, max(v) FROM idx_prune
    WHERE host IN ('h1', 'h3') GROUP BY host ORDER BY host;

-- restore defaults (these knobs are process-global)
SET tpu_dispatch_min_rows = 131072;

DROP TABLE idx_prune;
