-- EXPLAIN golden pinning the `rollup-rewrite` dispatch (ISSUE 3): a
-- GROUP BY date_bin whose stride is a multiple of a flow's stride is
-- re-targeted at the rollup sink; the rewrite line leads, the sink's
-- own dispatch decision follows. Plain EXPLAIN never folds, so the
-- sink stays empty (est_rows=0) and the text is deterministic.

CREATE TABLE cpu_roll (
    host STRING,
    ts TIMESTAMP TIME INDEX,
    v DOUBLE,
    PRIMARY KEY(host)
);

INSERT INTO cpu_roll VALUES
    ('a', 0, 1.0), ('a', 60000, 2.0), ('b', 0, 3.0);

CREATE FLOW cpu_roll_1m AS
    SELECT host, date_bin(INTERVAL '1 minute', ts) AS b,
           sum(v) AS v_sum, count(v) AS v_cnt
    FROM cpu_roll GROUP BY host, b;

-- stride 5m = 5 x flow stride: rewritten onto the sink
EXPLAIN SELECT host, date_bin(INTERVAL '5 minutes', ts) AS b,
               sum(v), avg(v)
        FROM cpu_roll GROUP BY host, b;

-- aligned time range + tag filter still rewrite
EXPLAIN SELECT date_bin(INTERVAL '1 minute', ts) AS b, count(v)
        FROM cpu_roll WHERE host = 'a' AND ts >= 60000 GROUP BY b;

-- 90s is not a multiple of 1m: raw scan
EXPLAIN SELECT date_bin(INTERVAL '90 seconds', ts) AS b, sum(v)
        FROM cpu_roll GROUP BY b;

-- an aggregate the flow does not store: raw scan
EXPLAIN SELECT date_bin(INTERVAL '5 minutes', ts) AS b, stddev(v)
        FROM cpu_roll GROUP BY b;

DROP FLOW cpu_roll_1m;

DROP TABLE cpu_roll_1m;

DROP TABLE cpu_roll;
