-- EXPLAIN ANALYZE goldens (ISSUE 2): the per-stage breakdown collected
-- by the ExecStats collector for each dispatch path — CPU columnar
-- fallback, device-resident scan cache, and streamed-cold slices. The
-- elapsed_ms column is wall clock and is normalized by the runner; the
-- stage names, row counts and path facts are deterministic.

CREATE TABLE cpu_analyze (
    hostname STRING,
    ts TIMESTAMP TIME INDEX,
    usage_user DOUBLE,
    PRIMARY KEY(hostname)
);

INSERT INTO cpu_analyze VALUES
    ('h1', 1000, 10.0),
    ('h1', 2000, 20.0),
    ('h2', 1000, 30.0);

-- pin the static floor first: SET also resets the latency-adaptive
-- floor, which earlier queries in this process may have raised
SET tpu_dispatch_min_rows = 131072;

-- small table: the cost model routes to the CPU columnar path
-- (scan -> aggregate -> project)
EXPLAIN ANALYZE SELECT hostname, avg(usage_user)
    FROM cpu_analyze GROUP BY hostname;

-- pin the dispatch floor (this also resets the latency-adaptive floor):
-- device-resident execution, scan_prep names the scan-cache outcome
SET tpu_dispatch_min_rows = 1;

EXPLAIN ANALYZE SELECT hostname, avg(usage_user)
    FROM cpu_analyze GROUP BY hostname;

-- stream the same query: one host-reduced slice; memtable rows defeat
-- the dedup-skip proof, so it reports merged_slices, not lean_slices
SET tpu_dispatch_min_rows = 1;

SET stream_threshold_rows = 2;

EXPLAIN ANALYZE SELECT hostname, avg(usage_user)
    FROM cpu_analyze GROUP BY hostname;

-- restore defaults (these knobs are process-global)
SET stream_threshold_rows = 64000000;

SET tpu_dispatch_min_rows = 131072;

DROP TABLE cpu_analyze;
