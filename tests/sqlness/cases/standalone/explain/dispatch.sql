-- EXPLAIN goldens pinning the TPU / CPU-fallback / streamed dispatch
-- decision per query shape (ISSUE 1 satellite). The dispatch line uses
-- the static floor so the text is deterministic; the SET knobs below
-- exercise every branch of the decision chain on a 3-row table.

CREATE TABLE cpu_explain (
    hostname STRING,
    ts TIMESTAMP TIME INDEX,
    usage_user DOUBLE,
    PRIMARY KEY(hostname)
);

INSERT INTO cpu_explain VALUES
    ('h1', 1000, 10.0),
    ('h1', 2000, 20.0),
    ('h2', 1000, 30.0);

-- aggregate on a tiny table: device plan exists, but the cost model
-- routes it to the CPU columnar path
EXPLAIN SELECT hostname, avg(usage_user) FROM cpu_explain GROUP BY hostname;

-- non-aggregate: plain CPU projection
EXPLAIN SELECT hostname, usage_user FROM cpu_explain WHERE usage_user > 20;

-- time-bucketed double group-by keeps the device plan shape
EXPLAIN SELECT hostname, date_bin(INTERVAL '1 hour', ts) AS bucket,
               avg(usage_user)
        FROM cpu_explain GROUP BY hostname, bucket;

-- aggregate the planner cannot lower (group by a field expression):
-- CPU aggregate fallback
EXPLAIN SELECT usage_user * 2 AS k, count(*) FROM cpu_explain GROUP BY k;

-- drop the dispatch floor: the same query now dispatches to the device
-- (resident, under the streaming threshold)
SET tpu_dispatch_min_rows = 1;

EXPLAIN SELECT hostname, avg(usage_user) FROM cpu_explain GROUP BY hostname;

-- drop the streaming threshold under the table's 3 rows: streamed-cold
SET stream_threshold_rows = 2;

EXPLAIN SELECT hostname, avg(usage_user) FROM cpu_explain GROUP BY hostname;

-- restore defaults (these knobs are process-global)
SET stream_threshold_rows = 64000000;

SET tpu_dispatch_min_rows = 131072;

DROP TABLE cpu_explain;
