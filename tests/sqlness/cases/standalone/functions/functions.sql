CREATE TABLE fx (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

INSERT INTO fx VALUES ('a', 0, 1.0), ('a', 60000, 2.0), ('a', 120000, 4.0), ('a', 180000, 8.0);

SELECT date_bin(INTERVAL '2 minutes', ts) AS bucket, sum(v) FROM fx GROUP BY bucket ORDER BY bucket;

SELECT ts, date_trunc('minute', ts) FROM fx ORDER BY ts LIMIT 2;

SELECT argmax(v) FROM fx;

SELECT percentile(v, 50) FROM fx;

SELECT abs(-2.5), sqrt(16.0), pow(2.0, 10.0);

DROP TABLE fx;
