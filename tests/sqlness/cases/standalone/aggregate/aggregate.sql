CREATE TABLE nums (host STRING, ts TIMESTAMP TIME INDEX, n BIGINT, PRIMARY KEY(host));

INSERT INTO nums VALUES ('a', 1, 1), ('a', 2, 2), ('a', 3, 3), ('b', 4, 10), ('b', 5, 20), ('b', 6, NULL);

SELECT sum(n) FROM nums;

SELECT min(n), max(n) FROM nums;

SELECT count(n), count(*) FROM nums;

SELECT host, sum(n) FROM nums GROUP BY host ORDER BY host;

SELECT host, avg(n) FROM nums GROUP BY host ORDER BY host;

SELECT sum(n) FROM nums WHERE host = 'a';

SELECT DISTINCT host FROM nums ORDER BY host;

DROP TABLE nums;
