CREATE TABLE ob (host STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(host));

INSERT INTO ob VALUES ('c', 1, 3.0), ('a', 2, 1.0), ('b', 3, 2.0), ('d', 4, NULL);

SELECT host, v FROM ob ORDER BY v;

SELECT host, v FROM ob ORDER BY v DESC;

SELECT host, v FROM ob ORDER BY host DESC LIMIT 2;

SELECT host, v FROM ob ORDER BY v LIMIT 2 OFFSET 1;

SELECT host FROM ob ORDER BY nonexistent;

DROP TABLE ob;
