"""sqlness-style golden-file SQL harness.

Reference behavior: tests/runner/src/{main,env,util}.rs + tests/cases/ —
`.sql` files run against a started server; outputs are diffed against
committed `.result` files. This is the reference's primary end-to-end
regression rig (SURVEY §4); this port executes each case file against a
fresh in-process standalone frontend and renders results in the same
shape (`Affected Rows: N` / ASCII tables / `Error: ...`).

Usage:
    python tests/sqlness/runner.py            # run all cases, diff
    python tests/sqlness/runner.py --update   # (re)generate .result files
    python tests/sqlness/runner.py name ...   # filter by substring

Pytest integration lives in tests/test_sqlness.py.
"""

from __future__ import annotations

import argparse
import difflib
import sys
import tempfile
from pathlib import Path
from typing import List, Optional

CASES_DIR = Path(__file__).parent / "cases"


def split_statements(text: str) -> List[str]:
    """Split a .sql file into ';'-terminated statements, respecting
    single-quoted strings and line comments."""
    statements, buf = [], []
    in_str = False
    in_comment = False
    for ch in text:
        if in_comment:
            buf.append(ch)
            if ch == "\n":
                in_comment = False
            continue
        if ch == "'" :
            in_str = not in_str
            buf.append(ch)
            continue
        if not in_str and ch == "-" and buf and buf[-1] == "-":
            in_comment = True
            buf.append(ch)
            continue
        if ch == ";" and not in_str:
            stmt = "".join(buf).strip()
            if stmt:
                statements.append(stmt + ";")
            buf = []
            continue
        buf.append(ch)
    tail = "".join(buf).strip()
    if tail:
        statements.append(tail)
    return statements


def _strip_comment_lines(stmt: str) -> str:
    lines = [ln for ln in stmt.splitlines()
             if not ln.lstrip().startswith("--")]
    return "\n".join(lines).strip()


#: column name -> placeholder: wall-clock / wall-advancing columns whose
#: values cannot byte-compare across runs (elapsed_ms in EXPLAIN ANALYZE;
#: flow watermark timestamps in SHOW FLOWS / information_schema.flows;
#: last-seen heartbeat times and dialed addresses in cluster_info)
_VOLATILE_COLUMNS = {"elapsed_ms": "<elapsed>", "watermark": "<watermark>",
                     "last_seen_ms": "<last_seen>", "peer_addr": "<addr>",
                     "op_id": "<op_id>",
                     # trace-store waterfall / background_jobs timings
                     # and ids (ISSUE 15)
                     "duration_ms": "<ms>", "self_ms": "<ms>",
                     "start_offset_ms": "<ms>", "start_ms": "<ms>",
                     "trace_id": "<trace>", "span_id": "<span>",
                     "parent_span_id": "<span>",
                     # continuous-profiler sample counts / stack hashes
                     # (ISSUE 17): wall-clock sampling never byte-repeats
                     "self_samples": "<n>", "total_samples": "<n>",
                     "stack_id": "<stack>"}

#: wall-clock fragments inside EXPLAIN ANALYZE detail strings: the
#: scatter's slowest-node latency, the per-node latency vector, and the
#: node rows' node-vs-network split
import re as _re  # noqa: E402

_VOLATILE_DETAIL = [
    (_re.compile(r"slowest_node_ms=[0-9.]+"), "slowest_node_ms=<ms>"),
    (_re.compile(r"node_ms=[0-9A-Za-z:./#-]+"), "node_ms=<ms>"),
    (_re.compile(r"network_ms=[0-9.]+"), "network_ms=<ms>"),
]


def _scrub_detail(v: str) -> str:
    for pattern, repl in _VOLATILE_DETAIL:
        v = pattern.sub(repl, v)
    return v


def _normalize_timings(out):
    """Replace volatile columns with fixed placeholders so goldens
    byte-compare across runs — the runner's stand-in for reference
    sqlness' result REPLACE directives. Rebuilds the batch with the
    column retyped to STRING so the pretty table renders identical
    widths every run."""
    from greptimedb_tpu.datatypes import data_type as dt
    from greptimedb_tpu.datatypes.record_batch import RecordBatch
    from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema
    from greptimedb_tpu.query.output import Output

    if not out.is_batches or not out.batches:
        return out
    if not any(set(b.schema.names()) & (set(_VOLATILE_COLUMNS) |
                                        {"detail"})
               for b in out.batches):
        return out
    batches = []
    for b in out.batches:
        data = b.to_pydict()
        cols = []
        for cs in b.schema.column_schemas:
            if cs.name in _VOLATILE_COLUMNS:
                data[cs.name] = [_VOLATILE_COLUMNS[cs.name]] * b.num_rows
                cols.append(ColumnSchema(cs.name, dt.STRING))
            else:
                if cs.name == "detail":
                    data[cs.name] = [
                        _scrub_detail(v) if isinstance(v, str) else v
                        for v in data[cs.name]]
                cols.append(cs)
        schema = Schema(cols)
        batches.append(RecordBatch.from_pydict(schema, data))
    return Output.record_batches(batches, batches[0].schema)


def render_output(out) -> str:
    from greptimedb_tpu.datatypes.record_batch import pretty_print
    out = _normalize_timings(out)
    if out.is_batches:
        if not out.batches or all(b.num_rows == 0 for b in out.batches):
            names = out.batches[0].schema.names() if out.batches else []
            if names:
                return pretty_print(out.batches)
            return "(empty)"
        return pretty_print(out.batches)
    return f"Affected Rows: {out.affected_rows or 0}"


def run_case(sql_text: str, frontend) -> str:
    """Execute a case file's statements; return the .result content."""
    from greptimedb_tpu.errors import GreptimeError
    from greptimedb_tpu.session import QueryContext

    ctx = QueryContext()
    blocks: List[str] = []
    for stmt in split_statements(sql_text):
        body = _strip_comment_lines(stmt)
        if not body:
            continue
        blocks.append(stmt)
        try:
            outputs = frontend.do_query(body, ctx)
            blocks.append(render_output(outputs[-1]))
        except GreptimeError as e:
            blocks.append(f"Error: {e}")
        except Exception as e:  # noqa: BLE001 — parser/planner crashes
            blocks.append(f"Error: {type(e).__name__}: {e}")
    return "\n\n".join(blocks) + "\n"


def make_frontend(data_home: str):
    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    dn = DatanodeInstance(DatanodeOptions(data_home=data_home,
                                          register_numbers_table=True))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    return fe


class _DistEnv:
    """2-datanode cluster frontend for cases/distributed/ (the reference
    runs the same golden cases against a distributed env,
    tests/runner/src/env.rs + tests/cases/distributed/)."""

    def __init__(self, data_home: str):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MetaClient, Peer
        from greptimedb_tpu.meta.kv import MemKv
        from greptimedb_tpu.meta.service import MetaSrv
        from greptimedb_tpu.storage.object_store import FsObjectStore
        self.datanodes = []
        self.srv = MetaSrv(MemKv())
        meta = MetaClient(self.srv)
        clients = {}
        # ONE shared object store (the elastic-region deployment shape:
        # migrate/split hand regions between nodes through it); control
        # state + WAL stay node-scoped
        shared = FsObjectStore(f"{data_home}/shared")
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{data_home}/dn{i}", node_id=i,
                register_numbers_table=False), store=shared)
            dn.start()
            dn.attach_meta(meta)
            self.datanodes.append(dn)
            clients[i] = LocalDatanodeClient(dn)
            self.srv.register_datanode(Peer(i, f"dn{i}"))
            self.srv.handle_heartbeat(i)
        self.fe = DistInstance(meta, clients)

    def do_query(self, sql: str, ctx=None):
        outs = self.fe.do_query(sql, ctx)
        self._pump_balancer()
        return outs

    def _pump_balancer(self):
        """Drive any balancer ops the statement enqueued to completion
        (the cooperative stand-in for the background tick + heartbeat
        loops, so ADMIN goldens are deterministic)."""
        for _ in range(24):
            if not self.srv.balancer.ops():
                return
            self.srv.balancer.tick()
            for dn in self.datanodes:
                resp = self.srv.handle_heartbeat(dn.opts.node_id)
                for msg in resp.mailbox:
                    dn._handle_mailbox(msg)

    def shutdown(self):
        for dn in self.datanodes:
            dn.shutdown()


def case_files(filters: List[str]) -> List[Path]:
    files = sorted(CASES_DIR.rglob("*.sql"))
    if filters:
        files = [f for f in files
                 if any(flt in str(f) for flt in filters)]
    return files


def run_one(sql_path: Path, update: bool) -> Optional[str]:
    result_path = sql_path.with_suffix(".result")
    distributed = "distributed" in sql_path.relative_to(CASES_DIR).parts
    # failpoint state/counters are process-global; a case sees them as a
    # fresh server would (system/failpoints.sql pins exact hit counts).
    # The background-job registry and trace knobs are process-global
    # too (system/background_jobs.sql pins exact job rows)
    from greptimedb_tpu.common import background_jobs, failpoint
    from greptimedb_tpu.common import profiler, trace_store
    failpoint.reset()
    background_jobs.reset()
    trace_store.configure(sample_ratio=0.01)
    # profiler knobs are process-global too; a case that SET them must
    # not leak into the next (the frontend construct installs a fresh
    # sampler, but enabled/hz/retention live at module level)
    profiler.configure(enabled=False, hz=19.0,
                       retention_ms=24 * 3600 * 1000)
    with tempfile.TemporaryDirectory() as home:
        fe = _DistEnv(home) if distributed else make_frontend(home)
        try:
            got = run_case(sql_path.read_text(), fe)
        finally:
            fe.shutdown()
    if update:
        result_path.write_text(got)
        return None
    if not result_path.exists():
        return f"{sql_path}: missing .result (run with --update)"
    want = result_path.read_text()
    if got != want:
        diff = "\n".join(difflib.unified_diff(
            want.splitlines(), got.splitlines(),
            fromfile=str(result_path), tofile="actual", lineterm=""))
        return f"{sql_path}:\n{diff}"
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="sqlness golden harness")
    parser.add_argument("--update", action="store_true",
                        help="regenerate .result files")
    parser.add_argument("filters", nargs="*",
                        help="substring filters on case paths")
    args = parser.parse_args(argv)

    failures = []
    files = case_files(args.filters)
    if not files:
        print("no cases matched", file=sys.stderr)
        return 2
    for f in files:
        err = run_one(f, args.update)
        status = "UPDATED" if args.update else ("FAIL" if err else "PASS")
        print(f"[{status}] {f.relative_to(CASES_DIR)}")
        if err:
            failures.append(err)
    if failures:
        print("\n" + "\n\n".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
    import jax
    jax.config.update("jax_platforms", "cpu")
    # run goldens in the production numeric regime (x64 off, as on TPU)
    jax.config.update("jax_enable_x64", False)
    sys.exit(main())
