"""Table layer + mito engine + catalog tests (mirrors src/mito engine tests
and src/catalog local manager tests)."""

import numpy as np
import pytest

from greptimedb_tpu import DEFAULT_CATALOG_NAME as CAT, DEFAULT_SCHEMA_NAME as SCH
from greptimedb_tpu.catalog import LocalCatalogManager, MemoryCatalogManager
from greptimedb_tpu.datatypes import data_type as dt
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.errors import (
    ColumnNotFoundError, InvalidArgumentsError, TableAlreadyExistsError)
from greptimedb_tpu.mito import MitoEngine
from greptimedb_tpu.partition.rule import (
    MAXVALUE, RangePartitionRule, rule_from_partitions)
from greptimedb_tpu.sql import parse_sql
from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
from greptimedb_tpu.table import (
    AddColumnRequest, AlterKind, AlterTableRequest, CreateTableRequest,
    DropTableRequest, NumbersTable, OpenTableRequest)


def monitor_schema():
    return Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
        ColumnSchema("memory", dt.FLOAT64),
    ])


def mk_engine(tmp):
    storage = StorageEngine(EngineConfig(data_home=str(tmp)))
    return MitoEngine(storage)


class TestMitoEngine:
    def test_create_insert_scan(self, tmp_path):
        eng = mk_engine(tmp_path)
        t = eng.create_table(CreateTableRequest(
            "monitor", monitor_schema(), primary_key_indices=[0]))
        assert t.info.ident.table_id >= 1024
        n = t.insert({"host": ["a", "b", "a"], "ts": [1000, 1000, 2000],
                      "cpu": [0.1, 0.2, 0.3], "memory": [1.0, 2.0, 3.0]})
        assert n == 3
        batches = t.scan_batches()
        rows = sorted(r for b in batches for r in b.rows())
        assert rows == [("a", 1000, 0.1, 1.0), ("a", 2000, 0.3, 3.0),
                        ("b", 1000, 0.2, 2.0)]
        raw = t.scan_raw()
        assert len(raw) == 1 and raw[0].num_rows == 3

    def test_create_if_not_exists_and_duplicate(self, tmp_path):
        eng = mk_engine(tmp_path)
        req = CreateTableRequest("t", monitor_schema())
        t1 = eng.create_table(req)
        with pytest.raises(TableAlreadyExistsError):
            eng.create_table(req)
        req2 = CreateTableRequest("t", monitor_schema(),
                                  create_if_not_exists=True)
        assert eng.create_table(req2) is t1

    def test_reopen_after_restart(self, tmp_path):
        eng = mk_engine(tmp_path)
        t = eng.create_table(CreateTableRequest(
            "monitor", monitor_schema(), primary_key_indices=[0]))
        t.insert({"host": ["a"], "ts": [1], "cpu": [0.5], "memory": [1.0]})
        t.flush()
        t.insert({"host": ["a"], "ts": [2], "cpu": [0.6], "memory": [2.0]})
        eng.close()
        # fresh engine over the same data home: WAL replay + manifest recovery
        eng2 = mk_engine(tmp_path)
        t2 = eng2.open_table(OpenTableRequest("monitor"))
        assert t2 is not None
        rows = sorted(r for b in t2.scan_batches() for r in b.rows())
        assert [(r[1], r[2]) for r in rows] == [(1, 0.5), (2, 0.6)]

    def test_alter_add_drop_rename(self, tmp_path):
        eng = mk_engine(tmp_path)
        eng.create_table(CreateTableRequest("m", monitor_schema(),
                                            primary_key_indices=[0]))
        t = eng.alter_table(AlterTableRequest(
            "m", AlterKind.ADD_COLUMNS,
            add_columns=[AddColumnRequest(ColumnSchema("load", dt.FLOAT64))]))
        assert "load" in t.schema.names()
        t.insert({"host": ["x"], "ts": [5], "cpu": [1.0], "memory": [2.0],
                  "load": [0.9]})
        rows = [r for b in t.scan_batches() for r in b.rows()]
        assert rows[0][-1] == 0.9
        t = eng.alter_table(AlterTableRequest(
            "m", AlterKind.DROP_COLUMNS, drop_columns=["memory"]))
        assert "memory" not in t.schema.names()
        with pytest.raises(InvalidArgumentsError):
            eng.alter_table(AlterTableRequest(
                "m", AlterKind.DROP_COLUMNS, drop_columns=["host"]))
        with pytest.raises(ColumnNotFoundError):
            eng.alter_table(AlterTableRequest(
                "m", AlterKind.DROP_COLUMNS, drop_columns=["nope"]))
        eng.alter_table(AlterTableRequest(
            "m", AlterKind.RENAME_TABLE, new_table_name="m2"))
        assert eng.table_exists(CAT, SCH, "m2")
        assert not eng.table_exists(CAT, SCH, "m")

    def test_drop_and_truncate(self, tmp_path):
        eng = mk_engine(tmp_path)
        t = eng.create_table(CreateTableRequest("d", monitor_schema()))
        t.insert({"host": ["a"], "ts": [1], "cpu": [1.0], "memory": [1.0]})
        assert eng.truncate_table(CAT, SCH, "d")
        t = eng.get_table(CAT, SCH, "d")
        assert sum(b.num_rows for b in t.scan_batches()) == 0
        assert eng.drop_table(DropTableRequest("d"))
        assert not eng.table_exists(CAT, SCH, "d")
        # re-creating the same name works
        eng.create_table(CreateTableRequest("d", monitor_schema()))

    def test_partitioned_table(self, tmp_path):
        eng = mk_engine(tmp_path)
        stmt = parse_sql("""
            CREATE TABLE p (host STRING, ts TIMESTAMP TIME INDEX,
                            cpu DOUBLE, PRIMARY KEY(host))
            PARTITION BY RANGE COLUMNS (host) (
              PARTITION r0 VALUES LESS THAN ('m'),
              PARTITION r1 VALUES LESS THAN (MAXVALUE))""")
        t = eng.create_table(CreateTableRequest(
            "p", monitor_schema().project(["host", "ts", "cpu"]),
            primary_key_indices=[0], partitions=stmt.partitions))
        assert len(t.regions) == 2
        t.insert({"host": ["alpha", "zulu", "beta"], "ts": [1, 2, 3],
                  "cpu": [0.1, 0.2, 0.3]})
        r0 = t.regions[0].snapshot().read_merged()
        r1 = t.regions[1].snapshot().read_merged()
        assert r0.num_rows == 2 and r1.num_rows == 1
        rows = sorted(r for b in t.scan_batches() for r in b.rows())
        assert [r[0] for r in rows] == ["alpha", "beta", "zulu"]

    def test_delete(self, tmp_path):
        eng = mk_engine(tmp_path)
        t = eng.create_table(CreateTableRequest(
            "del", monitor_schema(), primary_key_indices=[0]))
        t.insert({"host": ["a", "b"], "ts": [1, 1],
                  "cpu": [0.1, 0.2], "memory": [1, 2]})
        t.delete({"host": ["a"], "ts": [1]})
        rows = [r for b in t.scan_batches() for r in b.rows()]
        assert len(rows) == 1 and rows[0][0] == "b"


class TestPartitionRule:
    def test_range_rule_and_pruning(self):
        rule = RangePartitionRule("v", [10, 100, MAXVALUE], [0, 1, 2])
        assert rule.find_region((5,)) == 0
        assert rule.find_region((10,)) == 1
        assert rule.find_region((1000,)) == 2
        from greptimedb_tpu.sql import parse_sql
        q = parse_sql("SELECT * FROM t WHERE v >= 100 AND v < 200")
        assert rule.find_regions_by_filters([q.where]) == [2]
        q2 = parse_sql("SELECT * FROM t WHERE v < 10")
        assert rule.find_regions_by_filters([q2.where]) == [0]
        q3 = parse_sql("SELECT * FROM t WHERE v = 50")
        assert rule.find_regions_by_filters([q3.where]) == [1]

    def test_rule_from_partitions_multi_column(self):
        stmt = parse_sql("""
            CREATE TABLE t (a STRING, b INT, ts TIMESTAMP TIME INDEX,
                            PRIMARY KEY(a, b))
            PARTITION BY RANGE COLUMNS (a, b) (
              PARTITION p0 VALUES LESS THAN ('g', 10),
              PARTITION p1 VALUES LESS THAN (MAXVALUE, MAXVALUE))""")
        rule = rule_from_partitions(stmt.partitions)
        assert rule.find_region(("a", 5)) == 0
        assert rule.find_region(("g", 5)) == 0   # lexicographic: (g,5)<(g,10)
        assert rule.find_region(("g", 15)) == 1
        assert rule.find_region(("z", 0)) == 1


class TestCatalog:
    def test_memory_catalog(self):
        cm = MemoryCatalogManager()
        assert cm.catalog_names() == [CAT]
        cm.register_schema(CAT, "mydb")
        nt = NumbersTable()
        cm.register_table(CAT, "mydb", "numbers", nt)
        assert cm.table(CAT, "mydb", "numbers") is nt
        assert cm.table_names(CAT, "mydb") == ["numbers"]
        cm.deregister_table(CAT, "mydb", "numbers")
        cm.deregister_schema(CAT, "mydb")
        assert "mydb" not in cm.schema_names(CAT)

    def test_local_catalog_persistence(self, tmp_path):
        storage = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        eng = MitoEngine(storage)
        cm = LocalCatalogManager(storage.store, {"mito": eng})
        cm.start()
        cm.register_schema(CAT, "db2")
        t = eng.create_table(CreateTableRequest(
            "m", monitor_schema(), schema_name="db2",
            primary_key_indices=[0]))
        cm.register_table(CAT, "db2", "m", t)
        t.insert({"host": ["h"], "ts": [7], "cpu": [0.7], "memory": [7.0]})
        # restart world
        storage2 = StorageEngine(EngineConfig(data_home=str(tmp_path)))
        eng2 = MitoEngine(storage2)
        cm2 = LocalCatalogManager(storage2.store, {"mito": eng2})
        cm2.start()
        assert "db2" in cm2.schema_names(CAT)
        t2 = cm2.table(CAT, "db2", "m")
        assert t2 is not None
        rows = [r for b in t2.scan_batches() for r in b.rows()]
        assert rows == [("h", 7, 0.7, 7.0)]

    def test_numbers_table(self):
        nt = NumbersTable()
        b = nt.scan_batches(limit=10)[0]
        assert b.to_pydict()["number"] == list(range(10))
