"""Pytest face of the sqlness golden harness (tests/sqlness/runner.py).

Each `.sql` case runs against a fresh standalone frontend and its output
must byte-match the committed `.result` golden — the reference's primary
end-to-end regression rig (tests/runner/, SURVEY §4). Regenerate goldens
with `python tests/sqlness/runner.py --update`.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent / "sqlness"))
import runner  # noqa: E402


CASES = runner.case_files([])


@pytest.mark.parametrize(
    "sql_path", CASES,
    ids=[str(p.relative_to(runner.CASES_DIR))[:-4] for p in CASES])
def test_sqlness_case(sql_path):
    err = runner.run_one(sql_path, update=False)
    assert err is None, f"\n{err}"


def test_cases_exist():
    assert len(CASES) >= 13, "sqlness case suite went missing"


class TestStatementSplitter:
    def test_quotes_and_comments(self):
        stmts = runner.split_statements(
            "SELECT 'a;b' FROM t; -- trailing; comment\n"
            "INSERT INTO t VALUES (1);")
        assert len(stmts) == 2
        assert stmts[0] == "SELECT 'a;b' FROM t;"

    def test_unterminated_tail(self):
        stmts = runner.split_statements("SELECT 1")
        assert stmts == ["SELECT 1"]
