"""SQL parser tests mirroring the reference's create/tql/query parser suites
(src/sql/src/parsers/{create_parser,tql_parser}.rs test mods)."""

import pytest

from greptimedb_tpu.sql import (
    AlterTable, Between, BinaryOp, Column, Copy, CreateDatabase, CreateTable,
    Delete, DescribeTable, DropTable, Explain, FunctionCall, InList, Insert,
    Literal, ParserError, Query, SetVariable, ShowCreateTable, ShowDatabases,
    ShowTables, Star, Tql, UnaryOp, Use, parse_sql, parse_statements,
)


def test_create_table_full():
    stmt = parse_sql("""
        CREATE TABLE IF NOT EXISTS monitor (
            host STRING,
            ts TIMESTAMP TIME INDEX,
            cpu DOUBLE DEFAULT 0,
            memory DOUBLE NULL,
            PRIMARY KEY(host)
        ) ENGINE=mito WITH(regions=1, ttl='7d')""")
    assert isinstance(stmt, CreateTable)
    assert stmt.name.table == "monitor"
    assert stmt.if_not_exists
    assert stmt.time_index == "ts"
    assert stmt.primary_keys == ["host"]
    assert [c.name for c in stmt.columns] == ["host", "ts", "cpu", "memory"]
    ts_col = stmt.columns[1]
    assert ts_col.type_name.lower() == "timestamp"
    assert not ts_col.nullable
    assert stmt.options == {"regions": 1, "ttl": "7d"}
    assert stmt.engine == "mito"


def test_create_table_time_index_constraint():
    stmt = parse_sql("""
        CREATE TABLE t (ts TIMESTAMP(9), v DOUBLE, TIME INDEX (ts))""")
    assert stmt.time_index == "ts"
    assert stmt.columns[0].type_name == "TIMESTAMP(9)"


def test_create_table_requires_time_index():
    with pytest.raises(ParserError, match="TIME INDEX"):
        parse_sql("CREATE TABLE t (a INT, b DOUBLE)")


def test_create_table_partitions():
    stmt = parse_sql("""
        CREATE TABLE t (
          a STRING, ts TIMESTAMP TIME INDEX, PRIMARY KEY(a)
        ) PARTITION BY RANGE COLUMNS (a) (
          PARTITION r0 VALUES LESS THAN ('g'),
          PARTITION r1 VALUES LESS THAN (MAXVALUE)
        ) ENGINE=mito""")
    p = stmt.partitions
    assert p.columns == ["a"]
    assert [e.name for e in p.entries] == ["r0", "r1"]
    assert p.entries[0].values == ["g"]
    assert p.entries[1].values == ["MAXVALUE"]


def test_create_database():
    stmt = parse_sql("CREATE DATABASE IF NOT EXISTS mydb")
    assert isinstance(stmt, CreateDatabase) and stmt.name == "mydb"
    assert stmt.if_not_exists


def test_insert_values():
    stmt = parse_sql("""
        INSERT INTO monitor(host, ts, cpu) VALUES
          ('h1', 1000, 0.5), ('h2', 2000, NULL)""")
    assert isinstance(stmt, Insert)
    assert stmt.columns == ["host", "ts", "cpu"]
    assert len(stmt.rows) == 2
    assert stmt.rows[0][0].value == "h1"
    assert stmt.rows[1][2].value is None


def test_insert_negative_number():
    # the bulk-VALUES fast path folds the sign into the literal
    stmt = parse_sql("INSERT INTO t VALUES (-5, -1.5)")
    assert stmt.rows[0][0] == Literal(-5, "number")
    assert stmt.rows[0][1] == Literal(-1.5, "number")
    # non-literal rows still carry the expression form
    stmt = parse_sql("INSERT INTO t VALUES (-5 + 1, now())")
    assert isinstance(stmt.rows[0][0], BinaryOp) or \
        isinstance(stmt.rows[0][0], UnaryOp)


def test_select_full():
    q = parse_sql("""
        SELECT host, avg(cpu) AS c, count(*) FROM monitor
        WHERE ts >= 1000 AND ts < 2000 AND host != 'h3'
        GROUP BY host HAVING avg(cpu) > 0.1
        ORDER BY c DESC LIMIT 10 OFFSET 2""")
    assert isinstance(q, Query)
    assert q.from_.name.table == "monitor"
    assert q.projections[1].alias == "c"
    assert isinstance(q.projections[2].expr, FunctionCall)
    assert isinstance(q.where, BinaryOp) and q.where.op == "and"
    assert len(q.group_by) == 1
    assert q.having is not None
    assert q.order_by[0][1] is False
    assert q.limit == 10 and q.offset == 2


def test_select_star_and_qualified():
    q = parse_sql("SELECT *, m.cpu FROM db.m")
    assert isinstance(q.projections[0].expr, Star)
    col = q.projections[1].expr
    assert isinstance(col, Column) and col.table == "m" and col.name == "cpu"
    assert q.from_.name.parts == ["db", "m"]


def test_select_no_from():
    q = parse_sql("SELECT 1 + 2 * 3, version()")
    assert q.from_ is None
    e = q.projections[0].expr
    assert isinstance(e, BinaryOp) and e.op == "+"


def test_select_between_in_like_isnull():
    q = parse_sql("""
        SELECT * FROM t WHERE a BETWEEN 1 AND 5 AND b IN ('x','y')
          AND c NOT LIKE 'h%' AND d IS NOT NULL""")
    w = q.where
    # drill: ((between AND in) AND notlike) AND isnotnull
    assert isinstance(w, BinaryOp)
    found = []

    def walk(e):
        found.append(type(e).__name__)
        for attr in ("left", "right", "operand", "expr"):
            if hasattr(e, attr) and getattr(e, attr) is not None:
                walk(getattr(e, attr))
    walk(w)
    assert "Between" in found and "InList" in found and "IsNull" in found


def test_select_functions_and_case():
    q = parse_sql("""
        SELECT CASE WHEN cpu > 0.5 THEN 'hot' ELSE 'cold' END,
               date_bin(INTERVAL '1 minute', ts) FROM m""")
    assert q.projections[0].expr.whens
    fc = q.projections[1].expr
    assert fc.name == "date_bin"


def test_cast_forms():
    q = parse_sql("SELECT CAST(a AS BIGINT), b::double FROM t")
    assert q.projections[0].expr.type_name.lower() == "bigint"
    assert q.projections[1].expr.type_name.lower() == "double"


def test_joins():
    q = parse_sql("SELECT * FROM a LEFT JOIN b ON a.x = b.x, c")
    assert q.joins[0].kind == "left"
    assert q.joins[1].kind == "cross"


def test_subquery():
    q = parse_sql("SELECT * FROM (SELECT a FROM t) s WHERE a > 1")
    assert q.from_.subquery is not None
    assert q.from_.alias == "s"


def test_delete():
    stmt = parse_sql("DELETE FROM monitor WHERE host = 'h1' AND ts = 1000")
    assert isinstance(stmt, Delete)
    assert stmt.table.table == "monitor"


def test_alter_add_drop_rename():
    a = parse_sql("ALTER TABLE t ADD COLUMN load DOUBLE NULL")
    assert isinstance(a, AlterTable) and a.operation.column.name == "load"
    d = parse_sql("ALTER TABLE t DROP COLUMN load")
    assert d.operation.name == "load"
    r = parse_sql("ALTER TABLE t RENAME TO t2")
    assert r.operation.new_name == "t2"


def test_show_and_describe():
    assert isinstance(parse_sql("SHOW DATABASES"), ShowDatabases)
    st = parse_sql("SHOW TABLES FROM public LIKE 'mon%'")
    assert isinstance(st, ShowTables) and st.database == "public"
    assert st.like == "mon%"
    assert isinstance(parse_sql("SHOW CREATE TABLE m"), ShowCreateTable)
    assert isinstance(parse_sql("DESC TABLE m"), DescribeTable)
    assert isinstance(parse_sql("DESCRIBE m"), DescribeTable)


def test_use_set_explain():
    assert parse_sql("USE mydb").database == "mydb"
    s = parse_sql("SET time_zone = 'UTC'")
    assert isinstance(s, SetVariable)
    e = parse_sql("EXPLAIN SELECT 1")
    assert isinstance(e, Explain) and isinstance(e.statement, Query)


def test_tql_eval():
    t = parse_sql("TQL EVAL (0, 100, '5s') rate(cpu[1m] )")
    assert isinstance(t, Tql) and t.kind == "eval"
    assert t.start == "0" and t.end == "100" and t.step == "5s"
    assert "rate" in t.query and "[1m]" in t.query.replace(" ", "")


def test_tql_explain():
    t = parse_sql("TQL EXPLAIN (0, 10, '1s') up")
    assert t.kind == "explain" and t.query == "up"


def test_copy():
    c = parse_sql("COPY m TO '/tmp/out.parquet' WITH (format='parquet')")
    assert isinstance(c, Copy) and c.direction == "to"
    assert c.options == {"format": "parquet"}
    c2 = parse_sql("COPY m FROM '/tmp/in.parquet'")
    assert c2.direction == "from"


def test_multiple_statements():
    stmts = parse_statements("SELECT 1; SELECT 2;")
    assert len(stmts) == 2


def test_string_escapes_and_comments():
    q = parse_sql("""
        -- line comment
        SELECT 'it''s', "quoted col" /* block */ FROM t""")
    assert q.projections[0].expr.value == "it's"
    assert q.projections[1].expr.name == "quoted col"


def test_cte_inlining():
    # CTEs inline as derived tables; each reference is an independent copy
    q = parse_sql("WITH a AS (SELECT host, avg(cpu) c FROM m GROUP BY host)"
                  " SELECT x.host FROM a x JOIN a y ON x.host = y.host")
    assert q.from_.subquery is not None and q.from_.alias == "x"
    assert q.joins[0].table.subquery is not None
    assert q.from_.subquery is not q.joins[0].table.subquery
    # column list renames projections positionally
    q2 = parse_sql("WITH a(h, c) AS (SELECT host, avg(cpu) FROM m "
                   "GROUP BY host) SELECT h FROM a")
    assert [p.alias for p in q2.from_.subquery.projections] == ["h", "c"]
    # chained CTEs: later ones see earlier ones
    q3 = parse_sql("WITH a AS (SELECT host FROM m), b AS (SELECT host "
                   "FROM a) SELECT * FROM b")
    assert q3.from_.subquery.from_.subquery is not None
    # CTE names are not visible outside their statement / inside exprs
    with pytest.raises(ParserError, match="recursive"):
        parse_sql("WITH RECURSIVE r AS (SELECT 1) SELECT * FROM r")
    with pytest.raises(ParserError, match="duplicate"):
        parse_sql("WITH d AS (SELECT 1), d AS (SELECT 2) SELECT * FROM d")
    with pytest.raises(ParserError, match="column names"):
        parse_sql("WITH a(x, y) AS (SELECT host FROM m) SELECT * FROM a")


def test_error_reporting():
    with pytest.raises(ParserError):
        parse_sql("SELECT FROM")
    with pytest.raises(ParserError):
        parse_sql("FROBNICATE x")


def test_review_regressions():
    # unterminated type params must raise, not hang
    with pytest.raises(ParserError, match="unterminated"):
        parse_sql("SELECT CAST(a AS TIMESTAMP(3")
    # TQL needs all three range params
    with pytest.raises(ParserError, match="TQL"):
        parse_sql("TQL EVAL (0, 100) up")
    # a column named `time` coexists with the TIME INDEX constraint
    st = parse_sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, time BIGINT)")
    assert [c.name for c in st.columns] == ["ts", "time"]
    # an unterminated block comment must error, not parse as division
    # (advisor r3: the master-regex bcomment branch only matches closed
    # comments, so '/*' fell through to the op branch as '/' then '*')
    with pytest.raises((ParserError, ValueError), match="unterminated"):
        parse_sql("SELECT a /* b FROM t")
    st2 = parse_sql("CREATE TABLE t (ts TIMESTAMP, TIMESTAMP_INDEX(ts))")
    assert st2.time_index == "ts"
    # leading-zero ints parse as base 10; bad ints raise ParserError
    assert parse_sql("SELECT 1 LIMIT 010").limit == 10
    # SET with a negative number
    assert parse_sql("SET x = -5").value == -5
    # standalone VALUES is cleanly unsupported
    with pytest.raises(ParserError):
        parse_sql("VALUES (1, 2)")
