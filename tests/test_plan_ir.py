"""Differential matrix for the one plan IR (ISSUE 16).

Every front end lowers onto the same columnar plan (query/ir.py), so
the answers must agree across execution shapes:

- PromQL instant + range aggregates: the lowered moment-frame path vs
  the row path (numeric tolerance — the row path computes on device in
  float32 and quantizes to 6 significant digits, the lowered path
  finalizes in host float64);
- standalone vs in-process 4-datanode vs real-Flight sockets, over
  hash- AND range-partitioned tables: exact aggregates byte-identical
  (both sides fold the same f64 moment frames);
- flow folds (including avg) through the IR vs the host reduce;
- plan-codec version skew: an old datanode rejects a plan carrying a
  moment op it does not know, and the frontend degrades to the raw
  path — never a wrong answer.
"""

import logging
import time

import numpy as np
import pytest

from greptimedb_tpu.client import DatanodeClient, LocalDatanodeClient
from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.datatypes.record_batch import pretty_print
from greptimedb_tpu.errors import UnsupportedError
from greptimedb_tpu.frontend import FrontendInstance
from greptimedb_tpu.frontend.distributed import DistInstance, DistTable
from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
from greptimedb_tpu.query import tpu_exec
from greptimedb_tpu.session import QueryContext

# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

HASH_PART = " PARTITION BY HASH (host) PARTITIONS 8"
RANGE_PART = (" PARTITION BY RANGE COLUMNS (host) ("
              "PARTITION r0 VALUES LESS THAN ('h2'), "
              "PARTITION r1 VALUES LESS THAN ('h4'), "
              "PARTITION r2 VALUES LESS THAN (MAXVALUE))")

DDL = ("CREATE TABLE ctr (host STRING, dc STRING, ts TIMESTAMP TIME "
       "INDEX, val DOUBLE, PRIMARY KEY(host, dc))")


def _seed_rows():
    """Deterministic counter-ish series with gaps and resets."""
    rows = []
    rng = np.random.default_rng(11)
    for h in range(6):
        v = 0.0
        for i in range(80):
            if rng.random() < 0.2:
                continue                      # gap
            v += float(rng.integers(1, 9))
            if rng.random() < 0.06:
                v = 0.0                       # counter reset
            rows.append(f"('h{h}', 'dc{h % 2}', {i * 10_000}, {v})")
    return ",".join(rows)


@pytest.fixture()
def fe(tmp_path):
    inst = FrontendInstance(DatanodeInstance(
        DatanodeOptions(data_home=str(tmp_path / "sa"))))
    inst.start()
    inst.do_query(DDL)
    inst.do_query("INSERT INTO ctr VALUES " + _seed_rows())
    yield inst
    inst.shutdown()


def _mk_cluster(tmp_path, n, part):
    datanodes, clients = {}, {}
    srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
    meta = MetaClient(srv)
    for i in range(1, n + 1):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        datanodes[i] = dn
        clients[i] = LocalDatanodeClient(dn)
        srv.register_datanode(Peer(i, f"dn{i}"))
        srv.handle_heartbeat(i)
    fe = DistInstance(meta, clients)
    fe.do_query(DDL + part)
    fe.do_query("INSERT INTO ctr VALUES " + _seed_rows())
    return fe, datanodes


QUERIES = [
    "sum by (host) (rate(ctr[1m]))",
    "sum by (dc) (increase(ctr[1m]))",
    "sum (delta(ctr[1m]))",
    "avg by (host) (ctr)",
    "min by (host) (ctr{host!='h1'})",
    "count (sum_over_time(ctr[1m]))",
    "max by (host) (max_over_time(ctr{dc='dc0'}[1m]))",
    "sum by (host) (count_over_time(ctr[1m]))",
    "avg by (host) (avg_over_time(ctr[1m]))",
    "sum by (host) (last_over_time(ctr[1m]))",
    "sum by (host) (rate(ctr[1m] offset 30s))",
]
SPAN = (0, 790_000, 60_000)


def _vec(inst, q, span=SPAN):
    v, steps = inst.promql_engine().query_range(
        q, span[0], span[1], span[2], QueryContext())
    out = {}
    for i, lbl in enumerate(v.labels):
        out[tuple(sorted(lbl.items()))] = (v.values[i], v.ok[i])
    return out


def _tql(inst, q, span=SPAN):
    return pretty_print(inst.do_query(
        f"TQL EVAL ({span[0] // 1000}, {span[1] // 1000}, "
        f"'{span[2] // 1000}s') {q}")[0].batches)


def _assert_close(a, b, rtol):
    assert set(a) == set(b), (set(a) ^ set(b))
    for k in a:
        va, oka = a[k]
        vb, okb = b[k]
        assert np.array_equal(oka, okb), k
        assert np.allclose(np.where(oka, va, 0.0),
                           np.where(okb, vb, 0.0),
                           rtol=rtol, atol=1e-9), k


# ---------------------------------------------------------------------------
# PromQL: lowered vs row path (standalone)
# ---------------------------------------------------------------------------

class TestLoweredVsRowPath:
    @pytest.mark.parametrize("q", QUERIES)
    def test_differential(self, fe, q, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 10**9)
        row = _vec(fe, q)
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        lowered = _vec(fe, q)
        # row path: device float32 + 6-significant-digit quantization;
        # lowered path: host float64 moment finalization
        _assert_close(row, lowered, rtol=2e-5)

    def test_row_path_shapes_untouched(self, fe, monkeypatch):
        """Non-lowerable shapes give byte-identical answers whatever the
        dispatch floor says (they never lower)."""
        for q in ["topk(2, ctr)", "rate(ctr[2m])",      # non-tumbling
                  "stddev by (host) (ctr)",
                  "sum by (host) (rate(ctr{host=~'h[12]'}[1m]))"]:
            monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 10**9)
            row = _vec(fe, q)
            monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
            assert _vec(fe, q).keys() == row.keys(), q


# ---------------------------------------------------------------------------
# PromQL: distributed vs standalone (exact aggs byte-identical)
# ---------------------------------------------------------------------------

class TestDistVsStandalone:
    @pytest.mark.parametrize("part", [HASH_PART, RANGE_PART],
                             ids=["hash", "range"])
    def test_in_process_4dn(self, fe, tmp_path, part, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        dist, datanodes = _mk_cluster(tmp_path, 4, part)
        try:
            for q in QUERIES:
                assert _tql(fe, q) == _tql(dist, q), q
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_raw_pull_knob_still_correct(self, fe, tmp_path, monkeypatch):
        """SET dist_partial_agg = 0 forces the raw-pull row path on the
        distributed side; answers stay correct (f32 tolerance vs the
        lowered standalone)."""
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        dist, datanodes = _mk_cluster(tmp_path, 4, HASH_PART)
        try:
            dist.do_query("SET dist_partial_agg = 0")
            q = "sum by (host) (rate(ctr[1m]))"
            _assert_close(_vec(fe, q), _vec(dist, q), rtol=2e-5)
        finally:
            tpu_exec._PARTIAL_PUSHDOWN[0] = True
            for dn in datanodes.values():
                dn.shutdown()


# ---------------------------------------------------------------------------
# PromQL over real Flight sockets (was: silently empty)
# ---------------------------------------------------------------------------

@pytest.fixture()
def flight_cluster(tmp_path):
    from greptimedb_tpu.client.flight import FlightDatanodeClient
    from greptimedb_tpu.servers.flight import FlightDatanodeServer
    datanodes, servers, clients = {}, {}, {}
    srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
    meta = MetaClient(srv)
    for i in (1, 2):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        fs = FlightDatanodeServer(dn)
        fs.serve_in_background()
        t0 = time.time()
        while fs.port == 0 and time.time() - t0 < 10:
            time.sleep(0.01)
        datanodes[i] = dn
        servers[i] = fs
        clients[i] = FlightDatanodeClient(fs.address, node_id=i)
        srv.register_datanode(Peer(i, fs.address))
        srv.handle_heartbeat(i)
    fe = DistInstance(meta, clients)
    yield fe
    for c in clients.values():
        c.close()
    for s in servers.values():
        s.shutdown()
    for dn in datanodes.values():
        dn.shutdown()


class TestRealFlight:
    def test_lowered_and_row_paths_match_standalone(
            self, fe, flight_cluster, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        flight_cluster.do_query(DDL + HASH_PART)
        flight_cluster.do_query("INSERT INTO ctr VALUES " + _seed_rows())
        for q in ["sum by (host) (rate(ctr[1m]))",     # lowered scatter
                  "avg by (dc) (ctr)",                 # lowered instant
                  "rate(ctr{host='h1'}[2m])"]:         # row path -> wire scan
            a = _tql(fe, q)
            b = _tql(flight_cluster, q)
            assert b.count("\n") > 3, f"silently empty over Flight: {q}"
            assert a == b, q

    def test_version_skew_degrades_to_raw(self, fe, flight_cluster,
                                          monkeypatch):
        """An old datanode that doesn't know reset_corr rejects the
        shipped plan; the frontend degrades to the raw row path and the
        answer stays correct."""
        from greptimedb_tpu.query import plan_codec
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        flight_cluster.do_query(DDL + HASH_PART)
        flight_cluster.do_query("INSERT INTO ctr VALUES " + _seed_rows())
        monkeypatch.setattr(
            plan_codec, "KNOWN_MOMENT_OPS",
            plan_codec.KNOWN_MOMENT_OPS - {"reset_corr"})
        q = "sum by (host) (rate(ctr[1m]))"
        skewed = _vec(flight_cluster, q)
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 10**9)
        row = _vec(fe, q)
        _assert_close(row, skewed, rtol=2e-5)


class TestRemoteStubErrors:
    def test_unsupported_names_the_knob(self, fe, caplog):
        """A DistTable whose datanodes expose no data plane must raise a
        clear UnsupportedError naming the IR knob — never return an
        empty result."""
        table = fe.catalog.table("greptime", "public", "ctr")

        class RemoteStub(DatanodeClient):      # no .datanode attribute
            node_id = 99

        # the standalone catalog's table is region-backed; wrap its route
        # metadata into a DistTable whose every client is a dead stub
        dist, datanodes = None, {}
        try:
            import tempfile
            with tempfile.TemporaryDirectory() as td:
                from pathlib import Path
                dist, datanodes = _mk_cluster(Path(td), 1, HASH_PART)
                real = dist.catalog.table("greptime", "public", "ctr")
                stub = RemoteStub()
                remote = DistTable(real.info, real.partition_rule,
                                   real.route,
                                   {i: stub for i in dist.clients})
                with caplog.at_level(logging.WARNING):
                    assert remote.regions == {}
                from greptimedb_tpu.promql import lowering
                eng = dist.promql_engine()

                class Sel:
                    metric = "ctr"
                    matchers = []
                    at_ms = None

                with pytest.raises(UnsupportedError,
                                   match="dist_partial_agg"):
                    lowering._wire_scan_selection(
                        remote, Sel(), "ctr", ["host", "dc"], ["val"],
                        False, 0, 1000)
                del eng
        finally:
            for dn in datanodes.values():
                dn.shutdown()


# ---------------------------------------------------------------------------
# satellite 1: select decodes only referenced tag columns
# ---------------------------------------------------------------------------

class TestSelectiveTagDecode:
    def test_only_matcher_columns_decoded_fully(self, fe, monkeypatch):
        from greptimedb_tpu.storage.series import SeriesDict
        calls = []
        orig = SeriesDict.decode_tag_column

        def spy(self, ids, idx):
            calls.append((idx, len(np.atleast_1d(ids))))
            return orig(self, ids, idx)

        monkeypatch.setattr(SeriesDict, "decode_tag_column", spy)
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 10**9)
        _vec(fe, "rate(ctr{host='h1'}[2m])")   # row path, 2-tag table
        # tag 0 (host) is matcher-referenced: decoded for all series;
        # tag 1 (dc) is not: decoded only for the surviving series
        by_idx = {}
        for idx, n in calls:
            by_idx.setdefault(idx, set()).add(n)
        assert max(by_idx[0]) == 6              # all series
        assert max(by_idx[1]) == 1              # only h1 survived


# ---------------------------------------------------------------------------
# flows: IR moment-frame folds + avg
# ---------------------------------------------------------------------------

FLOW = ("CREATE FLOW ctr_1m AS SELECT host, dc, "
        "date_bin(INTERVAL '1 minute', ts) AS ts, avg(val) AS v_avg, "
        "sum(val) AS v_sum, count(val) AS n FROM ctr "
        "GROUP BY host, dc, ts")
SINK_Q = ("SELECT host, dc, ts, v_avg, v_sum, n FROM ctr_1m "
          "ORDER BY host, dc, ts")


def _sink_frame(inst):
    import pandas as pd
    parts = [pd.DataFrame(b.to_pydict())
             for b in inst.do_query(SINK_Q)[0].batches]
    return pd.concat(parts, ignore_index=True)


class TestFlowIrFolds:
    def test_flow_avg_standalone(self, fe):
        fe.do_query(FLOW)
        fe.datanode.flow_manager.tick()
        sink = _sink_frame(fe)
        raw = pretty_print(fe.do_query(
            "SELECT host, dc, date_bin(INTERVAL '1 minute', ts) AS b, "
            "avg(val), sum(val), count(val) FROM ctr "
            "GROUP BY host, dc, b ORDER BY host, dc, b")[0].batches)
        import re
        raw_avgs = [float(m) for m in re.findall(
            r"\|\s(-?\d+\.\d+)\s+\|\s-?\d+\.\d+\s+\|\s\d+\s+\|", raw)]
        assert len(raw_avgs) == len(sink)
        assert np.allclose(sink["v_avg"].to_numpy(), raw_avgs, rtol=2e-5)

    def test_flow_ir_fold_matches_host_reduce(self, fe, tmp_path):
        """Drive fold_generic directly against the DistTable (what a
        real-Flight frontend does): the IR moment-frame fold must match
        the standalone device fold within f32 tolerance, and the
        degrade knob must not change the answer."""
        from greptimedb_tpu.flow import lowering as flowering
        fe.do_query(FLOW)
        fe.datanode.flow_manager.tick()
        dist, datanodes = _mk_cluster(tmp_path, 4, HASH_PART)
        try:
            dist.do_query(FLOW)
            spec = dist.flow_manager.flows()[0]
            src = dist.catalog.table(spec.catalog, spec.schema,
                                     spec.source)
            dst = dist.catalog.table(spec.catalog, spec.schema, spec.sink)
            w, n = flowering.fold_generic(spec, src, dst)
            assert w > 0 and n > 0
            a, b = _sink_frame(fe), _sink_frame(dist)
            assert list(a["host"]) == list(b["host"])
            assert list(a["ts"]) == list(b["ts"])
            for col in ("v_avg", "v_sum", "n"):
                assert np.allclose(a[col].to_numpy(dtype=float),
                                   b[col].to_numpy(dtype=float),
                                   rtol=2e-5), col
            # incremental fold through the degrade (raw scan) path
            more = ",".join(f"('h{h}', 'dc{h % 2}', {800_000 + i * 1000},"
                            f" 1.0)" for h in range(6) for i in range(5))
            fe.do_query("INSERT INTO ctr VALUES " + more)
            dist.do_query("INSERT INTO ctr VALUES " + more)
            fe.datanode.flow_manager.tick()
            tpu_exec._PARTIAL_PUSHDOWN[0] = False
            try:
                flowering.fold_generic(spec, src, dst)
            finally:
                tpu_exec._PARTIAL_PUSHDOWN[0] = True
            a, b = _sink_frame(fe), _sink_frame(dist)
            for col in ("v_avg", "v_sum", "n"):
                assert np.allclose(a[col].to_numpy(dtype=float),
                                   b[col].to_numpy(dtype=float),
                                   rtol=2e-5), col
        finally:
            for dn in datanodes.values():
                dn.shutdown()


# ---------------------------------------------------------------------------
# EXPLAIN surface
# ---------------------------------------------------------------------------

class TestPromqlExplain:
    def test_tql_explain_standalone(self, fe, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        out = pretty_print(fe.do_query(
            "TQL EXPLAIN (0, 790, '60s') "
            "sum by (host) (rate(ctr[1m]))")[0].batches)
        assert "PromAggregate: sum by (host)" in out
        assert "TpuAggregateExec:" in out
        assert "time_bucket(60000ms)" in out
        assert "Dispatch:" in out

    def test_tql_explain_row_path_reason(self, fe, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 10**9)
        out = pretty_print(fe.do_query(
            "TQL EXPLAIN (0, 790, '60s') "
            "sum by (host) (rate(ctr[1m]))")[0].batches)
        assert "promql-row-path" in out

    def test_tql_analyze_stages(self, fe, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        out = pretty_print(fe.do_query(
            "TQL ANALYZE (0, 790, '60s') "
            "sum by (host) (rate(ctr[1m]))")[0].batches)
        assert "elapsed:" in out and "series:" in out
        assert "finalize" in out        # the IR executor's stage line

    def test_dist_explain_prints_scatter(self, tmp_path, monkeypatch):
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        dist, datanodes = _mk_cluster(tmp_path, 4, HASH_PART)
        try:
            out = pretty_print(dist.do_query(
                "TQL EXPLAIN (0, 790, '60s') "
                "sum by (host) (rate(ctr[1m]))")[0].batches)
            assert "aggregate-pushdown" in out
            assert "fan-out" in out
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_http_explain_param(self, fe, monkeypatch):
        """?explain=1 renders the same plan lines through the engine's
        public explain_lines API."""
        monkeypatch.setattr(tpu_exec, "TPU_DISPATCH_MIN_ROWS", 0)
        lines = fe.promql_engine().explain_lines(
            "sum by (host) (rate(ctr[1m]))", 0, 790_000, 60_000)
        joined = "\n".join(lines)
        assert "PromSeriesScan: ctr" in joined
        assert "TpuAggregateExec:" in joined
