"""PostgreSQL wire protocol server tests.

A minimal v3-protocol client (startup, cleartext auth, simple query 'Q',
extended Parse/Bind/Execute/Sync) drives the server end-to-end, mirroring
the reference's pgwire handler coverage (postgres/handler.rs:648).
"""

import socket
import struct

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.servers.auth import StaticUserProvider
from greptimedb_tpu.servers.postgres import PostgresServer


class MiniPgClient:
    def __init__(self, port, user="greptime", password=None,
                 database="public"):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self._startup(user, password, database)

    # ---- low level ----
    def _send(self, tag, body=b""):
        self.sock.sendall(tag + struct.pack("!I", len(body) + 4) + body)

    def _read_n(self, n):
        chunks = b""
        while len(chunks) < n:
            chunk = self.sock.recv(n - len(chunks))
            if not chunk:
                raise ConnectionError("eof")
            chunks += chunk
        return chunks

    def _read_message(self):
        head = self._read_n(5)
        length = struct.unpack_from("!I", head, 1)[0]
        return chr(head[0]), self._read_n(length - 4)

    # ---- startup ----
    def _startup(self, user, password, database):
        body = struct.pack("!I", 196608)
        body += b"user\x00" + user.encode() + b"\x00"
        body += b"database\x00" + database.encode() + b"\x00\x00"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        while True:
            tag, payload = self._read_message()
            if tag == "R":
                code = struct.unpack_from("!I", payload, 0)[0]
                if code == 3:
                    assert password is not None, "server demanded password"
                    self._send(b"p", password.encode() + b"\x00")
                elif code == 5:
                    import hashlib
                    assert password is not None, "server demanded password"
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        (password + user).encode()).hexdigest()
                    resp = "md5" + hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send(b"p", resp.encode() + b"\x00")
                elif code == 0:
                    pass
                else:
                    raise AssertionError(f"unexpected auth code {code}")
            elif tag == "E":
                raise ConnectionRefusedError(self._error_message(payload))
            elif tag == "Z":
                return
            # S (parameter status) / K (backend key data): ignore

    @staticmethod
    def _error_message(payload):
        for part in payload.split(b"\x00"):
            if part[:1] == b"M":
                return part[1:].decode()
        return "unknown error"

    # ---- simple query ----
    def query(self, sql):
        """Returns (names, rows) for selects, command tag string else."""
        self._send(b"Q", sql.encode() + b"\x00")
        return self._collect_result()

    def _collect_result(self):
        names, rows, tag_str = None, [], None
        while True:
            tag, payload = self._read_message()
            if tag == "T":
                names = self._parse_row_description(payload)
            elif tag == "D":
                rows.append(self._parse_data_row(payload))
            elif tag == "C":
                tag_str = payload.rstrip(b"\x00").decode()
            elif tag == "E":
                err = self._error_message(payload)
                self._sync_to_ready()
                raise RuntimeError(err)
            elif tag == "Z":
                break
        if names is not None:
            return names, rows
        return tag_str

    def _sync_to_ready(self):
        while True:
            tag, _ = self._read_message()
            if tag == "Z":
                return

    @staticmethod
    def _parse_row_description(payload):
        n = struct.unpack_from("!H", payload, 0)[0]
        names, pos = [], 2
        for _ in range(n):
            end = payload.index(b"\x00", pos)
            names.append(payload[pos:end].decode())
            pos = end + 1 + 18
        return names

    @staticmethod
    def _parse_data_row(payload):
        n = struct.unpack_from("!H", payload, 0)[0]
        pos, row = 2, []
        for _ in range(n):
            ln = struct.unpack_from("!i", payload, pos)[0]
            pos += 4
            if ln == -1:
                row.append(None)
            else:
                row.append(payload[pos:pos + ln].decode())
                pos += ln
        return row

    # ---- extended protocol ----
    def extended_query(self, sql, params=()):
        self._send(b"P", b"\x00" + sql.encode() + b"\x00"
                   + struct.pack("!H", 0))
        bind = b"\x00\x00" + struct.pack("!H", 0)
        bind += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                bind += struct.pack("!i", -1)
            else:
                raw = str(p).encode()
                bind += struct.pack("!i", len(raw)) + raw
        bind += struct.pack("!H", 0)
        self._send(b"B", bind)
        self._send(b"D", b"P\x00")
        self._send(b"E", b"\x00" + struct.pack("!I", 0))
        self._send(b"S")
        names, rows, tag_str = None, [], None
        while True:
            tag, payload = self._read_message()
            if tag == "T":
                names = self._parse_row_description(payload)
            elif tag == "D":
                rows.append(self._parse_data_row(payload))
            elif tag == "C":
                tag_str = payload.rstrip(b"\x00").decode()
            elif tag == "E":
                err = self._error_message(payload)
                self._sync_to_ready()
                raise RuntimeError(err)
            elif tag == "Z":
                break
        if names is not None:
            return names, rows
        return tag_str

    def extended_query_binary(self, sql, params, oids):
        """Extended flow with ALL parameters in binary format, declaring
        per-parameter type OIDs in Parse (JDBC/psycopg3 style)."""
        parse = b"\x00" + sql.encode() + b"\x00" \
            + struct.pack("!H", len(oids))
        for oid in oids:
            parse += struct.pack("!I", oid)
        self._send(b"P", parse)
        bind = b"\x00\x00" + struct.pack("!H", 1) + struct.pack("!H", 1)
        bind += struct.pack("!H", len(params))
        for raw in params:
            if raw is None:
                bind += struct.pack("!i", -1)
            else:
                bind += struct.pack("!i", len(raw)) + raw
        bind += struct.pack("!H", 0)
        self._send(b"B", bind)
        self._send(b"D", b"P\x00")
        self._send(b"E", b"\x00" + struct.pack("!I", 0))
        self._send(b"S")
        names, rows, tag_str = None, [], None
        while True:
            tag, payload = self._read_message()
            if tag == "T":
                names = self._parse_row_description(payload)
            elif tag == "D":
                rows.append(self._parse_data_row(payload))
            elif tag == "C":
                tag_str = payload.rstrip(b"\x00").decode()
            elif tag == "E":
                err = self._error_message(payload)
                self._sync_to_ready()
                raise RuntimeError(err)
            elif tag == "Z":
                break
        if names is not None:
            return names, rows
        return tag_str

    def close(self):
        try:
            self._send(b"X")
            self.sock.close()
        except OSError:
            pass


@pytest.fixture()
def server(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    srv = PostgresServer(fe)
    srv.serve_in_background()
    yield srv
    srv.shutdown()
    fe.shutdown()


@pytest.fixture()
def client(server):
    c = MiniPgClient(server.port)
    yield c
    c.close()


class TestPostgresProtocol:
    def test_quickstart_flow(self, client):
        assert client.query(
            "CREATE TABLE monitor (host STRING, ts TIMESTAMP TIME INDEX,"
            " cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))") == "CREATE"
        assert client.query(
            "INSERT INTO monitor VALUES ('host1', 1000, 66.6, 1024),"
            " ('host2', 2000, 77.7, 2048)") == "INSERT 0 2"
        names, rows = client.query(
            "SELECT host, avg(cpu) AS c FROM monitor GROUP BY host"
            " ORDER BY host")
        assert names == ["host", "c"]
        assert rows == [["host1", "66.6"], ["host2", "77.7"]]

    def test_command_tags(self, client):
        client.query("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        client.query("INSERT INTO t VALUES (1, 1.0), (2, 2.0)")
        assert client.query("DELETE FROM t WHERE ts = 1") == "DELETE 1"

    def test_timestamp_and_null_format(self, client):
        client.query("CREATE TABLE t2 (ts TIMESTAMP TIME INDEX, v DOUBLE,"
                     " s STRING)")
        client.query("INSERT INTO t2 (ts, v) VALUES (1672531200000, 1.5)")
        _, rows = client.query("SELECT ts, v, s FROM t2")
        assert rows == [["2023-01-01 00:00:00.000000", "1.5", None]]

    def test_error_then_recover(self, client):
        with pytest.raises(RuntimeError, match="not found"):
            client.query("SELECT * FROM missing_table")
        # connection still usable after ErrorResponse + ReadyForQuery
        client.query("CREATE TABLE ok1 (ts TIMESTAMP TIME INDEX, v DOUBLE)")

    def test_empty_query(self, client):
        assert client.query("") is None or True   # EmptyQueryResponse path

    def test_extended_protocol(self, client):
        client.query("CREATE TABLE ext (host STRING, ts TIMESTAMP"
                     " TIME INDEX, cpu DOUBLE, PRIMARY KEY(host))")
        assert client.extended_query(
            "INSERT INTO ext (host, ts, cpu) VALUES ($1, $2, $3)",
            ("h1", 1000, 2.5)) == "INSERT 0 1"
        names, rows = client.extended_query(
            "SELECT cpu FROM ext WHERE host = $1", ("h1",))
        assert rows == [["2.5"]]

    def test_show_tables(self, client):
        client.query("CREATE TABLE vis (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        names, rows = client.query("SHOW TABLES")
        assert ["vis"] in rows

    def _collect_until_ready(self, c):
        tags = {}
        while True:
            tag, payload = c._read_message()
            tags.setdefault(tag, []).append(payload)
            if tag == "Z":
                return tags

    def test_describe_portal_returns_row_description(self, client):
        # v3 protocol: drivers that plan on Describe (JDBC, psycopg3
        # extended) need the real RowDescription at Describe time
        c = client
        c.query("CREATE TABLE dsc (host STRING, ts TIMESTAMP TIME INDEX,"
                " cpu DOUBLE, PRIMARY KEY(host))")
        c.query("INSERT INTO dsc VALUES ('a', 1000, 1.5)")
        c._send(b"P", b"\x00SELECT host, cpu FROM dsc\x00"
                + struct.pack("!H", 0))
        c._send(b"B", b"\x00\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"D", b"P\x00")
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert "T" in tags, f"Describe portal replied {sorted(tags)}"
        assert c._parse_row_description(tags["T"][0]) == ["host", "cpu"]
        # Execute must not repeat the RowDescription Describe already sent
        c._send(b"B", b"\x00\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"D", b"P\x00")
        c._send(b"E", b"\x00" + struct.pack("!I", 0))
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert len(tags.get("T", [])) == 1
        assert [r for r in map(c._parse_data_row, tags.get("D", []))] == \
            [["a", "1.5"]]

    def test_describe_statement_returns_schema(self, client):
        c = client
        c.query("CREATE TABLE dss (host STRING, ts TIMESTAMP TIME INDEX,"
                " cpu DOUBLE, PRIMARY KEY(host))")
        c._send(b"P", b"s1\x00SELECT cpu, host FROM dss WHERE host = $1\x00"
                + struct.pack("!H", 0))
        c._send(b"D", b"Ss1\x00")
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert "t" in tags         # ParameterDescription: one text param
        assert struct.unpack_from("!H", tags["t"][0], 0)[0] == 1
        assert "T" in tags, f"Describe statement replied {sorted(tags)}"
        assert c._parse_row_description(tags["T"][0]) == ["cpu", "host"]

    def test_describe_cache_not_stale_across_sync(self, client):
        # a result cached by Describe lives only within one pipeline batch:
        # an Execute in a later cycle must see intervening writes
        c = client
        c.query("CREATE TABLE stale (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        c.query("INSERT INTO stale VALUES (1, 1.0)")
        c._send(b"P", b"\x00SELECT v FROM stale\x00" + struct.pack("!H", 0))
        c._send(b"B", b"\x00\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"D", b"P\x00")
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert "T" in tags
        c.query("INSERT INTO stale VALUES (2, 2.0)")
        c._send(b"E", b"\x00" + struct.pack("!I", 0))
        c._send(b"S")
        tags = self._collect_until_ready(c)
        got = sorted(r[0] for r in map(c._parse_data_row, tags.get("D", [])))
        assert got == ["1.0", "2.0"], got

    def test_binary_format_parameters(self, client):
        """JDBC/psycopg3 send binary params with OIDs declared in Parse
        (reference pgwire handles both formats, handler.rs:648)."""
        import struct as st
        client.query("CREATE TABLE binp (host STRING, ts TIMESTAMP TIME"
                     " INDEX, v DOUBLE, n BIGINT, ok BOOLEAN,"
                     " PRIMARY KEY(host))")
        tag = client.extended_query_binary(
            "INSERT INTO binp VALUES ($1, $2, $3, $4, $5)",
            [b"h1", st.pack("!q", 5000), st.pack("!d", 2.75),
             st.pack("!q", -12), b"\x01"],
            oids=[25, 20, 701, 20, 16])
        assert tag == "INSERT 0 1"
        names, rows = client.query(
            "SELECT host, ts, v, n, ok FROM binp")
        assert rows[0][0] == "h1"
        assert rows[0][2] == "2.75" and rows[0][3] == "-12"
        # int4 binary param in a predicate
        names, rows = client.extended_query_binary(
            "SELECT count(*) FROM binp WHERE n = $1",
            [st.pack("!i", -12)], oids=[23])
        assert rows == [["1"]]

    def test_bind_unknown_statement_errors(self, client):
        c = client
        c._send(b"B", b"\x00nope\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert "E" in tags and b"26000" in tags["E"][0]
        # connection still usable afterwards
        assert c.query("CREATE TABLE ok2 (ts TIMESTAMP TIME INDEX,"
                       " v DOUBLE)") == "CREATE"

    def test_error_skips_pipeline_until_sync(self, client):
        # v3: after an extended-protocol error, everything before Sync is
        # discarded — a pipelined Execute must NOT run a stale portal
        c = client
        c.query("CREATE TABLE pipe (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        c.query("INSERT INTO pipe VALUES (1, 1.0)")
        # bind the unnamed portal to a valid statement first
        c._send(b"P", b"\x00SELECT v FROM pipe\x00" + struct.pack("!H", 0))
        c._send(b"B", b"\x00\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"E", b"\x00" + struct.pack("!I", 0))
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert len(tags.get("D", [])) == 1
        # now a failing Bind followed by a pipelined Execute of the stale
        # unnamed portal: the Execute must be discarded, not served
        c._send(b"B", b"\x00gone\x00" + struct.pack("!HHH", 0, 0, 0))
        c._send(b"E", b"\x00" + struct.pack("!I", 0))
        c._send(b"S")
        tags = self._collect_until_ready(c)
        assert "E" in tags and b"26000" in tags["E"][0]
        assert "D" not in tags and "C" not in tags
        # recovered after Sync
        assert c.query("SELECT v FROM pipe")[1] == [["1.0"]]


class TestPostgresAuth:
    @pytest.fixture()
    def auth_server(self, tmp_path):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        srv = PostgresServer(fe, user_provider=StaticUserProvider(
            {"greptime": "hunter2"}))
        srv.serve_in_background()
        yield srv
        srv.shutdown()
        fe.shutdown()

    def test_good_password(self, auth_server):
        c = MiniPgClient(auth_server.port, password="hunter2")
        assert c.query("SELECT 1 AS one") in (("one", [["1"]]),
                                              (["one"], [["1"]]))
        c.close()

    def test_bad_password(self, auth_server):
        with pytest.raises(ConnectionRefusedError,
                           match="authentication failed"):
            MiniPgClient(auth_server.port, password="nope")
