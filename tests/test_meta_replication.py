"""Replicated meta store: raft-lite consensus, leader failover.

Reference behavior: etcd-backed meta KV + election
(src/meta-srv/src/service/store/etcd.rs:762,
src/meta-srv/src/election/etcd.rs:34-70) — the brain survives a node
loss. The VERDICT round-2 'done' bar: kill the leader, routes intact.
"""

import time

import pytest

from greptimedb_tpu.errors import GreptimeError
from greptimedb_tpu.meta.replication import (
    FlightTransport, HaMetaClient, NotLeaderError, RaftNode, ReplicatedKv,
    connect_local)
from greptimedb_tpu.meta.service import MetaSrv, Peer

FAST = dict(election_timeout=(0.25, 0.5), heartbeat_interval=0.08)


def wait_for(pred, timeout=8.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def make_cluster(n=3, tmp_path=None):
    ids = list(range(1, n + 1))
    nodes = [RaftNode(i, ids,
                      store_path=str(tmp_path / f"raft-{i}.json")
                      if tmp_path else None, **FAST) for i in ids]
    connect_local(nodes)
    for nd in nodes:
        nd.start()
    return nodes


def leader_of(nodes):
    live = [nd for nd in nodes if nd._threads]
    return wait_for(
        lambda: next((nd for nd in live if nd.is_leader), None),
        what="leader election")


def crash(node):
    """Stop the node and partition it away (simulates a process kill)."""
    node.stop()
    node.transports = {}
    for other_t in list(node.transports.values()):
        pass


def partition_away(nodes, dead):
    for nd in nodes:
        nd.transports.pop(dead.node_id, None)


class TestElection:
    def test_single_leader_emerges(self):
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            time.sleep(0.6)
            leaders = [nd for nd in nodes if nd.is_leader]
            assert leaders == [leader]
        finally:
            for nd in nodes:
                nd.stop()

    def test_new_leader_after_kill(self):
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            crash(leader)
            partition_away(nodes, leader)
            survivors = [nd for nd in nodes if nd is not leader]
            new = wait_for(
                lambda: next((nd for nd in survivors if nd.is_leader),
                             None), what="re-election")
            assert new is not leader
        finally:
            for nd in nodes:
                nd.stop()

    def test_non_leader_raises_with_hint(self):
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            kv.put("k", b"v")
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.leader_id == leader.node_id,
                     what="leader hint propagation")
            with pytest.raises(NotLeaderError) as ei:
                ReplicatedKv(follower).get("k")
            assert ei.value.leader_id == leader.node_id
        finally:
            for nd in nodes:
                nd.stop()


class TestBatch:
    def test_batch_atomic_and_guarded(self):
        """rename_table_route's multi-op: all-or-nothing under a guard,
        replicated as ONE raft command (advisor r3: the old two-step CAS
        + delete could crash half-renamed)."""
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            kv.put("route/old", b"r1")
            kv.put("tinfo/old", b"i1")
            ok = kv.batch(
                [("put", "route/new", b"r1"), ("delete", "route/old", None),
                 ("put", "tinfo/new", b"i1"), ("delete", "tinfo/old", None)],
                guard=("route/new", None))
            assert ok
            assert kv.get("route/new") == b"r1"
            assert kv.get("route/old") is None
            assert kv.get("tinfo/new") == b"i1"
            # guard failure: nothing applied
            kv.put("route/back", b"x")
            ok = kv.batch(
                [("put", "route/clobber", b"y"),
                 ("delete", "route/new", None)],
                guard=("route/back", None))     # exists -> guard fails
            assert not ok
            assert kv.get("route/clobber") is None
            assert kv.get("route/new") == b"r1"
            # the whole move is one log entry on every replica
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.applied_idx == leader.applied_idx,
                     what="follower apply")
            assert follower.state.get("route/new") == b"r1"
            assert "route/old" not in follower.state
        finally:
            for nd in nodes:
                nd.stop()

    def test_memkv_batch_guard(self):
        from greptimedb_tpu.meta.kv import MemKv
        kv = MemKv()
        kv.put("a", b"1")
        assert kv.batch([("put", "b", b"2"), ("delete", "a", None)],
                        guard=("b", None))
        assert kv.get("a") is None and kv.get("b") == b"2"
        assert not kv.batch([("put", "c", b"3")], guard=("b", None))
        assert kv.get("c") is None


class TestReplication:
    def test_writes_survive_leader_kill(self, tmp_path):
        nodes = make_cluster(3, tmp_path)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            for i in range(5):
                kv.put(f"key{i}", f"val{i}".encode())
            assert kv.compare_and_put("locked", None, b"a")
            crash(leader)
            partition_away(nodes, leader)
            survivors = [nd for nd in nodes if nd is not leader]
            new = wait_for(
                lambda: next((nd for nd in survivors if nd.is_leader),
                             None), what="re-election")
            kv2 = ReplicatedKv(new)
            for i in range(5):
                assert kv2.get(f"key{i}") == f"val{i}".encode()
            # CAS state carried over: second acquire must fail
            assert not kv2.compare_and_put("locked", None, b"b")
            assert kv2.compare_and_put("locked", b"a", b"b")
        finally:
            for nd in nodes:
                nd.stop()

    def test_incr_monotonic_across_failover(self, tmp_path):
        nodes = make_cluster(3, tmp_path)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            seen = [kv.incr("seq") for _ in range(3)]
            crash(leader)
            partition_away(nodes, leader)
            survivors = [nd for nd in nodes if nd is not leader]
            new = wait_for(
                lambda: next((nd for nd in survivors if nd.is_leader),
                             None), what="re-election")
            seen += [ReplicatedKv(new).incr("seq") for _ in range(3)]
            assert seen == sorted(set(seen)), "ids must stay unique+ordered"
        finally:
            for nd in nodes:
                nd.stop()

    def test_follower_catches_up(self):
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            kv.put("a", b"1")
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.state.get("a") == b"1",
                     what="follower apply")
        finally:
            for nd in nodes:
                nd.stop()

    def test_non_utf8_values_roundtrip(self):
        """Arbitrary bytes survive the JSON-encoded raft log (latin-1
        bridge) — matching MemKv/FileKv byte semantics."""
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            blob = bytes(range(256))
            kv.put("bin", blob)
            assert kv.get("bin") == blob
            assert kv.compare_and_put("bin", blob, b"\xff\xfe\x00")
            assert kv.get("bin") == b"\xff\xfe\x00"
            kv.batch([("put", "bin2", b"\x80\x81")])
            assert kv.get("bin2") == b"\x80\x81"
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.state.get("bin2") == b"\x80\x81",
                     what="binary replication")
        finally:
            for nd in nodes:
                nd.stop()


class TestLogCompaction:
    def test_log_stays_bounded(self, tmp_path):
        """1k writes keep the in-memory log and per-append persist cost
        bounded by compact_threshold (no O(n^2) bytes), and state stays
        complete across a restart from snapshot + tail."""
        import os as _os
        ids = [1, 2, 3]
        nodes = [RaftNode(i, ids, compact_threshold=32,
                          store_path=str(tmp_path / f"raft-{i}.json"),
                          **FAST) for i in ids]
        connect_local(nodes)
        for nd in nodes:
            nd.start()
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            for i in range(1000):
                kv.put(f"k{i % 50}", f"v{i}".encode())
            assert len(leader.log) <= 32 + 4, \
                "log must compact at the threshold"
            assert leader.base > 900
            # per-append persisted bytes are bounded: the log file holds
            # only the tail
            log_bytes = _os.path.getsize(tmp_path / "raft-1.json") + \
                _os.path.getsize(tmp_path / "raft-2.json")
            assert log_bytes < 64_000, "log file must stay tail-sized"
            assert kv.get("k49") is not None
            # full restart from snapshot + tail recovers everything
            lid = leader.node_id
            committed = leader.commit_idx     # everything acked pre-stop
            for nd in nodes:
                nd.stop()
            revived = [RaftNode(i, ids, compact_threshold=32,
                                store_path=str(tmp_path / f"raft-{i}.json"),
                                **FAST) for i in ids]
            connect_local(revived)
            for nd in revived:
                nd.start()
            nodes.extend(revived)
            leader2 = leader_of(revived)
            kv2 = ReplicatedKv(leader2)
            # >= base only proves the snapshot applied; the revived
            # leader must re-apply the persisted TAIL too before the
            # asserted values are visible (flaked under full-suite load)
            wait_for(lambda: leader2.applied_idx >= committed,
                     what="revived apply")
            for i in range(950, 1000):
                assert kv2.get(f"k{i % 50}") == f"v{i}".encode()
        finally:
            for nd in nodes:
                nd.stop()

    def test_crash_between_snapshot_and_log_write(self, tmp_path):
        """_compact_locked persists the .snap file first, then rewrites
        the log file with the advanced base. The `_load` overlap-drop
        branch (replication.py: "snapshot advanced past the log file")
        claims a crash BETWEEN those two writes is safe — this test
        actually creates that on-disk state and proves recovery: the
        reloaded node must drop the already-snapshotted overlap, apply
        the tail exactly once, and serve the full pre-crash state."""
        import shutil as _shutil
        ids = [1, 2, 3]
        nodes = [RaftNode(i, ids, compact_threshold=32,
                          store_path=str(tmp_path / f"raft-{i}.json"),
                          **FAST) for i in ids]
        connect_local(nodes)
        for nd in nodes:
            nd.start()
        stale = tmp_path / "stale-log.json"
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            # fill past one compaction so base > 0, then snapshot the
            # CURRENT log file (pre-next-compaction state)
            for i in range(100):
                kv.put(f"k{i % 20}", f"v{i}".encode())
            wait_for(lambda: leader.base > 0, what="first compaction")
            lid = leader.node_id
            log_path = tmp_path / f"raft-{lid}.json"
            _shutil.copy(log_path, stale)
            base_at_copy = leader.base
            # more writes + another compaction advance base and state
            for i in range(100, 200):
                kv.put(f"k{i % 20}", f"v{i}".encode())
            with leader._lock:
                last = leader._last_index()
            # push compaction past the last k-write so the snapshot alone
            # (the crash-consistent part) carries the full expected state
            j = 0
            while leader.base < last and j < 300:
                kv.put("filler", f"f{j}".encode())
                j += 1
            assert leader.base >= last, "compaction must pass the k-writes"
            expected = {f"k{j}": f"v{180 + j}" for j in range(20)}
            for nd in nodes:
                nd.stop()
            # simulate the crash: .snap is the NEW snapshot (written
            # first), but the log file never got its post-compaction
            # rewrite — restore the stale pre-compaction log, whose base
            # is BELOW the snapshot's and whose tail overlaps it
            _shutil.copy(stale, log_path)
            revived = RaftNode(lid, ids, compact_threshold=32,
                               store_path=str(log_path), **FAST)
            assert revived.base > base_at_copy, \
                "snapshot must define the base"
            assert revived.applied_idx == revived.base
            # overlap dropped: no log entry at or below the base survives
            assert len(revived.log) <= 32 + 4
            for key, val in expected.items():
                assert revived.state.get(key) == val.encode(), key
        finally:
            for nd in nodes:
                nd.stop()

    def test_lagging_follower_gets_snapshot_install(self):
        """A follower partitioned past the leader's compaction horizon
        rejoins via InstallSnapshot (not an index-0 replay) and
        converges."""
        ids = [1, 2, 3]
        nodes = [RaftNode(i, ids, compact_threshold=16, **FAST)
                 for i in ids]
        connect_local(nodes)
        for nd in nodes:
            nd.start()
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            kv.put("seed", b"1")
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.state.get("seed") == b"1",
                     what="initial sync")
            # partition the follower, then write far past the threshold
            follower.stop()
            partition_away(nodes, follower)
            for i in range(200):
                kv.put(f"k{i}", f"v{i}".encode())
            assert leader.base > 100, "leader must have compacted"
            assert follower.base == 0
            # reconnect: the needed tail is gone; snapshot must flow
            live = [nd for nd in nodes if nd is not follower] + [follower]
            connect_local(live)
            follower.start()
            wait_for(lambda: follower.state.get("k199") == b"v199",
                     what="snapshot install catch-up")
            assert follower.base > 0, "follower must have installed a " \
                "snapshot, not replayed from zero"
            assert follower.state.get("seed") == b"1"
        finally:
            for nd in nodes:
                nd.stop()


class TestMetaSrvFailover:
    """The VERDICT bar: kill the meta leader; routes stay resolvable."""

    def test_routes_survive_leader_kill(self, tmp_path):
        nodes = make_cluster(3, tmp_path)
        srvs = [MetaSrv(ReplicatedKv(nd)) for nd in nodes]
        ha = HaMetaClient(srvs)
        try:
            leader_of(nodes)
            ha.register(Peer(1, "dn1"))
            ha.register(Peer(2, "dn2"))
            ha.heartbeat(1)
            ha.heartbeat(2)
            route = ha.create_route("greptime.public.t1", [0, 1])
            tid = route.table_id
            leader = next(nd for nd in nodes if nd.is_leader)
            crash(leader)
            partition_away(nodes, leader)
            got = wait_for(lambda: _try_route(ha, "greptime.public.t1"),
                           what="route after failover")
            assert got.table_id == tid
            assert sorted(rr.region_number
                          for rr in got.region_routes) == [0, 1]
            # datanodes keep heartbeating; the new leader learns liveness
            # from them (its in-memory last-seen starts empty)
            ha.heartbeat(1)
            ha.heartbeat(2)
            # the new leader keeps allocating non-colliding table ids
            r2 = ha.create_route("greptime.public.t2", [0])
            assert r2.table_id != tid
        finally:
            for nd in nodes:
                nd.stop()


def _try_route(ha, name):
    try:
        return ha.route(name)
    except GreptimeError:
        return None


class TestFlightTransport:
    def test_wire_replication(self):
        from greptimedb_tpu.meta.flight import FlightMetaServer
        ids = [1, 2, 3]
        nodes = [RaftNode(i, ids, **FAST) for i in ids]
        servers = [FlightMetaServer(MetaSrv(ReplicatedKv(nd)),
                                    raft_node=nd) for nd in nodes]
        try:
            for s in servers:
                s.serve_in_background()
            for a, sa in zip(nodes, servers):
                for b, sb in zip(nodes, servers):
                    if a is not b:
                        a.transports[b.node_id] = FlightTransport(sb.address)
            for nd in nodes:
                nd.start()
            leader = wait_for(
                lambda: next((nd for nd in nodes if nd.is_leader), None),
                what="wire leader election")
            kv = ReplicatedKv(leader)
            kv.put("wire", b"ok")
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.state.get("wire") == b"ok",
                     what="wire follower apply")
        finally:
            for nd in nodes:
                nd.stop()
            for s in servers:
                s.shutdown()

    def test_cluster_info_redirects_to_leader(self):
        """Heartbeat state is leader-local memory: a follower answering
        cluster_info would report a healthy cluster as all-unknown, so
        it must raise NotLeaderError instead — and the failover client
        must ride that redirect to the leader's live view."""
        from greptimedb_tpu.meta.flight import (
            FailoverFlightMetaClient, FlightMetaClient, FlightMetaServer)
        ids = [1, 2, 3]
        nodes = [RaftNode(i, ids, **FAST) for i in ids]
        servers = [FlightMetaServer(MetaSrv(ReplicatedKv(nd)),
                                    raft_node=nd) for nd in nodes]
        try:
            for s in servers:
                s.serve_in_background()
            for a, sa in zip(nodes, servers):
                for b, sb in zip(nodes, servers):
                    if a is not b:
                        a.transports[b.node_id] = FlightTransport(sb.address)
            for nd in nodes:
                nd.start()
            leader = wait_for(
                lambda: next((nd for nd in nodes if nd.is_leader), None),
                what="wire leader election")
            leader_srv = servers[ids.index(leader.node_id)]
            leader_srv.srv.handle_heartbeat(7)     # registers datanode 7
            # a follower must redirect rather than answer from its empty
            # heartbeat memory. Leadership can churn under load with the
            # FAST election timeouts, so retry until we catch a node
            # answering while it is actually a follower.
            deadline = time.monotonic() + 8.0
            while True:
                follower_i = next((i for i, nd in enumerate(nodes)
                                   if not nd.is_leader), None)
                if follower_i is not None:
                    direct = FlightMetaClient(servers[follower_i].address)
                    try:
                        direct.cluster_info()
                    except NotLeaderError:
                        break                      # the expected redirect
                    finally:
                        direct.close()
                    if not nodes[follower_i].is_leader:
                        raise AssertionError(
                            "follower served cluster_info without "
                            "redirecting")
                if time.monotonic() > deadline:
                    raise AssertionError(
                        "never caught a stable follower to probe")
                time.sleep(0.05)
            ha = FailoverFlightMetaClient([s.address for s in servers])
            try:
                ha.cluster_info()                  # rides redirect → leader
                ha.heartbeat(7)                    # lands on that leader
                info = {n["peer_id"]: n for n in ha.cluster_info()}
                assert info[7]["lease_state"] == "alive"
                assert info[-1]["lease_state"] == "leader"
            finally:
                ha.close()
        finally:
            for nd in nodes:
                nd.stop()
            for s in servers:
                s.shutdown()


class TestConcurrentProposals:
    def test_parallel_writers_all_committed(self):
        """Many threads propose through the leader at once: every op must
        commit exactly once and the final state must reflect all of them
        (the scheduler replicates concurrently with the heartbeat
        ticker — the raft log-matching rules keep the log consistent)."""
        import threading
        nodes = make_cluster(3)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            errs = []

            def writer(tid):
                try:
                    for i in range(10):
                        kv.put(f"t{tid}-{i}", f"v{tid}-{i}".encode())
                        kv.incr("shared_seq")
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=writer, args=(t,))
                       for t in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errs, errs
            for tid in range(6):
                for i in range(10):
                    assert kv.get(f"t{tid}-{i}") == f"v{tid}-{i}".encode()
            assert int(kv.get("shared_seq")) == 60
            # followers converge to the same state
            follower = next(nd for nd in nodes if nd is not leader)
            wait_for(lambda: follower.applied_idx >= leader.applied_idx,
                     what="follower convergence")
            assert follower.state.get("shared_seq") == \
                leader.state.get("shared_seq")
        finally:
            for nd in nodes:
                nd.stop()


class TestRestartRejoin:
    def test_restarted_node_rejoins_with_word_kept(self, tmp_path):
        """A node restarted from its persisted (term, vote, log) rejoins
        and catches up; a full-cluster restart recovers all state by
        replay (the reference's etcd equivalent: raft snapshot + WAL)."""
        nodes = make_cluster(3, tmp_path)
        try:
            leader = leader_of(nodes)
            kv = ReplicatedKv(leader)
            for i in range(4):
                kv.put(f"r{i}", f"x{i}".encode())
            follower = next(nd for nd in nodes if nd is not leader)
            fid = follower.node_id
            wait_for(lambda: follower.state.get("r3") == b"x3",
                     what="follower sync")
            # stop the follower, write more, restart it from disk
            follower.stop()
            partition_away(nodes, follower)
            kv.put("during_outage", b"yes")
            revived = RaftNode(fid, [nd.node_id for nd in nodes],
                               store_path=str(tmp_path / f"raft-{fid}.json"),
                               **FAST)
            assert len(revived.log) >= 4, "persisted log must reload"
            live = [nd for nd in nodes if nd is not follower] + [revived]
            connect_local(live)
            revived.start()
            wait_for(lambda: revived.state.get("during_outage") == b"yes",
                     what="revived catch-up")
            assert revived.state.get("r0") == b"x0"
        finally:
            for nd in nodes:
                nd.stop()
