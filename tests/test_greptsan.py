"""Tier-1 gate for greptsan (devtools/greptsan), the happens-before
race detector: the selftest (every seeded concurrency bug fires), the
no-false-positive proof over the real flush+scan+compact path, the
multi-thread hammer (concurrent ingest+flush+compact+scatter+balancer
tick+self-monitor scrape must report ZERO races — the burn-down
regression surface), and the suppression-baseline policy (zero entries,
only ever shrinks).

The session-wide gate lives in tests/conftest.py: any unsuppressed race
recorded by ANY test fails the whole run at sessionfinish.
"""

import json
import subprocess
import sys
import threading
import time

import pytest

from greptimedb_tpu.devtools import greptsan
from greptimedb_tpu.devtools.greptsan import detector, selftest as seeded
from greptimedb_tpu.common.locks import TrackedLock

REPO = __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
BASELINE = __import__("os").path.join(REPO, ".greptsan-baseline.json")


@pytest.fixture(autouse=True)
def _isolated():
    """Seeded fixtures deliberately race; drain them so the session
    gate only ever sees races from production code paths."""
    detector.reset()
    yield
    detector.reset()


def _race_states(reports):
    return {r.state for r in reports}


class TestSeededBugsFire:
    def test_unlocked_dict_mutation_across_threads(self):
        name = seeded.unlocked_dict_mutation()
        reports = detector.drain_races()
        assert name in _race_states(reports), (
            f"seeded unlocked-dict race did not fire; got "
            f"{_race_states(reports)}")

    def test_notify_without_lock(self):
        name = seeded.notify_without_lock()
        reports = detector.drain_races()
        assert name in _race_states(reports), (
            f"seeded notify-before-publish race did not fire; got "
            f"{_race_states(reports)}")

    def test_pool_result_read_before_join_edge(self):
        name = seeded.pool_result_before_join()
        reports = detector.drain_races()
        assert name in _race_states(reports), (
            f"seeded done()-polling race did not fire; got "
            f"{_race_states(reports)}")

    def test_report_names_both_stacks_and_missing_edge(self):
        seeded.unlocked_dict_mutation()
        [report] = [r for r in detector.drain_races()
                    if r.state == "greptsan.selftest.unlocked_dict"][:1]
        text = report.render()
        assert "DATA RACE" in text
        assert "prior" in text and "current" in text
        # both stacks must carry the RACING frames (the fixture's bump
        # workers), not just detector/threading internals — regression
        # for the substring frame filter that ate selftest frames
        assert text.count("in bump") >= 2
        assert "missing edge" in text
        assert report.suppression_key().startswith(
            "greptsan.selftest.unlocked_dict:")


class TestHappensBeforeEdgesSuppressRaces:
    """The dual of the seeded tests: each sanctioned synchronization
    idiom must NOT report (a detector that cries wolf gets turned off)."""

    def test_same_tracked_lock_orders_access(self):
        lk = TrackedLock("t.san_edge_lock", force=True)
        d = greptsan.tracked_state({}, "t.san_locked")

        def bump():
            for _ in range(20):
                with lk:
                    d["n"] = d.get("n", 0) + 1

        ts = [threading.Thread(target=bump) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not detector.drain_races()
        assert d["n"] == 60

    def test_thread_join_edge(self):
        d = greptsan.tracked_state({}, "t.san_join")

        def child():
            d["x"] = 1

        t = threading.Thread(target=child)
        t.start()
        t.join()
        d["x"] = 2                         # ordered by join()
        assert not detector.drain_races()

    def test_pool_submit_and_result_edges(self):
        from concurrent.futures import ThreadPoolExecutor
        d = greptsan.tracked_state({}, "t.san_pool_ok")
        d["x"] = 0                         # submit edge orders this
        with ThreadPoolExecutor(2) as p:
            f = p.submit(lambda: d.__setitem__("x", d["x"] + 1))
            f.result()                     # result edge orders the next
            d["x"] = 9
        assert not detector.drain_races()

    def test_event_set_wait_edge(self):
        d = greptsan.tracked_state({}, "t.san_event")
        ev = threading.Event()

        def producer():
            d["x"] = 1
            ev.set()

        t = threading.Thread(target=producer)
        t.start()
        assert ev.wait(10)
        d["x"] = 2                         # ordered by set->wait
        t.join()
        assert not detector.drain_races()

    def test_condition_handoff_over_tracked_lock(self):
        lk = TrackedLock("t.san_cond", force=True)
        cond = threading.Condition(lk)
        d = greptsan.tracked_state({}, "t.san_cond_state")

        def producer():
            with cond:
                d["ready"] = 1             # published BEFORE the notify
                cond.notify()

        t = threading.Thread(target=producer)
        with cond:
            t.start()
            while not d.get("ready"):
                cond.wait(timeout=10)
        t.join()
        assert not detector.drain_races()


class TestNoFalsePositivesOnStorage:
    def test_flush_scan_compact_is_clean(self, tmp_path):
        """The real storage interleaving (the lock-order detector's
        no-FP scenario, now replayed against the race detector): tracked
        region maps, caches and scheduler queues see concurrent ingest,
        reads, flushes and compactions — zero reports."""
        from greptimedb_tpu.datanode.instance import (DatanodeInstance,
                                                      DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance

        assert greptsan.enabled()
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False,
            flush_size_bytes=64 * 1024))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        try:
            fe.do_query("CREATE TABLE sanfp (host STRING, ts TIMESTAMP "
                        "TIME INDEX, v DOUBLE, PRIMARY KEY(host))")
            detector.drain_races()         # isolate this workload
            stop = threading.Event()
            errors = []

            def writer():
                try:
                    for i in range(150):
                        fe.do_query(f"INSERT INTO sanfp VALUES"
                                    f" ('h{i % 4}', {i}, {float(i)})")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def reader():
                try:
                    while not stop.is_set():
                        fe.do_query("SELECT count(*) FROM sanfp")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def flusher():
                t = fe.catalog.table("greptime", "public", "sanfp")
                try:
                    while not stop.is_set():
                        t.flush()
                        for region in dn.storage.list_regions().values():
                            region.schedule_compaction()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            ts = [threading.Thread(target=f)
                  for f in (writer, reader, flusher)]
            for t in ts:
                t.start()
            ts[0].join(timeout=120)
            stop.set()
            for t in ts:
                t.join(timeout=30)
            assert not errors, errors
            reports = detector.drain_races()
            assert not reports, "false positive(s) on storage path:\n" + \
                "\n".join(r.render() for r in reports)
        finally:
            fe.shutdown()


class TestHammer:
    def test_concurrent_everything_reports_zero_races(self, tmp_path):
        """The burn-down surface: concurrent ingest + flush + compact +
        distributed scatter + balancer tick + self-monitor scrape over
        an in-process 2-datanode cluster. Every race this hammer ever
        finds gets FIXED (plus a regression test), never suppressed —
        the suppression baseline stays at zero entries."""
        from test_balancer import Cluster

        assert greptsan.enabled()
        c = Cluster(tmp_path, nodes=(1, 2))
        try:
            c.fe.do_query(
                "CREATE TABLE hammer (host STRING, ts TIMESTAMP TIME "
                "INDEX, v DOUBLE, PRIMARY KEY(host)) "
                "PARTITION BY HASH (host) PARTITIONS 4")
            detector.drain_races()
            stop = threading.Event()
            errors = []

            def guard(fn):
                def run():
                    try:
                        fn()
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                return run

            def ingest():
                i = 0
                while not stop.is_set():
                    vals = ", ".join(
                        f"('h{j % 8}', {i * 50 + j}, {float(j)})"
                        for j in range(50))
                    c.fe.do_query(f"INSERT INTO hammer VALUES {vals}")
                    i += 1

            def scatter():
                while not stop.is_set():
                    c.fe.do_query("SELECT host, count(*), max(v) FROM "
                                  "hammer GROUP BY host")
                    c.fe.do_query("SELECT count(*) FROM hammer "
                                  "WHERE host = 'h3'")

            def flush_compact():
                while not stop.is_set():
                    for dn in list(c.datanodes.values()):
                        for region in \
                                dn.storage.list_regions().values():
                            region.flush()
                            region.schedule_compaction()
                    time.sleep(0.01)

            def balancer_pump():
                while not stop.is_set():
                    c.srv.balancer.tick()
                    for i in list(c.datanodes):
                        resp = c.srv.handle_heartbeat(i)
                        for msg in resp.mailbox:
                            c.datanodes[i]._handle_mailbox(msg)
                    c.srv.cluster_info()
                    c.srv.region_heat()
                    time.sleep(0.005)

            def monitor():
                while not stop.is_set():
                    c.fe.self_monitor.tick()
                    time.sleep(0.02)

            ts = [threading.Thread(target=guard(f), name=f"hammer-{i}")
                  for i, f in enumerate((ingest, scatter, flush_compact,
                                         balancer_pump, monitor))]
            for t in ts:
                t.start()
            time.sleep(6.0)
            stop.set()
            for t in ts:
                t.join(timeout=60)
            assert not errors, errors
            reports = detector.drain_races()
            assert not reports, (
                "hammer found data race(s) — fix them (never suppress):"
                "\n" + "\n".join(r.render() for r in reports))
        finally:
            c.shutdown()


class TestSuppressionPolicy:
    def test_baseline_exists_version_1_and_zero_entries(self):
        """ISSUE 10 acceptance: the baseline is burned to zero in this
        PR and — like greptlint's — only ever shrinks. With a floor of
        zero, 'only shrinks' means it stays empty forever."""
        with open(BASELINE, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc.get("version") == 1
        assert doc.get("suppressions") == {}, (
            "greptsan suppressions must stay at ZERO entries: fix the "
            "race instead (ISSUE 10 burn-down policy)")

    def test_loader_and_filter_roundtrip(self, tmp_path):
        seeded.unlocked_dict_mutation()
        reports = detector.drain_races()
        assert reports
        key = reports[0].suppression_key()
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({
            "version": 1,
            "suppressions": {key: "seeded fixture, test-only"}}))
        left = detector.unsuppressed(reports[:1], path=str(bl))
        assert left == []
        # and an unrelated key still passes through
        left = detector.unsuppressed(reports[:1],
                                     path=str(tmp_path / "missing.json"))
        assert left == reports[:1]

    def test_suppression_key_is_stable_across_runs(self):
        seeded.pool_result_before_join()
        k1 = {r.suppression_key() for r in detector.drain_races()}
        seeded.pool_result_before_join()
        k2 = {r.suppression_key() for r in detector.drain_races()}
        assert k1 & k2, "same seeded bug must produce a stable key"


class TestProxyFidelity:
    def test_tracked_ordereddict_copy_returns_plain(self):
        """Regression: OrderedDict.copy() builds self.__class__(self),
        whose first positional on the proxy is the tracker NAME — the
        inherited copy raised TypeError only under the detector (the
        cache/scheduler structures are TrackedOrderedDicts in tests)."""
        from collections import OrderedDict
        d = greptsan.tracked_state(OrderedDict([("a", 1), ("b", 2)]),
                                   "t.od_copy")
        c = d.copy()
        assert type(c) is OrderedDict and c == OrderedDict(
            [("a", 1), ("b", 2)])
        d2 = greptsan.tracked_state({"a": 1}, "t.d_copy")
        assert type(d2.copy()) is dict and d2.copy() == {"a": 1}
        detector.drain_races()


class TestInactiveMode:
    def test_tracked_state_is_identity_when_off(self):
        """GREPTIME_RACE_CHECK=0 ⇒ tracked_state returns its argument
        unchanged (same object, plain type) — production pays nothing
        (bench.py greptsan_inactive_overhead asserts the wall clock)."""
        code = (
            "from greptimedb_tpu.devtools.greptsan import tracked_state,"
            " enabled\n"
            "assert not enabled()\n"
            "d = {}\n"
            "assert tracked_state(d, 'x') is d\n"
            "assert type(tracked_state(d, 'x')) is dict\n"
            "import threading\n"
            "from greptimedb_tpu.common.locks import TrackedLock\n"
            "assert type(TrackedLock('x')) is type(threading.Lock())\n"
            "assert threading.Thread.start.__qualname__ == "
            "'Thread.start'\n"       # stdlib unpatched when off
            "print('OFF_OK')\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
            env={"GREPTIME_RACE_CHECK": "0", "GREPTIME_LOCK_CHECK": "0",
                 "PATH": "/usr/bin", "JAX_PLATFORMS": "cpu"})
        assert "OFF_OK" in proc.stdout, proc.stderr

    def test_race_check_env_forces_lock_tracking_on(self):
        """GREPTIME_RACE_CHECK=1 outside pytest must switch the lock
        detector on too — greptsan's lock edges ride its hooks."""
        code = (
            "from greptimedb_tpu.common import locks\n"
            "from greptimedb_tpu.devtools.greptsan import detector\n"
            "assert locks.enabled() and detector.enabled()\n"
            "assert locks._RACE_HOOKS is not None\n"
            "print('FORCED_ON')\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
            env={"GREPTIME_RACE_CHECK": "1", "PATH": "/usr/bin",
                 "JAX_PLATFORMS": "cpu"})
        assert "FORCED_ON" in proc.stdout, proc.stderr


class TestGenerationHygiene:
    def test_new_generation_clears_vars_but_keeps_races(self):
        seeded.unlocked_dict_mutation()
        n = len(detector.races())
        assert n >= 1
        detector.new_generation()
        assert len(detector.races()) == n      # races survive
        with detector._san_lock:
            assert not detector._vars          # metadata does not
