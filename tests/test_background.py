"""Background machinery tests: scheduler, async flush + write stall,
compaction, TTL, file purger, downsample.

Mirrors the reference suites: src/storage/src/scheduler.rs tests,
region/tests/flush.rs, region/tests/compact.rs,
compaction/strategy.rs:130-322 bucketing tests, file_purger.rs tests.
"""

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.datatypes import data_type as dt
from greptimedb_tpu.datatypes.schema import ColumnSchema, Schema, SemanticType
from greptimedb_tpu.storage.compaction import (
    infer_time_bucket_ms, pick_compaction)
from greptimedb_tpu.storage.downsample import downsample_region
from greptimedb_tpu.storage.engine import EngineConfig, StorageEngine
from greptimedb_tpu.storage.file_purger import FilePurger
from greptimedb_tpu.storage.scheduler import LocalScheduler, RepeatedTask
from greptimedb_tpu.storage.sst import FileMeta, LevelMetas
from greptimedb_tpu.storage.write_batch import WriteBatch


def monitor_schema():
    return Schema([
        ColumnSchema("host", dt.STRING, nullable=False,
                     semantic_type=SemanticType.TAG),
        ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP),
        ColumnSchema("cpu", dt.FLOAT64),
    ])


def mk_engine(tmp_path, **cfg):
    cfg.setdefault("purge_grace_s", 0.0)
    cfg.setdefault("purge_interval_s", 3600)   # manual sweeps in tests
    return StorageEngine(EngineConfig(data_home=str(tmp_path), **cfg))


def put(region, hosts, ts, cpu):
    wb = WriteBatch(region.schema)
    wb.put({"host": hosts, "ts": ts, "cpu": cpu})
    region.write(wb)


def rows_of(region):
    data = region.snapshot().read_merged()
    return sorted(zip(region.series_dict.decode_tag_column(
        data.series_ids, 0), data.ts.tolist(),
        data.fields["cpu"][0].tolist()))


class TestScheduler:
    def test_dedup_queued(self):
        s = LocalScheduler(max_inflight=1)
        gate = threading.Event()
        ran = []

        def blocker():
            gate.wait(5)
            ran.append("block")

        def job():
            ran.append("job")

        s.submit("block", blocker)
        h1 = s.submit("k", job)
        h2 = s.submit("k", job)          # coalesces with h1
        assert h1 is h2
        gate.set()
        h1.wait(5)
        s.wait_idle(5)
        assert ran.count("job") == 1
        s.stop()

    def test_resubmit_while_running(self):
        s = LocalScheduler(max_inflight=2)
        started = threading.Event()
        gate = threading.Event()
        count = []

        def job():
            started.set()
            gate.wait(5)
            count.append(1)

        s.submit("k", job)
        assert started.wait(5)
        h2 = s.submit("k", lambda: count.append(1))   # queued follow-up
        gate.set()
        h2.wait(5)
        s.wait_idle(5)
        assert len(count) == 2
        s.stop()

    def test_error_propagates(self):
        s = LocalScheduler(max_inflight=1)

        def boom():
            raise ValueError("x")

        h = s.submit("k", boom)
        with pytest.raises(ValueError):
            h.wait(5)
        s.stop()

    def test_submit_after_stop_raises_taxonomy_error(self):
        """Regression (greptlint GL05): the stopped-scheduler rejection
        used to be a bare RuntimeError, invisible to the errors.*
        taxonomy; SchedulerStoppedError keeps RuntimeError compat for
        the shutdown paths that catch it."""
        from greptimedb_tpu.errors import (GreptimeError,
                                           SchedulerStoppedError,
                                           StorageError)
        s = LocalScheduler(max_inflight=1)
        s.stop()
        with pytest.raises(SchedulerStoppedError) as ei:
            s.submit("k", lambda: None)
        assert isinstance(ei.value, StorageError)
        assert isinstance(ei.value, GreptimeError)
        assert isinstance(ei.value, RuntimeError)   # legacy catch sites

    def test_stop_drains(self):
        s = LocalScheduler(max_inflight=1)
        out = []
        for i in range(5):
            s.submit(f"k{i}", lambda i=i: out.append(i))
        s.stop(drain=True)
        assert sorted(out) == [0, 1, 2, 3, 4]

    def test_repeated_task(self):
        hits = []
        t = RepeatedTask(0.05, lambda: hits.append(1))
        t.start()
        time.sleep(0.3)
        t.stop()
        assert len(hits) >= 2


class TestAsyncFlush:
    def test_write_triggers_background_flush(self, tmp_path):
        eng = mk_engine(tmp_path, flush_size_bytes=2000)
        r = eng.create_region("r", monitor_schema())
        for i in range(40):
            put(r, [f"h{i % 4}"] * 10, list(range(i * 10, i * 10 + 10)),
                [float(i)] * 10)
        eng.scheduler.wait_idle(30)
        v = r.version_control.current
        assert len(v.ssts.all_files()) >= 1
        assert v.flushed_sequence > 0
        # all rows still visible through the merged scan
        assert len(rows_of(r)) == 400
        eng.close()

    def test_flush_wait_semantics(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        put(r, ["a", "b"], [1, 2], [1.0, 2.0])
        files = r.flush()
        assert len(files) == 1
        assert r.version_control.current.memtables.total_bytes == 0
        eng.close()

    def test_flush_then_restart_replays_nothing(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        put(r, ["a"], [1], [1.0])
        r.flush()
        put(r, ["b"], [2], [2.0])     # in WAL only
        eng.close()
        eng2 = mk_engine(tmp_path)
        r2 = eng2.open_region("r")
        assert [h for h, _, _ in rows_of(r2)] == ["a", "b"]
        eng2.close()


class TestCompaction:
    def test_infer_bucket(self):
        assert infer_time_bucket_ms(1000) == 3600 * 1000
        assert infer_time_bucket_ms(3 * 3600 * 1000) == 12 * 3600 * 1000
        assert infer_time_bucket_ms(10**12) == 7 * 24 * 3600 * 1000

    def test_pick_respects_min_files(self):
        metas = LevelMetas().add_files([
            FileMeta("a", 0, (0, 10), 5, 100)])
        assert pick_compaction(metas, min_l0_files=2) is None
        plan = pick_compaction(metas, min_l0_files=1)
        assert [f.file_name for f in plan.inputs] == ["a"]

    def test_compact_merges_l0_to_l1(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        # 3 flushes → 3 L0 files with overlapping keys (later wins)
        for gen in range(3):
            put(r, ["a", "b"], [100, 200], [float(gen), float(gen) * 10])
            r.flush()
        assert len(r.version_control.current.ssts.levels[0]) == 3
        r.compact()
        v = r.version_control.current
        assert len(v.ssts.levels[0]) == 0
        assert len(v.ssts.levels[1]) == 1
        # newest generation visible, dedup collapsed history
        assert rows_of(r) == [("a", 100, 2.0), ("b", 200, 20.0)]
        l1 = v.ssts.levels[1][0]
        assert l1.num_rows == 2           # history physically collapsed
        eng.close()

    def test_scan_correct_mid_compaction(self, tmp_path):
        """Readers using the pre-compaction version stay correct: inputs
        are purged only after the grace period."""
        eng = mk_engine(tmp_path, purge_grace_s=3600)
        r = eng.create_region("r", monitor_schema())
        # overlapping time ranges so compaction really rewrites (disjoint
        # files would be trivially moved and nothing purged)
        for gen in range(2):
            put(r, ["a", "a"], [gen, gen + 1], [float(gen)] * 2)
            r.flush()
        snap_before = r.snapshot()
        r.compact()
        # old snapshot still reads the (now removed) input files
        data = snap_before.read_merged()
        assert data.num_rows == 3
        assert eng.purger.pending_count == 2
        eng.close()

    def test_purger_deletes_after_grace(self, tmp_path):
        eng = mk_engine(tmp_path, purge_grace_s=0.0)
        r = eng.create_region("r", monitor_schema())
        for gen in range(2):
            put(r, ["a", "a"], [gen, gen + 1], [float(gen)] * 2)
            r.flush()
        names = [f.file_name for f in
                 r.version_control.current.ssts.levels[0]]
        r.compact()
        assert eng.purger.sweep() == 2
        for n in names:
            assert not eng.store.exists(f"{r.descriptor.region_dir}/sst/{n}")
        # region still reads fine from L1
        assert len(rows_of(r)) == 3
        eng.close()

    def test_trivial_move_for_disjoint_files(self, tmp_path):
        """Time-disjoint L0 files re-level to L1 without a rewrite: same
        physical files, nothing purged, data intact."""
        eng = mk_engine(tmp_path, purge_grace_s=0.0)
        r = eng.create_region("r", monitor_schema())
        for gen in range(3):
            put(r, ["a", "b"], [gen * 10, gen * 10 + 1], [float(gen)] * 2)
            r.flush()
        names = sorted(f.file_name for f in
                       r.version_control.current.ssts.levels[0])
        r.compact()
        v = r.version_control.current
        assert not v.ssts.levels[0]
        assert sorted(f.file_name for f in v.ssts.levels[1]) == names
        assert eng.purger.sweep() == 0          # nothing deleted
        for n in names:
            assert eng.store.exists(f"{r.descriptor.region_dir}/sst/{n}")
        assert len(rows_of(r)) == 6
        # survives restart (manifest replays the move edit)
        eng.close()
        eng2 = mk_engine(tmp_path)
        r2 = eng2.open_region("r")
        v2 = r2.version_control.current
        assert sorted(f.file_name for f in v2.ssts.levels[1]) == names
        assert len(rows_of(r2)) == 6
        eng2.close()

    def test_auto_compaction_trigger(self, tmp_path):
        eng = mk_engine(tmp_path, flush_size_bytes=500, max_l0_files=2)
        r = eng.create_region("r", monitor_schema())
        for i in range(60):
            put(r, ["a"] * 5, list(range(i * 5, i * 5 + 5)), [1.0] * 5)
        eng.scheduler.wait_idle(30)
        v = r.version_control.current
        assert len(v.ssts.levels[1]) >= 1, "auto compaction never ran"
        assert len(rows_of(r)) == 300
        eng.close()

    def test_compaction_survives_restart(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        for gen in range(2):
            put(r, ["a", "b"], [1, 2], [float(gen), float(gen)])
            r.flush()
        r.compact()
        want = rows_of(r)
        eng.close()
        eng2 = mk_engine(tmp_path)
        r2 = eng2.open_region("r")
        assert rows_of(r2) == want
        assert len(r2.version_control.current.ssts.levels[1]) == 1
        eng2.close()

    def test_tombstones_survive_compaction(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        put(r, ["a", "b"], [1, 2], [1.0, 2.0])
        r.flush()                          # L0 #1 holds both rows
        wb = WriteBatch(r.schema)
        wb.delete({"host": ["a"], "ts": [1]})
        r.write(wb)
        r.flush()                          # L0 #2 holds the tombstone
        # compact ONLY the tombstone file: the delete must survive to L1
        # to keep shadowing L0 #1... compact both here and verify the key
        # stays deleted end-to-end
        r.compact()
        assert rows_of(r) == [("b", 2, 2.0)]
        eng.close()


class TestTtl:
    def test_ttl_rows_dropped_at_compaction(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        r.ttl_ms = 60_000
        now = 1_000_000
        put(r, ["a", "a", "a"], [now - 120_000, now - 30_000, now],
            [1.0, 2.0, 3.0])
        r.flush()
        r.compact(now_ms=now)
        got = rows_of(r)
        assert [t for _, t, _ in got] == [now - 30_000, now]
        eng.close()

    def test_ttl_whole_file_purge(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        r.ttl_ms = 60_000
        now = 10_000_000
        put(r, ["a"], [now - 600_000], [1.0])
        r.flush()
        put(r, ["a"], [now], [2.0])
        r.flush()
        assert r.apply_ttl(now_ms=now) == 1
        assert [t for _, t, _ in rows_of(r)] == [now]
        eng.close()

    def test_table_ttl_option_reaches_region(self, tmp_path):
        from greptimedb_tpu.mito import MitoEngine
        from greptimedb_tpu.table import CreateTableRequest
        eng = mk_engine(tmp_path)
        mito = MitoEngine(eng)
        t = mito.create_table(CreateTableRequest(
            "tt", monitor_schema(), primary_key_indices=[0],
            table_options={"ttl": "7d"}))
        region = next(iter(t.regions.values()))
        assert region.ttl_ms == 7 * 86_400_000
        eng.close()


class TestWriteStall:
    def test_stall_blocks_until_flush(self, tmp_path):
        eng = mk_engine(tmp_path, flush_size_bytes=800)
        r = eng.create_region("r", monitor_schema())
        r.stall_bytes = 1600
        # hammer writes; stall must keep frozen backlog bounded while
        # background flush drains — and nothing deadlocks
        for i in range(50):
            put(r, ["a"] * 8, list(range(i * 8, i * 8 + 8)), [1.0] * 8)
        eng.scheduler.wait_idle(30)
        assert len(rows_of(r)) == 400
        eng.close()


class TestDownsample:
    def test_downsample_1s_to_1m(self, tmp_path):
        eng = mk_engine(tmp_path)
        src = eng.create_region("src", monitor_schema())
        dst = eng.create_region("dst", monitor_schema())
        # 2 hosts × 300s of 1s samples
        n = 300
        for h in ("a", "b"):
            scale = 1.0 if h == "a" else 10.0
            put(src, [h] * n, [i * 1000 for i in range(n)],
                [scale * i for i in range(n)])
        wrote = downsample_region(src, dst, stride_ms=60_000)
        assert wrote == 2 * 5              # 5 minutes × 2 hosts
        got = rows_of(dst)
        # bucket 0 for host a: avg of 0..59 = 29.5
        assert ("a", 0, 29.5) == got[0]
        b0 = [g for g in got if g[0] == "b"][0]
        assert b0 == ("b", 0, 295.0)
        eng.close()

    def test_downsample_min_max_count(self, tmp_path):
        eng = mk_engine(tmp_path)
        src = eng.create_region("s2", monitor_schema())
        dst = eng.create_region("d2", monitor_schema())
        put(src, ["a"] * 4, [0, 1000, 60_000, 61_000], [5.0, 7.0, 1.0, 9.0])
        wrote = downsample_region(src, dst, stride_ms=60_000,
                                  aggs={"cpu": "max"})
        assert wrote == 2
        assert rows_of(dst) == [("a", 0, 7.0), ("a", 60_000, 9.0)]
        eng.close()


class TestReviewRegressions:
    def test_failed_flush_releases_stall(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        put(r, ["a"], [1], [1.0])
        # break SST writes; the stall event must still be released
        orig = r._flush_memtable
        r._flush_memtable = lambda mt: (_ for _ in ()).throw(IOError("disk"))
        with r._writer_lock:
            h = r._freeze_and_schedule_flush()
        with pytest.raises(IOError):
            h.wait(10)
        assert r._flush_done.is_set()
        r._flush_memtable = orig
        eng.close()

    def test_manual_compact_serialized_with_background(self, tmp_path):
        eng = mk_engine(tmp_path)
        r = eng.create_region("r", monitor_schema())
        for gen in range(2):
            put(r, ["a"], [1], [float(gen)])
            r.flush()
        # two concurrent manual compactions must not duplicate rows
        results = []
        ts_ = [threading.Thread(target=lambda: results.append(r.compact()))
               for _ in range(2)]
        for t in ts_:
            t.start()
        for t in ts_:
            t.join()
        v = r.version_control.current
        total_l1_rows = sum(f.num_rows for f in v.ssts.levels[1])
        assert total_l1_rows == 1, "duplicated L1 rows"
        eng.close()

    def test_close_force_purges_pending(self, tmp_path):
        eng = mk_engine(tmp_path, purge_grace_s=3600)
        r = eng.create_region("r", monitor_schema())
        for gen in range(2):
            put(r, ["a", "a"], [gen, gen + 1], [float(gen)] * 2)
            r.flush()
        names = [f.file_name for f in
                 r.version_control.current.ssts.levels[0]]
        region_dir = r.descriptor.region_dir
        store = eng.store
        r.compact()
        assert eng.purger.pending_count == 2
        eng.close()
        for n in names:
            assert not store.exists(f"{region_dir}/sst/{n}")


class TestTtlSweepTask:
    def test_periodic_ttl_sweep_drops_expired(self, tmp_path):
        eng = mk_engine(tmp_path, ttl_check_interval_s=0.1)
        r = eng.create_region("r", monitor_schema())
        r.ttl_ms = 1           # everything (epoch-near data) is expired
        put(r, ["a"], [1000], [1.0])
        r.flush()
        deadline = time.time() + 10
        while time.time() < deadline and \
                r.version_control.current.ssts.all_files():
            time.sleep(0.05)
        assert not r.version_control.current.ssts.all_files(), \
            "ttl sweep never dropped the expired file"
        eng.close()
