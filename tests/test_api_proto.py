"""greptime-proto interop plane: codec round-trips + Flight server.

Reference behavior: SDK tickets are GreptimeRequest protobufs
(src/client/src/database.rs:209-231), decoded by the Flight server
(src/servers/src/grpc/flight.rs:87-96). Field numbers mirror
greptime-proto v1 @ e8abf824 (src/api/Cargo.toml:13).
"""

import numpy as np
import pytest

from greptimedb_tpu.api import v1 as proto
from greptimedb_tpu.api.client import GreptimeDatabase


class TestCodec:
    def test_insert_round_trip(self):
        cols = [
            proto.Column.from_rows("host", ["a", "b", None],
                                   proto.ColumnDataType.STRING,
                                   proto.SemanticType.TAG),
            proto.Column.from_rows("ts", [1000, 2000, 3000],
                                   proto.ColumnDataType
                                   .TIMESTAMP_MILLISECOND,
                                   proto.SemanticType.TIMESTAMP),
            proto.Column.from_rows("v", [1.5, None, -2.5],
                                   proto.ColumnDataType.FLOAT64),
            proto.Column.from_rows("n", [-1, 2, None],
                                   proto.ColumnDataType.INT64),
            proto.Column.from_rows("ok", [True, False, True],
                                   proto.ColumnDataType.BOOLEAN),
        ]
        req = proto.GreptimeRequest(
            catalog="greptime", schema="public",
            insert=proto.InsertRequest("metrics", cols, row_count=3))
        data = proto.encode_greptime_request(req)
        got = proto.decode_greptime_request(data)
        assert got.catalog == "greptime" and got.schema == "public"
        ins = got.insert
        assert ins.table_name == "metrics" and ins.row_count == 3
        by_name = {c.column_name: c for c in ins.columns}
        assert by_name["host"].rows(3) == ["a", "b", None]
        assert by_name["host"].semantic_type == proto.SemanticType.TAG
        assert by_name["ts"].rows(3) == [1000, 2000, 3000]
        assert by_name["v"].rows(3) == [1.5, None, -2.5]
        assert by_name["n"].rows(3) == [-1, 2, None]
        assert by_name["ok"].rows(3) == [True, False, True]

    def test_query_round_trip(self):
        req = proto.GreptimeRequest(
            dbname="d", query=proto.QueryRequest(sql="SELECT 1"))
        got = proto.decode_greptime_request(
            proto.encode_greptime_request(req))
        assert got.query.sql == "SELECT 1"
        assert got.dbname == "d"

    def test_flight_metadata_affected_rows(self):
        data = proto.encode_affected_rows_metadata(42)
        assert proto.decode_flight_metadata_affected_rows(data) == 42

    def test_negative_ints_use_ten_byte_varints(self):
        # proto3 int64: negatives are 10-byte two's-complement varints
        c = proto.Column.from_rows("n", [-5], proto.ColumnDataType.INT64)
        dec = proto.decode_column(proto.encode_column(c))
        assert dec.rows(1) == [-5]

    def test_unknown_variant_flagged(self):
        from greptimedb_tpu.utils.protowire import field_bytes
        data = field_bytes(5, b"")     # DeleteRequest stub
        got = proto.decode_greptime_request(data)
        assert got.other == "delete"
        # unsupported DDL variants surface by name
        alter = field_bytes(4, field_bytes(3, b""))
        assert proto.decode_greptime_request(alter).ddl.other == "alter"


@pytest.fixture(scope="module")
def served():
    import tempfile

    from greptimedb_tpu.datanode.instance import (
        DatanodeInstance, DatanodeOptions)
    from greptimedb_tpu.frontend.instance import FrontendInstance
    from greptimedb_tpu.servers.flight import FlightFrontendServer
    dn = DatanodeInstance(DatanodeOptions(
        data_home=tempfile.mkdtemp(), register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    server = FlightFrontendServer(fe)
    server.serve_in_background()
    db = GreptimeDatabase(server.address)
    yield fe, db
    db.close()
    server.shutdown()
    fe.shutdown()


class TestInteropServer:
    """A reference-SDK-shaped client round-trips against our server."""

    def test_proto_insert_auto_creates_table(self, served):
        fe, db = served
        n = db.insert(
            "proto_metrics",
            {"host": ["h0", "h1", "h0"], "ts": [1000, 2000, 3000],
             "cpu": [0.5, None, 0.7]},
            tag_columns=["host"], timestamp_column="ts")
        assert n == 3

    def test_proto_sql_query(self, served):
        fe, db = served
        table, affected = db.sql(
            "SELECT host, cpu FROM proto_metrics ORDER BY ts")
        assert affected is None
        assert table.column("host").to_pylist() == ["h0", "h1", "h0"]
        assert table.column("cpu").to_pylist() == [0.5, None, 0.7]

    def test_proto_sql_affected_rows(self, served):
        fe, db = served
        table, affected = db.sql(
            "INSERT INTO proto_metrics VALUES ('h2', 4000, 1.0)")
        assert table is None
        assert affected == 1

    def test_json_tickets_still_work(self, served):
        import json

        import pyarrow.flight as flight
        fe, db = served
        reader = db.conn.do_get(flight.Ticket(json.dumps(
            {"type": "sql", "sql": "SELECT count(*) FROM proto_metrics"}
        ).encode()))
        table = reader.read_all()
        assert table.column(0)[0].as_py() == 4


    def test_proto_ddl_create_insert_drop(self, served):
        """The reference Database::create flow: DdlRequest(CreateTable)
        -> typed insert -> query -> drop, all over protobuf tickets."""
        fe, db = served
        db.create(
            "proto_ddl_t",
            [("host", proto.ColumnDataType.STRING),
             ("ts", proto.ColumnDataType.TIMESTAMP_MILLISECOND),
             ("n", proto.ColumnDataType.INT64),
             ("v", proto.ColumnDataType.FLOAT64)],
            time_index="ts", primary_keys=["host"])
        n = db.insert("proto_ddl_t",
                      {"host": ["a"], "ts": [1000], "n": [7],
                       "v": [0.5]},
                      tag_columns=["host"], timestamp_column="ts")
        assert n == 1
        table, _ = db.sql("SELECT host, n, v FROM proto_ddl_t")
        assert table.column("n").to_pylist() == [7]
        db.drop_table("proto_ddl_t")
        with pytest.raises(Exception):
            db.sql("SELECT 1 FROM proto_ddl_t")

    def test_ddl_round_trip_codec(self):
        expr = proto.CreateTableExpr(
            table_name="t", time_index="ts", primary_keys=["h"],
            create_if_not_exists=True,
            column_defs=[
                proto.ColumnDef("h", proto.ColumnDataType.STRING, True),
                proto.ColumnDef("ts",
                                proto.ColumnDataType.TIMESTAMP_MILLISECOND,
                                False)])
        req = proto.GreptimeRequest(ddl=proto.DdlRequest(create_table=expr))
        got = proto.decode_greptime_request(
            proto.encode_greptime_request(req))
        ct = got.ddl.create_table
        assert ct.table_name == "t" and ct.time_index == "ts"
        assert ct.primary_keys == ["h"] and ct.create_if_not_exists
        assert [c.name for c in ct.column_defs] == ["h", "ts"]
        sql = proto.create_table_to_sql(ct)
        assert "TIME INDEX" in sql and "PRIMARY KEY" in sql

    def test_ddl_variant_rejected_with_clear_error(self, served):
        import pyarrow.flight as flight

        from greptimedb_tpu.utils.protowire import field_bytes
        fe, db = served
        with pytest.raises(flight.FlightError, match="DdlRequest"):
            db.conn.do_get(flight.Ticket(
                field_bytes(4, field_bytes(3, b"")))).read_all()


class TestRegressionFindings:
    def test_null_tag_ids_stable_across_batch_sizes(self):
        """pd.factorize surfaces None as NaN; the dictionary must store
        the real None so ids agree between the bulk and per-value
        paths and across batches."""
        import numpy as np

        from greptimedb_tpu.ops.dictionary import Dictionary
        d = Dictionary()
        big = np.array(["a", None] * 300, dtype=object)
        ids1 = d.encode(big)
        ids2 = d.encode(big)
        assert (ids1 == ids2).all() and len(d) == 2
        assert d.encode(["a", None]).tolist() == [ids1[0], ids1[1]]
        assert d.value(int(ids1[1])) is None

    def test_unicode_identifiers_tokenize(self):
        from greptimedb_tpu.sql.parser import parse_sql
        q = parse_sql("SELECT tempé FROM températures")
        assert q.from_.name.table == "températures"

    def test_proto_header_schema_respected(self, served):
        """The RequestHeader's schema routes every request (reference:
        handlers resolve names through the header context,
        src/servers/src/grpc/handler.rs)."""
        fe, db = served
        fe.do_query("CREATE DATABASE protodb")
        other = GreptimeDatabase(db.address, schema="protodb")
        try:
            n = other.insert("hdr_t", {"host": ["x"], "ts": [1000],
                                       "v": [1.0]},
                             tag_columns=["host"], timestamp_column="ts")
            assert n == 1
            table, _ = other.sql("SELECT count(*) FROM hdr_t")
            assert table.column(0)[0].as_py() == 1
            # default-schema client cannot see it
            import pyarrow.flight as flight
            with pytest.raises(flight.FlightError):
                db.sql("SELECT count(*) FROM hdr_t")
        finally:
            other.close()
