"""UNION / UNION ALL execution tests."""

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import PlanError
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    f.do_query("CREATE TABLE t1 (host STRING, ts TIMESTAMP TIME INDEX,"
               " v DOUBLE, PRIMARY KEY(host))")
    f.do_query("INSERT INTO t1 VALUES ('a', 1, 1.0), ('b', 2, 2.0)")
    f.do_query("CREATE TABLE t2 (host STRING, ts TIMESTAMP TIME INDEX,"
               " v DOUBLE, PRIMARY KEY(host))")
    f.do_query("INSERT INTO t2 VALUES ('b', 2, 2.0), ('c', 3, 3.0)")
    yield f
    f.shutdown()


def _rows(fe, sql):
    out = fe.do_query(sql)[-1]
    return [tuple(r) for b in out.batches for r in b.rows()]


class TestUnion:
    def test_union_all(self, fe):
        rows = _rows(fe, "SELECT host, v FROM t1 UNION ALL"
                         " SELECT host, v FROM t2 ORDER BY host, v")
        assert rows == [("a", 1.0), ("b", 2.0), ("b", 2.0), ("c", 3.0)]

    def test_union_dedups(self, fe):
        rows = _rows(fe, "SELECT host, v FROM t1 UNION"
                         " SELECT host, v FROM t2 ORDER BY host")
        assert rows == [("a", 1.0), ("b", 2.0), ("c", 3.0)]

    def test_union_limit_applies_to_whole(self, fe):
        rows = _rows(fe, "SELECT host FROM t1 UNION ALL"
                         " SELECT host FROM t2 ORDER BY host LIMIT 3")
        assert len(rows) == 3

    def test_chained_unions(self, fe):
        rows = _rows(fe, "SELECT 1 AS n UNION ALL SELECT 2"
                         " UNION ALL SELECT 3 ORDER BY n")
        assert rows == [(1,), (2,), (3,)]

    def test_union_with_aggregates(self, fe):
        rows = _rows(fe, "SELECT sum(v) AS s FROM t1 UNION ALL"
                         " SELECT sum(v) FROM t2 ORDER BY s")
        assert rows == [(3.0,), (5.0,)]

    def test_mismatched_columns_rejected(self, fe):
        with pytest.raises(PlanError, match="columns"):
            fe.do_query("SELECT host, v FROM t1 UNION SELECT host FROM t2")

    def test_parenthesized_union_operand(self, fe):
        rows = _rows(fe, "(SELECT host FROM t1 ORDER BY host LIMIT 1)"
                         " UNION ALL SELECT host FROM t2 ORDER BY host")
        assert rows == [("a",), ("b",), ("c",)]
