"""Durable procedure framework tests.

Mirrors the reference's coverage: procedure state persistence + commit
cleanup (common/procedure/src/store tests), retry/backoff, recovery of
in-flight procedures on restart (local.rs:383-417), and the mito DDL
procedures' crash-resume behavior
(mito/src/engine/procedure/create.rs tests).
"""

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.procedure import (
    Procedure, ProcedureManager, RetryLater, Status)
from greptimedb_tpu.storage.object_store import FsObjectStore


class StepCounter(Procedure):
    type_name = "test.StepCounter"

    def __init__(self, total: int, done_steps: int = 0, log=None):
        self.total = total
        self.done_steps = done_steps
        self.log = log if log is not None else []

    def execute(self, ctx) -> Status:
        if self.done_steps >= self.total:
            return Status.done()
        self.done_steps += 1
        self.log.append(self.done_steps)
        return Status.executing()

    def dump(self) -> dict:
        return {"total": self.total, "done_steps": self.done_steps}


class Flaky(Procedure):
    type_name = "test.Flaky"

    def __init__(self, failures: int):
        self.failures = failures
        self.attempts = 0

    def execute(self, ctx) -> Status:
        self.attempts += 1
        if self.attempts <= self.failures:
            raise RetryLater("transient")
        return Status.done()

    def dump(self) -> dict:
        return {"failures": self.failures}


class Exploder(Procedure):
    type_name = "test.Exploder"

    def __init__(self):
        self.rolled_back = False

    def execute(self, ctx) -> Status:
        raise ValueError("boom")

    def dump(self) -> dict:
        return {}

    def rollback(self, ctx) -> None:
        self.rolled_back = True


@pytest.fixture()
def store(tmp_path):
    return FsObjectStore(str(tmp_path / "objects"))


class TestProcedureManager:
    def test_runs_to_done_and_cleans_up(self, store):
        mgr = ProcedureManager(store)
        proc = StepCounter(3)
        mgr.submit(proc).wait()
        assert proc.log == [1, 2, 3]
        assert store.list("procedures/") == []     # committed + GC'd

    def test_retry_later_backoff(self, store):
        mgr = ProcedureManager(store, max_retries=3, retry_delay_s=0.001)
        proc = Flaky(failures=2)
        mgr.submit(proc).wait()
        assert proc.attempts == 3

    def test_retry_exhaustion_fails(self, store):
        mgr = ProcedureManager(store, max_retries=1, retry_delay_s=0.001)
        with pytest.raises(RetryLater):
            mgr.submit(Flaky(failures=5)).wait()

    def test_failure_invokes_rollback_keeps_state(self, store):
        mgr = ProcedureManager(store)
        proc = Exploder()
        with pytest.raises(ValueError, match="boom"):
            mgr.submit(proc).wait()
        assert proc.rolled_back
        # failed procedure state is kept for inspection
        assert any(k.endswith(".step") for k in store.list("procedures/"))

    def test_recover_resumes_from_last_step(self, store):
        """Simulated crash: steps persisted, no commit marker; a fresh
        manager resumes from the dumped state, not from scratch."""
        mgr = ProcedureManager(store)
        # persist as if the procedure crashed after step 2 of 4
        crashed = StepCounter(4, done_steps=2)
        mgr._persist("deadbeef", 2, crashed)

        log = []
        mgr2 = ProcedureManager(store)
        mgr2.register_loader(
            StepCounter.type_name,
            lambda d: StepCounter(d["total"], d["done_steps"], log))
        recovered = mgr2.recover()
        assert recovered == ["deadbeef"]
        assert log == [3, 4]                       # only remaining steps
        assert store.list("procedures/") == []

    def test_recover_skips_committed(self, store):
        mgr = ProcedureManager(store)
        mgr._persist("aaaa", 0, StepCounter(1))
        store.write(mgr._commit_key("aaaa"), b"done")
        assert ProcedureManager(store).recover() == []
        assert store.list("procedures/") == []     # late GC

    def test_recover_without_loader_leaves_state(self, store):
        mgr = ProcedureManager(store)
        mgr._persist("bbbb", 0, StepCounter(1))
        mgr2 = ProcedureManager(store)
        assert mgr2.recover() == []
        assert any("bbbb" in k for k in store.list("procedures/"))

    def test_lock_serializes_same_key(self, store):
        order = []

        class Locked(Procedure):
            type_name = "test.Locked"

            def __init__(self, tag):
                self.tag = tag
                self.stepped = False

            def lock_key(self):
                return "same"

            def execute(self, ctx):
                if not self.stepped:
                    order.append(f"{self.tag}-start")
                    self.stepped = True
                    return Status.executing(persist=False)
                order.append(f"{self.tag}-end")
                return Status.done()

            def dump(self):
                return {}

        mgr = ProcedureManager(store, run_async=True)
        w1 = mgr.submit(Locked("a"))
        w2 = mgr.submit(Locked("b"))
        w1.wait()
        w2.wait()
        # no interleave: each procedure's start/end are adjacent
        starts = [order.index("a-start"), order.index("b-start")]
        ends = [order.index("a-end"), order.index("b-end")]
        first = min(starts)
        assert order[first + 1].endswith("-end")


class TestMitoDdlProcedures:
    def test_ddl_goes_through_procedures(self, tmp_path):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        fe.do_query("CREATE TABLE pt (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        fe.do_query("ALTER TABLE pt ADD COLUMN w DOUBLE")
        fe.do_query("INSERT INTO pt VALUES ('a', 1000, 1.0, 2.0)")
        out = fe.do_query("SELECT w FROM pt")[-1]
        assert next(out.batches[0].rows())[0] == 2.0
        fe.do_query("DROP TABLE pt")
        assert fe.catalog.table("greptime", "public", "pt") is None
        # no procedure residue after clean DDL
        assert dn.storage.store.list("procedures/") == []
        fe.shutdown()

    def test_create_resumes_after_crash_between_steps(self, tmp_path):
        """Crash after engine create, before catalog register: restart
        recovers the procedure and the table is fully usable."""
        from greptimedb_tpu.mito.procedure import CreateTableProcedure
        from greptimedb_tpu.table.requests import create_request_to_dict

        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        # build the request exactly as the statement executor would
        from greptimedb_tpu.frontend.statement import (
            build_schema_from_create)
        from greptimedb_tpu.sql import parse_statements
        from greptimedb_tpu.table.requests import CreateTableRequest
        stmt = parse_statements(
            "CREATE TABLE crashed (host STRING, ts TIMESTAMP TIME INDEX,"
            " v DOUBLE, PRIMARY KEY(host))")[0]
        schema, pk = build_schema_from_create(stmt)
        request = CreateTableRequest("crashed", schema,
                                     primary_key_indices=pk)
        # simulate: engine step ran + state persisted, then crash
        proc = CreateTableProcedure(request, dn.mito, dn.catalog)
        proc.execute(None)                 # engine_create done
        dn.procedure_manager._persist("cafe01", 1, proc)
        assert dn.catalog.table("greptime", "public", "crashed") is None
        fe.shutdown()

        dn2 = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn2.start()                        # recover() resumes the create
        fe2 = FrontendInstance(dn2)
        fe2.start()
        assert fe2.catalog.table("greptime", "public", "crashed") \
            is not None
        fe2.do_query("INSERT INTO crashed VALUES ('a', 1, 1.5)")
        out = fe2.do_query("SELECT count(*) FROM crashed")[-1]
        assert next(out.batches[0].rows())[0] == 1
        assert dn2.storage.store.list("procedures/") == []
        fe2.shutdown()
