"""Durable trace store tests (ISSUE 15).

Spans persist into the database they describe: the TraceSink buffers
completed spans per trace, the tail verdict fires at the root span's
exit (slow / error / KILLed / balancer / head-sample), and retained
spans flush through the self-monitor ingest path into
greptime_private.trace_spans. Datanodes buffer blind until the
frontend's verdict piggybacks on a later RPC; a TTL evicts the rest.
"""

import json
import logging
import time

import pytest

from greptimedb_tpu.common import trace_store
from greptimedb_tpu.common.telemetry import (
    root_span, set_slow_query_threshold_ms, span)
from greptimedb_tpu.common.trace_store import (
    PRIVATE_SCHEMA, TRACE_SPANS_TABLE, TraceSink)
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture(autouse=True)
def _restore_knobs():
    saved_ratio = trace_store.sample_ratio()
    saved_ret = trace_store.retention_ms()
    saved_sink = trace_store.sink()
    yield
    trace_store.configure(sample_ratio=saved_ratio,
                          retention_ms=saved_ret, buffer_ttl_s=300)
    trace_store.install(saved_sink)
    set_slow_query_threshold_ms(None)


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    frontend = FrontendInstance(dn)
    frontend.start()
    frontend.do_query(
        "CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
        "v DOUBLE, PRIMARY KEY(host))")
    frontend.do_query("INSERT INTO cpu VALUES ('a', 1000, 1.5), "
                      "('b', 2000, 2.5)")
    yield frontend
    frontend.shutdown()


def _pydict(fe, sql):
    out = fe.do_query(sql)[-1]
    return out.batches[0].to_pydict()


def _stored_names(fe, trace_id):
    rows = trace_store.fetch_trace(fe.catalog, trace_id)
    return sorted(str(r["span_name"]) for r in rows)


class TestTailSampling:
    def test_ratio_one_retains_and_stores(self, fe):
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        sink = trace_store.sink()
        tid = sink.last_retained
        assert tid is not None
        assert sink.flush() > 0
        names = _stored_names(fe, tid)
        assert "execute_stmt" in names

    def test_ratio_zero_fast_query_leaves_no_spans(self, fe):
        trace_store.configure(sample_ratio=0.0)
        sink = trace_store.sink()
        before = sink.stats["traces_retained"]
        fe.do_query("SELECT host FROM cpu")
        assert sink.stats["traces_retained"] == before
        assert sink.stats["traces_sampled_out"] > 0
        assert sink.flush() == 0

    def test_slow_query_retained_at_ratio_zero(self, fe):
        trace_store.configure(sample_ratio=0.0)
        set_slow_query_threshold_ms(1)      # everything is "slow"
        fe.do_query("SELECT host, v FROM cpu ORDER BY host")
        sink = trace_store.sink()
        tid = sink.last_retained
        assert tid is not None
        assert sink.flush() > 0
        assert "execute_stmt" in _stored_names(fe, tid)

    def test_error_retained_at_ratio_zero(self, fe):
        trace_store.configure(sample_ratio=0.0)
        sink = trace_store.sink()
        before = sink.stats["traces_retained"]
        from greptimedb_tpu.errors import GreptimeError
        with pytest.raises(GreptimeError):
            fe.do_query("SELECT host FROM no_such_table_xyz")
        assert sink.stats["traces_retained"] == before + 1
        tid = sink.last_retained
        sink.flush()
        rows = trace_store.fetch_trace(fe.catalog, tid)
        assert any(r["status"] == "error" for r in rows)

    def test_killed_query_always_retained(self, fe):
        """A KILLed statement reads as status=cancelled and retains at
        ratio 0 — the operator's first question after a KILL is 'what
        was it doing'."""
        trace_store.configure(sample_ratio=0.0)
        import threading

        import numpy as np
        from greptimedb_tpu.errors import QueryCancelledError
        n = 400_000
        fe.catalog.table("greptime", "public", "cpu").bulk_load({
            "host": np.array([f"h{i % 50}" for i in range(n)],
                             dtype=object),
            "ts": np.arange(n, dtype=np.int64) * 100,
            "v": np.random.default_rng(7).random(n)})
        fe.do_query("SET stream_threshold_rows = 1000")
        try:
            from greptimedb_tpu.common import process_list
            started = threading.Event()
            seen = {}
            orig = process_list.REGISTRY.register

            def spy(*a, **k):
                e = orig(*a, **k)
                seen["id"] = e.id
                started.set()
                return e
            process_list.REGISTRY.register = spy
            try:
                t = threading.Thread(
                    target=lambda: seen.setdefault("err", _run(fe)))

                def _run(fe):
                    try:
                        fe.do_query("SELECT host, avg(v) FROM cpu "
                                    "GROUP BY host")
                        return None
                    except QueryCancelledError as e:
                        return e
                t = threading.Thread(
                    target=lambda: seen.setdefault("err", _run(fe)))
                t.start()
                assert started.wait(10)
                # kill as soon as the statement registers; the scan
                # checks cancellation at slice boundaries
                process_list.REGISTRY.kill(seen["id"])
                t.join(30)
            finally:
                process_list.REGISTRY.register = orig
            sink = trace_store.sink()
            if isinstance(seen.get("err"), QueryCancelledError):
                tid = sink.last_retained
                assert tid is not None
                sink.flush()
                rows = trace_store.fetch_trace(fe.catalog, tid)
                assert any(r["status"] == "cancelled" for r in rows)
            else:
                # raced to completion before the kill landed: the
                # cancelled-retention path is still covered by the unit
                # test below
                pass
        finally:
            fe.do_query("SET stream_threshold_rows = 2000000")

    def test_cancelled_status_unit(self):
        """Sink-level: a QueryCancelledError crossing the root span
        retains the trace at ratio 0."""
        trace_store.configure(sample_ratio=0.0)
        sink = TraceSink(node_label="t", role="root", writer=None)
        trace_store.install(sink)
        from greptimedb_tpu.errors import QueryCancelledError
        with pytest.raises(QueryCancelledError):
            with span("execute_stmt"):
                raise QueryCancelledError("killed")
        assert sink.stats["traces_retained"] == 1

    def test_balancer_span_retained_at_ratio_zero(self):
        trace_store.configure(sample_ratio=0.0)
        sink = TraceSink(node_label="t", role="root", writer=None)
        trace_store.install(sink)
        with root_span("job_balancer_op", op_id="x"):
            pass
        assert sink.stats["traces_retained"] == 1

    def test_head_sample_deterministic(self):
        trace_store.configure(sample_ratio=0.5)
        tid = "deadbeef" * 4
        assert trace_store.head_sampled(tid) == \
            trace_store.head_sampled(tid)
        trace_store.configure(sample_ratio=0.0)
        assert not trace_store.head_sampled(tid)
        trace_store.configure(sample_ratio=1.0)
        assert trace_store.head_sampled(tid)


class TestSlowLogAnnotation:
    def test_slow_log_carries_trace_stored(self, fe, caplog):
        trace_store.configure(sample_ratio=0.0)
        set_slow_query_threshold_ms(1)
        with caplog.at_level(logging.WARNING,
                             logger="greptimedb_tpu.slow_query"):
            fe.do_query("SELECT host FROM cpu")
        msgs = [r.getMessage() for r in caplog.records
                if "slow query" in r.getMessage()]
        assert msgs and "trace_stored=yes" in msgs[-1]

    def test_fast_statement_reports_sampled_out(self, fe, caplog):
        """Threshold high enough that nothing is slow, but force the
        log by lowering it only for the check: instead, verify the
        sink's verdict function directly for a sampled-out trace."""
        trace_store.configure(sample_ratio=0.0)
        sink = trace_store.sink()
        fe.do_query("SELECT host FROM cpu")
        # the last trace was sampled out; its verdict reads accordingly
        with sink._lock:
            tid = next(reversed(sink._verdicts))
        assert sink.stored_verdict(tid) == "sampled-out"


class TestWaterfallSurfaces:
    def test_admin_show_trace_renders_tree(self, fe):
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host, v FROM cpu ORDER BY host")
        out = fe.do_query("ADMIN SHOW TRACE 'last'")[-1]
        d = out.batches[0].to_pydict()
        assert "execute_stmt" in d["span"][0]
        assert d["node"][0] == "standalone"
        assert d["status"][0] == "ok"
        # children render indented under the root
        for s in d["span"][1:]:
            assert s.startswith("  ")

    def test_admin_show_trace_unknown_id_clean_error(self, fe):
        from greptimedb_tpu.errors import InvalidArgumentsError
        with pytest.raises(InvalidArgumentsError, match="not found"):
            fe.do_query("ADMIN SHOW TRACE 'ffffffffffffffff'")

    def test_information_schema_trace_spans_view(self, fe):
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        d = _pydict(fe, "SELECT span_name, node, status, trace_id FROM "
                        "information_schema.trace_spans")
        assert "execute_stmt" in d["span_name"]
        assert all(s in ("ok", "error", "cancelled")
                   for s in d["status"])

    def test_waterfall_network_split_for_dist_rpc(self):
        rows = [
            {"span_id": "a", "parent_span_id": "", "span_name":
             "execute_stmt", "node": "frontend", "ts": 0,
             "duration_ms": 10.0, "status": "ok", "attrs": ""},
            {"span_id": "b", "parent_span_id": "a", "span_name":
             "dist_rpc", "node": "frontend", "ts": 1,
             "duration_ms": 8.0, "status": "ok", "attrs": ""},
            {"span_id": "c", "parent_span_id": "b", "span_name":
             "dn_scan", "node": "dn1", "ts": 2, "duration_ms": 5.0,
             "status": "ok", "attrs": ""},
        ]
        wf = trace_store.waterfall_rows(rows)
        assert [r["span"].strip().lstrip("└─ ") for r in wf] == \
            ["execute_stmt", "dist_rpc", "dn_scan"]
        rpc = wf[1]
        assert rpc["self_ms"] == pytest.approx(3.0)
        assert "network_ms=3.0" in rpc["detail"]
        assert wf[2]["node"] == "dn1"


class TestBackgroundJobs:
    def test_flush_job_registered_with_region(self, fe):
        from greptimedb_tpu.common import background_jobs
        background_jobs.reset()
        fe.do_query("ADMIN FLUSH TABLE cpu")
        rows = background_jobs.rows()
        flushes = [r for r in rows if r["kind"] == "flush"]
        assert flushes
        assert flushes[0]["state"] == "done"
        assert flushes[0]["region"]
        assert flushes[0]["trace_id"]
        assert flushes[0]["duration_ms"] is not None

    def test_background_jobs_view_serves_rows(self, fe):
        fe.do_query("ADMIN FLUSH TABLE cpu")
        d = _pydict(fe, "SELECT kind, state, node FROM "
                        "information_schema.background_jobs")
        assert "flush" in d["kind"]

    def test_live_job_shows_running(self):
        from greptimedb_tpu.common import background_jobs
        background_jobs.reset()
        with background_jobs.job("compaction", region="r1"):
            rows = background_jobs.rows()
            live = [r for r in rows if r["kind"] == "compaction"]
            assert live and live[0]["state"] == "running"
            assert live[0]["duration_ms"] is not None
        rows = background_jobs.rows()
        assert [r for r in rows if r["kind"] == "compaction"][0][
            "state"] == "done"

    def test_failed_job_records_error(self):
        from greptimedb_tpu.common import background_jobs
        background_jobs.reset()
        with pytest.raises(RuntimeError):
            with background_jobs.job("ttl_sweep", region="r9"):
                raise RuntimeError("boom")
        row = [r for r in background_jobs.rows()
               if r["kind"] == "ttl_sweep"][0]
        assert row["state"] == "failed"
        assert "boom" in row["error"]

    def test_background_job_trace_retained_on_failure(self):
        """A failed background job is an errored trace: retained at
        ratio 0, so the postmortem has its spans."""
        trace_store.configure(sample_ratio=0.0)
        sink = TraceSink(node_label="t", role="root", writer=None)
        trace_store.install(sink)
        from greptimedb_tpu.common import background_jobs
        with pytest.raises(RuntimeError):
            with background_jobs.job("compaction", region="r1"):
                raise RuntimeError("disk full")
        assert sink.stats["traces_retained"] == 1

    def test_root_span_restores_ambient_trace(self):
        with span("outer") as outer:
            with root_span("job_flush") as job_sp:
                assert job_sp["trace_id"] != outer["trace_id"]
                assert job_sp["parent_id"] is None
            with span("inner") as inner:
                assert inner["trace_id"] == outer["trace_id"]


class TestRecursionGuard:
    def test_storing_traces_never_retains_its_own_writes(self, fe):
        """The flush writes run under suppress_metrics: the spans they
        open are invisible to the sink, so the trace store can never
        feed itself (satellite: recursion test)."""
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        sink = trace_store.sink()
        sink.flush()
        retained_after_flush = sink.stats["traces_retained"]
        spans_after_flush = sink.stats["spans_recorded"]
        # repeated flushes with nothing pending record nothing
        for _ in range(3):
            sink.flush()
        assert sink.stats["traces_retained"] == retained_after_flush
        assert sink.stats["spans_recorded"] == spans_after_flush

    def test_monitor_tick_converges_with_trace_store_on(self, fe):
        """Scraper ticks (which now also flush traces) stay suppressed
        end to end — their own root span must not grow the registry."""
        trace_store.configure(sample_ratio=1.0)
        from greptimedb_tpu.common.telemetry import registry_snapshot

        def greptime_counters():
            # greptime_* only: process/python_gc counters tick on their
            # own regardless of the scraper
            return {(n, l): v for n, l, v, _ in registry_snapshot()
                    if n.startswith("greptime_")}
        fe.self_monitor.tick()
        before = greptime_counters()
        fe.self_monitor.tick()
        after = greptime_counters()
        assert before == after


class TestRetention:
    def test_trace_retention_sweep(self, fe):
        """Aged trace rows sweep on the monitor tick under the
        trace-specific knob (separate from self_monitor_retention_ms)."""
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        sink = trace_store.sink()
        sink.flush()
        n0 = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                         f"{TRACE_SPANS_TABLE}")["count(*)"][0]
        assert n0 > 0
        trace_store.configure(sample_ratio=0.0)  # no new retains
        fe.do_query("SET trace_retention_ms = 1")
        time.sleep(0.01)
        fe.self_monitor.tick()
        n1 = _pydict(fe, f"SELECT count(*) FROM {PRIVATE_SCHEMA}."
                         f"{TRACE_SPANS_TABLE}")["count(*)"][0]
        assert n1 == 0

    def test_set_trace_sample_ratio_validation(self, fe):
        from greptimedb_tpu.errors import InvalidArgumentsError
        with pytest.raises(InvalidArgumentsError):
            fe.do_query("SET trace_sample_ratio = 'banana'")
        with pytest.raises(InvalidArgumentsError):
            fe.do_query("SET trace_sample_ratio = 7")


class TestDatanodeBuffering:
    """Buffer-role sinks: the datanode half of tail sampling."""

    def _remote_span(self, sink, trace_id, name="dn_scan"):
        trace_store.install(sink)
        from greptimedb_tpu.common.telemetry import remote_context
        header = f"00-{trace_id}-00f067aa0ba902b7-01"
        with remote_context(header):
            with span(name, node=3):
                pass

    def test_buffer_role_holds_until_verdict(self):
        sink = TraceSink(node_label="dn3", service="datanode",
                         role="buffer")
        tid = "a" * 32
        self._remote_span(sink, tid)
        assert sink.take_export() == []          # nothing released
        sink.apply_verdicts({tid: True})
        rows = sink.take_export()
        assert len(rows) == 1
        assert rows[0]["trace_id"] == tid
        assert rows[0]["node"] == "dn3"

    def test_buffer_role_discards_on_negative_verdict(self):
        sink = TraceSink(node_label="dn3", service="datanode",
                         role="buffer")
        tid = "b" * 32
        self._remote_span(sink, tid)
        sink.apply_verdicts({tid: False})
        assert sink.take_export() == []
        assert sink.stats["traces_sampled_out"] == 1

    def test_ttl_evicts_verdictless_traces(self):
        trace_store.configure(buffer_ttl_s=1)
        sink = TraceSink(node_label="dn3", service="datanode",
                         role="buffer")
        tid = "c" * 32
        self._remote_span(sink, tid)
        assert sink.evict_expired(now=time.monotonic() + 5) == 1
        # a verdict arriving after eviction finds nothing to release
        sink.apply_verdicts({tid: True})
        assert sink.take_export() == []

    def test_late_span_follows_verdict(self):
        """A span completing after its trace's verdict (pool worker
        straggler) applies the verdict directly."""
        trace_store.configure(sample_ratio=0.0)
        sink = TraceSink(node_label="t", role="root", writer=None)
        trace_store.install(sink)
        set_slow_query_threshold_ms(1)
        import time as _t
        with span("execute_stmt") as sp:
            tid = sp["trace_id"]
            _t.sleep(0.005)
        # trace decided (slow → retained); a straggler span of the
        # same trace now completes
        sink.on_span_end({"name": "straggler", "trace_id": tid,
                          "span_id": "feedfeedfeedfeed",
                          "parent_id": sp["span_id"],
                          "attrs": {}, "start_unix_ns": 0}, 1.0, "ok")
        rows = sink.take_export()
        assert {r["span_name"] for r in rows} == \
            {"execute_stmt", "straggler"}

    def test_push_verdict_resurfaces_aged_out_verdicts(self):
        """A verdict older than the youngest-PIGGYBACK_MAX window never
        rides an RPC again on its own; the render path re-announces it
        (push_verdict) so SHOW TRACE can still release a datanode's
        buffer minutes later. A known sampled-out trace is not
        resurrected."""
        sink = TraceSink(node_label="fe", role="root")
        tid_old = "a" * 32
        with sink._lock:
            sink._verdicts[tid_old] = (True, time.monotonic())
            for i in range(sink.PIGGYBACK_MAX + 8):
                sink._verdicts[f"{i:032x}"] = (False, time.monotonic())
        assert tid_old not in sink.recent_verdicts()
        assert sink.push_verdict(tid_old)
        assert sink.recent_verdicts().get(tid_old) is True
        # sampled-out stays sampled-out (probe one still in-window)
        dropped = f"{sink.PIGGYBACK_MAX + 7:032x}"
        assert not sink.push_verdict(dropped)
        assert sink.recent_verdicts().get(dropped) is False

    def test_root_role_decides_for_remote_parent(self):
        """A frontend joining an external client's trace still decides
        the verdict (role=root), it does not buffer forever."""
        trace_store.configure(sample_ratio=1.0)
        sink = TraceSink(node_label="fe", role="root", writer=None)
        self._remote_span(sink, "d" * 32, name="execute_stmt")
        assert sink.stats["traces_retained"] == 1


class TestVerdictPiggybackWire:
    """Real Flight sockets: verdicts ride RPC bodies out, released
    spans ride responses home."""

    @pytest.fixture()
    def wire(self, tmp_path):
        from greptimedb_tpu.client.flight import FlightDatanodeClient
        from greptimedb_tpu.servers.flight import FlightDatanodeServer
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "dn"), node_id=7,
            register_numbers_table=False))
        dn.start()
        server = FlightDatanodeServer(dn)
        server.serve_in_background()
        client = FlightDatanodeClient(server.address, 7)
        client.ping()                       # wait for serving
        yield dn, server, client
        client.close()
        server.shutdown()
        dn.shutdown()

    def test_verdict_piggyback_releases_datanode_spans(self, wire):
        dn, server, client = wire
        # datanode-side sink buffers a remote-rooted span
        dn_sink = TraceSink(node_label="dn7", service="datanode",
                            role="buffer")
        tid = "e" * 32
        trace_store.install(dn_sink)
        from greptimedb_tpu.common.telemetry import remote_context
        with remote_context(f"00-{tid}-00f067aa0ba902b7-01"):
            with span("dn_scan", node=7):
                pass
        assert dn_sink.buffered_trace_count() == 1
        # frontend-side root sink carries a fresh verdict; the ping
        # piggybacks it and the released span rides the response. Both
        # sinks live in this process, so install the ROOT sink around
        # the client call (the server thread reads the same global:
        # single-process test of a two-process protocol — the wire
        # format is what's under test)
        root_sink = TraceSink(node_label="fe", role="root", writer=None)
        with root_sink._lock:
            root_sink._verdicts[tid] = (True, time.monotonic())
        # hand-deliver: apply verdicts on the dn sink via the server
        # path by sending an action whose body carries them
        import pyarrow.flight as flight
        body = json.dumps({trace_store.TRACE_VERDICTS_BODY_KEY:
                           {tid: True}}).encode()
        results = list(client.conn.do_action(flight.Action("ping",
                                                           body)))
        resp = json.loads(results[0].body.to_pybytes())
        assert resp["ok"]
        spans = resp.get("trace_spans")
        assert spans and spans[0]["trace_id"] == tid
        assert spans[0]["span_name"] == "dn_scan"

    def test_client_traced_attaches_verdicts(self, wire):
        """_traced() on a root sink attaches recent verdicts to every
        outbound body; the datanode drops the negatively-verdicted
        buffer."""
        dn, server, client = wire
        sink = TraceSink(node_label="fe", role="root", writer=None)
        trace_store.install(sink)
        tid = "f" * 32
        # buffer a trace on the (shared in-process) sink as if it were
        # the datanode's, then record a DROP verdict and ping
        from greptimedb_tpu.common.telemetry import remote_context
        dn_sink = TraceSink(node_label="dn7", service="datanode",
                            role="buffer")
        with sink._lock:
            sink._verdicts[tid] = (False, time.monotonic())
        trace_store.install(dn_sink)         # server side sees this
        with remote_context(f"00-{tid}-00f067aa0ba902b7-01"):
            with span("dn_scan", node=7):
                pass
        trace_store.install(sink)            # client side sees this
        assert sink.recent_verdicts() == {tid: False}
        trace_store.install(dn_sink)
        from greptimedb_tpu.client import flight as cflight
        body = cflight._traced({})
        # simulate what a root-sink client attaches
        trace_store.install(sink)
        body = cflight._traced({})
        assert body[trace_store.TRACE_VERDICTS_BODY_KEY] == {tid: False}


class TestDropAccounting:
    def _counter_value(self, name):
        from greptimedb_tpu.common.telemetry import registry_snapshot
        for n, _l, v, _k in registry_snapshot():
            if n == name:
                return v
        return 0.0

    def test_otlp_full_queue_drops_are_counted(self):
        """Satellite: beyond the one-shot log, a shedding OTLP exporter
        shows up in greptime_trace_export_dropped_total (and therefore
        in runtime_metrics / the scraped history)."""
        from greptimedb_tpu.common.telemetry import OtlpExporter
        exp = OtlpExporter("http://127.0.0.1:1", flush_interval=3600,
                           max_queue=2)
        try:
            before = self._counter_value(
                "greptime_trace_export_dropped_total")
            s = {"trace_id": "a" * 32, "span_id": "b" * 16,
                 "name": "x", "attrs": {}, "start_unix_ns": 1}
            for _ in range(5):
                exp.enqueue(dict(s), 1000)
            assert exp.dropped == 3
            after = self._counter_value(
                "greptime_trace_export_dropped_total")
            assert after - before == 3
        finally:
            exp.shutdown()

    def test_sink_overflow_drops_are_counted(self):
        """The new sink's drop counter surfaces the same way."""
        trace_store.configure(sample_ratio=0.0)
        sink = TraceSink(node_label="t", role="buffer")
        trace_store.install(sink)
        before = self._counter_value("greptime_trace_sink_dropped_total")
        from greptimedb_tpu.common.telemetry import remote_context
        for i in range(1, sink.MAX_TRACES + 6):
            # from 1: an all-zero trace id is invalid per W3C and the
            # remote_context would be a no-op for it
            tid = f"{i:032x}"
            with remote_context(f"00-{tid}-00f067aa0ba902b7-01"):
                with span("dn_scan"):
                    pass
        assert sink.stats["spans_dropped"] == 5
        after = self._counter_value("greptime_trace_sink_dropped_total")
        assert after - before == 5


class TestHttpTraceEndpoint:
    @pytest.fixture()
    def server(self, fe):
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(fe, addr="127.0.0.1:0")
        srv.start()
        yield srv
        srv.shutdown()

    def _get(self, srv, path):
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}",
                    timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_get_trace_waterfall(self, fe, server):
        trace_store.configure(sample_ratio=1.0)
        fe.do_query("SELECT host FROM cpu")
        sink = trace_store.sink()
        tid = sink.last_retained
        status, doc = self._get(server, f"/v1/trace/{tid}")
        assert status == 200
        assert doc["trace_id"] == tid
        assert doc["span_count"] >= 1
        assert any(s["span_name"] == "execute_stmt"
                   for s in doc["spans"])
        assert doc["waterfall"][0]["span"] == "execute_stmt"
        # 'last' resolves to the most recently retained trace... which
        # by now is the /v1/trace request's own statementless flush-free
        # trace or the SELECT — either way it renders, not 404s
        status, doc = self._get(server, "/v1/trace/last")
        assert status == 200

    def test_get_unknown_trace_404(self, fe, server):
        trace_store.configure(sample_ratio=0.0)
        status, doc = self._get(server, "/v1/trace/abcdef0123456789")
        assert status == 404
        assert "not found" in doc["error"]


class TestDistributedDifferential:
    """Satellite: a distributed query's stored spans reassemble into
    the same per-node tree EXPLAIN ANALYZE renders (structure match,
    modulo timing)."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MetaClient, Peer
        from greptimedb_tpu.meta.kv import MemKv
        from greptimedb_tpu.meta.service import MetaSrv
        datanodes, clients = {}, {}
        srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(srv)
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=str(tmp_path / f"dn{i}"), node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes[i] = dn
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        fe = DistInstance(meta, clients)
        yield fe
        for dn in datanodes.values():
            dn.shutdown()

    def test_stored_trace_matches_explain_analyze_nodes(self, cluster):
        fe = cluster
        fe.do_query(
            "CREATE TABLE m (host STRING, ts TIMESTAMP TIME INDEX, "
            "v DOUBLE, PRIMARY KEY(host)) "
            "PARTITION BY HASH (host) PARTITIONS 4")
        values = ", ".join(f"('h{i}', {1000 + i}, {float(i)})"
                           for i in range(32))
        fe.do_query(f"INSERT INTO m VALUES {values}")
        trace_store.configure(sample_ratio=1.0)
        sql = "SELECT host, avg(v) FROM m GROUP BY host"
        fe.do_query(sql)
        sink = trace_store.sink()
        tid = sink.last_retained
        assert tid is not None
        sink.flush()
        rows = trace_store.fetch_trace(fe.catalog, tid)
        # EXPLAIN ANALYZE's per-node blocks name the same datanodes the
        # stored dist_rpc spans recorded
        out = fe.do_query(f"EXPLAIN ANALYZE {sql}")[-1]
        d = out.batches[0].to_pydict()
        ea_text = json.dumps(d)
        ea_nodes = {n for n in ("dn1", "dn2") if n in ea_text}
        assert ea_nodes == {"dn1", "dn2"}
        rpc_spans = [r for r in rows if r["span_name"] == "dist_rpc"]
        span_peers = {json.loads(r["attrs"])["peer"] for r in rpc_spans}
        assert span_peers == ea_nodes
        # structure: every dist_rpc span hangs (possibly through
        # intermediate exec spans) under the one execute_stmt root —
        # the same tree shape the ANALYZE node blocks render
        root = [r for r in rows if r["span_name"] == "execute_stmt"]
        assert len(root) == 1
        by_id = {r["span_id"]: r for r in rows}

        def reaches_root(r, hops=10):
            while hops:
                pid = r.get("parent_span_id")
                if pid == root[0]["span_id"]:
                    return True
                r = by_id.get(pid)
                if r is None:
                    return False
                hops -= 1
            return False
        assert all(reaches_root(r) for r in rpc_spans)
        wf = trace_store.waterfall_rows(rows)
        assert wf[0]["span"] == "execute_stmt"
        indented = [r for r in wf if r["span"].lstrip().startswith("└─")]
        assert len(indented) >= len(rpc_spans)
