"""Python coprocessor / UDF engine tests.

Mirrors the reference's script engine coverage (src/script/src/python/
tests + engine.rs): decorator parsing, sql-bound execution, vector in/out,
persistence in the scripts table + restart recompile, SQL UDF
registration, HTTP script routes.
"""

import numpy as np
import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import GreptimeError, InvalidArgumentsError
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.query.functions import UDF_REGISTRY, unregister_udf
from greptimedb_tpu.script import ScriptEngine, copr
from greptimedb_tpu.script.copr import as_vectors


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=True))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    for name in list(UDF_REGISTRY):
        unregister_udf(name)
    f.shutdown()


class TestCoprDecorator:
    def test_basic(self):
        @copr(args=["a", "b"], returns=["s"])
        def add(a, b):
            return a + b
        assert add.arg_names == ["a", "b"]
        assert add.returns == ["s"]
        out = add(np.array([1.0, 2.0]), np.array([10.0, 20.0]))
        assert out.tolist() == [11.0, 22.0]

    def test_args_inferred_from_signature(self):
        @copr(returns=["v"])
        def f(x, y):
            return x * y
        assert f.arg_names == ["x", "y"]

    def test_as_vectors_scalar_broadcast(self):
        vecs = as_vectors((np.array([1, 2, 3]), 7.0), 2)
        assert vecs[1].tolist() == [7.0, 7.0, 7.0]

    def test_as_vectors_count_mismatch(self):
        with pytest.raises(InvalidArgumentsError, match="declared"):
            as_vectors(np.array([1.0]), 2)


SCRIPT = """
@copr(args=["cpu", "memory"], returns=["load"],
      sql="SELECT cpu, memory FROM monitor ORDER BY ts")
def load(cpu, memory):
    return cpu + memory / 1000.0
"""


class TestScriptEngine:
    def _seed(self, fe):
        fe.do_query("CREATE TABLE monitor (host STRING, ts TIMESTAMP"
                    " TIME INDEX, cpu DOUBLE, memory DOUBLE,"
                    " PRIMARY KEY(host))")
        fe.do_query("INSERT INTO monitor VALUES"
                    " ('h1', 1000, 1.0, 1000), ('h1', 2000, 2.0, 2000)")

    def test_compile_and_run_with_sql(self, fe):
        self._seed(fe)
        engine = ScriptEngine(fe)
        out = engine.run(SCRIPT, is_script_text=True)
        batch = out.batches[0]
        assert batch.schema.names() == ["load"]
        assert batch.column(0).to_pylist() == [2.0, 4.0]

    def test_compile_rejects_no_copr(self):
        with pytest.raises(InvalidArgumentsError, match="no @copr"):
            ScriptEngine.compile("x = 1")

    def test_compile_rejects_syntax_error(self):
        with pytest.raises(InvalidArgumentsError, match="syntax"):
            ScriptEngine.compile("def broken(:\n  pass")

    def test_insert_run_and_persist(self, fe):
        self._seed(fe)
        engine = ScriptEngine(fe)
        engine.insert_script("load", SCRIPT)
        out = engine.run("load")
        assert out.batches[0].column(0).to_pylist() == [2.0, 4.0]
        # persisted in the scripts system table
        got = engine.get_script("load")
        assert "def load" in got

    def test_params_without_sql(self, fe):
        engine = ScriptEngine(fe)
        script = """
@copr(args=["v"], returns=["doubled"])
def doubled(v):
    return v * 2
"""
        engine.insert_script("doubled", script)
        out = engine.run("doubled", params={"v": [1.0, 2.5]})
        assert out.batches[0].column(0).to_pylist() == [2.0, 5.0]

    def test_missing_param_errors(self, fe):
        engine = ScriptEngine(fe)
        engine.insert_script("need_v", """
@copr(args=["v"], returns=["r"])
def need_v(v):
    return v
""")
        with pytest.raises(InvalidArgumentsError, match="missing"):
            engine.run("need_v")

    def test_unknown_script_errors(self, fe):
        engine = ScriptEngine(fe)
        with pytest.raises(GreptimeError, match="not found"):
            engine.run("nope")

    def test_restart_reloads_scripts(self, fe, tmp_path):
        self._seed(fe)
        engine = ScriptEngine(fe)
        engine.insert_script("load", SCRIPT)
        fe.shutdown()
        dn2 = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=True))
        dn2.start()
        fe2 = FrontendInstance(dn2)
        fe2.start()                      # load_scripts runs here
        out = fe2.script_engine.run("load")
        assert out.batches[0].column(0).to_pylist() == [2.0, 4.0]
        fe2.shutdown()

    def test_udf_callable_from_sql(self, fe):
        """Coprocessors register as scalar SQL functions (reference:
        engine.rs:44-80)."""
        self._seed(fe)
        engine = ScriptEngine(fe)
        engine.insert_script("centi", """
@copr(args=["x"], returns=["c"])
def centi(x):
    return x * 100.0
""")
        out = fe.do_query(
            "SELECT host, centi(cpu) AS c FROM monitor ORDER BY ts")[-1]
        rows = [tuple(r) for b in out.batches for r in b.rows()]
        assert rows == [("h1", 100.0), ("h1", 200.0)]

    def test_jnp_coprocessor(self, fe):
        """A jnp-bodied coprocessor runs on the device path."""
        engine = ScriptEngine(fe)
        engine.insert_script("norm", """
@copr(args=["v"], returns=["n"])
def norm(v):
    x = jnp.asarray(v)
    return np.asarray(x / jnp.max(x))
""")
        out = engine.run("norm", params={"v": [1.0, 2.0, 4.0]})
        assert out.batches[0].column(0).to_pylist() == [0.25, 0.5, 1.0]


class TestScriptHttpRoutes:
    @pytest.fixture()
    def http(self, fe):
        from greptimedb_tpu.servers.auth import NoopUserProvider
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(fe, NoopUserProvider(), "127.0.0.1:0")
        srv.start()
        yield srv
        srv.shutdown()

    def test_scripts_roundtrip(self, http, fe):
        import json
        import urllib.request
        fe.do_query("CREATE TABLE monitor (host STRING, ts TIMESTAMP"
                    " TIME INDEX, cpu DOUBLE, memory DOUBLE,"
                    " PRIMARY KEY(host))")
        fe.do_query("INSERT INTO monitor VALUES ('h', 1000, 3.0, 500)")
        base = f"http://127.0.0.1:{http.port}"
        req = urllib.request.Request(
            f"{base}/v1/scripts?name=load&db=public",
            data=SCRIPT.encode(), method="POST")
        resp = json.load(urllib.request.urlopen(req))
        assert resp["code"] == 0
        req = urllib.request.Request(
            f"{base}/v1/run-script?name=load&db=public", data=b"",
            method="POST")
        resp = json.load(urllib.request.urlopen(req))
        assert resp["code"] == 0
        records = resp["output"][0]["records"]
        assert records["rows"] == [[3.5]]
