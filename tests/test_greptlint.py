"""Tier-1 gate for greptlint: the self-test (every rule fires on its
seeded fixture) and the repo scan (no findings beyond the baseline).

A new violation anywhere in greptimedb_tpu/ fails THIS test the round it
lands; the fix is to fix the code, suppress with an inline justification
(`# greptlint: disable=GLxx`), or — for deliberate grandfathering only —
re-run `python -m greptimedb_tpu.devtools.greptlint --write-baseline`.
"""

import glob
import os
import subprocess
import sys

import pytest

from greptimedb_tpu.devtools.greptlint import (ALL_RULES, apply_baseline,
                                               lint_paths, load_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "greptimedb_tpu")
SELFTEST = os.path.join(PKG, "devtools", "greptlint", "selftest")
BASELINE = os.path.join(REPO, ".greptlint-baseline.json")

#: grandfathered findings may never grow past this (ISSUE 7 acceptance);
#: shrink it as the burn-down continues
BASELINE_BUDGET = 10


def _fixture_for(rule_id):
    hits = glob.glob(os.path.join(SELFTEST, f"{rule_id.lower()}_*.py"))
    assert len(hits) == 1, (
        f"expected exactly one selftest fixture {rule_id.lower()}_*.py, "
        f"found {hits}")
    return hits[0]


@pytest.mark.parametrize("rule", ALL_RULES, ids=[r.id for r in ALL_RULES])
def test_rule_fires_on_its_fixture(rule):
    """Each rule must flag its seeded fixture — a rule that stops
    matching is a silently-dead invariant."""
    fixture = _fixture_for(rule.id)
    fresh, _all, errors = lint_paths([SELFTEST])
    assert not errors, errors
    hits = [f for f in fresh if f.rule == rule.id
            and os.path.basename(f.path) == os.path.basename(fixture)]
    assert hits, (f"{rule.id} did not fire on its fixture "
                  f"{os.path.basename(fixture)}")


def test_fixtures_trigger_only_their_own_rule():
    """Fixtures are minimal: exactly one finding per fixture file, and it
    belongs to the rule named in the filename."""
    fresh, _all, errors = lint_paths([SELFTEST])
    assert not errors, errors
    by_file = {}
    for f in fresh:
        by_file.setdefault(os.path.basename(f.path), []).append(f.rule)
    for fname, rules in sorted(by_file.items()):
        expected = fname.split("_", 1)[0].upper()
        assert rules == [expected], (
            f"{fname}: expected exactly [{expected}], got {rules}")


def test_cli_exits_nonzero_on_seeded_violations():
    proc = subprocess.run(
        [sys.executable, "-m", "greptimedb_tpu.devtools.greptlint",
         SELFTEST, "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    for rule in ALL_RULES:
        assert rule.id in proc.stdout, (
            f"{rule.id} missing from CLI output:\n{proc.stdout}")


def test_repo_is_clean_modulo_baseline():
    """THE gate: scanning the whole package yields no findings beyond
    the grandfathered baseline."""
    fresh, _all, errors = lint_paths([PKG], baseline_path=BASELINE)
    assert not errors, errors
    assert not fresh, (
        "new greptlint findings (fix, suppress with justification, or "
        "consciously re-baseline):\n" +
        "\n".join(f.render() for f in fresh))


def test_baseline_within_budget_and_not_stale():
    """The baseline may only shrink: every grandfathered key must still
    match a current finding (fixed code must leave the baseline), and
    the total stays within the burn-down budget."""
    baseline = load_baseline(BASELINE)
    total = sum(baseline.values())
    assert total <= BASELINE_BUDGET, (
        f"baseline has {total} findings, budget is {BASELINE_BUDGET} — "
        f"the baseline only ever shrinks")
    _fresh, all_findings, errors = lint_paths([PKG])
    assert not errors, errors
    current = {f.baseline_key() for f in all_findings}
    stale = sorted(k for k in baseline if k not in current)
    assert not stale, (
        "baseline entries no longer matched by any finding — the code "
        "was fixed, now delete the entries (--write-baseline):\n" +
        "\n".join(stale))


def test_suppression_comment_silences_a_finding(tmp_path):
    bad = 'import os\n\ndef f():\n    os.replace("a", "b")\n'
    p = tmp_path / "mod.py"
    p.write_text(bad)
    fresh, _a, _e = lint_paths([str(p)])
    assert [f.rule for f in fresh] == ["GL03"]
    p.write_text(bad.replace(
        'os.replace("a", "b")',
        'os.replace("a", "b")  # greptlint: disable=GL03'))
    fresh, _a, _e = lint_paths([str(p)])
    assert fresh == []


def test_baseline_is_line_move_stable(tmp_path):
    """Inserting unrelated lines above a grandfathered finding must not
    churn the baseline (keys hash the source line, not its number)."""
    from greptimedb_tpu.devtools.greptlint import save_baseline

    src = 'import os\n\ndef f():\n    os.replace("a", "b")\n'
    p = tmp_path / "mod.py"
    p.write_text(src)
    _f, all1, _e = lint_paths([str(p)])
    bl = str(tmp_path / "bl.json")
    save_baseline(bl, all1)

    p.write_text('import os\n\nX = 1\nY = 2\n\ndef f():\n'
                 '    os.replace("a", "b")\n')
    fresh, _a, _e = lint_paths([str(p)], baseline_path=bl)
    assert fresh == [], "line moves must not resurrect baselined findings"


def test_gl04_recognizes_aliased_register_imports(tmp_path):
    """Regression: the register() sweep missed aliased imports
    (`from ..common.failpoint import register as _fp_register`), so
    GL04 false-positived on dist_rpc/objstore_request/
    scan_cache_incremental — every point registered through the
    project's own idiom."""
    mod = tmp_path / "site.py"
    mod.write_text(
        "from greptimedb_tpu.common.failpoint import register as "
        "_fp_register\n"
        "from greptimedb_tpu.common.failpoint import fail_point\n"
        '_fp_register("aliased_point_regression")\n'
        "def f():\n"
        '    fail_point("aliased_point_regression")\n')
    fresh, _a, _e = lint_paths([str(mod)])
    assert [f for f in fresh if f.rule == "GL04"] == []


def test_gl09_catches_module_alias_and_skips_collections_counter(
        tmp_path):
    """Regression (the GL04 aliased-import lesson applied to GL09):
    `import prometheus_client as pc; pc.Counter(...)` must flag, while
    collections.Counter stays clean."""
    mod = tmp_path / "aliased_metric.py"
    mod.write_text(
        "import prometheus_client as pc\n"
        "from collections import Counter\n"
        'M = pc.Counter("aliased_total", "dodges the from-import check")\n'
        'C = Counter("abc")          # collections, not a metric\n')
    fresh, _a, _e = lint_paths([str(mod)])
    hits = [f for f in fresh if f.rule == "GL09"]
    assert len(hits) == 1 and hits[0].line == 3


def test_single_file_scan_matches_directory_scan():
    """Regression: explicitly-passed files used a bare basename as rel,
    so path-scoped rules (GL05 storage/, GL07 servers/) silently never
    ran on single-file scans and baseline keys differed between the two
    invocation styles."""
    target = os.path.join(PKG, "storage", "scheduler.py")
    from greptimedb_tpu.devtools.greptlint.core import collect_files
    [(path, rel)] = collect_files([target])
    assert rel == os.path.join("greptimedb_tpu", "storage",
                               "scheduler.py")
    # and the scoped scan agrees with what a directory walk produces
    dir_files = dict(collect_files([PKG]))
    assert dir_files[path] == rel


def test_rule_catalog_has_unique_ids_and_titles():
    ids = [r.id for r in ALL_RULES]
    assert len(ids) == len(set(ids))
    assert all(r.title for r in ALL_RULES)


# ---- interprocedural tier (GL10-GL12, ISSUE 10) ---------------------

def test_callgraph_resolves_calls_and_drops_hubs(tmp_path):
    """Name-based resolution with the hub cutoff: a unique callee links,
    a name with more defs than hub_limit resolves to nothing (precision
    over reach — the documented bias)."""
    from greptimedb_tpu.devtools.greptlint.core import (build_context,
                                                        collect_files)
    mod = tmp_path / "m.py"
    many = tmp_path / "many.py"
    many.write_text("\n".join(
        f"class C{i}:\n    def common(self):\n        pass"
        for i in range(12)))
    mod.write_text("def caller():\n    unique()\n    common()\n"
                   "def unique():\n    pass\n"
                   "def common():\n    pass\n")
    files = collect_files([str(tmp_path)])
    ctx = build_context(files, str(tmp_path))
    cg = ctx.callgraph
    [caller] = [f for f in cg.functions if f.name == "caller"]
    assert {t.name for t in cg.targets("unique")} == {"unique"}
    assert cg.targets("common") == []        # 13 defs > hub_limit: cut
    assert "unique" in caller.calls
    reach = cg.reachable([caller])
    assert any(f.name == "unique" for f in reach)
    assert not any(f.name == "common" for f in reach)


def test_gl10_taxonomy_and_factory_raises_stay_clean(tmp_path):
    """Raising a GreptimeError subclass (defined ANYWHERE, found by the
    fixpoint) or the result of a lowercase converter factory must not
    flag; an untyped class two calls up must."""
    srv = tmp_path / "servers"
    srv.mkdir()
    (srv / "__init__.py").write_text("")
    (srv / "flight.py").write_text(
        "class GreptimeError(Exception):\n    pass\n"
        "class MyTyped(GreptimeError):\n    pass\n"
        "class Untyped(Exception):\n    pass\n"
        "class Srv:\n"
        "    def do_get(self, t):\n"
        "        remote_context(None)\n"
        "        a()\n"
        "        b()\n"
        "        c()\n"
        "        d()\n"
        "        e()\n"
        "def a():\n    raise MyTyped('fine')\n"
        "def b():\n    raise _convert('fine')\n"
        "def c():\n    raise Untyped('flagged')\n"
        "def d():\n    raise RuntimeError\n"        # bare class, no parens
        "def e(exc=None):\n"
        "    try:\n        a()\n"
        "    except Exception as err:\n        raise err\n"
        "def _convert(m):\n    return MyTyped(m)\n")
    fresh, _a, _e = lint_paths([str(tmp_path)])
    gl10 = [f for f in fresh if f.rule == "GL10"]
    msgs = sorted(f.msg.split(" ")[1] for f in gl10)
    assert msgs == ["RuntimeError", "Untyped"], gl10


def test_gl11_fires_without_check_and_clears_with_it(tmp_path):
    """The cancellation check can live in a CALLEE (interprocedural
    coverage): adding check_cancelled anywhere on the loop's call path
    clears the finding; removing it brings it back."""
    q = tmp_path / "query"
    q.mkdir()
    (q / "__init__.py").write_text("")
    bad = (
        "register('objstore_read')\n"
        "def do_query(files):\n"
        "    for f in files:\n"
        "        _read(f)\n"
        "def _read(f):\n"
        "    fail_point('objstore_read')\n")
    (q / "exec.py").write_text(bad)
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f.rule for f in fresh if f.rule == "GL11"] == ["GL11"]
    # the fix: a cancellation point inside the callee
    (q / "exec.py").write_text(bad.replace(
        "def _read(f):\n",
        "def _read(f):\n    check_cancelled()\n"))
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f for f in fresh if f.rule == "GL11"] == []


def test_gl11_wait_loops_must_bound_or_cancel(tmp_path):
    """ISSUE 12 scope extension: a cohort-wait loop (group commit /
    ingest coalescer) parking on an un-bounded Event/Condition wait is
    flagged even when do_query cannot reach it; a timeout= bound OR a
    check_cancelled() in the loop clears it."""
    q = tmp_path / "query"
    q.mkdir()
    (q / "__init__.py").write_text("")
    bad = (
        "def follow(batch):\n"
        "    while not batch.done.is_set():\n"
        "        batch.done.wait()\n"
        "    return batch.result\n")
    (q / "cohort.py").write_text(bad)
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f.rule for f in fresh if f.rule == "GL11"] == ["GL11"]
    # fix 1: a bounded wait
    (q / "cohort.py").write_text(bad.replace(
        "batch.done.wait()", "batch.done.wait(timeout=0.05)"))
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f for f in fresh if f.rule == "GL11"] == []
    # fix 2: a cancellation point in the loop
    (q / "cohort.py").write_text(bad.replace(
        "batch.done.wait()",
        "check_cancelled()\n        batch.done.wait()"))
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f for f in fresh if f.rule == "GL11"] == []


def test_gl12_flags_never_evaluated_and_unreachable_sites(tmp_path):
    """Both death modes: a registered name with no fail_point site at
    all, and one whose only site sits in an uncalled function; a site
    reachable through a caller chain stays clean."""
    mod = tmp_path / "sites.py"
    mod.write_text(
        "register('never_evaluated')\n"
        "register('orphan_site')\n"
        "register('live_site')\n"
        "def _orphan():\n    fail_point('orphan_site')\n"
        "def _live():\n    fail_point('live_site')\n"
        "def flush():\n    _live()\n"
        "def entry():\n    flush()\n")
    fresh, _a, _e = lint_paths([str(mod)])
    gl12 = sorted(f.msg.split("'")[1] for f in fresh
                  if f.rule == "GL12")
    assert gl12 == ["never_evaluated", "orphan_site"]


def test_gl13_covered_and_rootless_callbacks(tmp_path):
    """GL13 (ISSUE 15): a RepeatedTask/scheduler callback that reaches
    background_jobs.job() or root_span() — directly or transitively —
    stays clean; one that roots no trace is flagged. Unresolvable
    callbacks (lambdas) are skipped for precision."""
    st = tmp_path / "storage"
    st.mkdir()
    src = (
        "class Engine:\n"
        "    def start(self):\n"
        "        self._t1 = RepeatedTask(5.0, self._covered_tick)\n"
        "        self._t2 = RepeatedTask(5.0, self._rootless_tick)\n"
        "        self.scheduler.submit('flush:x', self._covered_job)\n"
        "        self._t3 = RepeatedTask(5.0, lambda: None)\n"
        "    def _covered_tick(self):\n"
        "        self._do_work()\n"
        "    def _do_work(self):\n"
        "        with job('flush', region='r'):\n"
        "            pass\n"
        "    def _covered_job(self):\n"
        "        with root_span('job_flush'):\n"
        "            pass\n"
        "    def _rootless_tick(self):\n"
        "        sweep()\n")
    (st / "engine.py").write_text(src)
    fresh, _a, _e = lint_paths([str(tmp_path)])
    gl13 = [f for f in fresh if f.rule == "GL13"]
    assert len(gl13) == 1 and "_rootless_tick" in gl13[0].msg
    # ThreadPoolExecutor-style submit(fn) — no string key — is ignored
    (st / "engine.py").write_text(
        "def go(pool, fn):\n    pool.submit(fn)\n")
    fresh, _a, _e = lint_paths([str(tmp_path)])
    assert [f for f in fresh if f.rule == "GL13"] == []


def test_gl13_repo_burn_down_background_entry_points_rooted():
    """Every production RepeatedTask/scheduler callback now roots a
    trace: the repo scan stays at zero GL13 findings (covered by
    test_repo_is_clean_modulo_baseline, pinned here for the ISSUE 15
    burn-down specifically)."""
    fresh, _all, errors = lint_paths([PKG], baseline_path=BASELINE)
    assert not errors
    assert [f for f in fresh if f.rule == "GL13"] == []


def test_gl10_repo_burn_down_parser_errors_are_taxonomy_typed():
    """Regression for the ISSUE 10 burn-down: ParserError/TokenizeError
    joined the errors.* taxonomy, so a parse error crossing HTTP carries
    INVALID_SYNTAX/400 instead of a generic 500."""
    from greptimedb_tpu.errors import GreptimeError, StatusCode
    from greptimedb_tpu.sql.parser import ParserError
    from greptimedb_tpu.sql.tokenizer import TokenizeError
    for cls in (ParserError, TokenizeError):
        assert issubclass(cls, GreptimeError)
        assert issubclass(cls, ValueError)       # pre-taxonomy catches
        assert cls("x").status_code == StatusCode.INVALID_SYNTAX
        assert cls("x").to_http_status() == 400


def test_greptsan_baseline_only_shrinks():
    """The baseline-only-shrinks assertion, extended to the greptsan
    suppression file (ISSUE 10 satellite): burned to zero this PR, and
    zero is a floor it can never rise from."""
    import json
    path = os.path.join(REPO, ".greptsan-baseline.json")
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc.get("version") == 1
    assert doc.get("suppressions") == {}, (
        "the greptsan suppression baseline only ever shrinks, and it "
        "reached zero in ISSUE 10 — fix races, don't suppress them")


def test_mypy_scoped_modules_are_green():
    """Scoped type check (mypy.ini: common/, errors.py, utils/,
    devtools/). Skips where mypy isn't installed (the build image);
    CI installs it and runs the same config via `make typecheck`."""
    pytest.importorskip("mypy")
    proc = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file", "mypy.ini"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
