"""Parallel pruned scatter-gather tests (ISSUE 5).

Covers: hash/range partition pruning (rule level + end to end through a
2-datanode cluster, differential against the unpruned answer), the
region-granular prune shipped over the wire, limit/tag-filter pushdown in
DatanodeClient.scan_batches, parallel flush, transient-fault retry mid
fan-out (dist_rpc failpoint + greptime_dist_rpc_retry_total), the
bounded ordered gather, and the DistTable.regions remote degrade.
"""

import logging

import numpy as np
import pytest

from greptimedb_tpu.client import DatanodeClient, LocalDatanodeClient
from greptimedb_tpu.common import failpoint
from greptimedb_tpu.common.runtime import (
    configure_dist_fanout, dist_fanout, dist_runtime, parallel_imap)
from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.distributed import DistInstance, DistTable
from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
from greptimedb_tpu.partition.rule import (
    MAXVALUE, HashPartitionRule, RangePartitionRule)
from greptimedb_tpu.sql.ast import BinaryOp, Column, InList, Literal


@pytest.fixture(autouse=True)
def _clean_knobs():
    saved = dist_fanout()
    failpoint.reset()
    yield
    configure_dist_fanout(saved)
    failpoint.reset()


# ---------------------------------------------------------------------------
# rule-level pruning
# ---------------------------------------------------------------------------

class TestHashRule:
    def rule(self, n=8):
        return HashPartitionRule(["host"], list(range(n)))

    def test_find_region_stable_and_in_range(self):
        r = self.rule()
        a = r.find_region(("h3",))
        assert a == r.find_region("h3") == HashPartitionRule(
            ["host"], list(range(8))).find_region(("h3",))
        assert 0 <= a < 8

    def test_rows_spread_across_buckets(self):
        r = self.rule()
        hit = {r.find_region((f"h{i}",)) for i in range(64)}
        assert len(hit) > 4      # crc32 spreads 64 hosts over 8 buckets

    def test_equality_prunes_to_one(self):
        r = self.rule()
        pred = BinaryOp("=", Column("host"), Literal("h3"))
        assert r.find_regions_by_filters([pred]) == \
            [r.find_region(("h3",))]

    def test_in_list_prunes_to_members(self):
        r = self.rule()
        pred = InList(Column("host"),
                      [Literal("a"), Literal("b"), Literal("c")])
        want = {r.find_region((v,)) for v in ("a", "b", "c")}
        assert set(r.find_regions_by_filters([pred])) == want

    def test_contradictory_equalities_prune_to_zero(self):
        r = self.rule()
        preds = [BinaryOp("=", Column("host"), Literal("a")),
                 BinaryOp("=", Column("host"), Literal("b"))]
        assert r.find_regions_by_filters(preds) == []

    def test_unpinned_column_keeps_all(self):
        r = self.rule()
        pred = BinaryOp(">", Column("host"), Literal("h3"))
        assert r.find_regions_by_filters([pred]) == list(range(8))
        assert r.find_regions_by_filters([]) == list(range(8))

    def test_multi_column_needs_every_column(self):
        r = HashPartitionRule(["dc", "host"], list(range(4)))
        only_dc = [BinaryOp("=", Column("dc"), Literal("eu"))]
        assert r.find_regions_by_filters(only_dc) == list(range(4))
        both = only_dc + [BinaryOp("=", Column("host"), Literal("h1"))]
        assert r.find_regions_by_filters(both) == \
            [r.find_region(("eu", "h1"))]

    def test_negated_in_does_not_prune(self):
        r = self.rule()
        pred = InList(Column("host"), [Literal("a")], negated=True)
        assert r.find_regions_by_filters([pred]) == list(range(8))

    def test_numpy_scalars_hash_like_builtins(self):
        """Ingest routes numpy array values; pruning routes Python
        literals — identical keys must land in identical buckets."""
        r = HashPartitionRule(["id"], list(range(8)))
        assert r.find_region(np.int64(123)) == r.find_region(123)
        assert r.find_region(np.float64(4.0)) == r.find_region(4)
        assert r.find_region(np.str_("h3")) == r.find_region("h3")
        s = self.rule()
        assert s.find_region(np.str_("h3")) == s.find_region("h3")


class TestRangeRulePruning:
    def rule(self):
        return RangePartitionRule("host", ["h3", "h6", MAXVALUE],
                                  [0, 1, 2])

    def test_in_list_maps_values_to_regions(self):
        r = self.rule()
        pred = InList(Column("host"), [Literal("h0"), Literal("h7")])
        assert r.find_regions_by_filters([pred]) == [0, 2]

    def test_contradictory_range_prunes_to_zero(self):
        r = self.rule()
        preds = [BinaryOp("<", Column("host"), Literal("a")),
                 BinaryOp(">", Column("host"), Literal("z"))]
        assert r.find_regions_by_filters(preds) == []

    def test_value_above_all_bounds_without_maxvalue(self):
        r = RangePartitionRule("host", ["h3", "h6"], [0, 1])
        pred = BinaryOp("=", Column("host"), Literal("zzz"))
        assert r.find_regions_by_filters([pred]) == []


# ---------------------------------------------------------------------------
# cluster fixture + spies
# ---------------------------------------------------------------------------

class SpyClient(LocalDatanodeClient):
    """LocalDatanodeClient recording every data-plane RPC + its pruned
    region list."""

    def __init__(self, datanode, log):
        super().__init__(datanode)
        self.log = log

    def scan_batches(self, *a, **kw):
        self.log.append(("scan", self.node_id, kw.get("regions"),
                         kw.get("limit"), kw.get("filters")))
        return super().scan_batches(*a, **kw)

    def region_moments(self, *a, **kw):
        self.log.append(("moments", self.node_id, kw.get("regions"),
                         None, None))
        return super().region_moments(*a, **kw)

    def flush_table(self, *a, **kw):
        self.log.append(("flush", self.node_id, None, None, None))
        return super().flush_table(*a, **kw)


@pytest.fixture()
def cluster(tmp_path):
    """Frontend + 2 in-process datanodes with RPC spies."""
    datanodes, clients, log = {}, {}, []
    # long lease: the fixture heartbeats once, and a slow shared box can
    # take >15s (the default lease) inside one multi-seed test
    srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
    meta = MetaClient(srv)
    for i in (1, 2):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        datanodes[i] = dn
        clients[i] = SpyClient(dn, log)
        srv.register_datanode(Peer(i, f"dn{i}"))
        srv.handle_heartbeat(i)
    fe = DistInstance(meta, clients)
    yield fe, datanodes, log
    for dn in datanodes.values():
        dn.shutdown()


HASH_DDL = """
CREATE TABLE hashed (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                     PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 8
"""

RANGE_DDL = """
CREATE TABLE ranged (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                     PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h2'),
  PARTITION r1 VALUES LESS THAN ('h5'),
  PARTITION r2 VALUES LESS THAN (MAXVALUE))
"""

PLAIN_DDL = """
CREATE TABLE plain (host STRING, ts TIMESTAMP TIME INDEX, cpu DOUBLE,
                    PRIMARY KEY(host))
"""


def seed(fe, table, hosts=8, rows_per=6):
    vals = []
    for h in range(hosts):
        for i in range(rows_per):
            vals.append(f"('h{h}', {i * 1000}, {float(h * 100 + i)})")
    fe.do_query(f"INSERT INTO {table} VALUES " + ",".join(vals))


def rows_of(fe, sql):
    out = fe.do_query(sql)[-1]
    return [tuple(r.values())
            for b in out.batches for r in b.to_pylist()]


# ---------------------------------------------------------------------------
# end-to-end pruning differentials
# ---------------------------------------------------------------------------

FILTER_SHAPES = [
    "host = 'h3'",
    "host IN ('h1', 'h6')",
    "host = 'h3' AND cpu >= 0",
    "host > 'h5'",                       # range-prunable, hash-unprunable
    "host = 'h3' AND ts >= 2000 AND ts < 5000",
]


class TestPruningDifferential:
    """Every (rule × filter shape) answers exactly like the single-region
    table, for the pushdown aggregate AND the fallback scan, serial and
    parallel."""

    @pytest.mark.parametrize("where", FILTER_SHAPES)
    def test_differential(self, cluster, where):
        fe, _, log = cluster
        for ddl in (HASH_DDL, RANGE_DDL, PLAIN_DDL):
            fe.do_query(ddl)
        for t in ("hashed", "ranged", "plain"):
            seed(fe, t)
        for fanout in (1, 4):
            configure_dist_fanout(fanout)
            for t in ("hashed", "ranged", "plain"):
                agg = rows_of(
                    fe, f"SELECT host, count(*) AS c, avg(cpu) AS a "
                        f"FROM {t} WHERE {where} GROUP BY host "
                        f"ORDER BY host")
                raw = rows_of(
                    fe, f"SELECT host, ts, cpu FROM {t} WHERE {where} "
                        f"ORDER BY host, ts")
                assert agg == rows_of(
                    fe, f"SELECT host, count(*) AS c, avg(cpu) AS a "
                        f"FROM plain WHERE {where} GROUP BY host "
                        f"ORDER BY host"), (t, where, fanout)
                assert raw == rows_of(
                    fe, f"SELECT host, ts, cpu FROM plain "
                        f"WHERE {where} ORDER BY host, ts"), \
                    (t, where, fanout)

    def test_point_query_contacts_exactly_one_region(self, cluster):
        fe, _, log = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        table = fe.catalog.table("greptime", "public", "hashed")
        want = table.partition_rule.find_region(("h3",))
        log.clear()
        rows_of(fe, "SELECT host, avg(cpu) FROM hashed "
                    "WHERE host = 'h3' GROUP BY host")
        moments = [e for e in log if e[0] == "moments"]
        assert len(moments) == 1, "point query must contact one datanode"
        assert moments[0][2] == [want]

    def test_zero_region_prune_answers_empty(self, cluster):
        fe, _, log = cluster
        fe.do_query(RANGE_DDL)
        seed(fe, "ranged")
        log.clear()
        assert rows_of(
            fe, "SELECT host, count(*) FROM ranged "
                "WHERE host < 'a' AND host > 'z' GROUP BY host") == []
        assert rows_of(
            fe, "SELECT host, cpu FROM ranged "
                "WHERE host < 'a' AND host > 'z'") == []
        assert [e for e in log if e[0] in ("scan", "moments")] == [], \
            "zero surviving regions must contact no datanode"

    def test_no_rule_single_region_table(self, cluster):
        fe, _, log = cluster
        fe.do_query(PLAIN_DDL)
        seed(fe, "plain")
        log.clear()
        got = rows_of(fe, "SELECT host, count(*) AS c FROM plain "
                          "WHERE host = 'h1' GROUP BY host")
        assert got == [("h1", 6)]
        moments = [e for e in log if e[0] == "moments"]
        assert len(moments) == 1 and moments[0][2] == [0]

    def test_explain_analyze_names_pruned_scatter(self, cluster):
        fe, _, _ = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        out = fe.do_query(
            "EXPLAIN ANALYZE SELECT host, avg(cpu) FROM hashed "
            "WHERE host = 'h3' GROUP BY host")[-1]
        rows = [r for b in out.batches for r in b.to_pylist()]
        text = "\n".join(str(r) for r in rows)
        assert "regions pruned 7/8, fan-out=1" in text
        assert "slowest_node_ms" in text
        # plain EXPLAIN prints the same decision (shared helper)
        out = fe.do_query(
            "EXPLAIN SELECT host, avg(cpu) FROM hashed "
            "WHERE host = 'h3' GROUP BY host")[-1]
        plan = out.batches[0].to_pylist()[0]["plan"]
        assert "regions pruned 7/8, fan-out=1" in plan

    def test_group_by_fans_out_to_both_nodes(self, cluster):
        fe, _, log = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        log.clear()
        rows_of(fe, "SELECT host, count(*) FROM hashed GROUP BY host")
        assert {e[1] for e in log if e[0] == "moments"} == {1, 2}
        stats = fe.query_engine.last_exec_stats
        scatter = stats.stages["dist_scatter"].detail["scatter"]
        assert scatter == "regions pruned 0/8, fan-out=2"


# ---------------------------------------------------------------------------
# limit + filter pushdown over the client surface
# ---------------------------------------------------------------------------

class TestWirePushdown:
    @pytest.fixture(autouse=True)
    def _no_frame_cache(self, monkeypatch):
        """The in-process frame cache short-circuits the wire for local
        clusters; disable it so these tests exercise the scan RPC the
        way a remote (flight) topology always does."""
        from greptimedb_tpu.query import tpu_exec
        monkeypatch.setattr(tpu_exec, "cached_table_frame",
                            lambda table: None)

    def test_limit_travels_when_filters_fully_pushable(self, cluster):
        fe, _, log = cluster
        fe.do_query(PLAIN_DDL)
        seed(fe, "plain", hosts=4, rows_per=10)
        log.clear()
        got = rows_of(fe, "SELECT host, cpu FROM plain "
                          "WHERE host = 'h2' LIMIT 3")
        assert len(got) == 3 and all(r[0] == "h2" for r in got)
        scans = [e for e in log if e[0] == "scan"]
        assert scans and scans[0][3] == 3       # limit crossed the wire
        assert scans[0][4], "tag filter did not cross the wire"

    def test_limit_held_back_when_filter_not_pushable(self, cluster):
        fe, _, log = cluster
        fe.do_query(PLAIN_DDL)
        seed(fe, "plain", hosts=4, rows_per=10)
        log.clear()
        got = rows_of(fe, "SELECT host, cpu FROM plain "
                          "WHERE cpu - 100 >= 0 LIMIT 3")
        assert len(got) == 3
        scans = [e for e in log if e[0] == "scan"]
        assert scans and scans[0][3] is None
        # datanode-side rows: tag-eq filter drops the dead rows at the
        # source (4 hosts x 10 rows; only h2's 10 may cross)
        log.clear()
        out = fe.catalog.table("greptime", "public", "plain").scan_batches(
            filters=[BinaryOp("=", Column("host"), Literal("h2"))])
        assert sum(b.num_rows for b in out) == 10

    def test_pushed_filter_emptying_every_batch_keeps_dtypes(self,
                                                             cluster):
        """A shipped tag filter can drop every row of every region; the
        frontend's re-filter must still type-check (string columns came
        back float64 from empty pylists before)."""
        fe, _, _ = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        assert rows_of(
            fe, "SELECT host, cpu FROM hashed "
                "WHERE host < 'a' AND host > 'z'") == []

    def test_scan_filters_travel_over_flight(self, tmp_path):
        """The wire twin: filters/limit/regions ride the Arrow Flight
        scan ticket and the remote datanode applies them."""
        from greptimedb_tpu.client.flight import FlightDatanodeClient
        from greptimedb_tpu.servers.flight import FlightDatanodeServer
        import time as _time
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "dn"), node_id=1,
            register_numbers_table=False))
        dn.start()
        srv = FlightDatanodeServer(dn)
        srv.serve_in_background()
        t0 = _time.time()
        while srv.port == 0 and _time.time() - t0 < 10:
            _time.sleep(0.01)
        client = FlightDatanodeClient(srv.address, 1)
        try:
            from greptimedb_tpu.frontend.instance import FrontendInstance
            fe = FrontendInstance(dn)
            fe.start()
            fe.do_query(PLAIN_DDL)
            seed(fe, "plain", hosts=4, rows_per=10)
            batches = client.scan_batches(
                "greptime", "public", "plain",
                filters=[BinaryOp("=", Column("host"), Literal("h1"))])
            assert sum(b.num_rows for b in batches) == 10
            batches = client.scan_batches(
                "greptime", "public", "plain",
                filters=[InList(Column("host"),
                                [Literal("h1"), Literal("h3")])],
                limit=5)
            assert sum(b.num_rows for b in batches) == 5
            batches = client.scan_batches("greptime", "public", "plain",
                                          regions=[])
            assert sum(b.num_rows for b in batches) == 0
            # time ranges must survive the wire as real TimestampRanges
            # (the datanode's Region.scan dereferences .start/.end)
            from greptimedb_tpu.common.time import TimestampRange
            batches = client.scan_batches(
                "greptime", "public", "plain",
                time_range=TimestampRange(0, 3000))
            assert sum(b.num_rows for b in batches) == 4 * 3
        finally:
            client.close()
            srv.shutdown()
            dn.shutdown()


# ---------------------------------------------------------------------------
# parallel flush + writes
# ---------------------------------------------------------------------------

class TestParallelOps:
    def test_flush_contacts_every_datanode(self, cluster):
        fe, datanodes, log = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        table = fe.catalog.table("greptime", "public", "hashed")
        log.clear()
        table.flush()
        assert {e[1] for e in log if e[0] == "flush"} == {1, 2}
        for dn in datanodes.values():
            t = dn.catalog.table("greptime", "public", "hashed")
            for region in t.regions.values():
                v = region.version_control.current
                assert all(m.num_rows == 0
                           for m in v.memtables.all_memtables())

    def test_multi_region_write_lands_correctly(self, cluster):
        fe, datanodes, _ = cluster
        fe.do_query(HASH_DDL)
        configure_dist_fanout(4)
        seed(fe, "hashed", hosts=16, rows_per=4)
        got = rows_of(fe, "SELECT count(*) AS c FROM hashed")
        assert got == [(64,)]
        # every row on the region its hash names, across both datanodes
        table = fe.catalog.table("greptime", "public", "hashed")
        rule = table.partition_rule
        for dn in datanodes.values():
            t = dn.catalog.table("greptime", "public", "hashed")
            for rn, region in t.regions.items():
                data = region.snapshot().read_merged()
                sd = data.series_dict
                hosts = sd.decode_tag_column(data.series_ids, 0)
                assert all(rule.find_region((h,)) == rn for h in hosts)


# ---------------------------------------------------------------------------
# fault injection: transient retry mid fan-out
# ---------------------------------------------------------------------------

class TestScatterFaults:
    def _counter(self, name):
        from prometheus_client import REGISTRY
        v = REGISTRY.get_sample_value(name)
        return 0.0 if v is None else v

    def test_transient_fault_retries_and_answers(self, cluster):
        fe, _, _ = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        before = self._counter("greptime_dist_rpc_retry_total")
        # every OTHER dist RPC fails transiently: with fan-out=2 one
        # datanode fails mid scatter and must retry invisibly
        fe.do_query("SET failpoint_dist_rpc = '1x2*err(transient)'")
        try:
            got = rows_of(fe, "SELECT host, count(*) AS c FROM hashed "
                              "GROUP BY host ORDER BY host")
            assert got == [(f"h{h}", 6) for h in range(8)]
        finally:
            fe.do_query("SET failpoint_dist_rpc = 'off'")
        assert self._counter("greptime_dist_rpc_retry_total") > before

    def test_flight_unavailable_classifies_transient(self):
        """Real network hops must retry too: unavailable/timeout Flight
        errors map to TransientRpcError, which is_transient recognizes;
        application errors stay terminal."""
        import pyarrow.flight as flight
        from greptimedb_tpu.client.flight import _to_greptime_error
        from greptimedb_tpu.errors import TransientRpcError
        from greptimedb_tpu.storage.retry import is_transient
        e = _to_greptime_error(
            flight.FlightUnavailableError("failed to connect"))
        assert isinstance(e, TransientRpcError) and is_transient(e)
        e = _to_greptime_error(flight.FlightTimedOutError("deadline"))
        assert is_transient(e)
        e = _to_greptime_error(flight.FlightServerError("boom"))
        assert not is_transient(e)

    def test_abort_cancels_queued_work_on_shared_pool(self):
        """A failing gather must not leave its queued fan-out occupying
        the shared pool: unstarted futures are cancelled."""
        import threading
        import time as _time
        calls = []
        gate = threading.Event()

        def boom(i):
            calls.append(i)
            if i == 0:
                raise ValueError("x")
            gate.wait(2)
            return i

        with pytest.raises(ValueError):
            list(parallel_imap(boom, range(10), max_workers=2,
                               pool=dist_runtime()))
        gate.set()
        _time.sleep(0.2)
        # window=2: only items 0 and (maybe) 1 ever started; the other
        # eight were cancelled before a worker picked them up
        assert len(calls) <= 3

    def test_terminal_fault_surfaces(self, cluster):
        fe, _, _ = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        fe.do_query("SET failpoint_dist_rpc = 'err(boom)'")
        try:
            with pytest.raises(Exception, match="boom"):
                fe.do_query("SELECT count(*) FROM hashed")
        finally:
            fe.do_query("SET failpoint_dist_rpc = 'off'")


# ---------------------------------------------------------------------------
# runtime: bounded ordered gather
# ---------------------------------------------------------------------------

class TestBoundedGather:
    def test_order_preserved_with_shared_pool(self):
        import time as _time

        def slow_first(i):
            _time.sleep(0.05 if i == 0 else 0.0)
            return i * 10

        got = list(parallel_imap(slow_first, range(8), max_workers=4,
                                 pool=dist_runtime()))
        assert got == [i * 10 for i in range(8)]

    def test_window_bounds_in_flight(self):
        import threading
        import time as _time
        live = []
        peak = []
        lock = threading.Lock()

        def tracked(i):
            with lock:
                live.append(i)
                peak.append(len(live))
            _time.sleep(0.02)
            with lock:
                live.remove(i)
            return i

        got = list(parallel_imap(tracked, range(12), max_workers=3,
                                 pool=dist_runtime()))
        assert got == list(range(12))
        assert max(peak) <= 3

    def test_exception_propagates(self):
        def boom(i):
            if i == 3:
                raise ValueError("x")
            return i

        with pytest.raises(ValueError):
            list(parallel_imap(boom, range(6), max_workers=2,
                               pool=dist_runtime()))


# ---------------------------------------------------------------------------
# remote regions degrade (satellite 1)
# ---------------------------------------------------------------------------

class TestRemoteRegionsDegrade:
    def test_regions_warns_once_and_degrades(self, cluster, caplog):
        fe, _, _ = cluster
        fe.do_query(HASH_DDL)
        seed(fe, "hashed")
        table = fe.catalog.table("greptime", "public", "hashed")

        class RemoteStub(DatanodeClient):      # no .datanode attribute
            node_id = 99

        stub = RemoteStub()
        remote = DistTable(table.info, table.partition_rule, table.route,
                           {i: stub for i in fe.clients})
        with caplog.at_level(logging.WARNING):
            assert remote.regions == {}
            assert remote.regions == {}
        warns = [r for r in caplog.records
                 if "region metadata is unavailable" in r.message]
        assert len(warns) == 1
        # a MIXED view must also be empty — a partial union would be
        # served as the whole table by the local frame cache
        mixed = DistTable(table.info, table.partition_rule, table.route,
                          {1: fe.clients[1], 2: stub})
        assert mixed.regions == {}
