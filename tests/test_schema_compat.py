"""Schema read-compat matrices: old SSTs/memtables read under newer
schemas after chained alters.

Reference behavior: src/storage/src/schema/compat.rs:611 — readers adapt
files written under older schema versions to the current one: added
columns synthesize their DEFAULT (or null), type changes cast where the
values convert. Matrix here: data written at schema v1, altered twice
(v2 adds a defaulted column, v3 adds a nullable one), flushed at
different versions, then read back under v3 — across restart.
"""

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    f.shutdown()


def _rows(out):
    return [tuple(r) for b in out.batches for r in b.rows()]


class TestReadCompatMatrix:
    def test_chained_alters_with_defaults(self, fe):
        """v1 rows flushed → add defaulted col (v2) → flush v2 rows →
        add nullable col (v3) → all three generations read under v3."""
        fe.do_query("CREATE TABLE m (host STRING, ts TIMESTAMP TIME"
                    " INDEX, a DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO m VALUES ('h', 1000, 1.0)")
        t = fe.catalog.table("greptime", "public", "m")
        t.flush()                                   # SST at schema v1

        fe.do_query("ALTER TABLE m ADD COLUMN b DOUBLE DEFAULT 7.5")
        fe.do_query("INSERT INTO m VALUES ('h', 2000, 2.0, 20.0)")
        t.flush()                                   # SST at schema v2

        fe.do_query("ALTER TABLE m ADD COLUMN c STRING")
        fe.do_query("INSERT INTO m VALUES ('h', 3000, 3.0, 30.0, 'x')")
        # memtable at v3; v1+v2 SSTs on disk

        out = fe.do_query("SELECT ts, a, b, c FROM m ORDER BY ts")[-1]
        assert _rows(out) == [
            (1000, 1.0, 7.5, None),     # v1 SST: b ← default, c ← null
            (2000, 2.0, 20.0, None),    # v2 SST: c ← null
            (3000, 3.0, 30.0, "x"),
        ]

    def test_compat_survives_restart(self, fe, tmp_path):
        fe.do_query("CREATE TABLE r (ts TIMESTAMP TIME INDEX, a DOUBLE)")
        fe.do_query("INSERT INTO r VALUES (1000, 1.0)")
        fe.catalog.table("greptime", "public", "r").flush()
        fe.do_query("ALTER TABLE r ADD COLUMN b BIGINT DEFAULT 42")
        fe.shutdown()

        dn2 = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "d"), register_numbers_table=False))
        dn2.start()
        fe2 = FrontendInstance(dn2)
        fe2.start()
        out = fe2.do_query("SELECT a, b FROM r")[-1]
        assert _rows(out) == [(1.0, 42)]
        fe2.shutdown()

    def test_aggregate_over_defaulted_column(self, fe):
        """The TPU aggregate path must also see synthesized defaults."""
        fe.do_query("CREATE TABLE agg (host STRING, ts TIMESTAMP TIME"
                    " INDEX, a DOUBLE, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO agg VALUES ('h', 1000, 1.0),"
                    " ('h', 2000, 2.0)")
        fe.catalog.table("greptime", "public", "agg").flush()
        fe.do_query("ALTER TABLE agg ADD COLUMN w DOUBLE DEFAULT 10.0")
        fe.do_query("INSERT INTO agg VALUES ('h', 3000, 3.0, 30.0)")
        out = fe.do_query("SELECT sum(w) FROM agg")[-1]
        assert _rows(out) == [(50.0,)]               # 10 + 10 + 30

    def test_memtable_written_before_alter(self, fe):
        """Unflushed rows from before an alter default-fill too."""
        fe.do_query("CREATE TABLE mt (ts TIMESTAMP TIME INDEX, a DOUBLE)")
        fe.do_query("INSERT INTO mt VALUES (1000, 1.0)")  # memtable, v1
        fe.do_query("ALTER TABLE mt ADD COLUMN b DOUBLE DEFAULT 5.0")
        out = fe.do_query("SELECT a, b FROM mt")[-1]
        assert _rows(out) == [(1.0, 5.0)]
