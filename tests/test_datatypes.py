"""Tests for the type/vector/schema substrate.

Mirrors reference coverage in src/datatypes/src/{data_type,vectors,schema}
unit tests and src/common/time tests.
"""

import numpy as np
import pyarrow as pa
import pytest

from greptimedb_tpu.common.time import (
    Timestamp, TimeUnit, TimestampRange, parse_duration_ms)
from greptimedb_tpu.datatypes import (
    BOOLEAN, FLOAT64, INT64, STRING, TIMESTAMP_MILLISECOND, TIMESTAMP_SECOND,
    ColumnDefaultConstraint, ColumnSchema, RecordBatch, Schema, SemanticType,
    Vector, parse_type_name,
)


class TestTime:
    def test_convert(self):
        ts = Timestamp(1500, TimeUnit.MILLISECOND)
        assert ts.convert_to(TimeUnit.SECOND).value == 1
        assert ts.convert_to(TimeUnit.MICROSECOND).value == 1_500_000
        # floor semantics for negatives
        assert Timestamp(-1500, TimeUnit.MILLISECOND).convert_to(TimeUnit.SECOND).value == -2

    def test_ordering_across_units(self):
        assert Timestamp(1, TimeUnit.SECOND) < Timestamp(1001, TimeUnit.MILLISECOND)
        assert Timestamp(1, TimeUnit.SECOND) >= Timestamp(1000, TimeUnit.MILLISECOND)

    def test_from_str(self):
        assert Timestamp.from_str("1234").value == 1234
        t = Timestamp.from_str("1970-01-01 00:00:01")
        assert t.value == 1000
        t = Timestamp.from_str("1970-01-01T00:00:01.500Z")
        assert t.value == 1500

    def test_range(self):
        r = TimestampRange(10, 20)
        assert r.contains(10) and r.contains(19)
        assert not r.contains(20) and not r.contains(9)
        assert r.intersects(TimestampRange(19, 30))
        assert not r.intersects(TimestampRange(20, 30))
        assert TimestampRange(None, 5).intersects(TimestampRange(None, None))

    def test_duration(self):
        assert parse_duration_ms("5m") == 300_000
        assert parse_duration_ms("1h30m") == 5_400_000
        assert parse_duration_ms("100ms") == 100
        with pytest.raises(ValueError):
            parse_duration_ms("xyz")


class TestTypes:
    def test_parse_type_name(self):
        assert parse_type_name("DOUBLE") is FLOAT64
        assert parse_type_name("bigint") is INT64
        assert parse_type_name("TIMESTAMP") is TIMESTAMP_MILLISECOND
        assert parse_type_name("timestamp(0)") is TIMESTAMP_SECOND
        assert parse_type_name("VARCHAR") is STRING
        with pytest.raises(ValueError):
            parse_type_name("frobnicate")

    def test_cast_value(self):
        assert TIMESTAMP_MILLISECOND.cast_value("1970-01-01 00:00:01") == 1000
        assert FLOAT64.cast_value("3") == 3.0
        assert BOOLEAN.cast_value("true") is True


class TestVector:
    def test_pylist_roundtrip_with_nulls(self):
        v = Vector.from_pylist([1.0, None, 3.0], FLOAT64)
        assert v.to_pylist() == [1.0, None, 3.0]
        assert v.null_count == 1
        assert v.get(1) is None

    def test_arrow_roundtrip(self):
        v = Vector.from_pylist(["a", None, "c"], STRING)
        arr = v.to_arrow()
        assert arr.to_pylist() == ["a", None, "c"]
        v2 = Vector.from_arrow(arr)
        assert v2.to_pylist() == ["a", None, "c"]

    def test_timestamp_arrow_roundtrip(self):
        v = Vector.from_pylist([0, 1000, 2000], TIMESTAMP_MILLISECOND)
        arr = v.to_arrow()
        assert pa.types.is_timestamp(arr.type)
        v2 = Vector.from_arrow(arr)
        assert v2.dtype is TIMESTAMP_MILLISECOND
        assert list(v2.data) == [0, 1000, 2000]

    def test_ops(self):
        v = Vector.from_pylist([1, 2, 3, 4], INT64)
        assert v.filter(np.array([True, False, True, False])).to_pylist() == [1, 3]
        assert v.take(np.array([3, 0])).to_pylist() == [4, 1]
        assert v.slice(1, 2).to_pylist() == [2, 3]
        c = Vector.concat([v, Vector.from_pylist([5], INT64)])
        assert c.to_pylist() == [1, 2, 3, 4, 5]

    def test_cast(self):
        v = Vector.from_pylist([1000, 2000], TIMESTAMP_MILLISECOND)
        assert v.cast(TIMESTAMP_SECOND).to_pylist() == [1, 2]
        v = Vector.from_pylist([1, 2], INT64)
        assert v.cast(STRING).to_pylist() == ["1", "2"]


def make_monitor_schema() -> Schema:
    return Schema([
        ColumnSchema("host", STRING, nullable=False, semantic_type=SemanticType.TAG),
        ColumnSchema("ts", TIMESTAMP_MILLISECOND, nullable=False,
                     semantic_type=SemanticType.TIMESTAMP,
                     default=ColumnDefaultConstraint(function="current_timestamp")),
        ColumnSchema("cpu", FLOAT64),
        ColumnSchema("memory", FLOAT64),
    ])


class TestSchema:
    def test_roles(self):
        s = make_monitor_schema()
        assert s.timestamp_column.name == "ts"
        assert s.tag_names() == ["host"]
        assert s.field_names() == ["cpu", "memory"]

    def test_arrow_roundtrip(self):
        s = make_monitor_schema()
        s2 = Schema.from_arrow(s.to_arrow())
        assert s2.tag_names() == ["host"]
        assert s2.timestamp_column.name == "ts"
        assert s2.column_schema("cpu").dtype is FLOAT64

    def test_dict_roundtrip(self):
        s = make_monitor_schema()
        s2 = Schema.from_dict(s.to_dict())
        assert s == s2
        assert s2.column_schema("ts").default.function == "current_timestamp"

    def test_duplicate_time_index_rejected(self):
        with pytest.raises(ValueError):
            Schema([
                ColumnSchema("a", TIMESTAMP_MILLISECOND,
                             semantic_type=SemanticType.TIMESTAMP),
                ColumnSchema("b", TIMESTAMP_MILLISECOND,
                             semantic_type=SemanticType.TIMESTAMP),
            ])

    def test_default_vector(self):
        s = make_monitor_schema()
        v = s.column_schema("ts").create_default_vector(3)
        assert len(v) == 3 and v.null_count == 0
        v = s.column_schema("cpu").create_default_vector(2)
        assert v.null_count == 2


class TestRecordBatch:
    def test_pydict_and_arrow(self):
        s = make_monitor_schema()
        rb = RecordBatch.from_pydict(s, {
            "host": ["a", "b"], "ts": [0, 1000], "cpu": [0.5, 0.6],
            "memory": [None, 1024.0]})
        assert rb.num_rows == 2
        arrow = rb.to_arrow()
        rb2 = RecordBatch.from_arrow(arrow)
        assert rb2.to_pydict() == rb.to_pydict()

    def test_project_filter(self):
        s = make_monitor_schema()
        rb = RecordBatch.from_pydict(s, {
            "host": ["a", "b", "c"], "ts": [0, 1, 2], "cpu": [1.0, 2.0, 3.0],
            "memory": [1.0, 2.0, 3.0]})
        p = rb.project(["host", "cpu"])
        assert p.schema.names() == ["host", "cpu"]
        f = rb.filter(np.array([True, False, True]))
        assert f.column("host").to_pylist() == ["a", "c"]
