"""Continuous rollup flow tests: DDL, incremental fold + watermark,
transparent rollup rewrite (differential vs raw scan), crash recovery,
partitioned destinations, distributed (meta-kv) flows.

Covers the ISSUE 3 acceptance criteria: folds only rows past the
watermark (asserted on fold counters), survives restart without
double-folding, and serves matching GROUP BY date_bin queries via the
`rollup-rewrite` dispatch with answers equal to the raw scan.
"""

import math

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import (GreptimeError, InvalidArgumentsError,
                                   PlanError, UnsupportedError)
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.session import QueryContext


def mk_fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(
        data_home=str(tmp_path), register_numbers_table=False))
    dn.start()
    fe = FrontendInstance(dn)
    fe.start()
    return fe


@pytest.fixture()
def fe(tmp_path):
    inst = mk_fe(tmp_path)
    yield inst
    inst.shutdown()


def rows(out):
    return [list(r) for r in out.batches[0].rows()]


def q1(fe, sql):
    return rows(fe.do_query(sql)[0])


def _mk_cpu(fe, n_per_host=600, hosts=("a", "b"), with_nulls=False):
    fe.do_query("CREATE TABLE cpu (host STRING, ts TIMESTAMP TIME INDEX, "
                "v DOUBLE, PRIMARY KEY(host))")
    vals = []
    for h in hosts:
        scale = 1.0 if h == "a" else 10.0
        for i in range(n_per_host):
            v = "NULL" if with_nulls and i % 7 == 0 else repr(scale * i)
            vals.append(f"('{h}', {i * 1000}, {v})")
    fe.do_query("INSERT INTO cpu VALUES " + ",".join(vals))


FLOW_SQL = ("CREATE FLOW cpu_1m AS SELECT host, "
            "date_bin(INTERVAL '1 minute', ts) AS b, "
            "sum(v) AS v_sum, count(v) AS v_cnt, min(v) AS v_min, "
            "max(v) AS v_max, first(v) AS v_first, last(v) AS v_last, "
            "count(*) AS n FROM cpu GROUP BY host, b")


class TestFlowDdl:
    def test_create_show_drop(self, fe):
        _mk_cpu(fe, 120)
        fe.do_query(FLOW_SQL)
        got = q1(fe, "SHOW FLOWS")
        assert len(got) == 1
        name, src, sink, stride = got[0][:4]
        assert (name, src, sink, stride) == ("cpu_1m", "cpu", "cpu_1m",
                                             60_000)
        # the sink materialized as an ordinary table
        assert q1(fe, "SHOW TABLES LIKE 'cpu_1m'") == [["cpu_1m"]]
        # idempotent create
        fe.do_query(FLOW_SQL.replace("CREATE FLOW",
                                     "CREATE FLOW IF NOT EXISTS"))
        with pytest.raises(InvalidArgumentsError):
            fe.do_query(FLOW_SQL)
        fe.do_query("DROP FLOW cpu_1m")
        assert q1(fe, "SHOW FLOWS") == []
        with pytest.raises(InvalidArgumentsError):
            fe.do_query("DROP FLOW cpu_1m")
        fe.do_query("DROP FLOW IF EXISTS cpu_1m")   # silent

    def test_flow_listed_in_information_schema(self, fe):
        _mk_cpu(fe, 60)
        fe.do_query(FLOW_SQL)
        got = q1(fe, "SELECT flow_name, source_table, sink_table, "
                     "stride_ms FROM information_schema.flows")
        assert got == [["cpu_1m", "cpu", "cpu_1m", 60_000]]

    def test_create_flow_errors(self, fe):
        _mk_cpu(fe, 10)
        with pytest.raises(UnsupportedError, match="not derivable"):
            fe.do_query("CREATE FLOW f AS SELECT stddev(v) FROM cpu "
                        "GROUP BY date_bin(INTERVAL '1 minute', ts)")
        with pytest.raises(PlanError, match="date_bin"):
            fe.do_query("CREATE FLOW f AS SELECT host, sum(v) FROM cpu "
                        "GROUP BY host")
        with pytest.raises(PlanError, match="date_bin"):
            # zero stride
            fe.do_query("CREATE FLOW f AS SELECT sum(v) FROM cpu "
                        "GROUP BY date_bin(INTERVAL '0 minutes', ts)")
        with pytest.raises(PlanError, match="WHERE"):
            fe.do_query("CREATE FLOW f AS SELECT sum(v) FROM cpu "
                        "WHERE host = 'a' "
                        "GROUP BY date_bin(INTERVAL '1 minute', ts)")
        with pytest.raises(GreptimeError, match="not found"):
            fe.do_query("CREATE FLOW f AS SELECT sum(v) FROM nope "
                        "GROUP BY date_bin(INTERVAL '1 minute', ts)")
        with pytest.raises(InvalidArgumentsError, match="differ"):
            fe.do_query("CREATE FLOW cpu AS SELECT host, sum(v) FROM cpu "
                        "GROUP BY host, date_bin(INTERVAL '1 minute', ts)")


class TestIncrementalFold:
    def test_watermark_folds_only_new_rows(self, fe):
        _mk_cpu(fe, 600)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        written = fm.tick()
        assert written["greptime.public.cpu_1m"] == 2 * 10
        spec = fm.flows()[0]
        assert spec.stats["rows_folded"] == 1200
        assert spec.stats["folds"] == 1
        # steady state: nothing new → no fold work at all
        assert fm.tick()["greptime.public.cpu_1m"] == 0
        assert spec.stats["folds"] == 1
        # new rows: only the delta is folded, re-folding the touched
        # bucket idempotently
        fe.do_query("INSERT INTO cpu VALUES ('a', 600000, 600.0), "
                    "('a', 601000, 601.0)")
        fm.tick()
        assert spec.stats["rows_folded"] == 1202
        assert spec.stats["folds"] == 2
        got = q1(fe, "SELECT v_cnt, n FROM cpu_1m "
                     "WHERE host = 'a' AND ts = 600000")
        assert got == [[2.0, 2.0]]
        # a late (out-of-order) write re-folds from its bucket onward
        fe.do_query("INSERT INTO cpu VALUES ('a', 1000, 999.0)")
        fm.tick()
        got = q1(fe, "SELECT v_max FROM cpu_1m "
                     "WHERE host = 'a' AND ts = 0")
        assert got == [[999.0]]

    def test_rewrite_dispatch_and_equality(self, fe):
        _mk_cpu(fe, 600)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        sql = ("SELECT host, date_bin(INTERVAL '5 minutes', ts) AS b, "
               "sum(v), count(v), avg(v) FROM cpu "
               "GROUP BY host, b ORDER BY host, b")
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in \
            fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        raw = q1(fe, sql)
        assert "rollup-rewrite" not in \
            (fe.query_engine.last_exec_stats.dispatch or "")
        fe.do_query("SET rollup_rewrite = 1")
        assert rolled == raw
        # EXPLAIN names the dispatch without folding
        plan = q1(fe, "EXPLAIN " + sql)[0][1]
        assert "Dispatch: rollup-rewrite (flow cpu_1m" in plan
        assert "TableScan: cpu_1m" in plan
        # EXPLAIN ANALYZE records the rewrite stage + dispatch line
        stages = q1(fe, "EXPLAIN ANALYZE " + sql)
        by_stage = {r[0]: r[4] for r in stages}
        assert "rollup-rewrite" in by_stage["dispatch"]
        assert "flow=cpu_1m" in by_stage["rollup_rewrite"]

    def test_rewrite_refreshes_lagging_sink(self, fe):
        """A query through the rewrite first folds pending rows, so the
        transparent path never serves stale buckets."""
        _mk_cpu(fe, 300)
        fe.do_query(FLOW_SQL)
        # no manual tick: the SELECT itself must catch the sink up
        sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
               "sum(v) FROM cpu GROUP BY host, b ORDER BY host, b")
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            assert rolled == q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")


class TestRollupDifferential:
    """Acceptance: every rollup-rewritten query equals the raw-scan
    answer (fp tolerance) across aggs × strides."""

    AGGS = ["sum(v)", "count(v)", "count(*)", "min(v)", "max(v)",
            "first(v)", "last(v)", "avg(v)"]
    STRIDES = ["1 minute", "2 minutes", "5 minutes"]

    def _diff(self, fe, sql):
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in \
            fe.query_engine.last_exec_stats.dispatch, sql
        fe.do_query("SET rollup_rewrite = 0")
        try:
            raw = q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")
        assert len(rolled) == len(raw), sql
        for rr, rw in zip(rolled, raw):
            assert len(rr) == len(rw), sql
            for a, b in zip(rr, rw):
                if isinstance(a, float) or isinstance(b, float):
                    if (a is None) != (b is None):
                        raise AssertionError((sql, rr, rw))
                    if a is not None and not (
                            math.isnan(a) and math.isnan(b)):
                        assert abs(a - b) <= 1e-9 * max(
                            1.0, abs(a), abs(b)), (sql, rr, rw)
                else:
                    assert a == b, (sql, rr, rw)

    def test_aggs_by_strides(self, fe):
        _mk_cpu(fe, 600, with_nulls=True)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        for stride in self.STRIDES:
            cols = ", ".join(self.AGGS)
            self._diff(
                fe, f"SELECT host, date_bin(INTERVAL '{stride}', ts) AS b, "
                    f"{cols} FROM cpu GROUP BY host, b ORDER BY host, b")

    def test_filters_having_order(self, fe):
        _mk_cpu(fe, 600)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        # tag filter + aligned time range + HAVING over an aggregate
        self._diff(
            fe, "SELECT host, date_bin(INTERVAL '2 minutes', ts) AS b, "
                "sum(v) AS s FROM cpu "
                "WHERE host = 'b' AND ts >= 60000 AND ts < 480000 "
                "GROUP BY host, b HAVING sum(v) > 0 ORDER BY s DESC, b")
        # global (tagless) rollup over the time bucket only
        self._diff(
            fe, "SELECT date_bin(INTERVAL '5 minutes', ts) AS b, "
                "count(*), avg(v) FROM cpu GROUP BY b ORDER BY b")

    def test_non_rewritable_shapes_stay_raw(self, fe):
        _mk_cpu(fe, 600)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        for sql in [
            # stride not a multiple of the flow stride
            "SELECT date_bin(INTERVAL '90 seconds', ts) AS b, sum(v) "
            "FROM cpu GROUP BY b",
            # unaligned time bound would clip a fine bucket
            "SELECT date_bin(INTERVAL '1 minute', ts) AS b, sum(v) "
            "FROM cpu WHERE ts >= 1500 GROUP BY b",
            # field predicate cannot be applied post-aggregation
            "SELECT date_bin(INTERVAL '1 minute', ts) AS b, sum(v) "
            "FROM cpu WHERE v > 5 GROUP BY b",
            # aggregate the flow does not store
            "SELECT date_bin(INTERVAL '1 minute', ts) AS b, stddev(v) "
            "FROM cpu GROUP BY b",
            # finer stride than the flow
            "SELECT date_bin(INTERVAL '30 seconds', ts) AS b, sum(v) "
            "FROM cpu GROUP BY b",
        ]:
            fe.do_query(sql)
            d = fe.query_engine.last_exec_stats.dispatch or ""
            assert "rollup-rewrite" not in d, sql


class TestReviewRegressions:
    def test_dropped_sink_falls_back_to_raw(self, fe):
        """DROP TABLE on the sink (flow still registered) must not break
        queries on the source — the rewrite falls back to the raw scan."""
        _mk_cpu(fe, 120)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        fe.do_query("DROP TABLE cpu_1m")
        sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
               "sum(v) FROM cpu GROUP BY host, b ORDER BY host, b")
        got = q1(fe, sql)
        assert len(got) == 2 * 2
        d = fe.query_engine.last_exec_stats.dispatch or ""
        assert "rollup-rewrite" not in d

    def test_show_flows_where_rejected(self, fe):
        from greptimedb_tpu.sql.parser import ParserError
        with pytest.raises(ParserError, match="LIKE"):
            fe.do_query("SHOW FLOWS WHERE flow_name = 'x'")

    def test_delete_triggers_retraction_refold(self, fe):
        """DELETE of already-folded rows advances the sequence with no
        new scan rows — the fold must re-reduce instead of silently
        advancing the watermark past the retraction."""
        _mk_cpu(fe, 120)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        fm.tick()
        sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
               "sum(v), count(v) FROM cpu GROUP BY host, b "
               "ORDER BY host, b")
        fe.do_query("DELETE FROM cpu WHERE host = 'a' AND ts = 0")
        fm.tick()
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            assert rolled == q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")

    def test_delete_plus_insert_same_interval_refolds(self, fe):
        """A DELETE hidden behind new INSERTs in the same fold interval
        must still retract (the live-row count probe catches it even
        though the seq filter alone cannot)."""
        _mk_cpu(fe, 120)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        fm.tick()
        fe.do_query("DELETE FROM cpu WHERE host = 'a' AND ts = 0")
        fe.do_query("INSERT INTO cpu VALUES ('a', 200000, 1.0)")
        fm.tick()
        sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
               "count(v) FROM cpu GROUP BY host, b ORDER BY host, b")
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            assert rolled == q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")

    def test_integer_columns_keep_their_type(self, fe):
        """sum/min/max/first/last over integer source columns must come
        back integral through the rollup, as on the raw path."""
        fe.do_query("CREATE TABLE m (host STRING, ts TIMESTAMP TIME "
                    "INDEX, c BIGINT, PRIMARY KEY(host))")
        fe.do_query("INSERT INTO m VALUES " + ",".join(
            f"('a', {i * 1000}, {i})" for i in range(120)))
        fe.do_query("CREATE FLOW m_1m AS SELECT host, sum(c) AS c_sum, "
                    "max(c) AS c_max, first(c) AS c_first FROM m "
                    "GROUP BY host, date_bin(INTERVAL '1 minute', ts)")
        fe.datanode.flow_manager.tick()
        sql = ("SELECT host, date_bin(INTERVAL '2 minutes', ts) AS b, "
               "sum(c), max(c), first(c) FROM m GROUP BY host, b")
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            raw = q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")
        assert rolled == raw
        # exact int equality, not 1770.0 vs 1770
        assert all(isinstance(v, int) for v in rolled[0][2:])

    def test_first_last_require_full_tag_set(self, fe):
        """first/last cannot merge across collapsed tag dimensions: a
        GROUP BY without the flow's tags stays on the raw scan."""
        _mk_cpu(fe, 300)
        fe.do_query(FLOW_SQL)
        fe.datanode.flow_manager.tick()
        sql = ("SELECT date_bin(INTERVAL '5 minutes', ts) AS b, first(v) "
               "FROM cpu GROUP BY b ORDER BY b")
        raw_first = q1(fe, sql)
        d = fe.query_engine.last_exec_stats.dispatch or ""
        assert "rollup-rewrite" not in d
        # sanity: sum over the same collapsed shape still rewrites and
        # agrees with the raw answer
        sql2 = ("SELECT date_bin(INTERVAL '5 minutes', ts) AS b, sum(v) "
                "FROM cpu GROUP BY b ORDER BY b")
        rolled = q1(fe, sql2)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            assert rolled == q1(fe, sql2)
            assert raw_first == q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")

    def test_full_bucket_delete_removes_ghost_sink_rows(self, fe):
        """Deleting every row of a bucket must delete the bucket's sink
        row too — a refold alone cannot emit it, and a ghost row would
        make rollup answers diverge from the raw scan."""
        _mk_cpu(fe, 180)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        fm.tick()
        assert len(q1(fe, "SELECT ts FROM cpu_1m WHERE host = 'a'")) == 3
        fe.do_query("DELETE FROM cpu WHERE ts < 60000")
        fm.tick()
        # bucket 0 vanished from the sink for both hosts
        assert len(q1(fe, "SELECT ts FROM cpu_1m WHERE host = 'a'")) == 2
        sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
               "sum(v), count(*) FROM cpu GROUP BY host, b "
               "ORDER BY host, b")
        rolled = q1(fe, sql)
        assert "rollup-rewrite" in fe.query_engine.last_exec_stats.dispatch
        fe.do_query("SET rollup_rewrite = 0")
        try:
            assert rolled == q1(fe, sql)
        finally:
            fe.do_query("SET rollup_rewrite = 1")

    def test_retraction_does_not_inflate_fold_counters(self, fe):
        """rows_folded tracks rows newly past the watermark; a DELETE
        retraction re-reduces but must not count re-read old rows."""
        _mk_cpu(fe, 120)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        fm.tick()
        spec = fm.flows()[0]
        assert spec.stats["rows_folded"] == 240
        fe.do_query("DELETE FROM cpu WHERE host = 'a' AND ts = 0")
        fm.tick()
        assert spec.stats["rows_folded"] == 240

    def test_tag_subset_flow_rejected(self, fe):
        """A flow grouping by a tag subset would collapse distinct
        series onto one sink key (MVCC dedup keeps one) — reject it;
        coarser grouping belongs at query time via the rewrite."""
        _mk_cpu(fe, 10)
        with pytest.raises(PlanError, match="every tag column"):
            fe.do_query("CREATE FLOW f AS SELECT sum(v) FROM cpu "
                        "GROUP BY date_bin(INTERVAL '1 minute', ts)")

    def test_cold_region_fold_skips_scan_cache(self, fe):
        """A source region past the streaming threshold folds through
        the window-bounded host path — same answers, no scan-cache
        residency pinned by the background fold."""
        from greptimedb_tpu.query import stream_exec, tpu_exec
        _mk_cpu(fe, 600)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        saved = stream_exec.stream_threshold_rows()
        try:
            stream_exec.configure_streaming(threshold_rows=1)
            tpu_exec.SCAN_CACHE._entries.clear()
            fm.tick()
            assert tpu_exec.SCAN_CACHE.resident_bytes() == 0
            spec = fm.flows()[0]
            assert spec.stats["rows_folded"] == 1200
            # incremental on the cold path too (ts-watermarked: refolds
            # from the last bucket boundary only)
            fe.do_query("INSERT INTO cpu VALUES ('a', 600000, 1.0)")
            folded = spec.stats["rows_folded"]
            fm.tick()
            assert spec.stats["rows_folded"] - folded <= 2 * 60 + 1
            sql = ("SELECT host, date_bin(INTERVAL '5 minutes', ts) AS "
                   "b, sum(v), count(v) FROM cpu GROUP BY host, b "
                   "ORDER BY host, b")
            rolled = q1(fe, sql)
            fe.do_query("SET rollup_rewrite = 0")
            assert rolled == q1(fe, sql)
            fe.do_query("SET rollup_rewrite = 1")
        finally:
            stream_exec.configure_streaming(threshold_rows=saved)

    def test_create_flow_without_from_is_clean_error(self, fe):
        with pytest.raises(PlanError, match="FROM"):
            fe.do_query("CREATE FLOW f SINK TO s AS SELECT 1")

    def test_explain_converts_time_literals_like_execution(self, fe):
        _mk_cpu(fe, 300)
        fe.do_query(FLOW_SQL)
        plan = q1(fe, "EXPLAIN SELECT date_bin(INTERVAL '1 minute', ts) "
                      "AS b, sum(v) FROM cpu "
                      "WHERE ts >= '1970-01-01 00:01:00' GROUP BY b")[0][1]
        assert "Dispatch: rollup-rewrite" in plan

    def test_cross_schema_source_rejected(self, fe):
        fe.do_query("CREATE DATABASE other")
        fe.do_query("CREATE TABLE other.m (host STRING, ts TIMESTAMP "
                    "TIME INDEX, v DOUBLE, PRIMARY KEY(host))")
        with pytest.raises(UnsupportedError, match="current database"):
            fe.do_query("CREATE FLOW f AS SELECT sum(v) FROM other.m "
                        "GROUP BY date_bin(INTERVAL '1 minute', ts)")


class TestCrashRecovery:
    def test_flow_survives_restart_without_double_fold(self, tmp_path):
        fe = mk_fe(tmp_path)
        _mk_cpu(fe, 300)
        fe.do_query(FLOW_SQL)
        fm = fe.datanode.flow_manager
        fm.tick()
        spec = fm.flows()[0]
        assert spec.stats["rows_folded"] == 600
        before = q1(fe, "SELECT host, ts, v_cnt FROM cpu_1m "
                        "ORDER BY host, ts")
        fe.shutdown()

        fe2 = mk_fe(tmp_path)
        try:
            # flow + watermark + sink rows recovered
            assert q1(fe2, "SHOW FLOWS")[0][0] == "cpu_1m"
            fm2 = fe2.datanode.flow_manager
            spec2 = fm2.flows()[0]
            assert spec2.stats["rows_folded"] == 600
            assert spec2.watermarks
            # ticking after restart folds NOTHING (watermark held)
            fm2.tick()
            assert spec2.stats["rows_folded"] == 600
            assert q1(fe2, "SELECT host, ts, v_cnt FROM cpu_1m "
                           "ORDER BY host, ts") == before
            # new rows fold exactly once and counts still match raw
            fe2.do_query("INSERT INTO cpu VALUES ('a', 300000, 1.0), "
                         "('b', 300000, 2.0)")
            fm2.tick()
            assert spec2.stats["rows_folded"] == 602
            sql = ("SELECT host, date_bin(INTERVAL '1 minute', ts) AS b, "
                   "count(v) FROM cpu GROUP BY host, b ORDER BY host, b")
            rolled = q1(fe2, sql)
            fe2.do_query("SET rollup_rewrite = 0")
            assert rolled == q1(fe2, sql)
            fe2.do_query("SET rollup_rewrite = 1")
        finally:
            fe2.shutdown()


class TestPartitionedDestination:
    PART_DDL = ("CREATE TABLE agg (host STRING, ts TIMESTAMP TIME INDEX, "
                "v_sum DOUBLE, PRIMARY KEY(host)) "
                "PARTITION BY RANGE COLUMNS (host) ("
                "PARTITION p0 VALUES LESS THAN ('b'), "
                "PARTITION p1 VALUES LESS THAN (MAXVALUE))")

    def test_downsample_into_partitioned_table(self, fe):
        """Satellite: /v1/admin/downsample no longer refuses partitioned
        destinations — rows route through partition/splitter.py."""
        from greptimedb_tpu.storage.downsample import downsample_region
        _mk_cpu(fe, 300)
        fe.do_query(self.PART_DDL.replace("v_sum", "v"))
        src = fe.catalog.table("greptime", "public", "cpu")
        dst = fe.catalog.table("greptime", "public", "agg")
        assert len(dst.regions) == 2
        wrote = 0
        for region in src.regions.values():
            wrote += downsample_region(region, dst, stride_ms=60_000,
                                       aggs={"v": "avg"})
        assert wrote == 2 * 5
        # each bucket row landed in its partition's region
        per_region = [r.snapshot().read_merged().num_rows
                      for r in dst.regions.values()]
        assert sorted(per_region) == [5, 5]
        got = q1(fe, "SELECT host, ts, v FROM agg ORDER BY host, ts")
        assert got[0] == ["a", 0, 29.5]

    def test_flow_into_partitioned_sink(self, fe):
        _mk_cpu(fe, 300)
        fe.do_query(self.PART_DDL)
        fe.do_query("CREATE FLOW f1 SINK TO agg AS SELECT host, "
                    "sum(v) AS v_sum FROM cpu "
                    "GROUP BY host, date_bin(INTERVAL '1 minute', ts)")
        fe.datanode.flow_manager.tick()
        per_region = [r.snapshot().read_merged().num_rows
                      for r in fe.catalog.table(
                          "greptime", "public", "agg").regions.values()]
        assert sorted(per_region) == [5, 5]


class TestDistributedFlows:
    def _cluster(self, data_home):
        from greptimedb_tpu.client import LocalDatanodeClient
        from greptimedb_tpu.frontend.distributed import DistInstance
        from greptimedb_tpu.meta import MetaClient, Peer
        from greptimedb_tpu.meta.kv import MemKv
        from greptimedb_tpu.meta.service import MetaSrv
        srv = MetaSrv(MemKv())
        datanodes, clients = [], {}
        for i in (1, 2):
            dn = DatanodeInstance(DatanodeOptions(
                data_home=f"{data_home}/dn{i}", node_id=i,
                register_numbers_table=False))
            dn.start()
            datanodes.append(dn)
            clients[i] = LocalDatanodeClient(dn)
            srv.register_datanode(Peer(i, f"dn{i}"))
            srv.handle_heartbeat(i)
        return srv, datanodes, MetaClient(srv), clients

    def test_flow_on_distributed_frontend(self, tmp_path):
        from greptimedb_tpu.frontend.distributed import DistInstance
        srv, datanodes, meta, clients = self._cluster(str(tmp_path))
        try:
            fe = DistInstance(meta, clients)
            ctx = QueryContext()
            fe.do_query("CREATE TABLE cpu (host STRING, ts TIMESTAMP "
                        "TIME INDEX, v DOUBLE, PRIMARY KEY(host))", ctx)
            vals = ", ".join(f"('h{i % 3}', {i * 1000}, {float(i)})"
                             for i in range(240))
            fe.do_query("INSERT INTO cpu VALUES " + vals, ctx)
            fe.do_query("CREATE FLOW cpu_1m AS SELECT host, sum(v) AS "
                        "v_sum, count(v) AS v_cnt FROM cpu GROUP BY "
                        "host, date_bin(INTERVAL '1 minute', ts)", ctx)
            fe.flow_manager.tick()
            got = rows(fe.do_query(
                "SELECT host, ts, v_sum FROM cpu_1m "
                "ORDER BY host, ts", ctx)[0])
            assert len(got) == 3 * 4
            # a second frontend on the same meta recovers the flow
            fe2 = DistInstance(meta, clients)
            assert [f.name for f in fe2.flow_manager.flows()] == ["cpu_1m"]
            # incremental: a second tick with no new data writes the
            # refold of the last bucket only
            spec = fe.flow_manager.flows()[0]
            folded = spec.stats["rows_folded"]
            fe.flow_manager.tick()
            assert spec.stats["rows_folded"] - folded <= 3 * 60
        finally:
            for dn in datanodes:
                dn.shutdown()
