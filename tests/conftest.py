"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against `--xla_force_host_platform_device_count=8` on CPU, which exercises the
same SPMD partitioner XLA uses on real meshes.

Note: the image's sitecustomize pre-imports JAX with JAX_PLATFORMS=axon, so
plain env vars are too late — we reconfigure via jax.config before the first
backend initialization (which is lazy).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Production TPU never enables x64 — run the suite in the same numeric
# regime so int64→int32 narrowing bugs surface here, not in the driver's
# multichip gate (they escaped in rounds 1 and 2 because this was True).
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return str(tmp_path)
