"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against `--xla_force_host_platform_device_count=8` on CPU, which exercises the
same SPMD partitioner XLA uses on real meshes.

Note: the image's sitecustomize pre-imports JAX with JAX_PLATFORMS=axon, so
plain env vars are too late — we reconfigure via jax.config before the first
backend initialization (which is lazy).
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Production TPU never enables x64 — run the suite in the same numeric
# regime so int64→int32 narrowing bugs surface here, not in the driver's
# multichip gate (they escaped in rounds 1 and 2 because this was True).
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_data_dir(tmp_path):
    return str(tmp_path)


# ---------------------------------------------------------------------
# greptsan (devtools/greptsan): the happens-before race detector runs
# for the whole session (auto-on under pytest, like the lock-order
# detector); races are recorded, not raised, and THIS gate fails the
# run if any survived the suppression baseline. Importing the package
# is what installs the thread/pool/lock hooks.
# ---------------------------------------------------------------------

from greptimedb_tpu.devtools import greptsan  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GREPTSAN_BASELINE = os.path.join(_REPO, ".greptsan-baseline.json")


@pytest.fixture(autouse=True)
def _greptsan_generation():
    """Between-test hygiene: drop per-variable access metadata and let
    thread clocks reset lazily (bounds clock size to one test's thread
    count instead of the whole session's). Recorded races persist — the
    session gate below reads them."""
    yield
    if greptsan.enabled():
        greptsan.detector.new_generation()


def pytest_sessionfinish(session, exitstatus):
    if not greptsan.enabled():
        return
    fresh = greptsan.unsuppressed(greptsan.races(),
                                  path=_GREPTSAN_BASELINE)
    if fresh:
        print("\n" + "=" * 70, file=sys.stderr)
        print(f"greptsan: {len(fresh)} unsuppressed data race(s) "
              f"detected during this session:", file=sys.stderr)
        for r in fresh:
            print("\n" + r.render(), file=sys.stderr)
        print("=" * 70, file=sys.stderr)
        session.exitstatus = 1
