"""Auxiliary subsystem tests: telemetry, runtime, plugins, interceptors,
TLS, mem-prof.

Reference counterparts: common-telemetry (logging/tracing/timer),
common-runtime (named pools, RepeatedTask), common-base Plugins,
servers interceptor.rs, servers tls.rs, common-mem-prof.
"""

import logging
import socket
import ssl
import struct
import time

import pytest

from greptimedb_tpu.common.plugins import Plugins
from greptimedb_tpu.common.runtime import (
    RepeatedTask, spawn_bg, spawn_read, spawn_write)
from greptimedb_tpu.common.telemetry import (
    current_span, span, timer)
from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance
from greptimedb_tpu.servers.interceptor import (
    InterceptorChain, SqlQueryInterceptor)
from greptimedb_tpu.servers.tls import TlsOption, make_self_signed


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path / "d"),
                                          register_numbers_table=False))
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    f.shutdown()


class TestRuntime:
    def test_named_pools(self):
        assert spawn_bg(lambda: 1 + 1).result() == 2
        assert spawn_read(lambda: "r").result() == "r"
        assert spawn_write(lambda: "w").result() == "w"

    def test_repeated_task(self):
        hits = []
        t = RepeatedTask(0.01, lambda: hits.append(1), name="tick")
        t.start()
        time.sleep(0.08)
        t.stop()
        n = len(hits)
        assert n >= 2
        time.sleep(0.05)
        assert len(hits) == n            # stopped means stopped


class TestTelemetry:
    def test_span_propagates_into_pool_workers(self):
        """_tls.spans is thread-local, so pool stages used to detach
        from the parent trace; telemetry.propagate() (wired into
        spawn_* and parallel_map) captures the stack at submit and
        re-installs it in the worker."""
        from greptimedb_tpu.common.runtime import parallel_map
        from greptimedb_tpu.common.telemetry import propagate

        with span("parent") as parent:
            def work(_):
                with span("child") as child:
                    return child["trace_id"], child["parent_id"]
            # len > 1 so parallel_map actually uses its pool
            results = parallel_map(work, [1, 2])
            for trace_id, parent_id in results:
                assert trace_id == parent["trace_id"]
                assert parent_id == parent["span_id"]
            fut = spawn_bg(lambda: current_span())
            assert fut.result()["trace_id"] == parent["trace_id"]
            # direct helper: captured stack installs and restores
            wrapped = propagate(lambda: current_span()["span_id"])
        assert current_span() is None
        import threading
        out = []
        t = threading.Thread(target=lambda: out.append(wrapped()))
        t.start()
        t.join()
        assert out == [parent["span_id"]]

    def test_propagate_without_span_is_identity(self):
        from greptimedb_tpu.common.telemetry import propagate

        def fn():
            return 7
        assert propagate(fn) is fn

    def test_metric_sanitize_collision_detected(self, caplog):
        """"a.b" and "a-b" both sanitize to "a_b": the second name must
        get its own histogram (deterministic crc suffix) and the
        collision must be logged, not silently share one series."""
        from greptimedb_tpu.common.telemetry import (
            _histograms, _sanitize, _sanitized_owners)
        base = "collide.test.metric"
        other = "collide-test-metric"
        key1 = _sanitize(base)
        with caplog.at_level(logging.ERROR,
                             logger="greptimedb_tpu.common.telemetry"):
            key2 = _sanitize(other)
        assert key1 == "collide_test_metric"
        assert key2 != key1
        assert key2.startswith(key1 + "_x")
        assert any("collision" in r.message for r in caplog.records)
        # stable: the same colliding name keeps resolving to one key
        assert _sanitize(other) == key2
        assert _sanitized_owners[key1] == base
        assert _sanitized_owners[key2] == other
        with timer(base):
            pass
        with timer(other):
            pass
        assert key1 in _histograms and key2 in _histograms
        assert _histograms[key1] is not _histograms[key2]

    def test_metric_sanitize_is_thread_safe(self):
        """Regression (greptlint GL08): _sanitize mutated the module
        _sanitized_owners dict outside _metrics_lock although every
        caller takes that lock for the registries — two threads
        first-time-sanitizing colliding names could disagree on the
        owner. Hammer it and assert one stable mapping."""
        import concurrent.futures
        from greptimedb_tpu.common.telemetry import (_sanitize,
                                                     _sanitized_owners)
        names = [f"race.m{i}" for i in range(8)] + \
                [f"race-m{i}" for i in range(8)]   # 8 colliding pairs

        def worker(_):
            return {n: _sanitize(n) for n in names}

        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            results = list(pool.map(worker, range(16)))
        first = results[0]
        assert all(r == first for r in results[1:]), \
            "threads disagree on sanitized metric keys"
        assert len(set(first.values())) == len(names)  # no shared series
        for name, key in first.items():
            assert _sanitized_owners[key] == name

    def test_slow_query_threshold_set_get(self):
        from greptimedb_tpu.common.telemetry import (
            set_slow_query_threshold_ms, slow_query_threshold_ms)
        old = slow_query_threshold_ms()
        try:
            set_slow_query_threshold_ms(250)
            assert slow_query_threshold_ms() == 250
            set_slow_query_threshold_ms(0)      # 0 disables
            assert slow_query_threshold_ms() is None
        finally:
            set_slow_query_threshold_ms(old)

    def test_nested_spans_share_trace(self):
        with span("outer") as outer:
            assert current_span() is outer
            with span("inner", table="t") as inner:
                assert inner["trace_id"] == outer["trace_id"]
                assert inner["parent_id"] == outer["span_id"]
            assert current_span() is outer
        assert current_span() is None

    def test_timer_records(self):
        with timer("unit_test_timer"):
            time.sleep(0.002)
        from greptimedb_tpu.common.telemetry import _histograms
        assert "unit_test_timer" in _histograms

    def test_otlp_export_to_fake_collector(self):
        """Spans flow to an OTLP/HTTP collector: right path, right JSON
        shape, parenting preserved; export failures never raise."""
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from greptimedb_tpu.common.telemetry import configure_otlp

        received = []

        class Collector(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers["Content-Length"]))
                received.append((self.path, json.loads(body)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        srv = HTTPServer(("127.0.0.1", 0), Collector)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        exporter = configure_otlp(
            f"http://127.0.0.1:{srv.server_port}",
            service_name="gdb-test", flush_interval=60)
        try:
            with span("outer_op", table="m"):
                with span("inner_op"):
                    pass
            exporter.flush()
            assert received, "collector saw no export"
            path, doc = received[0]
            assert path == "/v1/traces"
            rs = doc["resourceSpans"][0]
            svc = {a["key"]: a["value"]["stringValue"]
                   for a in rs["resource"]["attributes"]}
            assert svc["service.name"] == "gdb-test"
            spans = rs["scopeSpans"][0]["spans"]
            byname = {sp["name"]: sp for sp in spans}
            assert set(byname) == {"outer_op", "inner_op"}
            assert byname["inner_op"]["parentSpanId"] == \
                byname["outer_op"]["spanId"]
            assert byname["inner_op"]["traceId"] == \
                byname["outer_op"]["traceId"]
            assert len(byname["outer_op"]["traceId"]) == 32
            outer_attrs = {a["key"] for a in
                           byname["outer_op"]["attributes"]}
            assert "table" in outer_attrs
            assert exporter.exported == 2
            # a dead collector must not raise into the traced path
            srv.shutdown()
            with span("after_death"):
                pass
            exporter.flush()
        finally:
            configure_otlp(None)
            srv.shutdown()

    def test_otlp_batch_golden_shape(self):
        """Golden-check one enqueued span's OTLP JSON — exact id padding
        (16-byte trace / 8-byte span ids), parentSpanId, attribute
        encoding and nanosecond window — plus the bounded queue's
        drop-when-full counter (ISSUE 2 satellite)."""
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from greptimedb_tpu.common.telemetry import OtlpExporter

        received = []

        class Collector(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers["Content-Length"]))
                received.append(json.loads(body))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = HTTPServer(("127.0.0.1", 0), Collector)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        exporter = OtlpExporter(
            f"http://127.0.0.1:{srv.server_port}",
            service_name="gdb-golden", flush_interval=60, max_queue=1)
        try:
            fake = {
                "name": "scan_slice",
                "trace_id": "abcd1234abcd1234",        # 16 hex chars
                "span_id": "11223344",                 # 8 hex chars
                "parent_id": "55667788",
                "attrs": {"region": "r1", "slices": 3},
                "start_unix_ns": 1_700_000_000_000_000_000,
            }
            exporter.enqueue(fake, duration_ns=42_000_000)
            # queue is full (max_queue=1): the next span must be DROPPED
            # and counted, never block or grow the buffer
            exporter.enqueue(dict(fake, span_id="99999999"), 1)
            assert exporter.dropped == 1
            exporter.flush()
            assert len(received) == 1
            doc = received[0]
            spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
            assert len(spans) == 1
            golden = {
                "traceId": "abcd1234abcd12340000000000000000",
                "spanId": "1122334400000000",
                "parentSpanId": "5566778800000000",
                "name": "scan_slice",
                "kind": 1,
                "startTimeUnixNano": "1700000000000000000",
                "endTimeUnixNano": "1700000000042000000",
                "attributes": [
                    {"key": "region", "value": {"stringValue": "r1"}},
                    {"key": "slices", "value": {"stringValue": "3"}},
                ],
            }
            assert spans[0] == golden
            assert exporter.exported == 1
        finally:
            exporter.shutdown()
            srv.shutdown()


class TestPlugins:
    def test_insert_get(self):
        p = Plugins()

        class Thing:
            pass

        t = Thing()
        p.insert(t)
        assert p.get(Thing) is t
        assert Thing in p

    def test_subclass_lookup(self):
        p = Plugins()
        chain = InterceptorChain()
        p.insert(chain)
        assert p.get(SqlQueryInterceptor) is chain


class TestInterceptors:
    def test_rewrite_and_audit(self, fe):
        audit = []

        class Audit(SqlQueryInterceptor):
            def pre_parsing(self, sql, ctx):
                audit.append(sql)
                return sql.replace("__TABLE__", "real_table")

            def pre_execute(self, stmt, ctx):
                audit.append(type(stmt).__name__)

        fe.plugins.insert(InterceptorChain([Audit()]))
        fe.do_query("CREATE TABLE real_table (ts TIMESTAMP TIME INDEX,"
                    " v DOUBLE)")
        fe.do_query("SELECT count(*) FROM __TABLE__")
        assert "SELECT count(*) FROM __TABLE__" in audit
        assert "Query" in audit

    def test_rejecting_interceptor(self, fe):
        class DenyDrops(SqlQueryInterceptor):
            def pre_execute(self, stmt, ctx):
                from greptimedb_tpu.sql import ast
                if isinstance(stmt, ast.DropTable):
                    raise PermissionError("drops are disabled")

        fe.plugins.insert(InterceptorChain([DenyDrops()]))
        fe.do_query("CREATE TABLE keepme (ts TIMESTAMP TIME INDEX,"
                    " v DOUBLE)")
        with pytest.raises(PermissionError):
            fe.do_query("DROP TABLE keepme")
        assert fe.catalog.table("greptime", "public", "keepme") is not None


class TestTls:
    def test_disable_mode(self):
        assert TlsOption("disable").setup() is None

    def test_require_needs_paths(self):
        with pytest.raises(ValueError):
            TlsOption("require").setup()

    def test_postgres_tls_upgrade(self, fe, tmp_path):
        """PG SSLRequest → 'S' → TLS handshake → normal query flow
        (reference: tls.rs + postgres startup)."""
        pytest.importorskip(
            "cryptography",
            reason="self-signed cert generation needs cryptography")
        from greptimedb_tpu.servers.postgres import PostgresServer
        cert, key = str(tmp_path / "c.pem"), str(tmp_path / "k.pem")
        make_self_signed(cert, key)
        ctx = TlsOption("require", cert, key).setup()
        srv = PostgresServer(fe, ssl_context=ctx)
        srv.serve_in_background()
        raw = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        raw.sendall(struct.pack("!II", 8, 80877103))     # SSLRequest
        assert raw.recv(1) == b"S"
        client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client_ctx.check_hostname = False
        client_ctx.verify_mode = ssl.CERT_NONE
        tls_sock = client_ctx.wrap_socket(raw)
        body = struct.pack("!I", 196608) + b"user\x00u\x00\x00"
        tls_sock.sendall(struct.pack("!I", len(body) + 4) + body)
        # AuthenticationOk arrives over the encrypted channel
        head = tls_sock.recv(5)
        assert head[0:1] == b"R"
        tls_sock.close()
        srv.shutdown()

    def test_mysql_no_ssl_advertised_without_context(self, fe):
        from greptimedb_tpu.servers.mysql import CLIENT_SSL, MysqlServer
        srv = MysqlServer(fe)
        srv.serve_in_background()
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=10)
        header = sock.recv(4)
        length = int.from_bytes(header[:3], "little")
        greeting = sock.recv(length)
        end = greeting.index(b"\x00", 1)
        caps_lo = struct.unpack_from(
            "<H", greeting, end + 1 + 4 + 8 + 1)[0]
        assert not (caps_lo & CLIENT_SSL)
        sock.close()
        srv.shutdown()


class TestMemProf:
    def test_mem_prof_route(self, fe):
        import urllib.request
        from greptimedb_tpu.servers.auth import NoopUserProvider
        from greptimedb_tpu.servers.http import HttpServer
        srv = HttpServer(fe, NoopUserProvider(), "127.0.0.1:0")
        srv.start()
        base = f"http://127.0.0.1:{srv.port}/v1/prof/mem"
        first = urllib.request.urlopen(base).read().decode()
        assert "tracemalloc" in first or "total traced" in first
        second = urllib.request.urlopen(base).read().decode()
        assert "total traced" in second
        srv.shutdown()
        import tracemalloc
        tracemalloc.stop()
