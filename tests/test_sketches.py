"""Distributed aggregation v2 tests (ISSUE 14).

Covers: sketch primitives (HLL accuracy, exact-set merge + degrade,
t-digest rank error, wire codec + typed corruption errors), the
differential matrix (new agg shapes × NULLs × empty regions × 1/4
datanodes × hash/range rules — exact ops byte-identical to the raw-row
fallback, sketch ops within the documented bound), the spy assertion
that count(DISTINCT) GROUP BY scatters region_moments partial RPCs and
ZERO raw-row scans, the sketch_codec corruption degrade (typed error →
raw-row retry → right answer + greptime_sketch_degrade_total), the
cost-based raw-pull choice, the SET knobs, and the flow-compile
rejection of approx aggregates.
"""

import math

import numpy as np
import pytest

from greptimedb_tpu.client import LocalDatanodeClient
from greptimedb_tpu.common import failpoint
from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import (
    InvalidArgumentsError, SketchCodecError, UnsupportedError)
from greptimedb_tpu.frontend.distributed import DistInstance
from greptimedb_tpu.meta import MemKv, MetaClient, MetaSrv, Peer
from greptimedb_tpu.query import sketches, tpu_exec
from greptimedb_tpu.query.sketches import (
    EXACT_SET_LIMIT, DistinctSketch, HyperLogLog, TDigest, decode_sketch,
    encode_sketch, hash64)
from greptimedb_tpu.session import QueryContext


@pytest.fixture(autouse=True)
def _clean_knobs():
    failpoint.reset()
    yield
    failpoint.reset()
    tpu_exec.configure_partial_pushdown(enabled=True)
    sketches.configure(exact_distinct=False, error_target=0.01)


# ---------------------------------------------------------------------------
# sketch primitives
# ---------------------------------------------------------------------------

class TestDistinctSketch:
    def test_exact_set_merge_is_exact(self):
        a = DistinctSketch.from_values(np.array([1.0, 2.0, 2.0, np.nan]))
        b = DistinctSketch.from_values(np.array([2.0, 3.0, -0.0, 0.0]))
        a.merge(b)
        assert a.exact and a.result() == 4       # {0, 1, 2, 3}

    def test_string_sets(self):
        a = DistinctSketch.from_values(np.array(["x", "y"], dtype=object))
        b = DistinctSketch.from_values(np.array(["y", "z"], dtype=object))
        assert a.merge(b).result() == 3

    def test_degrades_past_bound_and_stays_mergeable(self):
        a = DistinctSketch.from_values(
            np.arange(EXACT_SET_LIMIT - 100, dtype=np.int64))
        assert a.exact
        b = DistinctSketch.from_values(
            np.arange(2000, 6000, dtype=np.int64))
        a.merge(b)
        assert not a.exact
        est = a.result()
        assert abs(est - 6000) / 6000 < 0.05, est

    def test_hll_accuracy_within_documented_bound(self):
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 1 << 60, 100_000)
        h = HyperLogLog()
        h.add_hashes(hash64(vals))
        true = len(np.unique(vals))
        # documented: 1.04/sqrt(2^p) ≈ 0.8% at p=14; allow 3 sigma
        assert abs(h.result() - true) / true < 0.025

    def test_hash64_is_process_stable(self):
        # crc/splitmix, never Python's seeded hash(): same input, same
        # hashes, so sketches merge across processes
        assert hash64(np.array([1.5, 2.5])).tolist() == \
            hash64(np.array([1.5, 2.5])).tolist()
        assert hash64(np.array(["abc"], dtype=object))[0] == \
            hash64(np.array(["abc"], dtype=object))[0]


class TestTDigest:
    def test_rank_error_and_merge(self):
        rng = np.random.default_rng(3)
        v = rng.normal(0, 1, 50_000)
        whole = TDigest.from_values(v)
        parts = [TDigest.from_values(v[i::8]) for i in range(8)]
        merged = parts[0]
        for p in parts[1:]:
            merged = merged.merge(p)
        for d in (whole, merged):
            for q in (5, 50, 95, 99):
                val = d.quantile(q)
                rank = float((v <= val).mean())
                assert abs(rank - q / 100.0) < 0.015, (q, rank)

    def test_small_inputs(self):
        assert TDigest.from_values(np.array([], np.float64)) \
            .quantile(50) is None
        assert TDigest.from_values(np.array([4.0])).quantile(95) == 4.0


class TestCodec:
    def test_roundtrip(self):
        for sk in (DistinctSketch.from_values(np.array([1.5, 2.5])),
                   DistinctSketch.from_values(
                       np.array([3, 4], dtype=np.int64)),
                   DistinctSketch.from_values(
                       np.array(["a", "b"], dtype=object)),
                   TDigest.from_values(np.arange(100, dtype=np.float64))):
            enc = encode_sketch(sk)
            dec = decode_sketch(enc)
            if isinstance(sk, TDigest):
                assert dec.quantile(50) == sk.quantile(50)
            else:
                assert dec.result() == sk.result()

    def test_hll_roundtrip(self):
        sk = DistinctSketch.from_values(np.arange(EXACT_SET_LIMIT + 10))
        assert not sk.exact
        assert decode_sketch(encode_sketch(sk)).result() == sk.result()

    def test_corruption_raises_typed_error(self):
        good = encode_sketch(DistinctSketch.from_values(np.array([1.0])))
        for bad in (b"", b"GSK", good[:-1], good[:-4] + b"zzzz",
                    b"XXX" + good[3:], good[:5] + b"\xff" + good[6:],
                    3.14, None):
            with pytest.raises(SketchCodecError):
                decode_sketch(bad)

    def test_version_skew_raises(self):
        import struct
        import zlib
        good = encode_sketch(DistinctSketch.from_values(np.array([1.0])))
        body = bytearray(good[:-4])
        body[3] = 99                         # future codec version
        framed = bytes(body) + struct.pack(
            "<I", zlib.crc32(bytes(body)) & 0xFFFFFFFF)
        with pytest.raises(SketchCodecError, match="version"):
            decode_sketch(framed)

    def test_error_target_knob(self):
        sketches.configure(error_target=0.05)
        assert sketches.hll_precision() < 14
        with pytest.raises(InvalidArgumentsError):
            sketches.configure(error_target=0.5)


# ---------------------------------------------------------------------------
# cluster fixtures + spies
# ---------------------------------------------------------------------------

class SpyClient(LocalDatanodeClient):
    def __init__(self, datanode, log):
        super().__init__(datanode)
        self.log = log

    def scan_batches(self, *a, **kw):
        self.log.append(("scan", self.node_id))
        return super().scan_batches(*a, **kw)

    def region_moments(self, *a, **kw):
        self.log.append(("moments", self.node_id))
        return super().region_moments(*a, **kw)


def make_cluster(tmp_path, n_datanodes):
    datanodes, clients, log = {}, {}, []
    srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
    meta = MetaClient(srv)
    for i in range(1, n_datanodes + 1):
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / f"dn{i}"), node_id=i,
            register_numbers_table=False))
        dn.start()
        datanodes[i] = dn
        clients[i] = SpyClient(dn, log)
        srv.register_datanode(Peer(i, f"dn{i}"))
        srv.handle_heartbeat(i)
    return DistInstance(meta, clients), datanodes, log


HASH_DDL = """
CREATE TABLE {name} (host STRING, ts TIMESTAMP TIME INDEX, a DOUBLE,
                     b DOUBLE, n BIGINT, PRIMARY KEY(host))
PARTITION BY HASH (host) PARTITIONS 8
"""

RANGE_DDL = """
CREATE TABLE {name} (host STRING, ts TIMESTAMP TIME INDEX, a DOUBLE,
                     b DOUBLE, n BIGINT, PRIMARY KEY(host))
PARTITION BY RANGE COLUMNS (host) (
  PARTITION r0 VALUES LESS THAN ('h2'),
  PARTITION r1 VALUES LESS THAN ('h6'),
  PARTITION r2 VALUES LESS THAN (MAXVALUE))
"""


def seed(fe, name, ctx, hosts=6, rows_per=40):
    """Integer-valued doubles (so float sums fold exactly) with NULLs
    sprinkled through both fields; hosts h0..h5 over 8 hash buckets
    leave some regions EMPTY by construction."""
    vals = []
    for h in range(hosts):
        for i in range(rows_per):
            a = "NULL" if (h + i) % 11 == 0 else float(i % 9)
            b = "NULL" if (h * i) % 13 == 5 else float(1 + i % 4)
            vals.append(f"('h{h}', {i * 1000}, {a}, {b}, {i % 5})")
    fe.do_query(f"INSERT INTO {name} VALUES " + ",".join(vals), ctx)


def rows_of(fe, ctx, sql):
    out = fe.do_query(sql, ctx)[-1]
    return [tuple(r.values())
            for b in out.batches for r in b.to_pylist()]


SHAPES = [
    # (sql template, sketch columns by index — () = must be byte-identical)
    ("SELECT host, count(DISTINCT a) AS cd FROM {t} "
     "GROUP BY host ORDER BY host", ()),
    ("SELECT host, count(DISTINCT n) AS cd, count(a) AS c FROM {t} "
     "GROUP BY host ORDER BY host", ()),
    ("SELECT count(DISTINCT host) AS ch FROM {t}", ()),
    ("SELECT host, sum(a*b) AS s, avg(a+n) AS av FROM {t} "
     "GROUP BY host ORDER BY host", ()),
    ("SELECT host, count(DISTINCT a) AS cd FROM {t} "
     "WHERE host IN ('h1','h3') GROUP BY host ORDER BY host", ()),
    ("SELECT date_bin(INTERVAL '10 seconds', ts) AS tb, "
     "count(DISTINCT a) AS cd FROM {t} GROUP BY tb ORDER BY tb", ()),
    ("SELECT host, approx_distinct(a) AS ad FROM {t} "
     "GROUP BY host ORDER BY host", ()),
    ("SELECT host, approx_percentile(a, 95) AS p FROM {t} "
     "GROUP BY host ORDER BY host", (1,)),
    ("SELECT median(a) AS m FROM {t}", (0,)),
]


class TestDifferentialMatrix:
    """Every (shape × rule × cluster size): the partial pushdown answers
    exactly like the raw-row fallback for exact ops (incl. the exact-set
    distinct below the bound), and within the documented bound for
    sketch ops. NULLs and empty regions ride every case."""

    @pytest.mark.parametrize("n_dn", [1, 4])
    @pytest.mark.parametrize("ddl,table", [(HASH_DDL, "mh"),
                                           (RANGE_DDL, "mr")])
    def test_matrix(self, tmp_path, n_dn, ddl, table):
        fe, datanodes, log = make_cluster(tmp_path / f"{table}{n_dn}",
                                          n_dn)
        ctx = QueryContext()
        try:
            fe.do_query(ddl.format(name=table), ctx)
            seed(fe, table, ctx)
            for sql_t, approx_cols in SHAPES:
                sql = sql_t.format(t=table)
                got = rows_of(fe, ctx, sql)
                dispatch = fe.query_engine.last_exec_stats.dispatch
                fe.do_query("SET dist_partial_agg = 0", ctx)
                want = rows_of(fe, ctx, sql)
                fe.do_query("SET dist_partial_agg = 1", ctx)
                assert len(got) == len(want), (sql, got, want)
                for g, w in zip(got, want):
                    assert len(g) == len(w), sql
                    for i, (gv, wv) in enumerate(zip(g, w)):
                        if i in approx_cols:
                            # sketch vs exact percentile: both engines
                            # within the documented t-digest rank bound
                            # (tiny groups: centroids are the points)
                            assert isinstance(gv, float)
                            assert abs(gv - wv) <= 1.0 + 1e-9, \
                                (sql, gv, wv)
                        elif isinstance(gv, float) and \
                                isinstance(wv, float) and \
                                math.isnan(gv) and math.isnan(wv):
                            pass
                        else:
                            # exact ops: byte-identical to the raw path
                            assert gv == wv, (sql, i, g, w)
                # the shapes must actually push down (except under the
                # knob, restored above)
                assert dispatch is None or "raw-pull" not in dispatch, \
                    (sql, dispatch)
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_empty_table_shapes(self, tmp_path):
        fe, datanodes, log = make_cluster(tmp_path / "empty", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="e"), ctx)
            assert rows_of(fe, ctx,
                           "SELECT count(DISTINCT a) AS c FROM e") == [(0,)]
            got = rows_of(fe, ctx, "SELECT approx_percentile(a, 50) FROM e")
            assert len(got) == 1 and (got[0][0] is None or
                                      math.isnan(got[0][0]))
            assert rows_of(fe, ctx, "SELECT host, count(DISTINCT a) FROM e "
                                    "GROUP BY host") == []
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class TestSpyNoRawScan:
    def test_count_distinct_pushes_partials_only(self, tmp_path):
        """Acceptance: count(DISTINCT) GROUP BY over 4 datanodes issues
        region_moments partial RPCs and ZERO raw-row scan RPCs."""
        fe, datanodes, log = make_cluster(tmp_path / "spy", 4)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="spy"), ctx)
            seed(fe, "spy", ctx)
            log.clear()
            got = rows_of(fe, ctx, "SELECT host, count(DISTINCT a) AS cd, "
                                   "approx_percentile(a, 95) AS p FROM spy "
                                   "GROUP BY host ORDER BY host")
            assert len(got) == 6
            kinds = {k for k, _ in log}
            assert "moments" in kinds and "scan" not in kinds, log
            nodes = {n for k, n in log if k == "moments"}
            assert len(nodes) == 4, log      # every datanode reduced
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_exact_distinct_forces_raw_rows(self, tmp_path):
        fe, datanodes, log = make_cluster(tmp_path / "exact", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="ex"), ctx)
            seed(fe, "ex", ctx)
            fe.do_query("SET exact_distinct = 1", ctx)
            log.clear()
            got = rows_of(fe, ctx, "SELECT host, count(DISTINCT a) AS cd "
                                   "FROM ex GROUP BY host ORDER BY host")
            assert len(got) == 6
            # no sketch partials: the statement went through the raw
            # CPU fallback (in-process clients serve it from the local
            # frame cache, a real wire from scan_batches — either way,
            # zero region_moments RPCs)
            kinds = {k for k, _ in log}
            assert "moments" not in kinds, log
            assert fe.query_engine.last_exec_stats.dispatch == \
                "cpu-fallback"
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class TestDegrade:
    def test_corrupt_sketch_degrades_to_raw_and_counts(self, tmp_path):
        """A corrupt sketch frame raises the typed error, the statement
        retries via the raw-row path (greptime_sketch_degrade_total),
        and the answer is the exact one — never wrong."""
        from prometheus_client import REGISTRY

        def counter(name):
            return REGISTRY.get_sample_value(name) or 0.0

        fe, datanodes, log = make_cluster(tmp_path / "deg", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="dg"), ctx)
            seed(fe, "dg", ctx)
            want = rows_of(fe, ctx, "SELECT host, count(DISTINCT a) AS c "
                                    "FROM dg GROUP BY host ORDER BY host")
            before = counter("greptime_sketch_degrade_total")
            failpoint.configure("sketch_codec", "err")
            try:
                got = rows_of(fe, ctx,
                              "SELECT host, count(DISTINCT a) AS c "
                              "FROM dg GROUP BY host ORDER BY host")
            finally:
                failpoint.configure("sketch_codec", None)
            assert got == want
            assert counter("greptime_sketch_degrade_total") > before
            stats = fe.query_engine.last_exec_stats
            assert "sketch_degrade" in stats.stages
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_truncated_frame_in_finalize_is_typed(self):
        import pandas as pd
        plan = tpu_exec.TpuPlan(
            tag_groups=[], bucket=None,
            moments=[tpu_exec.Moment("distinct", "a", "__m0")],
            finals=[("__agg0", "count_distinct", ["__m0"])],
            time_lo=None, time_hi=None, tag_predicates=[],
            field_filters=[])
        good = encode_sketch(DistinctSketch.from_values(np.array([1.0])))
        df = pd.DataFrame({"__m0": [good[:-2]], "__rowcount": [1]})
        with pytest.raises(SketchCodecError):
            tpu_exec._finalize(df, plan)


class TestCostDispatch:
    def test_unique_keys_choose_raw_pull(self, tmp_path):
        """~1 row per group with a t-digest per group: the partial
        frames outweigh the raw rows, the planner says so in the SAME
        line EXPLAIN prints, and the answer still lands (via the
        raw-row scatter)."""
        fe, datanodes, log = make_cluster(tmp_path / "cost", 2)
        ctx = QueryContext()
        try:
            fe.do_query("CREATE TABLE u (k STRING, ts TIMESTAMP TIME "
                        "INDEX, v DOUBLE, PRIMARY KEY(k)) "
                        "PARTITION BY HASH (k) PARTITIONS 4", ctx)
            fe.do_query("INSERT INTO u VALUES " + ",".join(
                f"('k{i:03d}', {i * 1000}, {float(i)})"
                for i in range(64)), ctx)
            got = rows_of(fe, ctx, "SELECT k, approx_percentile(v, 95) "
                                   "AS p FROM u GROUP BY k ORDER BY k")
            assert len(got) == 64 and got[0] == ("k000", 0.0)
            dispatch = fe.query_engine.last_exec_stats.dispatch
            assert dispatch.startswith("raw-pull ("), dispatch
            assert "est_rows=" in dispatch
            # EXPLAIN renders the identical decision line
            out = fe.do_query("EXPLAIN SELECT k, approx_percentile(v, 95)"
                              " AS p FROM u GROUP BY k", ctx)[-1]
            text = out.batches[0].to_pylist()[0]["plan"]
            assert "raw-pull (" in text, text
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_group_reducing_shapes_choose_pushdown(self, tmp_path):
        fe, datanodes, log = make_cluster(tmp_path / "cost2", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="cp"), ctx)
            seed(fe, "cp", ctx)
            rows_of(fe, ctx, "SELECT host, count(DISTINCT a) AS c FROM cp "
                             "GROUP BY host ORDER BY host")
            dispatch = fe.query_engine.last_exec_stats.dispatch
            assert dispatch.startswith("aggregate-pushdown ("), dispatch
            assert "est_rows=" in dispatch and "est_groups=" in dispatch
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class TestKnobExplainParity:
    def test_dist_partial_agg_off_explains_what_executes(self, tmp_path):
        """Review fix: the kill switch is applied at PLAN time, so
        EXPLAIN and execution render the same (raw) decision instead of
        an EXPLAIN claiming pushdown over a raw-row execution."""
        fe, datanodes, log = make_cluster(tmp_path / "parity", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="pa"), ctx)
            seed(fe, "pa", ctx)
            fe.do_query("SET dist_partial_agg = 0", ctx)
            out = fe.do_query("EXPLAIN SELECT host, count(a) AS c "
                              "FROM pa GROUP BY host", ctx)[-1]
            text = out.batches[0].to_pylist()[0]["plan"]
            assert "aggregate-pushdown" not in text, text
            assert "CpuAggregateExec" in text, text
            rows_of(fe, ctx, "SELECT host, count(a) AS c FROM pa "
                             "GROUP BY host")
            assert fe.query_engine.last_exec_stats.dispatch == \
                "cpu-fallback"
            fe.do_query("SET dist_partial_agg = 1", ctx)
            out = fe.do_query("EXPLAIN SELECT host, count(a) AS c "
                              "FROM pa GROUP BY host", ctx)[-1]
            text = out.batches[0].to_pylist()[0]["plan"]
            assert "aggregate-pushdown" in text, text
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class _RemoteView:
    """Hides .datanode so a LocalDatanodeClient looks like a wire
    client to the cost estimator."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        if name == "datanode":
            raise AttributeError(name)
        return getattr(self._inner, name)


class TestHeartbeatEstimates:
    def test_remote_clients_estimate_from_heartbeat(self, tmp_path):
        """Review fix: datanodes behind a wire client feed the cost
        planner through the heartbeat's region_stats (rows + series +
        time span), so the cost-based choice is live on real clusters,
        not only in-process ones."""
        from greptimedb_tpu.meta.service import DatanodeStat
        from greptimedb_tpu.query.stream_exec import region_stat_entries

        fe, datanodes, log = make_cluster(tmp_path / "hb", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="hb"), ctx)
            seed(fe, "hb", ctx)
            srv = fe.meta._srv
            for i, dn in datanodes.items():
                regions = list(dn.storage.list_regions().values())
                entries, rows, size = region_stat_entries(regions)
                assert all("series" in e and "time_span" in e
                           for e in entries)
                srv.handle_heartbeat(i, DatanodeStat(
                    region_count=len(regions), approximate_rows=rows,
                    approximate_bytes=size, region_stats=entries))
            table = fe.catalog.table("greptime", "public", "hb")
            table.clients = {k: _RemoteView(v)
                             for k, v in table.clients.items()}
            wanted = [rr.region_number
                      for rr in table.route.region_routes]
            est = table._region_estimates(wanted)
            # every routed region is estimated via the heartbeat stats
            assert est, est
            assert sum(r for r, _, _ in est.values()) == 240  # 6×40 rows
            assert all(s >= 1 for rn, (r, s, _) in est.items() if r > 0)
            # and the dispatch line carries the estimates
            rows_got = rows_of(fe, ctx, "SELECT host, count(DISTINCT a) "
                                        "AS c FROM hb GROUP BY host "
                                        "ORDER BY host")
            assert len(rows_got) == 6
            dispatch = fe.query_engine.last_exec_stats.dispatch
            assert "est_rows=240" in dispatch, dispatch
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class TestObservability:
    def test_finalize_reports_partials_and_processes_column(self, tmp_path):
        fe, datanodes, log = make_cluster(tmp_path / "obs", 2)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="ob"), ctx)
            seed(fe, "ob", ctx)
            out = fe.do_query(
                "EXPLAIN ANALYZE SELECT host, count(DISTINCT a) AS cd, "
                "sum(a) AS s FROM ob GROUP BY host", ctx)[-1]
            by_stage = {r["stage"]: r for b in out.batches
                        for r in b.to_pylist()}
            fin = by_stage["finalize"]["detail"]
            assert "partial_frames=" in fin
            assert "partial_bytes=" in fin
            assert "count_distinct:sketch" in fin and "sum:exact" in fin
            # ExecStats totals carry partial bytes (processes view)
            totals = fe.query_engine.last_exec_stats.totals()
            assert totals["partial_bytes"] > 0
            # the information_schema view exposes the column
            out = fe.do_query("SELECT partial_bytes FROM "
                              "information_schema.processes", ctx)[-1]
            assert out.batches[0].schema.names() == ["partial_bytes"]
        finally:
            for dn in datanodes.values():
                dn.shutdown()


class TestStandaloneFallback:
    """Satellite 1: approx aggs in the standalone CPU executor answer
    within the same documented bound as the distributed sketch path."""

    @pytest.fixture()
    def standalone(self, tmp_path):
        from greptimedb_tpu.frontend.instance import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "sa"), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        yield fe
        dn.shutdown()

    def test_same_bound_both_engines(self, tmp_path, standalone):
        ctx = QueryContext()
        standalone.do_query(
            "CREATE TABLE s (host STRING, ts TIMESTAMP TIME INDEX, "
            "a DOUBLE, PRIMARY KEY(host))", ctx)
        rng = np.random.default_rng(5)
        vals = rng.normal(50, 10, 4000)
        standalone.do_query("INSERT INTO s VALUES " + ",".join(
            f"('h{i % 3}', {i * 100}, {v})"
            for i, v in enumerate(vals)), ctx)
        fe, datanodes, _ = make_cluster(tmp_path / "dsb", 2)
        try:
            fe.do_query("CREATE TABLE s (host STRING, ts TIMESTAMP TIME "
                        "INDEX, a DOUBLE, PRIMARY KEY(host)) "
                        "PARTITION BY HASH (host) PARTITIONS 4", ctx)
            fe.do_query("INSERT INTO s VALUES " + ",".join(
                f"('h{i % 3}', {i * 100}, {v})"
                for i, v in enumerate(vals)), ctx)
            for sql in ("SELECT approx_distinct(a) AS d FROM s",
                        "SELECT approx_percentile(a, 95) AS p FROM s"):
                (sa,) = rows_of(standalone, ctx, sql)
                (di,) = rows_of(fe, ctx, sql)
                if "distinct" in sql:
                    true = len(np.unique(vals))
                    for got in (sa[0], di[0]):
                        assert abs(got - true) / true < 0.03, (sql, got)
                else:
                    for got in (sa[0], di[0]):
                        rank = float((vals <= got).mean())
                        assert abs(rank - 0.95) < 0.02, (sql, got, rank)
        finally:
            for dn in datanodes.values():
                dn.shutdown()

    def test_approx_percentile_validates_params(self, standalone):
        ctx = QueryContext()
        standalone.do_query(
            "CREATE TABLE v (host STRING, ts TIMESTAMP TIME INDEX, "
            "a DOUBLE, PRIMARY KEY(host))", ctx)
        standalone.do_query("INSERT INTO v VALUES ('h', 0, 1.0)", ctx)
        with pytest.raises(InvalidArgumentsError):
            standalone.do_query("SELECT approx_percentile(a) FROM v", ctx)
        with pytest.raises(InvalidArgumentsError):
            standalone.do_query(
                "SELECT approx_percentile(a, 150) FROM v", ctx)


class TestSketchFramesOverWire:
    def test_flight_roundtrip_of_sketch_partials(self, tmp_path):
        """Sketch partials are a NEW wire shape (binary columns in the
        region_moments stream): push count(DISTINCT)+p95 through a real
        Flight socket and compare against the in-process answer."""
        import socket
        import time as _time

        from greptimedb_tpu.client.flight import FlightDatanodeClient
        from greptimedb_tpu.servers.flight import FlightDatanodeServer

        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path / "wire"), node_id=1,
            register_numbers_table=False))
        dn.start()
        srv = FlightDatanodeServer(dn)
        srv.serve_in_background()
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            try:
                with socket.create_connection(
                        ("127.0.0.1", srv.port), timeout=0.2):
                    break
            except OSError:
                _time.sleep(0.05)
        meta_srv = MetaSrv(MemKv(), datanode_lease_secs=3600)
        meta = MetaClient(meta_srv)
        meta_srv.register_datanode(Peer(1, srv.address))
        meta_srv.handle_heartbeat(1)
        client = FlightDatanodeClient(srv.address, node_id=1)
        fe = DistInstance(meta, {1: client})
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="w"), ctx)
            seed(fe, "w", ctx, hosts=3, rows_per=20)
            got = rows_of(fe, ctx,
                          "SELECT host, count(DISTINCT a) AS cd, "
                          "approx_percentile(a, 95) AS p, sum(a*b) AS s "
                          "FROM w GROUP BY host ORDER BY host")
            assert "aggregate-pushdown" in \
                fe.query_engine.last_exec_stats.dispatch
            fe.do_query("SET dist_partial_agg = 0", ctx)
            want = rows_of(fe, ctx,
                           "SELECT host, count(DISTINCT a) AS cd, "
                           "approx_percentile(a, 95) AS p, sum(a*b) AS s "
                           "FROM w GROUP BY host ORDER BY host")
            fe.do_query("SET dist_partial_agg = 1", ctx)
            assert len(got) == 3
            for g, w in zip(got, want):
                assert g[0] == w[0] and g[1] == w[1] and g[3] == w[3]
                assert abs(g[2] - w[2]) <= 1.0 + 1e-9
        finally:
            client.close()
            srv.shutdown()
            dn.shutdown()


class TestFlowRejectsApprox:
    def test_create_flow_with_approx_agg_hints(self, tmp_path):
        fe, datanodes, _ = make_cluster(tmp_path / "flow", 1)
        ctx = QueryContext()
        try:
            fe.do_query(HASH_DDL.format(name="src"), ctx)
            with pytest.raises(UnsupportedError,
                               match="sketch"):
                fe.do_query(
                    "CREATE FLOW f AS SELECT host, "
                    "date_bin(INTERVAL '1 minute', ts) AS tb, "
                    "approx_distinct(a) AS d FROM src "
                    "GROUP BY host, tb", ctx)
        finally:
            for dn in datanodes.values():
                dn.shutdown()
