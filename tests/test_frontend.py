"""Standalone frontend end-to-end SQL tests — the README quick-start flow
(reference: src/frontend/src/tests/instance_test.rs shapes)."""

import math

import numpy as np
import pytest

from greptimedb_tpu.datanode import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.errors import (
    DatabaseNotFoundError, GreptimeError, TableNotFoundError)
from greptimedb_tpu.frontend import FrontendInstance
from greptimedb_tpu.session import QueryContext


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
    inst = FrontendInstance(dn)
    inst.start()
    yield inst
    inst.shutdown()


def q(fe, sql, ctx=None):
    outs = fe.do_query(sql, ctx)
    return outs[-1]


MONITOR_DDL = """
CREATE TABLE monitor (
  host STRING,
  ts TIMESTAMP TIME INDEX,
  cpu DOUBLE DEFAULT 0,
  memory DOUBLE,
  PRIMARY KEY(host))"""


class TestStandaloneFlow:
    def test_readme_quickstart(self, fe):
        q(fe, MONITOR_DDL)
        out = q(fe, """
            INSERT INTO monitor(host, ts, cpu, memory) VALUES
              ('host1', 1000, 0.5, 1024),
              ('host2', 1000, 0.9, 2048),
              ('host1', 2000, 0.7, 1100)""")
        assert out.affected_rows == 3
        out = q(fe, "SELECT * FROM monitor ORDER BY host, ts")
        rows = out.batches[0].to_pylist()
        assert rows[0]["host"] == "host1" and rows[0]["cpu"] == 0.5
        out = q(fe, "SELECT host, avg(cpu) AS c FROM monitor GROUP BY host "
                    "ORDER BY host")
        rows = out.batches[0].to_pylist()
        assert math.isclose(rows[0]["c"], 0.6, rel_tol=1e-6)
        assert math.isclose(rows[1]["c"], 0.9, rel_tol=1e-6)

    def test_default_values_and_partial_insert(self, fe):
        q(fe, MONITOR_DDL)
        q(fe, "INSERT INTO monitor(host, ts) VALUES ('h', 5)")
        rows = q(fe, "SELECT cpu, memory FROM monitor").batches[0].to_pylist()
        assert rows[0]["cpu"] == 0.0 and rows[0]["memory"] is None

    def test_restart_recovers_everything(self, tmp_path):
        dn = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
        fe1 = FrontendInstance(dn)
        fe1.start()
        fe1.do_query(MONITOR_DDL)
        fe1.do_query("INSERT INTO monitor(host, ts, cpu) VALUES ('a', 1, 0.1)")
        fe1.do_query("CREATE DATABASE mydb")
        fe1.shutdown()
        dn2 = DatanodeInstance(DatanodeOptions(data_home=str(tmp_path)))
        fe2 = FrontendInstance(dn2)
        fe2.start()
        out = q(fe2, "SELECT host, cpu FROM monitor")
        assert out.batches[0].to_pylist() == [{"host": "a", "cpu": 0.1}]
        dbs = [r["Databases"] for r in
               q(fe2, "SHOW DATABASES").batches[0].to_pylist()]
        assert "mydb" in dbs
        fe2.shutdown()

    def test_use_database_and_qualified_names(self, fe):
        ctx = QueryContext()
        q(fe, "CREATE DATABASE db2", ctx)
        q(fe, "USE db2", ctx)
        assert ctx.current_schema == "db2"
        q(fe, MONITOR_DDL, ctx)
        q(fe, "INSERT INTO monitor(host, ts) VALUES ('x', 1)", ctx)
        out = q(fe, "SELECT count(*) AS c FROM db2.monitor")
        assert out.batches[0].to_pylist()[0]["c"] == 1
        with pytest.raises(TableNotFoundError):
            q(fe, "SELECT * FROM public.monitor")

    def test_alter_flow(self, fe):
        q(fe, MONITOR_DDL)
        q(fe, "INSERT INTO monitor(host, ts) VALUES ('a', 1)")
        q(fe, "ALTER TABLE monitor ADD COLUMN disk DOUBLE")
        q(fe, "INSERT INTO monitor(host, ts, disk) VALUES ('a', 2, 9.5)")
        rows = q(fe, "SELECT ts, disk FROM monitor ORDER BY ts") \
            .batches[0].to_pylist()
        assert rows[0]["disk"] is None and rows[1]["disk"] == 9.5
        q(fe, "ALTER TABLE monitor RENAME TO monitor2")
        assert q(fe, "SELECT count(*) AS c FROM monitor2") \
            .batches[0].to_pylist()[0]["c"] == 2

    def test_delete_and_truncate(self, fe):
        q(fe, MONITOR_DDL)
        q(fe, "INSERT INTO monitor(host, ts) VALUES ('a', 1), ('b', 1), "
              "('a', 2)")
        out = q(fe, "DELETE FROM monitor WHERE host = 'a' AND ts = 1")
        assert out.affected_rows == 1
        assert q(fe, "SELECT count(*) AS c FROM monitor") \
            .batches[0].to_pylist()[0]["c"] == 2
        q(fe, "TRUNCATE TABLE monitor")
        assert q(fe, "SELECT count(*) AS c FROM monitor") \
            .batches[0].to_pylist()[0]["c"] == 0

    def test_insert_select(self, fe):
        q(fe, MONITOR_DDL)
        q(fe, "CREATE TABLE copy1 (host STRING, ts TIMESTAMP TIME INDEX, "
              "cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))")
        q(fe, "INSERT INTO monitor(host, ts, cpu) VALUES ('a', 1, 0.5)")
        out = q(fe, "INSERT INTO copy1 SELECT host, ts, cpu, memory "
                    "FROM monitor")
        assert out.affected_rows == 1
        assert q(fe, "SELECT host FROM copy1").batches[0].to_pylist() == \
            [{"host": "a"}]

    def test_copy_to_from(self, fe, tmp_path):
        q(fe, MONITOR_DDL)
        q(fe, "INSERT INTO monitor(host, ts, cpu) VALUES ('a', 1, 0.5), "
              "('b', 2, 0.7)")
        path = str(tmp_path / "out.parquet")
        out = q(fe, f"COPY monitor TO '{path}'")
        assert out.affected_rows == 2
        q(fe, "CREATE TABLE m2 (host STRING, ts TIMESTAMP TIME INDEX, "
              "cpu DOUBLE, memory DOUBLE, PRIMARY KEY(host))")
        out = q(fe, f"COPY m2 FROM '{path}'")
        assert out.affected_rows == 2
        rows = q(fe, "SELECT host, cpu FROM m2 ORDER BY host") \
            .batches[0].to_pylist()
        assert rows == [{"host": "a", "cpu": 0.5}, {"host": "b", "cpu": 0.7}]

    def test_multi_statement(self, fe):
        outs = fe.do_query(MONITOR_DDL + ";"
                           "INSERT INTO monitor(host, ts) VALUES ('a', 1);"
                           "SELECT count(*) AS c FROM monitor")
        assert outs[-1].batches[0].to_pylist()[0]["c"] == 1

    def test_drop_database(self, fe):
        ctx = QueryContext()
        q(fe, "CREATE DATABASE tmp1", ctx)
        q(fe, "USE tmp1", ctx)
        q(fe, MONITOR_DDL, ctx)
        q(fe, "USE public", ctx)
        q(fe, "DROP DATABASE tmp1", ctx)
        with pytest.raises(DatabaseNotFoundError):
            q(fe, "SHOW TABLES FROM tmp1")


class TestAutoCreateIngest:
    def test_create_on_demand(self, fe):
        n = fe.handle_row_insert(
            "metrics_auto",
            {"host": ["a", "b"], "greptime_timestamp": [1000, 2000],
             "value": [1.5, 2.5]},
            tag_columns=["host"])
        assert n == 2
        rows = q(fe, "SELECT * FROM metrics_auto ORDER BY greptime_timestamp") \
            .batches[0].to_pylist()
        assert rows[0]["host"] == "a" and rows[0]["value"] == 1.5
        desc = q(fe, "DESCRIBE metrics_auto").batches[0].to_pylist()
        by = {r["Column"]: r for r in desc}
        assert by["host"]["Semantic Type"] == "TAG"
        assert by["greptime_timestamp"]["Key"] == "TIME INDEX"

    def test_alter_on_demand(self, fe):
        fe.handle_row_insert(
            "m", {"host": ["a"], "greptime_timestamp": [1], "v1": [1.0]},
            tag_columns=["host"])
        fe.handle_row_insert(
            "m", {"host": ["a"], "greptime_timestamp": [2], "v1": [2.0],
                  "v2": [3.0]},
            tag_columns=["host"])
        rows = q(fe, "SELECT v1, v2 FROM m ORDER BY greptime_timestamp") \
            .batches[0].to_pylist()
        assert rows[0]["v2"] is None and rows[1]["v2"] == 3.0
