"""Concurrency stress tests: parallel writers/readers vs background jobs.

The reference's safety is by construction (single-writer-per-region
mutex, atomic version swaps — SURVEY §5); these tests drive those
invariants under real thread contention: concurrent SQL writers, readers
racing flush/compaction, and mixed DDL+DML.
"""

import concurrent.futures
import threading

import pytest

from greptimedb_tpu.datanode.instance import DatanodeInstance, DatanodeOptions
from greptimedb_tpu.frontend.instance import FrontendInstance


@pytest.fixture()
def fe(tmp_path):
    dn = DatanodeInstance(DatanodeOptions(
        data_home=str(tmp_path / "d"), register_numbers_table=False,
        flush_size_bytes=256 * 1024))    # small: flushes trigger mid-test
    dn.start()
    f = FrontendInstance(dn)
    f.start()
    yield f
    f.shutdown()


class TestConcurrentWrites:
    def test_parallel_sql_writers_lose_nothing(self, fe):
        fe.do_query("CREATE TABLE w (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        workers, per = 8, 50
        errors = []

        def writer(wid):
            try:
                for i in range(per):
                    ts = wid * 1_000_000 + i
                    fe.do_query(f"INSERT INTO w VALUES"
                                f" ('h{wid}', {ts}, {float(i)})")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        with concurrent.futures.ThreadPoolExecutor(workers) as pool:
            list(pool.map(writer, range(workers)))
        assert not errors
        out = fe.do_query("SELECT count(*) FROM w")[-1]
        assert next(out.batches[0].rows())[0] == workers * per
        out = fe.do_query("SELECT host, count(*) AS c FROM w"
                          " GROUP BY host ORDER BY host")[-1]
        assert all(r[1] == per for b in out.batches for r in b.rows())

    def test_readers_race_writers_and_flushes(self, fe):
        fe.do_query("CREATE TABLE rw (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        stop = threading.Event()
        errors = []
        counts = []

        def writer():
            try:
                i = 0
                while not stop.is_set() and i < 300:
                    fe.do_query(f"INSERT INTO rw VALUES"
                                f" ('h{i % 4}', {i}, {float(i)})")
                    i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                while not stop.is_set():
                    out = fe.do_query("SELECT count(*) AS c FROM rw")[-1]
                    counts.append(next(out.batches[0].rows())[0])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def flusher():
            t = fe.catalog.table("greptime", "public", "rw")
            try:
                while not stop.is_set():
                    t.flush()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer),
                   threading.Thread(target=reader),
                   threading.Thread(target=flusher)]
        for t in threads:
            t.start()
        threads[0].join(timeout=60)       # writer finishes its 300 rows
        stop.set()
        for t in threads[1:]:
            t.join(timeout=30)
        assert not errors
        # monotonic visibility: counts never go backwards
        assert all(a <= b for a, b in zip(counts, counts[1:]))
        out = fe.do_query("SELECT count(*) FROM rw")[-1]
        assert next(out.batches[0].rows())[0] == 300

    def test_parallel_ingest_auto_alter(self, fe):
        """Concurrent row inserts adding DIFFERENT new columns: the
        alter path must serialize and nothing may be lost."""
        fe.handle_row_insert(
            "grow", {"host": ["h"], "greptime_timestamp": [0],
                     "base": [0.0]}, tag_columns=["host"])
        errors = []

        def inserter(wid):
            try:
                for i in range(10):
                    fe.handle_row_insert(
                        "grow",
                        {"host": ["h"],
                         "greptime_timestamp": [1 + wid * 100 + i],
                         "base": [1.0], f"col{wid}": [float(wid)]},
                        tag_columns=["host"])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            list(pool.map(inserter, range(4)))
        assert not errors
        out = fe.do_query("SELECT count(*) FROM grow")[-1]
        assert next(out.batches[0].rows())[0] == 41
        table = fe.catalog.table("greptime", "public", "grow")
        for wid in range(4):
            assert table.schema.contains(f"col{wid}")


class TestCachedFrameRaces:
    """The CPU-fallback frame cache (query/tpu_exec.cached_table_frame)
    is keyed on region versions; concurrent writers must never make a
    reader see torn or stale-beyond-version results."""

    def test_reads_see_monotonic_counts_under_writes(self, tmp_path):
        import threading

        from greptimedb_tpu.datanode.instance import (
            DatanodeInstance, DatanodeOptions)
        from greptimedb_tpu.frontend.instance import FrontendInstance
        dn = DatanodeInstance(DatanodeOptions(
            data_home=str(tmp_path), register_numbers_table=False))
        dn.start()
        fe = FrontendInstance(dn)
        fe.start()
        fe.do_query("CREATE TABLE cfr (host STRING, ts TIMESTAMP TIME"
                    " INDEX, v DOUBLE, PRIMARY KEY(host))")
        t = fe.catalog.table("greptime", "public", "cfr")
        errs = []
        stop = threading.Event()
        counts = []

        def writer():
            try:
                for i in range(40):
                    t.insert({"host": [f"h{i % 4}"] * 50,
                              "ts": list(range(i * 50, i * 50 + 50)),
                              "v": [float(i)] * 50})
                    if i % 10 == 9:
                        t.flush()
            except Exception as e:  # noqa: BLE001
                errs.append(e)
            finally:
                stop.set()

        def reader():
            try:
                while not stop.is_set():
                    out = fe.do_query("SELECT count(*) FROM cfr")
                    if isinstance(out, list):
                        out = out[0]
                    counts.append(out.batches[0].columns[0].to_pylist()[0])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        w = threading.Thread(target=writer)
        rs = [threading.Thread(target=reader) for _ in range(3)]
        w.start()
        for r in rs:
            r.start()
        w.join(timeout=60)
        for r in rs:
            r.join(timeout=30)
        assert not errs, errs
        # final read sees everything; interim counts are all multiples of
        # a batch and never exceed the total
        out = fe.do_query("SELECT count(*) FROM cfr")
        if isinstance(out, list):
            out = out[0]
        assert out.batches[0].columns[0].to_pylist()[0] == 2000
        assert all(0 <= c <= 2000 and c % 50 == 0 for c in counts)
        fe.shutdown()
